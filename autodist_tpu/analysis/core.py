"""graftlint engine: parsing, directives, check registry, baseline, output.

Design notes:

- One :class:`Module` per source file: the ast tree, the raw lines, and every
  ``# graftlint:`` directive found by a ``tokenize`` pass (comments are not in
  the AST). Checks receive the Module plus a repo-level :class:`Context` and
  return :class:`Finding` lists; the engine applies suppressions and the
  baseline afterwards so checks stay oblivious to both.
- Finding fingerprints are line-number-free — ``check|path|scope|message`` —
  so a committed baseline survives unrelated edits above a grandfathered
  finding. ``scope`` is the enclosing def/class qualname.
- GL000 is the analyzer's own meta-check (malformed directives, reasonless
  suppressions, unparseable files). GL000 findings cannot be suppressed —
  otherwise a typo'd suppression could silence the report about itself.
"""

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import time
import tokenize
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

META_CHECK = "GL000"
_CHECK_ID_RE = re.compile(r"^GL\d{3}$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``scope`` + ``message`` (not line) key the baseline."""

    check: str
    path: str          # repo-relative, forward slashes
    line: int
    col: int
    message: str
    scope: str = ""    # enclosing def/class qualname ("" = module level)

    @property
    def fingerprint(self) -> str:
        return f"{self.check}|{self.path}|{self.scope}|{self.message}"

    def render(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        scope = f" [{self.scope}]" if self.scope else ""
        return f"{where}: {self.check} {self.message}{scope}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]            # new findings (post-suppress, post-baseline)
    suppressed: List[Tuple[Finding, str]]   # (finding, reason)
    baselined: List[Finding]
    stale_baseline: List[str]          # fingerprints no longer produced
    files_checked: int = 0
    wall_time_s: float = 0.0
    cache_info: Optional[Dict[str, object]] = None

    @property
    def ok(self) -> bool:
        return not self.findings


class Check:
    """Registry entry: id, one-line title, the check fn, and --explain docs.

    ``program=True`` checks run once over the whole linted file set with
    ``fn(program, ctx)`` (``program`` is a
    :class:`~autodist_tpu.analysis.program.ProgramIndex`) instead of
    per-module; their results are never file-cached (a finding in one file
    can depend on another file's content). ``full_program=True`` marks the
    subset that is only sound over the COMPLETE default path set (registry
    checks like GL009: a producer missing from a partial file set is not a
    missing producer) — ``--changed-only`` skips those.
    """

    def __init__(self, check_id: str, title: str, fn: Callable, doc: str,
                 program: bool = False, full_program: bool = False):
        self.id = check_id
        self.title = title
        self.fn = fn
        self.doc = doc or ""
        self.program = program
        self.full_program = full_program


_CHECKS: Dict[str, Check] = {}


def register(check_id: str, title: str, program: bool = False,
             full_program: bool = False):
    """Decorator registering ``fn(module, ctx) -> [Finding]`` (or, with
    ``program=True``, ``fn(program, ctx)``) under ``GLxxx``."""
    if not _CHECK_ID_RE.match(check_id):
        raise ValueError(f"check id must match GLnnn, got {check_id!r}")

    def deco(fn):
        if check_id in _CHECKS:
            raise ValueError(f"duplicate check id {check_id}")
        _CHECKS[check_id] = Check(check_id, title, fn, fn.__doc__,
                                  program=program, full_program=full_program)
        return fn

    return deco


def register_program(check_id: str, title: str, full_program: bool = False):
    """Decorator registering a whole-program check
    ``fn(program, ctx) -> [Finding]`` under ``GLxxx`` (see :class:`Check`)."""
    return register(check_id, title, program=True,
                    full_program=full_program)


def all_checks() -> Dict[str, Check]:
    """The registry, with the built-in check modules imported."""
    from autodist_tpu.analysis import checks  # noqa: F401  (side effect: registration)
    return dict(_CHECKS)


# ------------------------------------------------------------------ directives

_DIRECTIVE_RE = re.compile(r"#\s*graftlint\s*:\s*(.+?)\s*$")
_DISABLE_ENTRY_RE = re.compile(r"(GL\d{3})\s*(\(([^()]*)\))?")
_LOCK_ORDER_RE = re.compile(
    r"lock-order\s*=\s*([A-Za-z_][\w]*)\s*->\s*([A-Za-z_][\w]*)")


class Module:
    """One parsed source file plus its graftlint directives."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[Finding] = None
        # line -> {check_id: reason}
        self.suppressions: Dict[int, Dict[str, str]] = {}
        self.lock_orders: List[Tuple[str, str]] = []
        self.directive_findings: List[Finding] = []
        self._scopes: Optional[List[Tuple[int, int, str]]] = None
        try:
            self.tree = ast.parse(source)
        except SyntaxError as e:
            self.parse_error = Finding(
                META_CHECK, self.relpath, e.lineno or 1, e.offset or 0,
                f"file does not parse: {e.msg}")
        self._scan_directives()

    # -- directives ---------------------------------------------------------
    def _scan_directives(self):
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, SyntaxError, IndentationError):
            return  # the parse_error finding already covers it
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _DIRECTIVE_RE.search(tok.string)
            if m is None:
                continue
            line = tok.start[0]
            standalone = not self.lines[line - 1][:tok.start[1]].strip()
            target = self._next_code_line(line + 1) if standalone else line
            self._parse_directive(m.group(1), line, target)

    def _next_code_line(self, start: int) -> int:
        for i in range(start, len(self.lines) + 1):
            text = self.lines[i - 1].strip()
            if text and not text.startswith("#"):
                return i
        return start

    def _parse_directive(self, body: str, line: int, target: int):
        recognized = False
        if "disable" in body:
            recognized = True
            # Everything after "disable=" is the entry list.
            _, _, entries = body.partition("disable")
            entries = entries.lstrip("= ")
            matched_any = False
            for m in _DISABLE_ENTRY_RE.finditer(entries):
                matched_any = True
                check_id, reason = m.group(1), (m.group(3) or "").strip()
                if not reason:
                    self.directive_findings.append(Finding(
                        META_CHECK, self.relpath, line, 0,
                        f"suppression of {check_id} has no reason; write "
                        f"`# graftlint: disable={check_id}(why it is safe)`"))
                    continue
                if check_id == META_CHECK:
                    self.directive_findings.append(Finding(
                        META_CHECK, self.relpath, line, 0,
                        "GL000 (analyzer meta findings) cannot be suppressed"))
                    continue
                self.suppressions.setdefault(target, {})[check_id] = reason
            if not matched_any:
                self.directive_findings.append(Finding(
                    META_CHECK, self.relpath, line, 0,
                    f"malformed disable directive {body!r}; expected "
                    f"`disable=GLnnn(reason)`"))
        for m in _LOCK_ORDER_RE.finditer(body):
            recognized = True
            self.lock_orders.append((m.group(1), m.group(2)))
        if not recognized:
            self.directive_findings.append(Finding(
                META_CHECK, self.relpath, line, 0,
                f"unrecognized graftlint directive {body!r} (known: "
                f"disable=GLnnn(reason), lock-order=a->b)"))

    def suppression_for(self, finding: Finding) -> Optional[str]:
        """The reason suppressing ``finding``, or None. A directive applies to
        its own line (trailing comment) or, standalone, to the next code line."""
        if finding.check == META_CHECK:
            return None
        by_line = self.suppressions.get(finding.line)
        if by_line and finding.check in by_line:
            return by_line[finding.check]
        return None

    # -- scopes -------------------------------------------------------------
    def scope_at(self, node_or_line) -> str:
        """Innermost enclosing def/class qualname for a node or line number."""
        line = getattr(node_or_line, "lineno", node_or_line)
        if self._scopes is None:
            self._scopes = []
            if self.tree is not None:
                self._collect_scopes(self.tree, "")
        best = ""
        best_span = None
        for start, end, name in self._scopes:
            if start <= line <= end:
                span = end - start
                if best_span is None or span <= best_span:
                    best, best_span = name, span
        return best

    def _collect_scopes(self, node, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                self._scopes.append(
                    (child.lineno, child.end_lineno or child.lineno, qual))
                self._collect_scopes(child, qual)
            else:
                self._collect_scopes(child, prefix)


# Repo-level files checks read OUTSIDE the linted set, hashed into the
# cache keys so an edit to any of them invalidates cached results.
# MODULE inputs (read by per-file checks: GL007's flag registry, GL008's
# markers) key BOTH layers; PROGRAM inputs (read only by program checks,
# whose results are never file-cached) key only the whole-program layer —
# a docs-only observability.md edit must not re-lint 188 files' module
# checks. Context.doc_text REFUSES paths not listed here — a future check
# cannot read a repo input the cache key does not cover (the stale-cache
# bug class, closed structurally).
CACHE_MODULE_INPUTS = ("autodist_tpu/const.py", "pyproject.toml")
CACHE_PROGRAM_INPUTS = ("docs/usage/observability.md",)
CACHE_EXTRA_INPUTS = CACHE_MODULE_INPUTS + CACHE_PROGRAM_INPUTS


class Context:
    """Repo-level facts shared across modules (const.py flag registry,
    pyproject markers). Lazily computed, overridable for fixture tests."""

    def __init__(self, root: str, known_flags: Optional[Set[str]] = None):
        self.root = root
        self._known_flags = known_flags
        self._pyproject_markers: Optional[Set[str]] = None
        # Set by lint_paths when program checks run (Phase 2 — AFTER the
        # module-check loop, so module checks must NOT read it: besides
        # always seeing None, a module check whose findings depended on
        # other files would poison the per-file cache layer).
        self.program = None
        self._doc_text: Dict[str, Optional[str]] = {}

    def doc_text(self, relpath: str) -> Optional[str]:
        """The text of a repo doc file (``docs/usage/observability.md``) or
        None when absent — fixture trees get the checks that need it
        silently skipped rather than everything flagged. Only paths in
        :data:`CACHE_EXTRA_INPUTS` may be read: anything else would be an
        input the result cache's keys do not hash."""
        if relpath not in CACHE_EXTRA_INPUTS:
            raise ValueError(
                f"check reads repo input {relpath!r} outside "
                f"CACHE_EXTRA_INPUTS; add it there so cache keys cover it")
        if relpath not in self._doc_text:
            path = os.path.join(self.root, *relpath.split("/"))
            text: Optional[str] = None
            if os.path.isfile(path):
                try:
                    with open(path, encoding="utf-8") as f:
                        text = f.read()
                except OSError:
                    text = None
            self._doc_text[relpath] = text
        return self._doc_text[relpath]

    def known_flags(self) -> Optional[Set[str]]:
        """AUTODIST_* names registered in const.py's KNOWN_FLAGS (falling back
        to _ENV_DEFAULTS keys); None when const.py is absent (fixture trees),
        which disables the unknown-flag rule rather than flagging everything."""
        if self._known_flags is not None:
            return self._known_flags
        const_path = os.path.join(self.root, "autodist_tpu", "const.py")
        if not os.path.isfile(const_path):
            return None
        flags: Set[str] = set()
        try:
            with open(const_path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name) \
                        and target.id in ("KNOWN_FLAGS", "_ENV_DEFAULTS") \
                        and isinstance(node.value, ast.Dict):
                    for key in node.value.keys:
                        if isinstance(key, ast.Constant) \
                                and isinstance(key.value, str):
                            flags.add(key.value)
        self._known_flags = flags or None
        return self._known_flags

    def pyproject_markers(self) -> Set[str]:
        """Marker names registered under [tool.pytest.ini_options] markers."""
        if self._pyproject_markers is not None:
            return self._pyproject_markers
        markers: Set[str] = set()
        path = os.path.join(self.root, "pyproject.toml")
        if os.path.isfile(path):
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                text = ""
            # A full TOML parse is overkill for one list of "name: help" strings.
            for m in re.finditer(r'"([A-Za-z_][\w]*)\s*:', text):
                markers.add(m.group(1))
        self._pyproject_markers = markers
        return markers


# -------------------------------------------------------------------- baseline

def load_baseline(path: str) -> Set[str]:
    """Fingerprints grandfathered by the committed baseline file."""
    if not path or not os.path.isfile(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"] for e in data.get("findings", [])}


def write_baseline(path: str, findings: Sequence[Finding]):
    """Rewrite the baseline from the current findings (sorted, stable diffs).
    GL000 meta-findings (malformed directives etc.) are never written: they
    must be fixed, not grandfathered — the baseline matcher ignores them
    anyway (see :func:`lint_paths`)."""
    entries = sorted(
        ({"fingerprint": f.fingerprint, "note": f.render()}
         for f in findings if f.check != META_CHECK),
        key=lambda e: e["fingerprint"])
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"comment": "graftlint grandfathered findings; new findings "
                              "fail CI, these do not. Regenerate with "
                              "tools/graftlint.py --write-baseline.",
                   "findings": entries}, f, indent=1)
        f.write("\n")


# ------------------------------------------------------------------ file walks

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules", "native"}


def iter_py_files(paths: Sequence[str], root: str):
    """Yield .py files under ``paths`` (files taken verbatim, dirs walked).
    A nonexistent path raises: a CI gate that silently lints 0 files on a
    typo'd/renamed path would green-light everything it exists to block."""
    seen = set()
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full):
            raise FileNotFoundError(f"graftlint: path does not exist: {p}")
        if os.path.isfile(full):
            if full not in seen:
                seen.add(full)
                yield full
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    f = os.path.join(dirpath, name)
                    if f not in seen:
                        seen.add(f)
                        yield f


# ----------------------------------------------------------------------- cache

_VERSION_CACHE: Optional[str] = None


def checks_version() -> str:
    """Content hash of the analysis package's own sources — the cache key
    component that invalidates every cached result the moment a check (or
    the engine) changes, so a stale cache can never mask a new rule."""
    global _VERSION_CACHE
    if _VERSION_CACHE is None:
        h = hashlib.sha1()
        pkg_dir = os.path.dirname(os.path.abspath(__file__))
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    h.update(name.encode())
                    with open(os.path.join(dirpath, name), "rb") as f:
                        h.update(f.read())
        _VERSION_CACHE = h.hexdigest()
    return _VERSION_CACHE


def _finding_from_json(d: dict) -> Finding:
    return Finding(check=d["check"], path=d["path"], line=d["line"],
                   col=d["col"], message=d["message"],
                   scope=d.get("scope", ""))


class LintCache:
    """On-disk result cache under ``.graftlint_cache/``.

    Two layers, both keyed on content hashes plus :func:`checks_version`
    (cached RAW findings are pre-baseline, so editing the baseline never
    needs an invalidation):

    - **per-file**: (file sha1, module-check-id set) -> that file's
      module-check findings + suppressions. Program checks are excluded by
      construction — their findings can depend on *other* files.
    - **whole-program**: sha1 over every linted (relpath, sha1) pair, the
      full check selection, and the repo-level inputs the program checks
      read (const.py, pyproject.toml, observability.md) -> the complete raw
      result. An unchanged tree re-lints in file-hash time — the warm path
      ci.sh asserts.
    """

    def __init__(self, dir_path: str):
        self.dir = dir_path
        self.path = os.path.join(dir_path, "cache.json")
        self.hits = 0
        self.misses = 0
        self.program_hit = False
        self._dirty = False
        self._data: Dict[str, dict] = {"version": checks_version(),
                                       "files": {}, "program": {}}
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            if isinstance(data, dict) \
                    and data.get("version") == checks_version():
                self._data = data
                self._data.setdefault("files", {})
                self._data.setdefault("program", {})
        except (OSError, ValueError):
            pass

    @staticmethod
    def file_key(sha1: str, check_ids: Sequence[str],
                 extras_sha: str = "") -> str:
        # extras_sha covers CACHE_EXTRA_INPUTS: GL007/GL008 read const.py /
        # pyproject.toml, so a flag or marker deleted THERE must invalidate
        # every file's cached result, not just the program layer.
        return sha1 + "|" + ",".join(sorted(check_ids)) + "|" + extras_sha

    def get_file(self, relpath: str, key: str) -> Optional[dict]:
        entry = self._data["files"].get(relpath)
        if entry is not None and entry.get("key") == key:
            self.hits += 1
            return entry
        self.misses += 1
        return None

    def put_file(self, relpath: str, key: str,
                 findings: Sequence[Finding],
                 suppressed: Sequence[Tuple[Finding, str]]):
        self._data["files"][relpath] = {
            "key": key,
            "findings": [f.to_json() for f in findings],
            "suppressed": [[f.to_json(), r] for f, r in suppressed]}
        self._dirty = True

    PROGRAM_SLOTS = 8   # full run, --changed-only, a few --check subsets

    def get_program(self, key: str) -> Optional[dict]:
        slots = self._data["program"]
        entry = slots.get(key) if isinstance(slots, dict) else None
        if entry is not None:
            self.program_hit = True
            # Refresh recency (insertion order IS the eviction order): the
            # hot full-run entry must outlive a burst of --changed-only
            # keys, not be evicted as the oldest insertion.
            slots.pop(key)
            slots[key] = entry
            self._dirty = True
        return entry

    def put_program(self, key: str, files_checked: int,
                    findings: Sequence[Finding],
                    suppressed: Sequence[Tuple[Finding, str]]):
        # Multi-slot: a --changed-only or --check run must not evict the
        # full run's warm entry (dict insertion order = LRU-ish eviction).
        slots = self._data["program"]
        if not isinstance(slots, dict) or "key" in slots:
            slots = {}
        slots.pop(key, None)
        slots[key] = {
            "files_checked": files_checked,
            "findings": [f.to_json() for f in findings],
            "suppressed": [[f.to_json(), r] for f, r in suppressed]}
        while len(slots) > self.PROGRAM_SLOTS:
            slots.pop(next(iter(slots)))
        self._data["program"] = slots
        self._dirty = True

    def prune_files(self, root: str):
        """Drop per-file entries whose source no longer exists (renames,
        deletions, CLI runs against temp fixtures) — the growth bound."""
        for rel in list(self._data["files"]):
            if not os.path.isfile(os.path.join(root, *rel.split("/"))):
                del self._data["files"][rel]
                self._dirty = True

    def save(self):
        if not self._dirty:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._data, f)
            os.replace(tmp, self.path)   # atomic: parallel shards last-win
        except OSError:
            pass   # a cache that cannot write is a slow cache, not an error

    def stats(self) -> Dict[str, object]:
        return {"enabled": True, "program_hit": self.program_hit,
                "file_hits": self.hits, "file_misses": self.misses}


# ---------------------------------------------------------------------- driver

def _sha1(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()


def _triage(raw: List[Finding], suppressed, baseline: Set[str],
            files: int, t0: float, cache_info) -> LintResult:
    # GL000 never matches the baseline: grandfathering a malformed/reasonless
    # directive would defeat the "GL000 cannot be suppressed" invariant
    # through the --write-baseline side door.
    new = [f for f in raw
           if f.check == META_CHECK or f.fingerprint not in baseline]
    grandfathered = [f for f in raw
                     if f.check != META_CHECK and f.fingerprint in baseline]
    stale = sorted(baseline - {f.fingerprint for f in raw})
    order = lambda f: (f.path, f.line, f.col, f.check)  # noqa: E731
    return LintResult(findings=sorted(new, key=order),
                      suppressed=suppressed,
                      baselined=sorted(grandfathered, key=order),
                      stale_baseline=stale,
                      files_checked=files,
                      wall_time_s=round(time.perf_counter() - t0, 4),
                      cache_info=cache_info)


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               baseline: Optional[Set[str]] = None,
               checks: Optional[Sequence[str]] = None,
               context: Optional[Context] = None,
               cache: Optional[LintCache] = None,
               skip_full_program: bool = False) -> LintResult:
    """Run the registry over ``paths``; returns the triaged result.

    ``baseline`` is a fingerprint set (see :func:`load_baseline`); matching
    findings are reported separately and do not fail the run. ``checks``
    restricts to a subset of check ids (fixture tests). ``cache`` enables
    the :class:`LintCache` layers; ``skip_full_program`` drops the checks
    only sound over the complete path set (the ``--changed-only`` mode)."""
    t0 = time.perf_counter()
    root = os.path.abspath(root or os.getcwd())
    ctx = context or Context(root)
    registry = all_checks()
    selected = [registry[c] for c in checks] if checks \
        else list(registry.values())
    if skip_full_program:
        selected = [c for c in selected if not c.full_program]
    module_checks = [c for c in selected if not c.program]
    program_checks = [c for c in selected if c.program]
    baseline = baseline or set()

    # Phase 0: read + hash every file (the warm path's whole cost).
    entries = []      # (abspath, relpath, source|None, read_error|None)
    for path in iter_py_files(paths, root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "rb") as f:
                data = f.read()
            source = data.decode("utf-8")
            entries.append((path, rel, source, _sha1(data), None))
        except (OSError, UnicodeDecodeError) as e:
            entries.append((path, rel, None, "", Finding(
                META_CHECK, rel, 1, 0, f"unreadable file: {e}")))

    prog_key = None
    extras_sha = ""
    if cache is not None:
        def _inputs_sha(inputs):
            he = hashlib.sha1()
            for extra in inputs:
                p = os.path.join(root, *extra.split("/"))
                try:
                    with open(p, "rb") as f:
                        he.update(_sha1(f.read()).encode())
                except OSError:
                    pass   # absent/unreadable: hashed as missing; a
                    #        transient failure costs one miss, never the run
            return he.hexdigest()

        extras_sha = _inputs_sha(CACHE_MODULE_INPUTS)
        h = hashlib.sha1(checks_version().encode())
        for _, rel, _, sha, _ in entries:
            h.update(f"{rel}:{sha};".encode())
        h.update(",".join(sorted(c.id for c in selected)).encode())
        h.update(extras_sha.encode())
        h.update(_inputs_sha(CACHE_PROGRAM_INPUTS).encode())
        prog_key = h.hexdigest()
        hit = cache.get_program(prog_key)
        if hit is not None:
            raw = [_finding_from_json(d) for d in hit["findings"]]
            supp = [(_finding_from_json(d), r) for d, r in hit["suppressed"]]
            cache.save()   # persist the hit's recency refresh (LRU order)
            return _triage(raw, supp, baseline, hit["files_checked"], t0,
                           cache.stats())

    # Phase 1: parse + directives + per-module checks (file-cacheable).
    raw: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    modules: Dict[str, Module] = {}
    module_check_ids = [c.id for c in module_checks]
    for path, rel, source, sha, err in entries:
        if err is not None:
            raw.append(err)
            continue
        mod = Module(path, rel, source)
        raw.extend(mod.directive_findings)
        if mod.parse_error is not None:
            raw.append(mod.parse_error)
            continue
        modules[rel] = mod
        if cache is not None:
            key = LintCache.file_key(sha, module_check_ids, extras_sha)
            entry = cache.get_file(rel, key)
            if entry is not None:
                raw.extend(_finding_from_json(d) for d in entry["findings"])
                suppressed.extend((_finding_from_json(d), r)
                                  for d, r in entry["suppressed"])
                continue
        file_raw: List[Finding] = []
        file_supp: List[Tuple[Finding, str]] = []
        for check in module_checks:
            for finding in check.fn(mod, ctx):
                reason = mod.suppression_for(finding)
                if reason is not None:
                    file_supp.append((finding, reason))
                else:
                    file_raw.append(finding)
        raw.extend(file_raw)
        suppressed.extend(file_supp)
        if cache is not None:
            cache.put_file(
                rel, LintCache.file_key(sha, module_check_ids, extras_sha),
                file_raw, file_supp)

    # Phase 2: whole-program checks over the parsed set.
    if program_checks and modules:
        from autodist_tpu.analysis.program import ProgramIndex
        ctx.program = ProgramIndex(modules)
        for check in program_checks:
            for finding in check.fn(ctx.program, ctx):
                mod = modules.get(finding.path)
                reason = mod.suppression_for(finding) \
                    if mod is not None else None
                if reason is not None:
                    suppressed.append((finding, reason))
                else:
                    raw.append(finding)

    if cache is not None and prog_key is not None:
        cache.put_program(prog_key, len(entries), raw, suppressed)
        cache.prune_files(root)
        cache.save()
    cache_info = cache.stats() if cache is not None else None
    return _triage(raw, suppressed, baseline, len(entries), t0, cache_info)
