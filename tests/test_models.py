"""Model zoo: each model trains a few steps under a distribution strategy."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.models import bert, ncf, resnet, transformer_lm, vgg
from autodist_tpu.strategy import AllReduce, Parallax, PartitionedPS, PS
from shardmap_compat import requires_shard_map

TINY_LM = transformer_lm.TransformerLMConfig(
    vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=64,
    dtype=jnp.float32)


def test_transformer_lm_trains_allreduce():
    model, params = transformer_lm.init_params(TINY_LM)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(TINY_LM, batch_size=16, seq_len=16)
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.adam(1e-2), example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


@pytest.mark.parametrize("tied", [False, True])
def test_transformer_lm_fused_head_matches_xla_head(tied):
    """fused_head=True (pallas head+loss) must equal the XLA-head loss and
    produce the same training trajectory, tied and untied."""
    cfg = dataclasses.replace(TINY_LM, tied_output=tied)
    cfg_f = dataclasses.replace(cfg, fused_head=True)
    model, params = transformer_lm.init_params(cfg)
    model_f, _ = transformer_lm.init_params(cfg_f)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=8, seq_len=16)
    l_xla = float(transformer_lm.make_loss_fn(model)(params, batch))
    l_fused = float(transformer_lm.make_loss_fn(model_f)(params, batch))
    np.testing.assert_allclose(l_fused, l_xla, rtol=1e-5)

    def run(m):
        ad = AutoDist(strategy_builder=AllReduce())
        step = ad.function(transformer_lm.make_loss_fn(m), params,
                           optax.adam(1e-2), example_batch=batch)
        return [float(step(batch)) for _ in range(4)]

    np.testing.assert_allclose(run(model_f), run(model), rtol=5e-4, atol=5e-4)


def test_transformer_lm_embedding_detected_sparse_and_parallax_routes_it():
    # Untied output: the embedding is gather-only (like the reference lm1b model's
    # separate softmax weights), so its gradient is row-sparse.
    cfg = dataclasses.replace(TINY_LM, tied_output=False)
    model, params = transformer_lm.init_params(cfg)
    loss_fn = transformer_lm.make_loss_fn(model)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=8, seq_len=16)
    ad = AutoDist(strategy_builder=Parallax())
    step = ad.function(loss_fn, params, optax.sgd(1e-2), example_batch=batch)
    step(batch)
    kinds = {n.var_name: n.WhichOneof("synchronizer") for n in ad._strategy.node_config}
    emb_nodes = [k for n, k in kinds.items() if "embed" in n and "pos" not in n]
    assert emb_nodes and all(k == "ps_synchronizer" for k in emb_nodes)


def test_transformer_lm_remat_matches_no_remat():
    cfg_plain = TINY_LM
    cfg_remat = dataclasses.replace(cfg_plain, remat=True)
    model_p, params = transformer_lm.init_params(cfg_plain)
    model_r, _ = transformer_lm.init_params(cfg_remat)
    batch = transformer_lm.synthetic_batch(cfg_plain, batch_size=8, seq_len=16)
    lp = transformer_lm.make_loss_fn(model_p)(params, batch)
    lr = transformer_lm.make_loss_fn(model_r)(params, batch)
    np.testing.assert_allclose(float(lp), float(lr), rtol=1e-6)


def test_resnet_tiny_trains():
    cfg = resnet.ResNet50Config(num_classes=10, stage_sizes=(1, 1), width=8,
                                dtype=jnp.float32, norm_groups=4)
    model, params = resnet.init_params(cfg, image_size=32)
    loss_fn = resnet.make_loss_fn(model)
    batch = resnet.synthetic_batch(cfg, batch_size=8, image_size=32)
    ad = AutoDist(strategy_builder=PS())
    step = ad.function(loss_fn, params, optax.sgd(0.05), example_batch=batch)
    losses = [float(step(batch)) for _ in range(3)]
    assert losses[-1] < losses[0]


def test_resnet_sync_batchnorm_is_cross_replica():
    """norm='batch' computes GLOBAL batch statistics under the data-sharded
    step: the 8-device AllReduce loss equals the single-process jit loss on
    the same batch (per-replica statistics would differ — each shard of 2
    examples has different moments than the global 16)."""
    cfg = resnet.ResNet50Config(num_classes=10, stage_sizes=(1, 1), width=8,
                                dtype=jnp.float32, norm="batch")
    model, params = resnet.init_params(cfg, image_size=32)
    loss_fn = resnet.make_loss_fn(model)
    batch = resnet.synthetic_batch(cfg, batch_size=16, image_size=32)

    single = float(jax.jit(loss_fn)(params, {k: jnp.asarray(v)
                                             for k, v in batch.items()}))
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.sgd(0.05), example_batch=batch)
    # step() returns the loss at the PRE-update params (value_and_grad), so
    # the first call is directly comparable to the single-process loss.
    losses = [float(step(batch)) for _ in range(3)]
    np.testing.assert_allclose(losses[0], single, rtol=1e-5, atol=1e-5)
    # And it trains.
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_resnet_sync_batchnorm_ema_inference_parity():
    """The flag-gated BN-EMA eval mode: stats calibrated from ONE batch equal
    that batch's own moments, so the EMA-reading model reproduces batch-stats
    outputs exactly on it — and, unlike batch-stats mode, gives the same
    per-example logits at ANY eval batch size (reference BatchNorm inference
    behavior; stats live outside params)."""
    import dataclasses

    cfg = resnet.ResNet50Config(num_classes=4, stage_sizes=(1,), width=8,
                                dtype=jnp.float32, norm="batch")
    model, params = resnet.init_params(cfg, image_size=16)
    rng = np.random.RandomState(0)
    images = rng.randn(4, 16, 16, 3).astype(np.float32)

    ema = resnet.calibrate_bn_ema(model, params, [images])
    eval_model = resnet.ResNet(dataclasses.replace(cfg, bn_ema=True))
    y_ema = np.asarray(eval_model.apply({"params": params, "bn_ema": ema},
                                        images))
    y_batch = np.asarray(model.apply({"params": params}, images))
    np.testing.assert_allclose(y_ema, y_batch, rtol=1e-5, atol=1e-5)
    # Batch-size independence: a singleton eval batch scores identically.
    y_one = np.asarray(eval_model.apply({"params": params, "bn_ema": ema},
                                        images[:1]))
    np.testing.assert_allclose(y_one[0], y_ema[0], rtol=1e-5, atol=1e-5)


def test_vgg_tiny_trains_partitioned_ps():
    model = vgg.VGG16(num_classes=10, dtype=jnp.float32)
    images = jnp.zeros((2, 32, 32, 3))
    params = jax.jit(model.init)(jax.random.PRNGKey(0), images)["params"]
    loss_fn = vgg.make_loss_fn(model)
    rng = np.random.RandomState(0)
    batch = {"images": rng.randn(8, 32, 32, 3).astype(np.float32),
             "labels": rng.randint(0, 10, size=(8,)).astype(np.int32)}
    ad = AutoDist(strategy_builder=PartitionedPS())
    step = ad.function(loss_fn, params, optax.sgd(0.01), example_batch=batch)
    losses = [float(step(batch)) for _ in range(3)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


@requires_shard_map
def test_bert_tiny_mlm_trains():
    cfg = bert.BertConfig(vocab_size=128, d_model=32, n_heads=4, n_layers=2,
                          d_ff=64, max_len=64, dtype=jnp.float32)
    model = bert.Bert(cfg)
    batch = bert.synthetic_batch(cfg, batch_size=8, seq_len=16, n_predictions=4)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), jnp.asarray(batch["tokens"]),
                        jnp.asarray(batch["token_types"]))["params"]
    loss_fn = bert.make_mlm_loss_fn(model)
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.adam(1e-2), example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert losses[-1] < losses[0]


@requires_shard_map
def test_ncf_trains_parallax_sparse():
    cfg = ncf.NeuMFConfig(num_users=64, num_items=32, mf_dim=8, mlp_dims=(16, 8))
    model = ncf.NeuMF(cfg)
    batch = ncf.synthetic_batch(cfg, batch_size=16)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), jnp.asarray(batch["users"]),
                        jnp.asarray(batch["items"]))["params"]
    loss_fn = ncf.make_loss_fn(model)
    ad = AutoDist(strategy_builder=Parallax())
    step = ad.function(loss_fn, params, optax.adam(1e-2), example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert losses[-1] < losses[0]
    kinds = {n.var_name: n.WhichOneof("synchronizer") for n in ad._strategy.node_config}
    emb = [k for n, k in kinds.items() if "embed" in n and "embedding" in n.lower()]
    assert emb and all(k == "ps_synchronizer" for k in emb)


def test_densenet_tiny_trains():
    from autodist_tpu.models import densenet
    cfg = densenet.DenseNet121Config(num_classes=10, block_sizes=(2, 2),
                                     growth_rate=8, init_features=16,
                                     dtype=jnp.float32, norm_groups=4)
    model, params = densenet.init_params(cfg, image_size=32)
    loss_fn = densenet.make_loss_fn(model)
    batch = densenet.synthetic_batch(cfg, batch_size=8, image_size=32)
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.sgd(0.05), example_batch=batch)
    losses = [float(step(batch)) for _ in range(3)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_inception_v3_tiny_trains():
    from autodist_tpu.models import inception
    # Full-size stem needs 299px; a reduced 96px input and one block per
    # repeated stage still exercise every block type (A, B grid-reduce,
    # C factorized-7x7, D, E) — the full 11-block graph costs ~80s of XLA
    # compile on the CPU test host for no extra coverage.
    cfg = inception.InceptionV3Config(num_classes=10, dtype=jnp.float32,
                                      norm_groups=4, repeats=(1, 1, 1))
    model, params = inception.init_params(cfg, image_size=96)
    loss_fn = inception.make_loss_fn(model)
    batch = inception.synthetic_batch(cfg, batch_size=4, image_size=96)
    ad = AutoDist(strategy_builder=AllReduce())
    # Inception's init produces large early gradients (~55 global norm at this
    # size); SGD at CNN-test rates diverges, Adam converges.
    step = ad.function(loss_fn, params, optax.adam(1e-3), example_batch=batch)
    losses = [float(step(batch)) for _ in range(3)]
    # Random-label fitting at this depth is noisy step-to-step; the training
    # signal asserted is: finite everywhere and an improvement over the start.
    assert np.isfinite(losses).all() and min(losses[1:]) < losses[0]


def test_lstm_lm_sampled_softmax_trains_parallax():
    from autodist_tpu.models import lstm_lm
    cfg = lstm_lm.LSTMLMConfig(vocab_size=256, emb_dim=16, hidden_dim=32,
                               n_layers=2, num_sampled=64, dtype=jnp.float32)
    model, params = lstm_lm.init_params(cfg)
    loss_fn = lstm_lm.make_loss_fn(model)
    batch = lstm_lm.synthetic_batch(cfg, batch_size=8, seq_len=12)
    ad = AutoDist(strategy_builder=Parallax())
    step = ad.function(loss_fn, params, optax.adam(1e-2), example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_lstm_lm_sampled_softmax_approximates_full_softmax():
    # With every vocab id in the sampled set and no importance correction,
    # sampled softmax == full softmax (accidental-hit masking removes the
    # duplicated true class).
    from autodist_tpu.models import lstm_lm
    cfg = lstm_lm.LSTMLMConfig(vocab_size=32, emb_dim=8, hidden_dim=16,
                               n_layers=1, num_sampled=32, dtype=jnp.float32,
                               subtract_log_q=False)
    model, params = lstm_lm.init_params(cfg)
    loss_fn = lstm_lm.make_loss_fn(model)
    batch = lstm_lm.synthetic_batch(cfg, batch_size=4, seq_len=8, sampled=False)
    full = float(loss_fn(params, batch))
    batch["neg_ids"] = np.arange(32, dtype=np.int32)
    sampled = float(loss_fn(params, batch))
    np.testing.assert_allclose(sampled, full, rtol=1e-5)


def test_lstm_lm_bf16_sampled_softmax_trains_and_tracks_f32():
    """The accelerator dtype path: finite bf16 training, losses near the f32
    run within bf16 tolerance (the suite otherwise pins f32, which would make
    the bf16 casts dead code under test)."""
    from autodist_tpu.models import lstm_lm

    def run(dtype):
        cfg = lstm_lm.LSTMLMConfig(vocab_size=256, emb_dim=16, hidden_dim=32,
                                   n_layers=2, num_sampled=64, dtype=dtype)
        model, params = lstm_lm.init_params(cfg)
        loss_fn = lstm_lm.make_loss_fn(model)
        batch = lstm_lm.synthetic_batch(cfg, batch_size=8, seq_len=12)
        ad = AutoDist(strategy_builder=Parallax())
        step = ad.function(loss_fn, params, optax.adam(1e-2), example_batch=batch)
        return [float(step(batch)) for _ in range(4)]

    f32, bf16 = run(jnp.float32), run(jnp.bfloat16)
    assert np.isfinite(bf16).all() and bf16[-1] < bf16[0]
    np.testing.assert_allclose(bf16, f32, rtol=0.05)


def test_lstm_lm_fused_full_softmax_matches_plain():
    """The pallas fused full-softmax loss equals the naive full softmax."""
    from autodist_tpu.models import lstm_lm
    cfg = lstm_lm.LSTMLMConfig(vocab_size=96, emb_dim=8, hidden_dim=16,
                               n_layers=1, dtype=jnp.float32)
    model, params = lstm_lm.init_params(cfg)
    batch = lstm_lm.synthetic_batch(cfg, batch_size=4, seq_len=8, sampled=False)
    plain = float(lstm_lm.make_loss_fn(model)(params, batch))
    fused = float(lstm_lm.make_fused_full_softmax_loss_fn(model)(params, batch))
    np.testing.assert_allclose(fused, plain, rtol=1e-5)
    # And it trains.
    ad = AutoDist(strategy_builder=Parallax())
    step = ad.function(lstm_lm.make_fused_full_softmax_loss_fn(model), params,
                       optax.adam(1e-2), example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_lstm_lm_log_q_correction_matches_manual():
    # subtract_log_q shifts each logit by -log q(id) under the log-uniform
    # sampler; verify against a hand-computed correction of the uncorrected loss.
    import dataclasses as dc

    from autodist_tpu.models import lstm_lm
    cfg = lstm_lm.LSTMLMConfig(vocab_size=64, emb_dim=8, hidden_dim=16,
                               n_layers=1, num_sampled=16, dtype=jnp.float32)
    model, params = lstm_lm.init_params(cfg)
    batch = lstm_lm.synthetic_batch(cfg, batch_size=2, seq_len=4)
    corrected = float(lstm_lm.make_loss_fn(model)(params, batch))

    plain_model = lstm_lm.LSTMLMWithHead(dc.replace(cfg, subtract_log_q=False))

    def manual(params, batch):
        tokens, neg_ids = batch["tokens"], batch["neg_ids"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        h = np.asarray(plain_model.apply({"params": params}, inputs),
                       dtype=np.float32)
        w = np.asarray(params["softmax_w"])
        b = np.asarray(params["softmax_b"])

        def log_q(ids):
            q = (np.log(ids + 2.0) - np.log(ids + 1.0)) / np.log(cfg.vocab_size + 1)
            return np.log(q)

        true_logit = np.einsum("bth,bth->bt", h, w[targets]) + b[targets] \
            - log_q(targets.astype(np.float64))
        neg = np.einsum("bth,sh->bts", h, w[neg_ids]) + b[neg_ids] \
            - log_q(neg_ids.astype(np.float64))[None, None, :]
        neg = np.where(neg_ids[None, None, :] == targets[..., None], -1e9, neg)
        all_logits = np.concatenate([true_logit[..., None], neg], axis=-1)
        lse = np.log(np.exp(all_logits - all_logits.max(-1, keepdims=True))
                     .sum(-1)) + all_logits.max(-1)
        return float((-true_logit + lse).mean())

    np.testing.assert_allclose(corrected, manual(params, batch), rtol=1e-4)
