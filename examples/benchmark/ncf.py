"""NCF (NeuMF) recommender benchmark — the sparse-heavy workload.

Port of reference ``examples/benchmark/ncf.py`` + ``utils/recommendation``:
MovieLens-scale NeuMF with row-sparse embedding gradients, trained under the
Parallax hybrid (embeddings -> PS placement, dense towers -> all-reduce).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models import ncf
from autodist_tpu.strategy import Parallax
from autodist_tpu.utils.metrics import ThroughputMeter


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=110)
    parser.add_argument("--batch_size", type=int, default=0)
    parser.add_argument("--log_every", type=int, default=100)
    parser.add_argument("--resource_spec", type=str, default=None)
    args = parser.parse_args(argv)

    # NCF is gather-bound: per-step dispatch dominates at small batches, so
    # throughput scales nearly linearly with batch (v5e sweep: 172k ex/s at
    # 1024, 1.26M at 8k, 7.9M at 64k — still converging; 256k+ trains
    # unstably at this fixed lr). The reference's NCF likewise ran very large
    # batches. The default is the measured 64k GLOBAL batch whatever the
    # device count — scale explicitly (with the lr) for bigger sweeps.
    batch_size = args.batch_size or 65536

    cfg = ncf.NeuMFConfig()
    model = ncf.NeuMF(cfg)
    batch = ncf.synthetic_batch(cfg, batch_size)
    import jax.numpy as jnp
    from autodist_tpu.models.common import jit_init
    params = jit_init(model, jnp.asarray(batch["users"]), jnp.asarray(batch["items"]))
    loss_fn = ncf.make_loss_fn(model)

    ad = AutoDist(args.resource_spec, Parallax())
    step = ad.function(loss_fn, params, optax.adam(1e-3), example_batch=batch)
    # Keep the synthetic batch device-resident (measure the chip, not the link).
    batch = step.runner.shard_batch(batch)

    meter = ThroughputMeter(batch_size=batch_size, log_every=args.log_every)
    loss = None
    for _ in range(args.steps):
        loss = step(batch)
        meter.step(sync=loss)
    print(f"ncf: final loss {float(loss):.4f}, {meter.average or 0:.1f} examples/sec")
    from autodist_tpu.utils import flops as flops_util
    flops_util.report_mfu(
        flops_util.train_step_flops(step.runner, step.get_state(), batch),
        (meter.average or 0) / batch_size)
    return meter.average


if __name__ == "__main__":
    main()
