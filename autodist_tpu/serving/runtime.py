"""Model runtime adapters: the device half of the serving plane.

:class:`LMEngine` drives the flagship Transformer LM's prefill+decode
KV-cache path (``models/transformer_lm.py``) for continuous batching:

- one SHARED decode cache of ``max_batch`` slots (``[B, max_len, H, D]`` per
  layer), each slot an independent request parked at its own write frontier
  — the per-row ``pos_offset`` vector added to the model's decode path
  carries every slot's position through ONE compiled step;
- per-bucket jitted PREFILL programs (prompt right-padded to its bucket; the
  pad tail's K/V is masked until decode overwrites it position by position,
  so results are bit-identical to an unpadded prefill);
- a jitted INSERT that scatters a prefilled single-request cache into the
  shared cache's slot row — admission at decode-step granularity without
  recompiling anything;
- slot REUSE without scrubbing: a freed slot's stale K/V beyond the next
  occupant's frontier is never unmasked, and everything below it is
  overwritten by the occupant's own prefill.

:class:`ApplyEngine` is the stateless counterpart for the classifier /
recommender families: stack the gathered examples, pad the batch dim to a
power-of-two bucket (bounded jit cache), one jitted ``apply``, split.

Both engines hold the jit cache keyed by bucket so the compile count is
``len(buckets) + 2`` for the LM (prefills + decode + insert) and
``log2(max_batch)`` for apply — the continuous batcher's admission churn
never compiles.
"""

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu import telemetry
from autodist_tpu.serving.batcher import (ServeConfig, bucket_for,
                                          default_buckets, pad_prompt)


class LMEngine:
    """Continuous-batching decode engine over a Transformer LM.

    ``params`` are used as placed (replicated or sharded — XLA inserts any
    collectives, same contract as :func:`transformer_lm.generate`). Position
    bookkeeping lives HERE, host-side (``pos[slot]`` = the cache row's write
    frontier = tokens so far for that request); the model's per-row
    ``pos_offset`` vector is fed from it every step.
    """

    def __init__(self, model, params, config: Optional[ServeConfig] = None):
        config = config or ServeConfig()
        self.model = model
        self.config = config
        self._params = params
        cfg = model.config
        self.capacity = config.max_batch
        self.max_len = cfg.max_len
        self.buckets = tuple(b for b in (config.buckets
                                         or default_buckets(cfg.max_len))
                             if b <= cfg.max_len)
        if not self.buckets:
            raise ValueError(f"no pad bucket fits max_len {cfg.max_len}")
        self._sampling = (float(config.temperature), int(config.top_k),
                          float(config.top_p))
        B = self.capacity
        self._pos = np.zeros(B, np.int32)       # per-slot write frontier
        self._active = np.zeros(B, bool)
        self._last = np.zeros(B, np.int32)      # last sampled token per slot
        self._prefill_fns: Dict[int, Callable] = {}
        self._decode_fn = self._make_decode()
        # The shared cache is donated through insert for the same reason
        # decode donates it: it dominates serving HBM, and an undonated
        # insert would copy the whole cache per admission (callers rebind on
        # the same line).
        self._insert_fn = jax.jit(self._insert_slot, donate_argnums=(0,))
        # Shared decode cache: created by one dummy decode apply (writes junk
        # at position 0, overwritten by the first admission's prefill).
        _, variables = model.apply(
            {"params": params}, jnp.zeros((B, 1), jnp.int32),
            decode=True, mutable=["cache"])
        self._cache = variables["cache"]

    # ------------------------------------------------------------- jit cache

    def _make_decode(self):
        model, (temp, top_k, top_p) = self.model, self._sampling
        from autodist_tpu.models.common import sample_logits

        def decode_step(params, cache, toks, pos, keys):
            logits, variables = model.apply(
                {"params": params, "cache": cache}, toks[:, None],
                pos_offset=pos, decode=True, mutable=["cache"])
            lg = logits[:, 0]                                  # [B, V]
            if temp == 0.0:
                nxt = sample_logits(lg, None, 0.0)
            else:
                # Per-row keys: every slot samples from ITS request's key
                # schedule, so a slot's token stream is independent of who
                # shares the batch (and bit-matches the batch-1 run).
                nxt = jax.vmap(lambda l, k: sample_logits(
                    l[None], k, temp, top_k, top_p)[0])(lg, keys)
            return variables["cache"], nxt

        # The cache is donated: at real sizes it dominates serving HBM and
        # every step rewrites it (callers rebind on the same line).
        return jax.jit(decode_step, donate_argnums=(1,))

    def _prefill(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        model, (temp, top_k, top_p) = self.model, self._sampling
        tied = model.config.tied_output
        from autodist_tpu.models.common import lm_head_logits, sample_logits

        def prefill(params, padded, plen, key):
            # Whole padded prompt in one decode apply (the chunked cache
            # write); only the LAST REAL position's logits are projected, so
            # the [1, L, V] tensor never materializes — same trick as
            # transformer_lm.generate's prefill.
            hidden, variables = model.apply(
                {"params": params}, padded, pos_offset=0, decode=True,
                return_hidden=True, mutable=["cache"])
            last_h = jax.lax.dynamic_slice_in_dim(hidden, plen - 1, 1,
                                                  axis=1)[:, 0]
            lg = lm_head_logits(last_h, params, tied=tied)
            return variables["cache"], sample_logits(lg, key, temp, top_k,
                                                     top_p)[0]

        fn = self._prefill_fns[bucket] = jax.jit(prefill)
        return fn

    @staticmethod
    def _insert_slot(dec_cache, pre_cache, slot):
        """Scatter a [1, ...] prefilled cache into slot row ``slot`` of the
        shared [B, ...] cache (scalar leaves — the unused cache_index — keep
        the shared value)."""
        return jax.tree_util.tree_map(
            lambda d, p: d if p.ndim == 0
            else jax.lax.dynamic_update_slice_in_dim(d, p, slot, axis=0),
            dec_cache, pre_cache)

    # ------------------------------------------------------ engine interface

    def make_keys(self, seed: int, n: int) -> Optional[np.ndarray]:
        """The request's per-step sampling key schedule — ``split(key, n)``,
        the SAME schedule :func:`transformer_lm.generate` uses, so a served
        request at batch 1 reproduces ``generate()`` bit for bit. Greedy
        engines return None (argmax needs no keys)."""
        if self._sampling[0] == 0.0:
            return None
        return np.asarray(jax.random.split(jax.random.PRNGKey(seed), n))

    def admit(self, slot: int, prompt: np.ndarray,
              key: Optional[np.ndarray]) -> int:
        """Prefill ``prompt`` into ``slot``; returns the first sampled token.
        The prompt is right-padded to its bucket — pad K/V beyond the true
        length is masked now and overwritten by decode steps later, so
        padding never changes results."""
        plen = int(prompt.size)
        bucket = bucket_for(plen, self.buckets)
        padded = pad_prompt(prompt, bucket)
        key = jnp.zeros((2,), jnp.uint32) if key is None else key
        cache1, first = self._prefill(bucket)(
            self._params, padded, np.int32(plen), key)
        self._cache = self._insert_fn(self._cache, cache1, np.int32(slot))
        first = int(jax.device_get(first))
        self._pos[slot] = plen
        self._active[slot] = True
        self._last[slot] = first
        return first

    def step(self, keys: Optional[np.ndarray] = None) -> np.ndarray:
        """One decode step for EVERY slot (inactive rows compute garbage at
        position 0, masked for any later occupant); returns the [B] sampled
        tokens. Frontiers advance for active slots only."""
        if keys is None:
            keys = np.zeros((self.capacity, 2), np.uint32)
        self._cache, toks = self._decode_fn(
            self._params, self._cache, self._last, self._pos, keys)
        toks = np.asarray(jax.device_get(toks))
        self._pos = np.where(self._active, self._pos + 1, 0).astype(np.int32)
        self._last = np.where(self._active, toks, 0).astype(np.int32)
        return toks

    def free(self, slot: int):
        """Release a slot (early exit / completion). No cache scrub: the next
        occupant's prefill overwrites [0, bucket) and its mask never reaches
        past its own frontier, and idle rows park their writes at position 0
        which every prefill overwrites too."""
        self._active[slot] = False
        self._pos[slot] = 0
        self._last[slot] = 0

    @property
    def num_active(self) -> int:
        return int(self._active.sum())

    def compiled_programs(self) -> Tuple[int, int]:
        """(prefill programs, total jitted entry points) — the jit-cache
        boundedness the bucketing exists for; tests pin it."""
        return len(self._prefill_fns), len(self._prefill_fns) + 2


class ApplyEngine:
    """Stateless inference engine: ``apply_fn(params, stacked_examples) ->
    stacked_outputs`` jitted per power-of-two batch bucket. Examples are
    pytrees of ndarrays WITHOUT a batch dim (one example each); outputs are
    split back one per request."""

    def __init__(self, apply_fn, params, config: Optional[ServeConfig] = None):
        config = config or ServeConfig()
        self.config = config
        self.capacity = config.max_batch
        self._params = params
        self._apply = jax.jit(apply_fn)

    def run(self, examples: List) -> List:
        n = len(examples)
        # Pad the batch dim to the next power of two (bounded jit cache) by
        # repeating the last example; padded outputs are dropped.
        padded_n = 1
        while padded_n < n:
            padded_n *= 2
        batch = examples + [examples[-1]] * (padded_n - n)
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs, axis=0), *batch)
        with telemetry.span("serve.apply_dispatch", batch=n, padded=padded_n):
            out = self._apply(self._params, stacked)
        out = jax.device_get(out)
        return [jax.tree_util.tree_map(lambda a: np.asarray(a)[i], out)
                for i in range(n)]
