"""Sharding plan — the compiled, executable form of a Strategy.

This is the TPU-native counterpart of the reference's GraphTransformer pipeline
(``kernel/graph_transformer.py:55-92``): where the reference materialized a strategy
by rewriting the graph (Partitioner -> Replicator -> Synchronizers), we compile it
into per-parameter ``PartitionSpec``s plus synchronization metadata, and let the XLA
SPMD partitioner insert the collectives:

- AllReduce synchronizer  -> parameter replicated; the gradient cross-replica sum is
  the implicit psum in the backward pass (reference ``all_reduce_synchronizer.py``).
- PS synchronizer         -> weight-update sharding: optimizer state (and the update
  computation) sharded along the ``reduce`` axis; XLA lowers the grad flow into
  reduce-scatter + local update + all-gather (reference PS push/pull + accumulators,
  ``ps_synchronizer.py:556-633``).
- Partitioner             -> the parameter itself is stored sharded on the ``model``
  axis (reference ``kernel/partitioner.py`` rebuilt vars as PartitionedVariables).
"""

import collections
import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.proto import strategy_pb2

# Data-parallel axes: the batch dimension shards over both; with PS strategies the
# reduce axis doubles as the weight-update sharding axis (every device is a data
# replica AND a parameter shard).
DP_AXES = (const.MESH_AXIS_DATA, const.MESH_AXIS_REDUCE)

SYNC_ALLREDUCE = "allreduce"
SYNC_PS = "ps"

COMP_NONE = strategy_pb2.AllReduceSynchronizer.NONE
COMP_BF16 = strategy_pb2.AllReduceSynchronizer.BF16
COMP_BF16_EF = strategy_pb2.AllReduceSynchronizer.BF16_EF
COMP_POWER_SGD = strategy_pb2.AllReduceSynchronizer.POWER_SGD


@dataclasses.dataclass(frozen=True)
class ParamPlan:
    """Compiled distribution of one parameter."""

    name: str
    pspec: P                      # parameter storage sharding
    opt_pspec: P                  # optimizer-state sharding (ZeRO shard for PS family)
    sync: str                     # SYNC_ALLREDUCE | SYNC_PS
    compressor: int = COMP_NONE   # strategy_pb2.AllReduceSynchronizer.Compressor
    power_sgd_rank: int = 1       # approximation rank when compressor == POWER_SGD
    group: int = 0                # collective fusion group (bucketing)
    spec: int = 0                 # network tier: AUTO | ICI | DCN (hierarchical)
    sparse: bool = False
    staleness: int = 0
    synchronous: bool = True
    partition_axis: Optional[int] = None   # tensor axis sharded on a mesh axis
    num_shards: Tuple[int, ...] = ()       # logical shard counts from the strategy
    # Mesh axis the partition maps onto: "model" for tensor parallelism, "expert"
    # for expert parallelism (PartitionConfig.mesh_axis).
    partition_mesh_axis: str = const.MESH_AXIS_MODEL
    # Uneven partitioning (reference kernel/partitioner.py:660-704 sliced remainders;
    # XLA shardings need even tiles, so storage is zero-padded to padded_dim along
    # partition_axis and sliced back to logical_dim around the user's loss fn).
    padded_dim: Optional[int] = None
    logical_dim: Optional[int] = None
    # Batch-leaf name providing this sparse param's gather indices (model_spec jaxpr
    # provenance): enables the (indices, rows) wire format for gradient sync.
    index_leaf: Optional[str] = None
    # Logical parameter shape (model_spec metadata): lets plan-level transforms
    # (ZeRO opt-state sharding) reason about tiling without a live tree.
    shape: Tuple[int, ...] = ()


class ShardingPlan:
    """Per-parameter plans + mesh shape, derived from a compiled Strategy."""

    # ZeRO-style weight-update sharding (arXiv 2004.13336) off by default;
    # :meth:`with_zero_update` returns a plan with it on. An instance
    # attribute on derived plans, a class default here so pre-existing
    # pickles/constructions keep working.
    zero = False

    def __init__(self, mesh_axes: "collections.OrderedDict[str, int]",
                 params: Dict[str, ParamPlan]):
        self.mesh_axes = mesh_axes
        self.params = params

    # ------------------------------------------------------------------ build
    @classmethod
    def from_strategy(cls, strategy, model_spec: ModelSpec) -> "ShardingPlan":
        mesh_axes = collections.OrderedDict(
            (a.name, a.size) for a in strategy.mesh_config.axes)

        nodes = {n.var_name: n for n in strategy.node_config}
        plans: Dict[str, ParamPlan] = {}
        for name, pspec_meta in model_spec.params.items():
            if not pspec_meta.trainable:
                plans[name] = ParamPlan(name=name, pspec=P(), opt_pspec=P(),
                                        sync=SYNC_ALLREDUCE,
                                        shape=tuple(pspec_meta.shape))
                continue
            node = nodes.get(name)
            plans[name] = cls._plan_for(node, pspec_meta, mesh_axes)
        placement_only = [p.name for p in plans.values()
                          if p.partition_axis is not None and p.pspec == P()]
        if placement_only:
            from autodist_tpu.utils import logging
            logging.warning(
                "Partitioning for %d parameter(s) is placement-only (the mesh has "
                "no matching partition axis > 1, so storage stays replicated): %s",
                len(placement_only), ", ".join(sorted(placement_only)[:8]))
        return cls(mesh_axes, plans)

    @staticmethod
    def _plan_for(node, meta, mesh_axes) -> ParamPlan:
        reduce_size = mesh_axes.get(const.MESH_AXIS_REDUCE, 1)
        if node is None:
            # No config for this param: replicate + implicit psum (safe default).
            return ParamPlan(name=meta.name, pspec=P(), opt_pspec=P(),
                             sync=SYNC_ALLREDUCE, sparse=meta.sparse,
                             index_leaf=meta.index_leaf,
                             shape=tuple(meta.shape))

        partition_axis = None
        num_shards: Tuple[int, ...] = ()
        param_pspec = P()
        partition_mesh_axis = const.MESH_AXIS_MODEL
        if node.HasField("partitioner"):
            num_shards = tuple(node.partitioner.num_shards)
            active = [i for i, k in enumerate(num_shards) if k > 1]
            if active:
                partition_axis = active[0]
            if node.partitioner.mesh_axis:
                partition_mesh_axis = node.partitioner.mesh_axis

        # Physical storage sharding: put the target mesh axis ("model" for tensor
        # parallelism, "expert" for expert parallelism) on the partitioned tensor
        # axis when the mesh has one. Dimensions that don't tile evenly get padded
        # storage: zero-pad to the next multiple of the axis size and slice back to
        # the logical shape around the user's computation (the TPU-native form of
        # the reference's remainder slicing, kernel/partitioner.py:660-704).
        axis_size = mesh_axes.get(partition_mesh_axis, 1)
        padded_dim = logical_dim = None
        if partition_axis is not None and axis_size > 1:
            spec_dims: list = [None] * len(meta.shape)
            spec_dims[partition_axis] = partition_mesh_axis
            param_pspec = P(*spec_dims)
            dim = meta.shape[partition_axis]
            if dim % axis_size != 0:
                logical_dim = dim
                padded_dim = -(-dim // axis_size) * axis_size

        kind = node.WhichOneof("synchronizer")
        if kind is None and node.part_config:
            # Partitioned node: children carry the synchronizer; they are homogeneous
            # by construction, so inspect the first.
            kind = node.part_config[0].WhichOneof("synchronizer")
            sync_node = node.part_config[0]
        else:
            sync_node = node

        if kind == "ps_synchronizer":
            ps = sync_node.ps_synchronizer
            opt_pspec = _zero_style_opt_pspec(meta, param_pspec, reduce_size)
            return ParamPlan(name=meta.name, pspec=param_pspec, opt_pspec=opt_pspec,
                             sync=SYNC_PS, sparse=meta.sparse or node.sparse,
                             staleness=ps.staleness, synchronous=ps.sync,
                             partition_axis=partition_axis, num_shards=num_shards,
                             partition_mesh_axis=partition_mesh_axis,
                             padded_dim=padded_dim, logical_dim=logical_dim,
                             index_leaf=meta.index_leaf,
                             shape=tuple(meta.shape))

        ar = sync_node.all_reduce_synchronizer
        return ParamPlan(name=meta.name, pspec=param_pspec, opt_pspec=param_pspec,
                         sync=SYNC_ALLREDUCE, compressor=ar.compressor,
                         power_sgd_rank=max(1, ar.power_sgd_rank), group=ar.group,
                         spec=ar.spec,
                         sparse=meta.sparse or node.sparse,
                         partition_axis=partition_axis, num_shards=num_shards,
                         partition_mesh_axis=partition_mesh_axis,
                         padded_dim=padded_dim, logical_dim=logical_dim,
                         index_leaf=meta.index_leaf,
                         shape=tuple(meta.shape))

    # -------------------------------------------------------------- accessors
    @property
    def dp_size(self) -> int:
        return (self.mesh_axes.get(const.MESH_AXIS_DATA, 1)
                * self.mesh_axes.get(const.MESH_AXIS_REDUCE, 1))

    @property
    def has_compression(self) -> bool:
        return any(p.compressor != COMP_NONE for p in self.params.values())

    @property
    def is_async(self) -> bool:
        """True when any PS node requests a non-synchronous regime (sync=False or
        staleness>0) — these compile to the host-driven dispatch loop
        (parallel/staleness.py), not to one SPMD program."""
        return any(p.sync == SYNC_PS and (not p.synchronous or p.staleness > 0)
                   for p in self.params.values())

    @property
    def max_staleness(self) -> int:
        return max((p.staleness for p in self.params.values()), default=0)

    @property
    def all_params_replicated(self) -> bool:
        return all(p.pspec == P() for p in self.params.values())

    @property
    def has_padding(self) -> bool:
        """True when any parameter uses padded storage (uneven partitioning)."""
        return any(p.padded_dim is not None for p in self.params.values())

    @property
    def sparse_wire_params(self) -> Dict[str, ParamPlan]:
        """Sparse params eligible for the (indices, rows) wire format: replicated
        storage, known index source, no compressor (the reference likewise kept
        sparse grads out of the compressor, all_reduce_synchronizer.py:132-173)."""
        return {n: p for n, p in self.params.items()
                if p.sparse and p.index_leaf and p.pspec == P()
                and p.compressor == COMP_NONE}

    # ------------------------------------------------- uneven (padded) storage
    def pad_params(self, tree: Any) -> Any:
        """Zero-pad unevenly-partitioned leaves to their physical storage shape.

        Works on params AND optimizer-state trees (optax states embed copies of the
        parameter tree, matched by name suffix). Traceable: usable inside jit.
        """
        return self._map_padded(tree, pad=True)

    def unpad_params(self, tree: Any) -> Any:
        """Slice padded-storage leaves back to their logical shapes (inverse of
        :meth:`pad_params`; differentiating through this slice yields zero
        gradients in the pad region, which is the masked update)."""
        return self._map_padded(tree, pad=False)

    def _map_padded(self, tree: Any, pad: bool) -> Any:
        if not self.has_padding:
            return tree
        import jax
        import jax.numpy as jnp

        padded = {n: p for n, p in self.params.items() if p.padded_dim is not None}
        match = _suffix_matcher(padded)

        def visit(path, leaf):
            name = match(_leaf_name(path))
            if name is not None:
                p = padded[name]
                ax, want = p.partition_axis, (p.logical_dim if pad else p.padded_dim)
                shape = getattr(leaf, "shape", ())
                if len(shape) > ax and shape[ax] == want:
                    if pad:
                        widths = [(0, 0)] * len(shape)
                        widths[ax] = (0, p.padded_dim - p.logical_dim)
                        return jnp.pad(leaf, widths)
                    return jax.lax.slice_in_dim(leaf, 0, p.logical_dim, axis=ax)
            return leaf

        return jax.tree_util.tree_map_with_path(visit, tree)

    def batch_pspec(self, ndim: int = 1) -> P:
        """Batch arrays shard their leading dim over all data-parallel axes
        (reference Remapper split batches along the first dim, remapper.py:109-118)."""
        return P(DP_AXES, *([None] * (ndim - 1)))

    def param_sharding_tree(self, mesh: Mesh, params: Any):
        """NamedSharding pytree for the parameter tree (by leaf path name)."""
        return _tree_shardings_by_name(mesh, params, {n: p.pspec for n, p in self.params.items()})

    # ------------------------------------------- ZeRO weight-update sharding
    def with_zero_update(self, mesh: Optional[Mesh] = None) -> "ShardingPlan":
        """A copy of this plan with ZeRO-style weight-update sharding ON.

        Every trainable parameter's ``opt_pspec`` shards the first axis that
        tiles evenly over ALL data-parallel axes (not just the PS family's
        ``reduce`` axis): optimizer-state memory drops to ``~size/dp`` per
        device, and a jitted step whose grads/updates are constrained to these
        specs lowers the update into reduce-scatter -> shard-local
        ``optimizer.update`` -> all-gather (the arXiv 2004.13336 formulation,
        inserted by XLA's SPMD partitioner under plain ``jit`` — no manual
        collectives). Parameters whose shape has no evenly-tiling free axis
        keep their existing (replicated / PS) opt sharding — the same
        degeneration tiny variables already had.

        ``mesh`` supplies the axis sizes the state will actually live on (the
        runner may legally rebuild a smaller mesh than the strategy was built
        for); defaults to the plan's own ``mesh_axes``."""
        if mesh is not None:
            axis_sizes = {a: mesh.shape.get(a, 1) for a in DP_AXES}
        else:
            axis_sizes = {a: self.mesh_axes.get(a, 1) for a in DP_AXES}
        dp = int(np.prod(list(axis_sizes.values()))) if axis_sizes else 1
        params = {}
        for name, p in self.params.items():
            pspec = _zero_update_pspec(p, dp)
            params[name] = dataclasses.replace(p, opt_pspec=pspec) \
                if pspec is not None else p
        plan = ShardingPlan(self.mesh_axes, params)
        plan.zero = True
        return plan

    def constrain_update(self, mesh: Mesh, tree: Any) -> Any:
        """``lax.with_sharding_constraint`` a params-shaped tree (gradients or
        optimizer updates) to the per-parameter ``opt_pspec``s — the
        reduce-scatter insertion point of the ZeRO update. Traceable."""
        return _constrain_tree(tree, _tree_shardings_by_name(
            mesh, tree, {n: p.opt_pspec for n, p in self.params.items()}))

    def constrain_opt(self, mesh: Mesh, opt_state: Any) -> Any:
        """Constrain an optimizer-state tree to the plan's opt shardings
        (shard-local moments stay sharded through the jitted step)."""
        return _constrain_tree(opt_state, self.opt_sharding_tree(mesh, opt_state))

    def constrain_params(self, mesh: Mesh, params: Any) -> Any:
        """Constrain an updated parameter tree back to its storage shardings —
        the all-gather closing the ZeRO update."""
        return _constrain_tree(params, self.param_sharding_tree(mesh, params))

    def opt_sharding_tree(self, mesh: Mesh, opt_state: Any):
        """NamedSharding pytree for the optimizer state.

        Optimizer states (optax) embed copies of the parameter tree (mu/nu/trace...):
        each leaf whose path ends with a parameter's path gets that parameter's
        ``opt_pspec``; everything else (step counters etc.) replicates. This is how
        the reference moved optimizer slots with their variable to the PS
        (``kernel/partitioner.py:570-573`` re-instantiated the optimizer over moved
        vars); here placement is a sharding, not a device string.
        """
        return _tree_shardings_by_name(
            mesh, opt_state, {n: p.opt_pspec for n, p in self.params.items()})

    def __repr__(self):
        kinds = collections.Counter(p.sync for p in self.params.values())
        return f"ShardingPlan(mesh={dict(self.mesh_axes)}, {dict(kinds)})"


def _first_tiling_axis_pspec(shape, base_pspec: P, axis_token,
                             divisor: int) -> Optional[P]:
    """The single "shard the first free evenly-tiling axis" rule shared by
    BOTH opt-state sharding derivations (PS-family ``reduce`` sharding and
    ZeRO's full-dp sharding), so the two can never drift.

    Puts ``axis_token`` on the first tensor axis that is not already taken by
    a model/expert axis in ``base_pspec`` and whose dim divides ``divisor``
    evenly; returns ``None`` when no axis tiles (callers pick their own
    degeneration)."""
    if divisor <= 1 or not shape:
        return None
    dims: list = list(base_pspec) if base_pspec \
        and len(base_pspec) == len(shape) else [None] * len(shape)
    for axis, dim in enumerate(shape):
        if dims[axis] is None and dim > 0 and dim % divisor == 0:
            dims[axis] = axis_token
            return P(*dims)
    return None


def _zero_update_pspec(p: ParamPlan, dp: int) -> Optional[P]:
    """The ZeRO opt-state PartitionSpec for one parameter, or ``None`` to keep
    the plan's existing one.

    The first free axis whose STORAGE dim (padded, for uneven partitioning)
    tiles evenly over the TOTAL data-parallel size gets the whole ``DP_AXES``
    tuple — every device is a data replica AND an update shard (meshes built
    by :func:`~autodist_tpu.parallel.mesh.build_mesh` always carry both axes,
    at size 1 when unused). Shapes with no evenly-tiling free axis return
    ``None`` (keep replicated/PS sharding — the degeneration tiny variables
    already had)."""
    shape = list(p.shape)
    if p.padded_dim is not None and p.partition_axis is not None:
        shape[p.partition_axis] = p.padded_dim  # opt state embeds padded storage
    return _first_tiling_axis_pspec(shape, p.pspec, DP_AXES, dp)


def _constrain_tree(tree: Any, shardings: Any) -> Any:
    import jax
    return jax.tree_util.tree_map(
        jax.lax.with_sharding_constraint, tree, shardings)


def _zero_style_opt_pspec(meta, param_pspec: P, reduce_size: int) -> P:
    """Optimizer-state sharding for a PS parameter.

    Shard the first axis that tiles evenly over the ``reduce`` axis and is not
    already taken by the model axis. Falls back to the parameter's own sharding when
    nothing tiles (small/odd shapes) — those replicate, which is also what the
    reference's single-PS placement degenerates to for tiny vars.
    """
    pspec = _first_tiling_axis_pspec(meta.shape, param_pspec,
                                     const.MESH_AXIS_REDUCE, reduce_size)
    return pspec if pspec is not None else param_pspec


def _leaf_name(path) -> str:
    from autodist_tpu.model_spec import _path_name
    return _path_name(path)


def _suffix_matcher(names):
    """Longest-suffix param-name matching (w vs emb/w): the single definition used
    by BOTH sharding derivation and pad/unpad, so the two can never disagree about
    which tree leaves are parameter-derived."""
    ordered = sorted(names, key=len, reverse=True)

    def match(leaf_name: str) -> Optional[str]:
        for name in ordered:
            if leaf_name == name or leaf_name.endswith("/" + name):
                return name
        return None

    return match


def _tree_shardings_by_name(mesh: Mesh, tree: Any, pspecs_by_name: Dict[str, P]):
    """Map each leaf to a NamedSharding by longest param-name suffix match."""
    import jax

    match = _suffix_matcher(pspecs_by_name)

    def choose(path, leaf):
        name = match(_leaf_name(path))
        if name is not None:
            pspec = pspecs_by_name[name]
            if _pspec_fits(pspec, getattr(leaf, "shape", ())):
                return NamedSharding(mesh, pspec)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(choose, tree)


def _pspec_fits(pspec: P, shape) -> bool:
    if not pspec:
        return True
    return len(pspec) <= len(shape)
