"""BERT pretraining benchmark.

Port of reference ``examples/benchmark/bert.py:41-47,194-215`` (BERT-large
pretraining inside the AutoDist scope): masked-LM objective, AllReduce with bf16
mixed precision, examples/sec instrumentation, and a REAL pretrain data path —
the reference consumed masked tfrecords via ``get_pretrain_dataset_fn``
(``bert.py:82-98`` -> ``utils/input_pipeline.py``); here ``--tokenize_corpus``
prepares raw token shards from a text corpus and ``--data_dir`` trains from
them with dynamic per-batch masking (``autodist_tpu/data/mlm.py``). Without
``--data_dir``, synthetic input with the same fixed-prediction-slot layout
(max_predictions_per_seq).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models import bert
from autodist_tpu.strategy import AllReduce
from autodist_tpu.utils.metrics import ThroughputMeter

SIZES = {
    "tiny": dict(d_model=128, n_heads=2, n_layers=2, d_ff=512),
    "base": dict(d_model=768, n_heads=12, n_layers=12, d_ff=3072),
    "large": dict(d_model=1024, n_heads=16, n_layers=24, d_ff=4096),
}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", choices=list(SIZES), default="base")
    parser.add_argument("--steps", type=int, default=110)
    parser.add_argument("--batch_size", type=int, default=0)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--max_predictions", type=int, default=20)
    parser.add_argument("--accum", type=int, default=1,
                        help="gradient-accumulation micro-batches per step "
                             "(global batch = --batch_size; must divide it)")
    parser.add_argument("--log_every", type=int, default=100)
    parser.add_argument("--resource_spec", type=str, default=None)
    parser.add_argument("--data_dir", type=str, default=None,
                        help="train from mlm token shards (prepared by "
                             "--tokenize_corpus) with dynamic masking; "
                             "default = synthetic batches")
    parser.add_argument("--tokenize_corpus", type=str, default=None,
                        help="text file/glob: prepare raw MLM shards into "
                             "--data_dir and exit")
    parser.add_argument("--vocab_size", type=int, default=30000,
                        help="corpus-built vocab budget for --tokenize_corpus")
    parser.add_argument("--segments", action="store_true",
                        help="prep with [CLS] a [SEP] b [SEP] segment pairs")
    parser.add_argument("--eval", action="store_true",
                        help="one deterministic pass over --data_dir: "
                             "masked-LM accuracy (the reference's "
                             "masked_lm_accuracy metric)")
    parser.add_argument("--restore", type=str, default=None,
                        help="checkpoint prefix to evaluate (Saver format)")
    args = parser.parse_args(argv)

    if args.tokenize_corpus:
        if not args.data_dir:
            parser.error("--tokenize_corpus needs --data_dir")
        from autodist_tpu.data import mlm, text_corpus
        vocab = text_corpus.build_vocab(args.tokenize_corpus,
                                        max_size=args.vocab_size)
        paths = mlm.prepare_mlm_shards(args.tokenize_corpus, vocab,
                                       args.data_dir, seq_len=args.seq_len,
                                       segments=args.segments)
        print(f"prepared {len(paths['tokens'])} MLM shard(s) in "
              f"{args.data_dir}; train with --data_dir {args.data_dir}")
        return 0

    n_dev = len(jax.devices())
    batch_size = args.batch_size or 8 * n_dev
    on_accel = jax.default_backend() != "cpu"
    size_kw = dict(SIZES[args.size])

    if args.eval and not args.data_dir:
        parser.error("--eval needs --data_dir")
    feed = None
    loader = None
    if args.data_dir:
        from autodist_tpu.data import mlm
        try:
            # Eval = one deterministic pass: sequential read, seeded masking.
            loader, meta = mlm.open_mlm_loader(args.data_dir,
                                               batch_size=batch_size,
                                               shuffle=not args.eval,
                                               prefetch=4)
        except FileNotFoundError as e:
            parser.error(str(e))
        if meta["seq_len"] != args.seq_len:
            parser.error(f"corpus was prepared at seq_len {meta['seq_len']}, "
                         f"got --seq_len {args.seq_len}")
        batcher = mlm.MLMBatcher(loader, vocab_size=meta["vocab_size"],
                                 max_predictions=args.max_predictions)
        size_kw["vocab_size"] = meta["vocab_size"]
        batch = batcher.next()
    cfg = bert.BertConfig(max_len=args.seq_len,
                          dtype=jnp.bfloat16 if on_accel else jnp.float32,
                          **size_kw)

    model = bert.Bert(cfg)
    if not args.data_dir:
        batch = bert.synthetic_batch(cfg, batch_size, args.seq_len,
                                     n_predictions=args.max_predictions)
    if args.eval and args.restore:
        # The restore below replaces params wholesale; skip the (expensive on
        # bert-large) fresh initialization.
        params = None
    else:
        from autodist_tpu.models.common import jit_init
        params = jit_init(model, jnp.asarray(batch["tokens"]),
                          jnp.asarray(batch["token_types"]))
    loss_fn = bert.make_mlm_loss_fn(model)

    ad = AutoDist(args.resource_spec, AllReduce(compressor="HorovodCompressor"))

    if args.eval:
        import numpy as np

        if args.restore:
            from autodist_tpu.checkpoint import Saver
            params = Saver().restore_params(args.restore)

        def metric_fn(p, b):
            logits = model.apply({"params": p}, b["tokens"],
                                 b["token_types"],
                                 mlm_positions=b["mlm_positions"])
            pred = jnp.argmax(logits.astype(jnp.float32), -1)
            w = b["mlm_weights"]
            return jnp.stack([((pred == b["mlm_targets"]) * w).sum(), w.sum()])

        step = ad.function(loss_fn, params, optax.sgd(0.0),
                           example_batch=batch)
        state = step.get_state()
        n_batches = loader.n_rows // batch_size
        counts = np.zeros(2)
        for i in range(n_batches):
            b = batch if i == 0 else batcher.next()  # first rows already drawn
            counts += np.asarray(step.runner.evaluate(state, b, fn=metric_fn))
        loader.close()
        skipped = loader.n_rows - n_batches * batch_size
        if skipped:
            print(f"WARNING: {skipped} tail row(s) skipped (static batch "
                  f"shapes drop the remainder); pick a --batch_size dividing "
                  f"{loader.n_rows} for exact coverage")
        acc = counts[0] / max(counts[1], 1)
        print(f"bert-{args.size} eval ({int(counts[1])} masked positions over "
              f"{n_batches * batch_size}/{loader.n_rows} rows): "
              f"masked_lm_accuracy {acc:.4f}")
        return float(acc)

    step = ad.function(loss_fn, params, optax.adamw(1e-4), example_batch=batch,
                       accumulation_steps=args.accum)
    feed = None
    if args.data_dir:
        # Masked batches stream from disk through the prefetch ring; the
        # host->HBM transfer overlaps the running step (device_prefetch).
        from autodist_tpu.data import device_prefetch
        feed = device_prefetch(batcher, step.runner, depth=2)
        next_batch = lambda: next(feed)  # noqa: E731
    else:
        # Keep the synthetic batch device-resident: re-shipping it from host
        # every step benchmarks the host link, not the chip.
        batch = step.runner.shard_batch(batch)
        next_batch = lambda: batch  # noqa: E731

    meter = ThroughputMeter(batch_size=batch_size, log_every=args.log_every)
    loss = None
    try:
        for _ in range(args.steps):
            loss = step(next_batch())
            meter.step(sync=loss)
        jax.device_get(loss)  # fence: trailing async steps must not inflate avg
        # meter.average is a LIVE clock read — capture it before the MFU call
        # below triggers its own lowering/compile work.
        avg = meter.average or 0.0
    finally:
        if feed is not None:
            feed.close()   # stop the producer before its loader goes away
        if loader is not None:
            loader.close()
    src = "disk" if args.data_dir else "synthetic"
    print(f"bert-{args.size} ({src}): final loss {float(loss):.4f}, "
          f"{avg:.1f} examples/sec")
    from autodist_tpu.utils import flops as flops_util
    per_step = flops_util.train_step_flops(step.runner, step.get_state(),
                                           step.runner.shard_batch(batch))
    if per_step and args.accum > 1:
        # XLA's cost analysis counts a lax.scan body ONCE, not per trip: the
        # accumulation scan runs accum micro-batches per step. Scaling the
        # whole count slightly over-weights the (tiny) optimizer apply.
        per_step *= args.accum
    flops_util.report_mfu(per_step, avg / batch_size)
    return avg


if __name__ == "__main__":
    main()
