"""Request queue + continuous/dynamic batcher — the serving plane's host core.

The reference's serving story ended at SavedModel export; this module is the
missing online half: incoming requests are packed into padded device batches
and driven to completion by ONE background thread per batcher (the device is
a serial resource; a thread per request would just contend for it). Two
batching disciplines share the loop:

- ``continuous`` (default) — admission at decode-step granularity: whenever a
  slot is free and a request is waiting, the request is prefilled into the
  slot *between* decode steps, and a request that finishes early (hit its
  token budget or the EOS id) leaves the batch immediately, freeing its
  KV-cache slot for the next waiter. Short generations never wait for long
  ones (no convoy effect).
- ``static`` — classic wave batching: admit a full batch only when the
  previous wave has drained. Simpler, worse tail latency under mixed
  generation lengths; kept as the bench baseline (``bench.py --serve``).

Prompts are padded to BUCKETED lengths (powers of two by default) so the jit
cache holds one prefill program per bucket, not one per prompt length.

This module is deliberately jax-free: the device work hides behind the small
engine interface (:mod:`autodist_tpu.serving.runtime` implements it; tests
drive the loop with a fake), so packing/bucketing/slot-reuse logic is
unit-testable without compiling anything.

SLO metrics ride the process-global :mod:`autodist_tpu.telemetry` registry
(always on — they are the service's product, a few dict operations per
request): ``serve.latency_s.{queue,prefill,decode,total}`` histograms with
ms-scale buckets (``metrics.BUCKET_FAMILIES``), ``serve.queue_depth`` /
``serve.batch_fill`` gauges, ``serve.requests.{submitted,completed,rejected}``
counters. Host spans (``serve.prefill``, ``serve.decode_step``) appear in the
PR 5 cluster trace when telemetry is enabled.
"""

import dataclasses
import inspect
import itertools
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu import telemetry
# The admission queue is the input-data plane's staging core (BoundedQueue:
# bounded, closeable, GL005-clean waits) — ONE queue implementation behind
# the prefetch producers and the serving batchers. data.prefetch stays
# jax-free at import, preserving this module's jax-free contract.
from autodist_tpu.data.prefetch import EMPTY, BoundedQueue, QueueClosed
from autodist_tpu.telemetry import reqtrace as _reqtrace
from autodist_tpu.testing.sanitizer import san_lock, san_event

# Request-phase attribution vocabulary (the serving twin of
# profiling.ATTR_PHASES): per-round share gauges serve.attr.<phase>, shares
# summing to 1.0 over the completions the round observed.
ATTR_PHASES = ("wire", "queue", "prefill", "decode")


class ServeError(RuntimeError):
    """A rejected or failed serving request (invalid shape, queue full,
    server-side failure) — shipped to remote clients as an error reply."""


class ServeBusy(ServeError):
    """Typed overload rejection: the admission queue (or the paged-KV pool
    behind it) is full RIGHT NOW, but the request itself is valid — retry
    later, or on another replica. The fleet router keys its shed-vs-fail
    decision on this type: a ``ServeBusy`` from one replica cascades to the
    next; any other ``ServeError`` is deterministic and is surfaced to the
    client unchanged."""


def default_buckets(max_len: int, floor: int = 8) -> Tuple[int, ...]:
    """Power-of-two prompt pad lengths up to ``max_len`` (inclusive as the
    last bucket even when max_len is not a power of two) — one jitted prefill
    program per bucket instead of one per prompt length."""
    out: List[int] = []
    b = floor
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= ``length``; raises :class:`ServeError` when the
    prompt exceeds every bucket (the request can never fit the cache)."""
    for b in buckets:
        if length <= b:
            return b
    raise ServeError(f"prompt length {length} exceeds the largest pad "
                     f"bucket {max(buckets)}")


def pad_prompt(prompt: np.ndarray, bucket: int) -> np.ndarray:
    """``[P] -> [1, bucket]`` right-padded with zeros. Right padding keeps
    positions [0, P) real; the pad tail's K/V is masked until decode steps
    overwrite it position by position (see runtime.LMEngine)."""
    out = np.zeros((1, bucket), np.int32)
    out[0, :len(prompt)] = prompt
    return out


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving knobs (defaults from the ``AUTODIST_SERVE_*`` flags via
    :meth:`from_env`). ``buckets=()`` lets the engine derive power-of-two pad
    lengths from the model's ``max_len``. Sampling statics (temperature/
    top_k/top_p) are per-server, not per-request — a per-request temperature
    would be one compiled decode program per value."""

    max_batch: int = 8          # decode slot capacity (AUTODIST_SERVE_MAX_BATCH)
    mode: str = "continuous"    # or "static" (AUTODIST_SERVE_MODE)
    max_queue: int = 256        # admission bound (AUTODIST_SERVE_QUEUE)
    request_timeout_s: float = 120.0  # completion-wait cap (AUTODIST_SERVE_TIMEOUT_S)
    buckets: Tuple[int, ...] = ()     # prompt pad lengths; () = engine default
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: int = -1            # generation stops at this token id; -1 disables
    # Paged-KV knobs (serving/paged.py): page length in tokens (0 = the
    # dense per-slot slab), pool size in pages (0 = derived at HBM parity
    # with the dense slab), and the shared-prefix page cache toggle.
    page_len: int = 0           # AUTODIST_KV_PAGE_LEN; 0 = dense slab
    kv_pages: int = 0           # pool pages incl. scratch; 0 = derived
    prefix_cache: bool = True   # AUTODIST_PREFIX_CACHE

    def __post_init__(self):
        if self.mode not in ("continuous", "static"):
            raise ValueError(f"unknown serving mode {self.mode!r}; valid: "
                             f"'continuous', 'static'")
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if self.buckets and list(self.buckets) != sorted(self.buckets):
            raise ValueError("buckets must be ascending")
        if self.page_len < 0 or self.kv_pages < 0:
            raise ValueError("page_len/kv_pages must be >= 0")

    @staticmethod
    def from_env(**overrides) -> "ServeConfig":
        from autodist_tpu import const
        base = dict(max_batch=const.ENV.AUTODIST_SERVE_MAX_BATCH.val,
                    mode=const.ENV.AUTODIST_SERVE_MODE.val,
                    max_queue=const.ENV.AUTODIST_SERVE_QUEUE.val,
                    request_timeout_s=const.ENV.AUTODIST_SERVE_TIMEOUT_S.val,
                    page_len=const.ENV.AUTODIST_KV_PAGE_LEN.val,
                    prefix_cache=const.ENV.AUTODIST_PREFIX_CACHE.val)
        base.update(overrides)
        return ServeConfig(**base)


class ServeRequest:
    """One in-flight request: payload + completion event + timing stamps.

    ``done`` is set exactly once, after ``tokens``/``output``/``error`` and
    the timing stamps are final — the transport handler thread waits on it
    (bounded) and reads the result without further locking.

    ``abandoned``/``deadline`` are the dead-request plane: the transport
    marks a request abandoned when its client's wait times out, and the
    batcher stamps a server-side deadline at submission — either way the
    scheduler drops the request at its next decision point (admission pop,
    or the decode round for an in-flight slot) instead of burning capacity
    on output nobody will read."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "seed", "keys",
                 "t_submit", "t_admit", "t_prefill_done", "t_done",
                 "done", "tokens", "output", "error", "slot",
                 "abandoned", "deadline", "rid_token", "wire_s")

    def __init__(self, rid: int, prompt, max_new_tokens: int = 0,
                 seed: int = 0, rid_token: Optional[str] = None,
                 wire_s: float = 0.0):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.seed = seed
        # Fleet-scope identity: the router's rid token (when one rode the
        # wire) is what joins this request's records across processes; the
        # local rid stays the slot-table/dedup key. wire_s is the
        # trace-context decomposition the transport computed at receive
        # (origin send stamp + per-connection clock offset).
        self.rid_token = rid_token
        self.wire_s = wire_s
        self.keys = None                  # per-step sampling keys [max_new, 2]
        self.t_submit = time.perf_counter()
        self.t_admit = 0.0
        self.t_prefill_done = 0.0
        self.t_done = 0.0
        self.done = san_event()
        self.tokens: List[int] = []       # generated ids (LM path)
        self.output = None                # model output (apply path)
        self.error: Optional[str] = None
        self.slot = -1
        self.abandoned = False            # client gave up; drop, don't decode
        self.deadline = 0.0               # t_submit + request_timeout_s

    @property
    def trace_key(self):
        """The request-trace join key: the fleet rid token when one exists,
        else the local rid (direct clients trace per process only)."""
        return self.rid_token if self.rid_token is not None else self.rid

    def abandon(self):
        """Mark the request not worth finishing (its client stopped
        waiting). A plain flag, no lock: the scheduler reads it at the next
        decision point and dropping one round late is harmless."""
        self.abandoned = True

    def dead(self, now: float) -> bool:
        return self.abandoned or (self.deadline and now > self.deadline)

    def timing(self) -> dict:
        """Wire-encodable latency breakdown (seconds) plus the server-side
        correlation id, shipped in the reply — ``request_id`` is what ties a
        client-observed latency to the server's spans (``serve.prefill``/
        ``serve.decode_step`` carry the same id in their args) and to the
        ``status`` opcode's in-flight table (``tools/adtop.py``)."""
        return {"request_id": self.rid,
                "queue_s": round(self.t_admit - self.t_submit, 6),
                "prefill_s": round(self.t_prefill_done - self.t_admit, 6),
                "decode_s": round(self.t_done - self.t_prefill_done, 6),
                "total_s": round(self.t_done - self.t_submit, 6)}

    def finish(self, error: Optional[str] = None):
        self.stamp_done(error)
        self.done.set()

    def stamp_done(self, error: Optional[str] = None):
        """Set the completion timestamps WITHOUT signalling the waiter —
        the batcher books its SLO counters between stamping and the
        ``done.set()``, so a client whose reply arrived can never read a
        ``stats``/``status`` snapshot that misses its own request."""
        self.t_done = time.perf_counter()
        if not self.t_admit:          # rejected/failed before admission
            self.t_admit = self.t_prefill_done = self.t_done
        if not self.t_prefill_done:
            self.t_prefill_done = self.t_done
        self.error = error


class _ServeMetrics:
    """Cached instrument handles for the serve.* SLO families (get-or-create
    once, not per request)."""

    def __init__(self):
        reg = telemetry.registry()
        self.lat = {f: reg.histogram(f"serve.latency_s.{f}")
                    for f in ("queue", "prefill", "decode", "total")}
        self.depth = reg.gauge("serve.queue_depth")
        self.fill = reg.gauge("serve.batch_fill")
        self.submitted = reg.counter("serve.requests.submitted")
        self.completed = reg.counter("serve.requests.completed")
        self.rejected = reg.counter("serve.requests.rejected")
        # Per-phase attribution (the serving twin of train.attr.*): shares
        # of completed requests' wall time summing to 1.0, recomputed each
        # scheduler round from the completions since the last flush.
        self.attr = {p: reg.gauge(f"serve.attr.{p}") for p in ATTR_PHASES}
        self._attr_acc = {p: 0.0 for p in ATTR_PHASES}
        self._attr_lock = san_lock()

    def observe(self, req: ServeRequest):
        t = req.timing()
        self.lat["queue"].observe(t["queue_s"])
        self.lat["prefill"].observe(t["prefill_s"])
        self.lat["decode"].observe(t["decode_s"])
        # The total histogram carries the slowest-in-window EXEMPLAR: rid +
        # phase breakdown, so a firing serve_p99_burn names a concrete
        # traceable request instead of a quantile.
        self.lat["total"].observe(t["total_s"], exemplar={
            "rid": str(req.trace_key), "wire_s": round(req.wire_s, 6),
            "queue_s": t["queue_s"], "prefill_s": t["prefill_s"],
            "decode_s": t["decode_s"], "total_s": t["total_s"]})
        with self._attr_lock:
            self._attr_acc["wire"] += max(0.0, req.wire_s)
            self._attr_acc["queue"] += max(0.0, t["queue_s"])
            self._attr_acc["prefill"] += max(0.0, t["prefill_s"])
            self._attr_acc["decode"] += max(0.0, t["decode_s"])

    def flush_attr(self):
        """Fold the completions observed since the last flush into the
        serve.attr.* share gauges (called once per scheduler round; a round
        with no completions keeps the previous shares — gauges that flap to
        zero between requests would be unreadable on a console)."""
        with self._attr_lock:
            parts = dict(self._attr_acc)
            total = sum(parts.values())
            if total <= 0.0:
                return
            for p in ATTR_PHASES:
                self._attr_acc[p] = 0.0
        for p in ATTR_PHASES:
            self.attr[p].set(round(parts[p] / total, 4))


class _BatcherBase:
    """Shared queue/loop/lifecycle machinery for the two batchers: bounded
    admission queue, ONE daemon scheduling thread, dead-request dropping,
    drain-and-fail shutdown. Subclasses own :meth:`run_once` (the actual
    scheduling policy) and their ``submit`` validation."""

    kind = ""
    # Bounded idle wait between queue polls when no slot is active (GL005:
    # package waits are always bounded).
    IDLE_WAIT_S = 0.02

    def __init__(self, engine, config: ServeConfig, thread_name: str):
        self._engine = engine
        self.config = config
        self._metrics = _ServeMetrics()
        self._lock = san_lock()          # slot/engine state
        # Admission staging on the shared input-plane queue core: bounded
        # (max_queue), instant-rejection try_put, close-and-drain shutdown.
        self._waiting = BoundedQueue(config.max_queue)
        self._rid = itertools.count()
        self._stop = san_event()
        self._thread: Optional[threading.Thread] = None
        self._thread_name = thread_name

    def _start(self):
        """Start the scheduling thread. Subclasses call this LAST in their
        ``__init__`` — the loop reads subclass state (e.g. the slot table),
        so it must not run before that state exists."""
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self._thread_name)
        self._thread.start()

    def _enqueue(self, req: ServeRequest) -> ServeRequest:
        """Admission control: better an instant rejection than an unbounded
        queue whose tail latency is infinite. O(1) host work — anything
        per-request and device-touching happens at admission, not here."""
        req.deadline = req.t_submit + self.config.request_timeout_s
        if self._stop.is_set():
            # After close() no loop thread exists to ever serve this;
            # reject now instead of parking the caller for its full
            # timeout on a queue nobody drains.
            self._metrics.rejected.inc()
            raise ServeError("server is shutting down")
        try:
            admitted = self._waiting.try_put(req)
        except QueueClosed:
            self._metrics.rejected.inc()
            raise ServeError("server is shutting down") from None
        if not admitted:
            self._metrics.rejected.inc()
            _reqtrace.mark(req.trace_key, "shed", reason="queue_full")
            raise ServeBusy(
                f"serving queue is full ({self.config.max_queue} "
                f"waiting); retry later")
        self._metrics.submitted.inc()
        self._metrics.depth.set(len(self._waiting))
        _reqtrace.mark(req.trace_key, "queued", depth=len(self._waiting))
        return req

    def queue_depth(self) -> int:
        return len(self._waiting)

    def _inflight_locked(self) -> List[ServeRequest]:
        """Hook (called under ``_lock`` from :meth:`close`): active requests
        to fail at shutdown; implementations must also detach them."""
        return []

    def in_flight_snapshot(self) -> List[dict]:
        """Wire-encodable per-request view of what is on the device right
        now (the ``status`` opcode's in-flight table): empty for batchers
        whose requests are transient (the apply path runs whole waves inside
        one ``run_once``)."""
        return []

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        # Fail whatever is still queued/in-flight so no handler waits out its
        # full timeout on a server that is gone. Closing the staging queue
        # AFTER the join also converts racing late submits into instant
        # shutting-down rejections (QueueClosed in _enqueue).
        pending = self._waiting.close()
        with self._lock:
            pending += self._inflight_locked()
        for req in pending:
            req.finish(error="server shutting down")

    def _loop(self):
        from autodist_tpu.telemetry import alerts as _alerts
        from autodist_tpu.telemetry import history as _history
        while not self._stop.is_set():
            # Metric-history tick between scheduler rounds: serving
            # processes have no train-loop boundary, so the SLO histograms'
            # series (and the burn-rate alert windows over them) sample
            # here. Throttled to min_interval_s inside maybe_sample; the
            # un-armed cost is two module-global reads per round. A halt
            # alert cannot stop a loop that owns live requests — log it,
            # keep serving (the gauges/events are booked for pollers).
            try:
                _history.maybe_sample(reason="serve_round")
            except _alerts.AlertHalt as e:
                from autodist_tpu.utils import logging as _logging
                _logging.warning("serving: %s (AUTODIST_ALERT_ACTION=halt "
                                 "does not stop the scheduler loop; drain "
                                 "via the router instead)", e)
            # Per-round phase attribution: fold the completions this round
            # observed into the serve.attr.* share gauges (no-op when no
            # request completed since the last round).
            self._metrics.flush_attr()
            if not self.run_once() and not self._stop.is_set():
                # Bounded idle poll on the staging queue (wakes instantly
                # on an admission, at IDLE_WAIT_S otherwise).
                self._waiting.wait_nonempty(self.IDLE_WAIT_S)

    def _drop_dead(self, req: ServeRequest):
        """A request whose client stopped waiting (abandoned) or whose
        server-side deadline passed: reply with the reason, count it
        rejected, never touch the device for it."""
        req.finish(error="request abandoned by its client" if req.abandoned
                   else "request timed out (request_timeout_s passed)")
        self._metrics.rejected.inc()
        _reqtrace.mark(req.trace_key, "shed",
                       reason="abandoned" if req.abandoned else "deadline")

    def run_once(self) -> bool:
        raise NotImplementedError


class Batcher(_BatcherBase):
    """Continuous/static batching loop over an LM engine.

    The engine interface (implemented by ``runtime.LMEngine``, faked in
    tests): ``capacity`` (slot count), ``admit(slot, prompt, key) -> int``
    (prefill + first sampled token), ``step(keys) -> np[int32 B]`` (one
    decode step for every slot), ``free(slot)``, ``make_keys(seed, n)``
    (per-step sampling keys; None for greedy engines).

    ``start=False`` leaves the loop un-started (tests drive :meth:`run_once`
    by hand for deterministic admission/step interleaving).
    """

    kind = "lm"

    def __init__(self, engine, config: ServeConfig, start: bool = True):
        super().__init__(engine, config, "serve-batcher")
        self._slots: List[Optional[ServeRequest]] = [None] * engine.capacity
        # Admission holdback: a request popped from the queue that the
        # engine cannot admit YET (paged engines gate on free pages, not
        # free slots) parks here and is retried FIRST next round —
        # BoundedQueue has no push-front, and skipping it would reorder
        # FIFO admission. Guarded by _lock: _admit_ready swaps it out and
        # restores it under the lock, and close() collects it via
        # _inflight_locked — if join(30) times out the scheduler thread is
        # still live, so the bare-access version raced.
        self._held: Optional[ServeRequest] = None
        # Paged engines accept the trace rid on can_admit (they mark the
        # admission wait behind the page budget); plain engines/fakes keep
        # the two-argument form. Resolved once, not per admission round.
        ca = getattr(engine, "can_admit", None)
        self._can_admit_rid = (ca is not None and
                               "rid" in inspect.signature(ca).parameters)
        if start:
            self._start()

    # ------------------------------------------------------------- admission

    def submit(self, prompt: np.ndarray, max_new_tokens: int,
               seed: int = 0, rid_token: Optional[str] = None,
               wire_s: float = 0.0) -> ServeRequest:
        """Validate + enqueue; returns the request whose ``done`` event the
        caller waits on. Raises :class:`ServeError` on an invalid request or
        a full queue. The sampling-key schedule is built at ADMISSION, not
        here — a rejected request must cost no device work. ``rid_token`` /
        ``wire_s`` are the transport's trace context: the fleet-scope rid
        and the decomposed wire seconds (see :class:`ServeRequest`)."""
        prompt = self._validate(prompt, max_new_tokens)
        return self._enqueue(ServeRequest(next(self._rid), prompt,
                                          max_new_tokens, seed=seed,
                                          rid_token=rid_token,
                                          wire_s=wire_s))

    def _validate(self, prompt, max_new_tokens: int) -> np.ndarray:
        if not isinstance(prompt, np.ndarray) or prompt.ndim != 1 \
                or prompt.dtype.kind not in "iu" or prompt.size < 1:
            raise ServeError(
                f"prompt must be a non-empty 1-D integer ndarray, got "
                f"{type(prompt).__name__}"
                + (f" {prompt.dtype}/{prompt.shape}"
                   if isinstance(prompt, np.ndarray) else ""))
        if not isinstance(max_new_tokens, int) or max_new_tokens < 1:
            raise ServeError(f"max_new_tokens must be a positive int, got "
                             f"{max_new_tokens!r}")
        # Bucket fit + cache fit (prompt pads to its bucket; generation
        # extends from the TRUE length, so prompt+new bounds the frontier).
        bucket_for(prompt.size, self._engine.buckets)
        limit = self._engine.max_len
        if prompt.size + max_new_tokens > limit:
            raise ServeError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the engine's max_len ({limit})")
        return prompt.astype(np.int32)

    # ------------------------------------------------------------------ loop

    def _inflight_locked(self) -> List[ServeRequest]:
        inflight = [r for r in self._slots if r is not None]
        self._slots = [None] * len(self._slots)
        if self._held is not None:
            inflight.append(self._held)
            self._held = None
        return inflight

    @property
    def num_active(self) -> int:
        with self._lock:
            return sum(r is not None for r in self._slots)

    def in_flight_snapshot(self) -> List[dict]:
        """One dict per occupied decode slot: request id, slot, seconds in
        the system, tokens generated so far, prompt length — what an
        operator needs to spot the request a batch is convoyed behind."""
        now = time.perf_counter()
        with self._lock:
            slots = list(enumerate(self._slots))
        out = []
        for slot, req in slots:
            if req is None:
                continue
            out.append({"request_id": req.rid, "slot": slot,
                        "age_s": round(now - req.t_submit, 3),
                        "tokens": len(req.tokens),
                        "prompt_len": int(req.prompt.size),
                        "max_new_tokens": int(req.max_new_tokens)})
        return out

    def run_once(self) -> bool:
        """One scheduling round: admit what the mode allows, then one decode
        step for the active batch. Returns False when there was nothing to
        do (the loop then parks briefly). Tests call this directly for
        deterministic interleaving."""
        self._admit_ready()
        with self._lock:
            active = [(s, r) for s, r in enumerate(self._slots)
                      if r is not None]
            n_slots = len(self._slots)
        # An in-flight request whose client gave up — or whose deadline
        # passed mid-generation — leaves the batch NOW; its remaining decode
        # budget goes to live requests instead.
        now = time.perf_counter()
        for slot, req in [a for a in active if a[1].dead(now)]:
            self._release(slot)
            self._drop_dead(req)
            active = [a for a in active if a[0] != slot]
        self._metrics.fill.set(round(len(active) / max(1, n_slots), 4))
        if not active:
            return False
        keys = self._step_keys(active, n_slots)
        # The rids join is per-TOKEN work in the scheduler thread: build it
        # only when the span will actually record it (disabled-mode serving
        # must stay at the one-attribute-check contract).
        rids = ",".join(str(r.rid) for _, r in active) \
            if telemetry.enabled() else ""
        with telemetry.span("serve.decode_step", active=len(active),
                            rids=rids):
            toks = self._engine.step(keys)
        for slot, req in active:
            tok = int(toks[slot])
            req.tokens.append(tok)
            if len(req.tokens) >= req.max_new_tokens \
                    or tok == self.config.eos_id:
                self._complete(slot, req)
        return True

    def _step_keys(self, active, n_slots: int) -> np.ndarray:
        keys = np.zeros((n_slots, 2), np.uint32)
        for slot, req in active:
            if req.keys is not None and len(req.tokens) < len(req.keys):
                keys[slot] = req.keys[len(req.tokens)]
        return keys

    def _admit_ready(self):
        """Admission policy: continuous admits into any free slot at every
        round; static admits only a fresh wave into an EMPTY batch. Prefill
        (device work) runs OUTSIDE the queue lock — only the pop is locked.
        Dead waiters (abandoned / past deadline) are dropped at the pop, so
        under overload a backlog of expired requests never reaches the
        device."""
        now = time.perf_counter()
        dropped: List[ServeRequest] = []
        # _held is shared with close() (which collects it under _lock via
        # _inflight_locked, and may run concurrently if join(30) expires):
        # swap it out under the lock, work on the local, and restore any
        # held-back request under the same lock that publishes the batch.
        with self._lock:
            free = [s for s, r in enumerate(self._slots) if r is None]
            n_slots = len(self._slots)
            held, self._held = self._held, None
        if ((held is None and not len(self._waiting)) or not free
                or (self.config.mode == "static" and len(free) != n_slots)):
            if held is not None:
                with self._lock:
                    self._held = held
            return
        # Paged engines expose can_admit(prompt_len, max_new) — admission
        # gates on RESERVABLE PAGES, not free slots. A request that cannot
        # be admitted yet holds back (FIFO preserved); one that can NEVER
        # fit (needs more pages than the pool owns) raises and is rejected
        # here instead of blocking the head of the line forever.
        can_admit = getattr(self._engine, "can_admit", None)
        batch: List[Tuple[int, ServeRequest]] = []
        while free:
            if held is not None:
                req, held, fresh = held, None, False
            else:
                req = self._waiting.pop_nowait()
                fresh = True
                if req is EMPTY:
                    break
            if req.dead(now):
                dropped.append(req)
                continue
            if can_admit is not None:
                try:
                    # The trace rid rides only the FIRST check: a held-back
                    # request is re-checked every round, and one admit_wait
                    # mark per wait (not per 20ms retry) is the record.
                    if self._can_admit_rid and fresh:
                        ok = can_admit(int(req.prompt.size),
                                       req.max_new_tokens, rid=req.trace_key)
                    else:
                        ok = can_admit(int(req.prompt.size),
                                       req.max_new_tokens)
                except ServeError as e:
                    req.finish(error=str(e))
                    self._metrics.rejected.inc()
                    continue
                if not ok:
                    held = req
                    break
            batch.append((free.pop(0), req))
        self._metrics.depth.set(len(self._waiting))
        with self._lock:
            if held is not None:
                self._held = held
            for slot, req in batch:
                self._slots[slot] = req
        for req in dropped:
            self._drop_dead(req)
        for slot, req in batch:
            req.t_admit = time.perf_counter()
            req.slot = slot
            _reqtrace.mark(req.trace_key, "admitted", slot=slot)
            # Key schedule built here, not in submit(): only admitted
            # requests may cost device work.
            req.keys = self._engine.make_keys(req.seed, req.max_new_tokens)
            _reqtrace.mark(req.trace_key, "prefill_start",
                           prompt_len=int(req.prompt.size))
            try:
                with telemetry.span("serve.prefill", slot=slot, rid=req.rid,
                                    prompt_len=int(req.prompt.size)):
                    first = self._engine.admit(
                        slot, req.prompt,
                        req.keys[0] if req.keys is not None
                        and len(req.keys) else None)
            except Exception as e:   # a bad admit must not kill the loop
                self._release(slot)
                req.finish(error=f"{type(e).__name__}: {e}")
                self._metrics.rejected.inc()
                continue
            req.t_prefill_done = time.perf_counter()
            _reqtrace.mark(req.trace_key, "prefill_end")
            _reqtrace.mark(req.trace_key, "first_token")
            req.tokens.append(int(first))
            if len(req.tokens) >= req.max_new_tokens \
                    or int(first) == self.config.eos_id:
                self._complete(slot, req)

    def _release(self, slot: int):
        """Free a slot's engine cache row and unbind it (no cache scrub
        needed — the next occupant's prefill overwrites [0, bucket) and its
        mask never reaches past its own frontier)."""
        self._engine.free(slot)
        with self._lock:
            self._slots[slot] = None

    def _complete(self, slot: int, req: ServeRequest):
        """Early exit: the finished request leaves the batch NOW, freeing its
        KV-cache slot for the next waiter."""
        self._release(slot)
        req.stamp_done()
        _reqtrace.mark(req.trace_key, "done", tokens=len(req.tokens))
        self._metrics.completed.inc()
        self._metrics.observe(req)
        req.done.set()


class ApplyBatcher(_BatcherBase):
    """Dynamic batcher for the stateless families (classifier / recommender):
    gather whatever is waiting (up to ``max_batch``), run ONE padded jitted
    ``apply``, split the outputs back per request. No KV cache, no slots —
    a request's payload is one example pytree and its result one output
    pytree. The engine interface: ``capacity``, ``run(examples) -> outputs``
    (list in, list out, same order)."""

    kind = "apply"

    def __init__(self, engine, config: ServeConfig, start: bool = True):
        super().__init__(engine, config, "serve-apply-batcher")
        if start:
            self._start()

    def submit(self, example) -> ServeRequest:
        return self._enqueue(ServeRequest(next(self._rid), example))

    def run_once(self) -> bool:
        now = time.perf_counter()
        dropped: List[ServeRequest] = []
        batch: List[ServeRequest] = []
        while len(batch) < self._engine.capacity:
            req = self._waiting.pop_nowait()
            if req is EMPTY:
                break
            (dropped if req.dead(now) else batch).append(req)
        self._metrics.depth.set(len(self._waiting))
        for req in dropped:
            self._drop_dead(req)
        if not batch:
            return bool(dropped)
        now = time.perf_counter()
        for req in batch:
            req.t_admit = req.t_prefill_done = now
        self._metrics.fill.set(
            round(len(batch) / max(1, self._engine.capacity), 4))
        try:
            with telemetry.span("serve.apply", batch=len(batch)):
                outs = self._engine.run([r.prompt for r in batch])
        except Exception as e:
            for req in batch:
                req.finish(error=f"{type(e).__name__}: {e}")
                self._metrics.rejected.inc()
            return True
        for req, out in zip(batch, outs):
            req.output = out
            req.stamp_done()
            self._metrics.completed.inc()
            self._metrics.observe(req)
            req.done.set()
        return True
