"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability beyond the reference (which has no sequence parallelism,
SURVEY.md §5.7): the sequence dimension is sharded over the ``seq`` axis; each
device holds its local Q/K/V shard, and K/V shards rotate around the ring via
``jax.lax.ppermute`` while every device accumulates its queries' attention with the
online-softmax merge (:mod:`autodist_tpu.ops.blockwise_attention`). After
``seq_size`` steps every query has attended to every key, with peak activation
memory O(L/seq_size) per device and communication overlapping compute the XLA way
(each ppermute is independent of the current step's FLOPs).

Causality is preserved globally: each ring step knows the global offset of the K/V
shard it currently holds and masks accordingly.
"""

import jax
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.ops.blockwise_attention import (blockwise_attention_with_carry as _bw_carry, finalize as _bw_finalize)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   causal: bool = True, axis_name: str = const.MESH_AXIS_SEQ,
                   block_size: int = 256) -> jax.Array:
    """Attention with K/V rotating around the ``axis_name`` ring.

    Must run inside a ``shard_map`` (or any SPMD context) where ``axis_name`` is a
    mesh axis and the inputs' sequence dimension (axis 1 of [B, L_local, H, D]) is
    the local shard of the global sequence in ring order: device r holds global
    positions [r*L_local, (r+1)*L_local).
    """
    ring_size = jax.lax.axis_size(axis_name)
    my_index = jax.lax.axis_index(axis_name)
    _, l_local, _, _ = q.shape

    q_offset = my_index * l_local

    acc = None
    k_cur, v_cur = k, v
    # The shard we hold at step s originated at device (my_index - s) mod ring.
    for step in range(ring_size):
        src = (my_index - step) % ring_size
        k_offset = src * l_local

        def attend(operands):
            q_, k_, v_, carry = operands
            return _bw_carry(q_, k_, v_, carry, causal=causal,
                             block_size=block_size, q_offset=q_offset,
                             k_offset=k_offset)

        if acc is None:
            acc = attend((q, k_cur, v_cur, None))
        elif causal:
            # Shards originating strictly after ours are fully future under the
            # causal mask — skip their FLOPs entirely (the merge is the identity).
            acc = jax.lax.cond(src <= my_index, attend,
                               lambda operands: operands[3],
                               (q, k_cur, v_cur, acc))
        else:
            acc = attend((q, k_cur, v_cur, acc))
        if step != ring_size - 1:
            perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    out = _bw_finalize(*acc)                         # [B, H, Lq, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def make_ring_attention_fn(mesh: Mesh, *, causal: bool = True,
                           block_size: int = 256):
    """Wrap :func:`ring_attention` in a shard_map over (data, seq): batch shards on
    the data axes, sequence on ``seq``, heads/depth replicated."""
    spec = P((const.MESH_AXIS_DATA, const.MESH_AXIS_REDUCE),
             const.MESH_AXIS_SEQ, None, None)

    def fn(q, k, v):
        return ring_attention(q, k, v, causal=causal, block_size=block_size)

    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)
