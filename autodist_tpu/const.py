"""Constants and environment-variable configuration.

Capability parity with the reference's ``autodist/const.py``: working directories under
``/tmp/autodist_tpu``, a typed env-var enum with per-var defaults (reference
``const.py:55-89``), and the chief/worker role-split variables that the coordinator
propagates to remote hosts (reference ``coordinator.py:66-90``).
"""

import enum
import os

# Working directories (reference const.py:30-38 uses /tmp/autodist).
DEFAULT_WORKING_DIR = os.environ.get("AUTODIST_WORKING_DIR", "/tmp/autodist_tpu")
DEFAULT_SERIALIZATION_DIR = os.path.join(DEFAULT_WORKING_DIR, "strategies")
DEFAULT_LOG_DIR = os.path.join(DEFAULT_WORKING_DIR, "logs")
DEFAULT_TRACE_DIR = os.path.join(DEFAULT_WORKING_DIR, "traces")
DEFAULT_GRAPH_DUMP_DIR = os.path.join(DEFAULT_WORKING_DIR, "graphs")
DEFAULT_CHECKPOINT_DIR = os.path.join(DEFAULT_WORKING_DIR, "checkpoints")

# Port range for the coordination service (reference const.py:38 used 15000-16000 for
# tf.Server; here it is the jax.distributed coordinator port range).
DEFAULT_PORT_RANGE = iter(range(15000, 16000))
DEFAULT_COORDINATOR_PORT = 15000

# Mesh axis names. The reference reified data-parallel replicas as a device list
# (strategy.proto:62-68); the TPU build reifies them as named mesh axes.
MESH_AXIS_DATA = "data"          # data parallelism (batch dim)
MESH_AXIS_REDUCE = "reduce"      # weight-update/PS sharding axis (ZeRO-style)
MESH_AXIS_MODEL = "model"        # tensor/variable partitioning axis
MESH_AXIS_SEQ = "seq"            # sequence/context parallelism axis
MESH_AXIS_EXPERT = "expert"      # expert parallelism axis
MESH_AXIS_PIPE = "pipe"          # pipeline parallelism axis

MAX_INT32 = 2**31 - 1
MAX_INT64 = 2**63 - 1


# Registry of EVERY AUTODIST_* environment flag the project reads anywhere —
# package, tests, tools, CI scripts. One line of doc per flag. graftlint's
# GL007 check parses this dict statically (an AUTODIST_* string literal not
# listed here fails lint — the typo tripwire), and
# :func:`warn_unknown_autodist_flags` enforces it at runtime for flags that
# are SET with a typo (a misspelled AUTODIST_PS_OVERLAP would otherwise
# silently leave the default on). Flags with typed defaults additionally get
# an ENV member below; test-harness-only knobs are registry-only.
KNOWN_FLAGS = {
    "AUTODIST_WORKING_DIR": "root for strategies/logs/traces/checkpoints",
    "AUTODIST_WORKER": "non-empty => this process is a worker replica",
    "AUTODIST_STRATEGY_ID": "strategy id shipped by the chief",
    "AUTODIST_MIN_LOG_LEVEL": "framework logger verbosity",
    "AUTODIST_IS_TESTING": "extra invariants under test",
    "AUTODIST_DEBUG_REMOTE": "verbose remote launch logging",
    "AUTODIST_INTERNAL_TF": "API parity no-op",
    "AUTODIST_PATCH_TF": "API parity no-op",
    "AUTODIST_COORDINATOR_ADDR": "ip:port of jax.distributed coordinator",
    "AUTODIST_COORDINATOR_PORT": "chief's coordinator port",
    "AUTODIST_NUM_PROCESSES": "multi-host process count",
    "AUTODIST_PROCESS_ID": "this process's rank",
    "AUTODIST_PS_ADDR": "async-PS transport host:port",
    "AUTODIST_PS_OVERLAP": "overlapped PS client (0 = serial pulls)",
    "AUTODIST_DUMP_GRAPHS": "dump jaxpr/StableHLO per build stage",
    "AUTODIST_NATIVE_TRANSPORT": "0/false disables the native send/recv lib",
    "AUTODIST_PEAK_FLOPS": "per-device peak FLOP/s override for MFU math",
    "AUTODIST_BENCHMARK_LOG_DIR": "benchmark metric file sink directory",
    "AUTODIST_TELEMETRY": "enable host span tracing + metrics registry",
    "AUTODIST_TELEMETRY_RING": "span ring-buffer capacity (spans retained)",
    "AUTODIST_TRACE_PULL": "PS worker pushes its span ring to the chief at "
                           "close (cluster trace plane)",
    "AUTODIST_WATCHDOG": "PS straggler/stall watchdog thread (0 disables)",
    "AUTODIST_WATCHDOG_SEC": "watchdog sample interval seconds (a worker "
                             "silent for 3x this is flagged stalled)",
    "AUTODIST_ZERO": "ZeRO-style weight-update sharding: 0 off (default), "
                     "1 on, N>1 on with N server-side PS apply shards",
    "AUTODIST_SERVE_ADDR": "inference-server transport host:port for "
                           "serving clients/examples",
    "AUTODIST_SERVE_MAX_BATCH": "serving decode-batch slot capacity",
    "AUTODIST_SERVE_MODE": "serving batcher discipline: 'continuous' "
                           "(decode-step admission) or 'static' (waves)",
    "AUTODIST_SERVE_QUEUE": "serving admission-queue bound; beyond it "
                            "requests are rejected, not parked",
    "AUTODIST_SERVE_TIMEOUT_S": "server-side cap (seconds) on one serving "
                                "request's completion wait",
    "AUTODIST_SERVE_REPLICAS": "fleet-router replica count: InferenceServer "
                               "replicas the router spawns/fronts",
    "AUTODIST_KV_PAGE_LEN": "paged-KV page length in tokens (0 = the dense "
                            "per-slot slab, the pre-paging behavior)",
    "AUTODIST_PREFIX_CACHE": "paged-KV shared-prefix cache: requests with a "
                             "common prompt prefix reuse prefilled pages",
    "AUTODIST_ROUTER_ADDR": "fleet-router transport host:port for serving "
                            "clients (empty = loopback, OS-picked port)",
    "AUTODIST_HEALTH": "training-health monitors: per-step on-device "
                       "numerics bundle (grad norm, update/param ratio, "
                       "NaN/Inf) + host-side loss-spike detection",
    "AUTODIST_HEALTH_ACTION": "what a health anomaly does: 'warn' (log), "
                              "'record' (flight-recorder snapshot), 'halt' "
                              "(raise HealthHalt with the state attached)",
    "AUTODIST_HEALTH_ZMAX": "loss-spike EWMA z-score threshold (a boundary "
                            "loss this many sigmas above the running mean "
                            "is an anomaly)",
    "AUTODIST_RECORDER": "flight recorder: anomalies (watchdog + health) "
                         "auto-capture trace/metrics/events snapshots",
    "AUTODIST_RECORDER_DIR": "flight-recorder snapshot root (default "
                             "<AUTODIST_WORKING_DIR>/flightrec)",
    "AUTODIST_RECORDER_KEEP": "flight-recorder ring size: latest-K snapshot "
                              "dirs kept, older ones evicted",
    "AUTODIST_RECORDER_MIN_S": "min seconds between automatic snapshots "
                               "(an anomaly storm must not write one per "
                               "step); manual `record` requests bypass it",
    "AUTODIST_PROFILE": "performance-attribution plane: per-program XLA "
                        "cost records, train.attr.* phase shares, "
                        "train.mfu/membw_util roofline gauges (implies "
                        "span recording)",
    "AUTODIST_PROFILE_DIR": "directory train() writes the per-run profile "
                            "JSON into at run end (tools/adprof.py reads "
                            "and diffs these)",
    "AUTODIST_PEAK_MEMBW": "per-device peak HBM bytes/s override for the "
                           "membw_util roofline gauge (peak-spec helper)",
    "AUTODIST_TUNE": "plan autotuner: create_distributed_session searches "
                     "the strategy x execution-knob space (predict-prune-"
                     "probe) and applies the winner",
    "AUTODIST_PLAN_CACHE": "path of the persistent plan-cache JSON file; a "
                           "warm entry applies the tuned plan with zero "
                           "probe steps (empty = no persistence)",
    "AUTODIST_TUNE_TOPK": "autotuner stage-2 budget: at most this many "
                          "stage-1 survivors are measured with real steps",
    "AUTODIST_TUNE_BUDGET": "autotuner stage-1 budget: cap on enumerated "
                            "candidates ranked by the calibrated cost model",
    "AUTODIST_PREFETCH_DEPTH": "train() input-pipeline prefetch depth: a "
                               "background producer pulls + shards this "
                               "many batches (blocks under unroll=K) ahead "
                               "of the step; 0 = synchronous feed",
    "AUTODIST_PREFETCH_WORKERS": "prefetch producer worker threads: source "
                                 "pulls stay serialized/ordered, the "
                                 "shard/stack transform parallelizes",
    "AUTODIST_METRICS_DIR": "metric-history shard directory: each registry "
                            "sample appends one JSONL line (rotation-capped "
                            "shards); also arms boundary sampling",
    "AUTODIST_METRICS_PORT": "OpenMetrics/Prometheus scrape endpoint port "
                             "(/metrics + /healthz); empty/0 = no endpoint",
    "AUTODIST_METRICS_INTERVAL_S": "min seconds between metric-history "
                                   "samples; > 0 also starts the wall-clock "
                                   "sampler thread (0 = boundary-driven "
                                   "only, 10s throttle)",
    "AUTODIST_ALERT_RULES": "alert rule source: a JSON file path or inline "
                            "JSON, overlaid on the shipped default rules; "
                            "setting it arms boundary sampling",
    "AUTODIST_ALERT_ACTION": "what a firing alert does: 'warn' (log), "
                             "'record' (flight-recorder snapshot), 'halt' "
                             "(raise AlertHalt out of the sampling loop), "
                             "'recover' (roll back to the last good snapshot "
                             "and resume, bounded by AUTODIST_RECOVER_MAX)",
    "AUTODIST_EVICT_AFTER_S": "auto-eviction: a worker the PS watchdog sees "
                              "silent for this many seconds is retired from "
                              "the staleness gate (its parked RPCs fail "
                              "typed, live workers resume); 0/unset = "
                              "detect-and-warn only",
    "AUTODIST_WORKER_FAILURE": "coordinator policy for a nonzero worker "
                               "exit: 'halt' (fail-fast chief kill, the "
                               "reference behavior) or 'respawn' (relaunch "
                               "with bounded exponential backoff, up to "
                               "AUTODIST_RECOVER_MAX times per worker)",
    "AUTODIST_RECOVER_MAX": "recovery attempt budget: rollback attempts "
                            "under action=recover / respawns per worker "
                            "before escalating to the existing halt",
    "AUTODIST_WIRE_RETRIES": "PS transport retry budget: transient connect "
                             "refusals/resets on IDEMPOTENT opcodes retry "
                             "this many times with jittered exponential "
                             "backoff before surfacing",
    "AUTODIST_WIRE_BACKOFF_S": "base seconds of the wire retry backoff "
                               "(doubles per attempt, jittered, capped)",
    "AUTODIST_FAULTS": "deterministic fault-injection spec for the chaos "
                       "tests/bench (testing/faults.py grammar: "
                       "'worker_crash@step=3,worker=1;nan_grads@step=5'); "
                       "empty = disarmed",
    "AUTODIST_SANITIZE": "runtime concurrency sanitizer (testing/"
                         "sanitizer.py): comma-set of 'locks' (lock-order "
                         "graph + dynamic deadlock-cycle detection), 'waits' "
                         "(unbounded/lock-holding waits), 'threads' "
                         "(non-daemon thread-leak fence); empty = disarmed "
                         "(san_lock() returns bare primitives)",
    "AUTODIST_REQTRACE": "request-trace plane: per-process ring of serving "
                         "request lifecycle records (received/queued/"
                         "admitted/prefill/decode/shed/replayed/finished) "
                         "keyed by rid, pullable fleet-wide via the "
                         "`reqtrace` opcode (tools/adtrace.py)",
    "AUTODIST_REQTRACE_RING": "request-trace ring capacity (lifecycle "
                              "records retained per process)",
    "AUTODIST_MEM_BUDGET": "per-device memory budget override in BYTES for "
                           "the memory plane (async-PS optimizer rule, "
                           "autotune OOM pre-flight, pressure fallback) when "
                           "the backend reports no allocator limit; 0/unset "
                           "= the warned 8 GiB default",
    "AUTODIST_MEM_PRESSURE": "memory-pressure ratio (bytes_in_use/"
                             "bytes_limit, or live/budget on statless "
                             "backends) past which the mem_pressure rule "
                             "fires and paged-KV admission holds back "
                             "reservable pages; default 0.92",
    "AUTODIST_WIRE_DTYPE": "quantized PS gradient push: 'fp16', 'bf16' or "
                           "'int8' compresses eligible gradient leaves on "
                           "the wire (error feedback keeps convergence); "
                           "empty/'off' = exact fp32 push. The autotuner's "
                           "wire_dtype knob overrides when a tuned plan is "
                           "applied",
    "AUTODIST_COMPRESS_MIN_BYTES": "wire-compression size floor: gradient "
                                   "leaves smaller than this (and all "
                                   "vectors/scalars) bypass quantization "
                                   "and push exact",
    "AUTODIST_SPARSE_PUSH": "sparse top-k PS push: gradients of params the "
                            "plan marks row-sparse (Parallax embeddings) "
                            "ship as (row indices, touched rows) frames "
                            "with server-side scatter-apply; '0' forces "
                            "dense pushes",
    # Test/CI harness knobs (read by tests, tools/ and ci.sh, not the package).
    "AUTODIST_MATRIX_PROCS": "strategy-matrix process count (tests)",
    "AUTODIST_MATRIX_SINGLE": "strategy-matrix single-process leg (tests)",
    "AUTODIST_MATRIX_CKPT_DIR": "strategy-matrix checkpoint dir (tests)",
    "AUTODIST_DRYRUN_MULTIPROCESS": "skip real-process dryrun legs",
    "AUTODIST_CI_SERIAL": "ci.sh: single-process pytest instead of shards",
    "AUTODIST_SSH_SHIM_LOG": "docker/ssh_shim call-log path (dist tests)",
}


def warn_unknown_autodist_flags():
    """Warn (once per process) about AUTODIST_* env vars that are not in
    :data:`KNOWN_FLAGS` — a typo'd flag silently becomes a no-op otherwise.
    Called at package import; returns the unknown names for tests."""
    unknown = sorted(k for k in os.environ
                     if k.startswith("AUTODIST_") and k not in KNOWN_FLAGS)
    if unknown:
        from autodist_tpu.utils import logging
        logging.warning(
            "Unknown AUTODIST_* environment variable(s): %s — not a "
            "recognized flag (typo? see autodist_tpu/const.py KNOWN_FLAGS "
            "for the registry)", ", ".join(unknown))
    return unknown


# Defaults for the ENV enum below. Kept outside the enum body: members whose values
# compare equal would silently become enum *aliases* (all reading the first member's
# env var), so each member's value is its own name.
_ENV_DEFAULTS = {
    "AUTODIST_WORKER": "",                 # non-empty => this process is a worker
    "AUTODIST_STRATEGY_ID": "",            # strategy id shipped by the chief
    "AUTODIST_MIN_LOG_LEVEL": "INFO",
    "AUTODIST_IS_TESTING": False,          # extra invariants under test
    "AUTODIST_DEBUG_REMOTE": False,        # verbose remote launch logging
    "AUTODIST_INTERNAL_TF": False,         # kept for API parity (no-op on TPU)
    "AUTODIST_PATCH_TF": False,            # kept for API parity (no-op on TPU)
    "SYS_DATA_PATH": "",
    "SYS_RESOURCE_PATH": "",
    # TPU-native additions: multi-host bootstrap (replaces tf.Server membership).
    "AUTODIST_COORDINATOR_ADDR": "",       # "ip:port" of jax.distributed coordinator
    "AUTODIST_COORDINATOR_PORT": DEFAULT_COORDINATOR_PORT,  # chief's coordinator port
    "AUTODIST_NUM_PROCESSES": 1,
    "AUTODIST_PROCESS_ID": 0,
    # Async-PS transport address ("host:port"); set by the chief's coordinator
    # for worker processes when the strategy requests a non-synchronous regime.
    "AUTODIST_PS_ADDR": "",
    # Overlapped PS client: stream the next parameter pull on a second socket
    # while the gradient push / gate round-trips run (default on; "0" forces
    # the serial pull-then-push client for debugging).
    "AUTODIST_PS_OVERLAP": True,
    # Dump jaxpr/StableHLO per build stage (reference graph visualizer parity).
    "AUTODIST_DUMP_GRAPHS": False,
    # Native C send/recv plane for the PS transport ("0"/"false" disables;
    # the zero-copy Python plane is used either way on pooled hot paths).
    "AUTODIST_NATIVE_TRANSPORT": True,
    # Per-device peak FLOP/s override for MFU reporting (utils/flops.py).
    "AUTODIST_PEAK_FLOPS": "",
    # Directory for benchmark metric files (utils/benchmark_logger.py).
    "AUTODIST_BENCHMARK_LOG_DIR": "",
    # Host-side telemetry (autodist_tpu/telemetry): span recording + registry
    # mirroring on/off, and the span ring buffer's capacity.
    "AUTODIST_TELEMETRY": False,
    "AUTODIST_TELEMETRY_RING": 65536,
    # Cluster trace plane: a remote PS worker deposits its span ring on the
    # chief when it closes (telemetry must also be enabled for there to be
    # spans to push).
    "AUTODIST_TRACE_PULL": False,
    # PS-server straggler/stall watchdog: samples per-worker last-seen ages
    # and staleness lags, flags anomalies into the metrics registry, warns
    # (rate-limited) naming the slow worker. On by default — one bounded-wait
    # thread per server, a handful of dict reads per interval.
    "AUTODIST_WATCHDOG": True,
    "AUTODIST_WATCHDOG_SEC": 10.0,
    # ZeRO-style cross-replica weight-update sharding (arXiv 2004.13336):
    # 0 = off (replicate the optimizer update, today's default), 1 = on
    # (collective path shards opt state + update over the data-parallel axes;
    # async-PS chiefs apply over the default shard count), N > 1 = on with N
    # concurrent server-side PS apply shards. See DistributedRunner(zero=...).
    "AUTODIST_ZERO": 0,
    # Serving plane (autodist_tpu/serving): transport address for clients,
    # decode-batch slot capacity, batching discipline, admission-queue bound,
    # and the server-side completion-wait cap. ServeConfig.from_env() reads
    # these; constructor arguments override.
    "AUTODIST_SERVE_ADDR": "",
    "AUTODIST_SERVE_MAX_BATCH": 8,
    "AUTODIST_SERVE_MODE": "continuous",
    "AUTODIST_SERVE_QUEUE": 256,
    "AUTODIST_SERVE_TIMEOUT_S": 120.0,
    # Fleet serving (autodist_tpu/serving/router.py + serving/paged.py):
    # replica count the router fronts, paged-KV page length in tokens
    # (0 keeps the dense per-slot slab), the shared-prefix page cache
    # toggle, and the router's own transport address. ServeConfig.from_env()
    # reads the KV knobs; Router reads the fleet knobs.
    "AUTODIST_SERVE_REPLICAS": 2,
    "AUTODIST_KV_PAGE_LEN": 0,
    "AUTODIST_PREFIX_CACHE": True,
    "AUTODIST_ROUTER_ADDR": "",
    # Training-health plane (autodist_tpu/telemetry/health.py): per-step
    # on-device numerics bundle + host-side loss-spike detection, and the
    # policy an anomaly triggers. Off by default — the step body stays
    # byte-identical to the unmonitored program.
    "AUTODIST_HEALTH": False,
    "AUTODIST_HEALTH_ACTION": "warn",
    "AUTODIST_HEALTH_ZMAX": 6.0,
    # Flight recorder (autodist_tpu/telemetry/recorder.py): bounded
    # latest-K ring of self-contained anomaly snapshot dirs (merged cluster
    # trace + metrics/events + env manifest). AUTODIST_RECORDER=1 arms the
    # automatic triggers (watchdog + health anomalies); the `record` wire
    # opcode and FlightRecorder.record() work either way.
    "AUTODIST_RECORDER": False,
    "AUTODIST_RECORDER_DIR": "",
    "AUTODIST_RECORDER_KEEP": 8,
    "AUTODIST_RECORDER_MIN_S": 30.0,
    # Performance-attribution plane (autodist_tpu/telemetry/profiling.py):
    # static per-program cost extraction + phase-share/MFU gauges + the
    # per-run profile store. Off by default; enabling implies span
    # recording (attribution joins span durations). AUTODIST_PEAK_MEMBW
    # pairs with AUTODIST_PEAK_FLOPS as the peak-spec overrides.
    "AUTODIST_PROFILE": False,
    "AUTODIST_PROFILE_DIR": "",
    "AUTODIST_PEAK_MEMBW": "",
    # Plan autotuner (autodist_tpu/strategy/autotune.py): predict-prune-probe
    # search over the strategy x {unroll, zero, accumulation, overlap} space,
    # ranked by the calibrated cost model (telemetry/costmodel.py) and
    # settled by a few real steps for the top-k survivors; the winner
    # persists in the plan-cache file so later launches of the same
    # (model, topology, version) skip the search entirely.
    "AUTODIST_TUNE": False,
    "AUTODIST_PLAN_CACHE": "",
    "AUTODIST_TUNE_TOPK": 3,
    "AUTODIST_TUNE_BUDGET": 32,
    # Input-data plane (autodist_tpu/data/prefetch.py): async sharded
    # prefetch behind train()/device_prefetch. DEPTH is the bounded queue
    # of batches (blocks under unroll=K) the background producer keeps
    # pre-sharded ahead of the step (0 = the synchronous feed, the
    # previous behavior); WORKERS parallelizes the shard/stack transform
    # stage (loader pulls always stay serialized and ordered).
    "AUTODIST_PREFETCH_DEPTH": 0,
    "AUTODIST_PREFETCH_WORKERS": 1,
    # Fleet metrics plane (autodist_tpu/telemetry/{history,openmetrics,
    # alerts}.py): on-disk metric history, the Prometheus-format scrape
    # endpoint, and declarative SLO/drift alert rules evaluated on every
    # history sample. All off by default; any of METRICS_DIR /
    # METRICS_INTERVAL_S / ALERT_RULES arms the boundary sampler.
    "AUTODIST_METRICS_DIR": "",
    "AUTODIST_METRICS_PORT": "",
    "AUTODIST_METRICS_INTERVAL_S": 0.0,
    "AUTODIST_ALERT_RULES": "",
    "AUTODIST_ALERT_ACTION": "warn",
    # Recovery plane (autodist_tpu/parallel/recovery.py): close the
    # detect->act loop. EVICT_AFTER_S arms watchdog auto-eviction (0 = the
    # previous warn-only behavior); WORKER_FAILURE picks the coordinator's
    # reaction to a dead worker (the reference could only fail-fast);
    # RECOVER_MAX bounds rollback/respawn attempts before escalating to
    # halt; the WIRE pair tunes the transport's idempotent-op retry; FAULTS
    # arms the deterministic chaos harness (testing/faults.py).
    "AUTODIST_EVICT_AFTER_S": 0.0,
    "AUTODIST_WORKER_FAILURE": "halt",
    "AUTODIST_RECOVER_MAX": 3,
    "AUTODIST_WIRE_RETRIES": 2,
    "AUTODIST_WIRE_BACKOFF_S": 0.2,
    "AUTODIST_FAULTS": "",
    # Runtime concurrency sanitizer (autodist_tpu/testing/sanitizer.py):
    # comma-set of modes ('locks', 'waits', 'threads'). Disarmed (the
    # default) the san_lock()/san_rlock()/san_condition()/san_event()
    # factories return bare threading primitives — hot-path cost is one
    # module-global check at CREATION time, zero per acquire.
    "AUTODIST_SANITIZE": "",
    # Request-trace plane (autodist_tpu/telemetry/reqtrace.py): bounded
    # per-process ring of serving request lifecycle records keyed by rid.
    # Off by default — the disarmed cost on every mark site is one module
    # attribute read (the spans.py contract, gated by
    # bench.py --reqtrace-overhead).
    "AUTODIST_REQTRACE": False,
    "AUTODIST_REQTRACE_RING": 4096,
    # Wire-compression plane (parallel/synchronization.WirePushCompressor):
    # quantized gradient pushes with error feedback plus sparse top-k pushes
    # for row-sparse params. WIRE_DTYPE empty = exact pushes (the tuned
    # plan's wire_dtype knob, when applied, takes precedence); the size
    # floor keeps small leaves exact where scale bytes + host quantize cost
    # would exceed the wire saving; SPARSE_PUSH defaults on because it is
    # lossless (it only changes framing, never values).
    "AUTODIST_WIRE_DTYPE": "",
    "AUTODIST_COMPRESS_MIN_BYTES": 65536,
    "AUTODIST_SPARSE_PUSH": True,
    # HBM memory plane (telemetry/memplane.py): the budget override only
    # matters where the backend reports no allocator limit (CPU/sim — the
    # default is warned once), and the pressure threshold drives both the
    # shipped mem_pressure alert rule and the paged-KV admission holdback.
    "AUTODIST_MEM_BUDGET": 0,
    "AUTODIST_MEM_PRESSURE": 0.92,
}

class ENV(enum.Enum):
    """Typed environment variables with defaults (reference const.py:55-89)."""

    AUTODIST_WORKER = "AUTODIST_WORKER"
    AUTODIST_STRATEGY_ID = "AUTODIST_STRATEGY_ID"
    AUTODIST_MIN_LOG_LEVEL = "AUTODIST_MIN_LOG_LEVEL"
    AUTODIST_IS_TESTING = "AUTODIST_IS_TESTING"
    AUTODIST_DEBUG_REMOTE = "AUTODIST_DEBUG_REMOTE"
    AUTODIST_INTERNAL_TF = "AUTODIST_INTERNAL_TF"
    AUTODIST_PATCH_TF = "AUTODIST_PATCH_TF"
    SYS_DATA_PATH = "SYS_DATA_PATH"
    SYS_RESOURCE_PATH = "SYS_RESOURCE_PATH"
    AUTODIST_COORDINATOR_ADDR = "AUTODIST_COORDINATOR_ADDR"
    AUTODIST_COORDINATOR_PORT = "AUTODIST_COORDINATOR_PORT"
    AUTODIST_NUM_PROCESSES = "AUTODIST_NUM_PROCESSES"
    AUTODIST_PROCESS_ID = "AUTODIST_PROCESS_ID"
    AUTODIST_PS_ADDR = "AUTODIST_PS_ADDR"
    AUTODIST_PS_OVERLAP = "AUTODIST_PS_OVERLAP"
    AUTODIST_DUMP_GRAPHS = "AUTODIST_DUMP_GRAPHS"
    AUTODIST_NATIVE_TRANSPORT = "AUTODIST_NATIVE_TRANSPORT"
    AUTODIST_PEAK_FLOPS = "AUTODIST_PEAK_FLOPS"
    AUTODIST_BENCHMARK_LOG_DIR = "AUTODIST_BENCHMARK_LOG_DIR"
    AUTODIST_TELEMETRY = "AUTODIST_TELEMETRY"
    AUTODIST_TELEMETRY_RING = "AUTODIST_TELEMETRY_RING"
    AUTODIST_TRACE_PULL = "AUTODIST_TRACE_PULL"
    AUTODIST_WATCHDOG = "AUTODIST_WATCHDOG"
    AUTODIST_WATCHDOG_SEC = "AUTODIST_WATCHDOG_SEC"
    AUTODIST_ZERO = "AUTODIST_ZERO"
    AUTODIST_SERVE_ADDR = "AUTODIST_SERVE_ADDR"
    AUTODIST_SERVE_MAX_BATCH = "AUTODIST_SERVE_MAX_BATCH"
    AUTODIST_SERVE_MODE = "AUTODIST_SERVE_MODE"
    AUTODIST_SERVE_QUEUE = "AUTODIST_SERVE_QUEUE"
    AUTODIST_SERVE_TIMEOUT_S = "AUTODIST_SERVE_TIMEOUT_S"
    AUTODIST_SERVE_REPLICAS = "AUTODIST_SERVE_REPLICAS"
    AUTODIST_KV_PAGE_LEN = "AUTODIST_KV_PAGE_LEN"
    AUTODIST_PREFIX_CACHE = "AUTODIST_PREFIX_CACHE"
    AUTODIST_ROUTER_ADDR = "AUTODIST_ROUTER_ADDR"
    AUTODIST_HEALTH = "AUTODIST_HEALTH"
    AUTODIST_HEALTH_ACTION = "AUTODIST_HEALTH_ACTION"
    AUTODIST_HEALTH_ZMAX = "AUTODIST_HEALTH_ZMAX"
    AUTODIST_RECORDER = "AUTODIST_RECORDER"
    AUTODIST_RECORDER_DIR = "AUTODIST_RECORDER_DIR"
    AUTODIST_RECORDER_KEEP = "AUTODIST_RECORDER_KEEP"
    AUTODIST_RECORDER_MIN_S = "AUTODIST_RECORDER_MIN_S"
    AUTODIST_PROFILE = "AUTODIST_PROFILE"
    AUTODIST_PROFILE_DIR = "AUTODIST_PROFILE_DIR"
    AUTODIST_PEAK_MEMBW = "AUTODIST_PEAK_MEMBW"
    AUTODIST_TUNE = "AUTODIST_TUNE"
    AUTODIST_PLAN_CACHE = "AUTODIST_PLAN_CACHE"
    AUTODIST_TUNE_TOPK = "AUTODIST_TUNE_TOPK"
    AUTODIST_TUNE_BUDGET = "AUTODIST_TUNE_BUDGET"
    AUTODIST_PREFETCH_DEPTH = "AUTODIST_PREFETCH_DEPTH"
    AUTODIST_PREFETCH_WORKERS = "AUTODIST_PREFETCH_WORKERS"
    AUTODIST_METRICS_DIR = "AUTODIST_METRICS_DIR"
    AUTODIST_METRICS_PORT = "AUTODIST_METRICS_PORT"
    AUTODIST_METRICS_INTERVAL_S = "AUTODIST_METRICS_INTERVAL_S"
    AUTODIST_ALERT_RULES = "AUTODIST_ALERT_RULES"
    AUTODIST_ALERT_ACTION = "AUTODIST_ALERT_ACTION"
    AUTODIST_EVICT_AFTER_S = "AUTODIST_EVICT_AFTER_S"
    AUTODIST_WORKER_FAILURE = "AUTODIST_WORKER_FAILURE"
    AUTODIST_RECOVER_MAX = "AUTODIST_RECOVER_MAX"
    AUTODIST_WIRE_RETRIES = "AUTODIST_WIRE_RETRIES"
    AUTODIST_WIRE_BACKOFF_S = "AUTODIST_WIRE_BACKOFF_S"
    AUTODIST_FAULTS = "AUTODIST_FAULTS"
    AUTODIST_SANITIZE = "AUTODIST_SANITIZE"
    AUTODIST_REQTRACE = "AUTODIST_REQTRACE"
    AUTODIST_REQTRACE_RING = "AUTODIST_REQTRACE_RING"
    AUTODIST_WIRE_DTYPE = "AUTODIST_WIRE_DTYPE"
    AUTODIST_COMPRESS_MIN_BYTES = "AUTODIST_COMPRESS_MIN_BYTES"
    AUTODIST_SPARSE_PUSH = "AUTODIST_SPARSE_PUSH"
    AUTODIST_MEM_BUDGET = "AUTODIST_MEM_BUDGET"
    AUTODIST_MEM_PRESSURE = "AUTODIST_MEM_PRESSURE"

    @property
    def val(self):
        """Return the env value, parsed to the default's type when set."""
        raw = os.environ.get(self.name)
        default = _ENV_DEFAULTS[self.name]
        if raw is None:
            return default
        if isinstance(default, bool):
            return raw.strip().lower() not in ("", "0", "false", "no", "off")
        if isinstance(default, int):
            return int(raw)
        if isinstance(default, float):
            return float(raw)
        return raw


# Every typed ENV flag must be registered (GL007/warn_unknown parse/scan
# KNOWN_FLAGS, not _ENV_DEFAULTS — an unregistered member would make its own
# uses fail lint).
_unregistered = [k for k in _ENV_DEFAULTS
                 if k.startswith("AUTODIST_") and k not in KNOWN_FLAGS]
assert not _unregistered, f"ENV flags missing from KNOWN_FLAGS: {_unregistered}"


def is_worker() -> bool:
    """True when this process was launched by the coordinator as a worker replica."""
    return bool(ENV.AUTODIST_WORKER.val)


def is_chief_process() -> bool:
    return not is_worker()
