"""Gradient accumulation: k micro-batches, one update — value-exact vs one big batch.

The reference had no accumulation (its effective batch was replicas x feed); this is
a beyond-reference feature, so the correctness bar is self-imposed: for mean-reduced
losses the accumulated update must equal the full-batch update exactly (equal-sized
micro-batches make the mean of synced micro-gradients the full-batch gradient).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.strategy import AllReduce, Parallax, PartitionedPS, PS
from shardmap_compat import requires_shard_map

BATCH = 32


def _dense_data(seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(BATCH, 4).astype(np.float32),
            "y": rng.randn(BATCH, 1).astype(np.float32)}


def _dense_loss(p, b):
    pred = b["x"] @ p["w"] + p["b"]
    return jnp.mean((b["y"] - pred) ** 2)


def _dense_params():
    rng = np.random.RandomState(7)
    return {"w": rng.randn(4, 1).astype(np.float32),
            "b": np.zeros((1,), np.float32)}


def _run_steps(strategy, accum, n_steps=3, optimizer=None, seed=0):
    ad = AutoDist(strategy_builder=strategy)
    runner = ad.create_distributed_session(
        _dense_loss, _dense_params(), optimizer or optax.sgd(0.1),
        example_batch=_dense_data(), accumulation_steps=accum)
    state = runner.init(_dense_params())
    losses = []
    for i in range(n_steps):
        state, loss = runner.run(state, _dense_data(seed + i))
        losses.append(float(loss))
    return jax.device_get(runner.logical_params(state)), losses


@pytest.mark.parametrize("strategy_cls", [AllReduce, PS, PartitionedPS])
def test_accumulated_update_matches_full_batch(strategy_cls):
    params_full, losses_full = _run_steps(strategy_cls(), accum=1)
    params_acc, losses_acc = _run_steps(strategy_cls(), accum=4)
    for k in params_full:
        np.testing.assert_allclose(params_acc[k], params_full[k],
                                   rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(losses_acc, losses_full, rtol=2e-6, atol=2e-6)


def test_accumulation_with_adam_matches():
    params_full, _ = _run_steps(AllReduce(), accum=1, optimizer=optax.adam(1e-2))
    params_acc, _ = _run_steps(AllReduce(), accum=2, optimizer=optax.adam(1e-2))
    for k in params_full:
        np.testing.assert_allclose(params_acc[k], params_full[k],
                                   rtol=2e-6, atol=2e-6)


@requires_shard_map
def test_sparse_wire_accumulation_matches():
    """Parallax routes the embedding over the sparse wire path inside the scan."""
    rng = np.random.RandomState(3)
    params = {"emb": rng.randn(61, 8).astype(np.float32),
              "w": rng.randn(8, 1).astype(np.float32)}
    batch = {"idx": rng.randint(0, 61, (BATCH,)),
             "y": rng.randn(BATCH, 1).astype(np.float32)}

    def loss_fn(p, b):
        rows = jnp.take(p["emb"], b["idx"], axis=0)
        return jnp.mean((b["y"] - rows @ p["w"]) ** 2)

    def run(accum):
        ad = AutoDist(strategy_builder=Parallax())
        runner = ad.create_distributed_session(
            loss_fn, params, optax.sgd(0.1), example_batch=batch,
            accumulation_steps=accum)
        state = runner.init(params)
        for _ in range(2):
            state, _ = runner.run(state, batch)
        return jax.device_get(runner.logical_params(state))

    full, acc = run(1), run(4)
    for k in full:
        np.testing.assert_allclose(acc[k], full[k], rtol=2e-6, atol=2e-6)


@requires_shard_map
def test_compressed_accumulation_converges():
    """EF state threads through the micro scan (not value-exact by design)."""
    ad = AutoDist(strategy_builder=AllReduce(compressor="HorovodCompressorEF"))
    runner = ad.create_distributed_session(
        _dense_loss, _dense_params(), optax.sgd(0.05),
        example_batch=_dense_data(), accumulation_steps=4)
    state = runner.init(_dense_params())
    first = last = None
    for i in range(20):
        state, loss = runner.run(state, _dense_data())
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first * 0.5


def test_fetches_see_logical_batch():
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(
        _dense_loss, _dense_params(), optax.sgd(0.1),
        example_batch=_dense_data(), accumulation_steps=4)
    state = runner.init(_dense_params())
    batch = _dense_data()
    preds = lambda p, b: b["x"] @ p["w"] + p["b"]  # noqa: E731
    expected = jax.device_get(preds(
        {k: jnp.asarray(v) for k, v in _dense_params().items()},
        {k: jnp.asarray(v) for k, v in batch.items()}))
    state, (loss, fetched) = runner.run(state, batch, fetches=preds)
    assert fetched.shape == (BATCH, 1)
    np.testing.assert_allclose(jax.device_get(fetched), expected, rtol=1e-5, atol=1e-5)


def test_aux_shapes_match_accum1():
    """Scalar aux averages across micros; per-example aux folds back to [B]."""
    def loss_with_aux(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        per_ex = ((b["y"] - pred) ** 2)[:, 0]
        return jnp.mean(per_ex), {"mean_abs": jnp.mean(jnp.abs(per_ex)),
                                  "per_example": per_ex}

    def run(accum):
        ad = AutoDist(strategy_builder=AllReduce())
        runner = ad.create_distributed_session(
            loss_with_aux, _dense_params(), optax.sgd(0.1),
            example_batch=_dense_data(), has_aux=True, accumulation_steps=accum)
        state = runner.init(_dense_params())
        _, (loss, aux) = runner.run(state, _dense_data())
        return jax.device_get(aux)

    a1, a4 = run(1), run(4)
    assert a4["per_example"].shape == a1["per_example"].shape == (BATCH,)
    np.testing.assert_allclose(a4["per_example"], a1["per_example"],
                               rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(a4["mean_abs"], a1["mean_abs"], rtol=2e-6, atol=2e-6)


def test_non_batch_leaves_stay_whole():
    """Auxiliary leaves (per-class weights, small constants) must not be
    micro-sliced: only leaves at the global batch size scan."""
    cw = np.array([1.0, 2.0, 3.0, 4.0], np.float32)

    def loss_fn(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        return jnp.mean((b["y"] - pred) ** 2) * jnp.sum(b["cw"])

    def run(accum):
        ad = AutoDist(strategy_builder=AllReduce())
        batch = dict(_dense_data(), cw=cw, three=np.ones((3,), np.float32))
        runner = ad.create_distributed_session(
            loss_fn, _dense_params(), optax.sgd(0.01), example_batch=batch,
            accumulation_steps=accum)
        state = runner.init(_dense_params())
        state, loss = runner.run(state, batch)
        return float(loss), jax.device_get(runner.logical_params(state))

    (l1, p1), (l4, p4) = run(1), run(4)
    assert l1 == pytest.approx(l4, rel=1e-6)
    for k in p1:
        np.testing.assert_allclose(p4[k], p1[k], rtol=2e-6, atol=2e-6)


def test_vector_aux_averages_not_concats():
    """A fixed-size vector aux (not per-example) keeps its shape under accum."""
    def loss_with_aux(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        err = (b["y"] - pred)[:, 0]
        return jnp.mean(err ** 2), jnp.stack([jnp.mean(err), jnp.max(err),
                                              jnp.min(err)])

    def run(accum):
        ad = AutoDist(strategy_builder=AllReduce())
        runner = ad.create_distributed_session(
            loss_with_aux, _dense_params(), optax.sgd(0.1),
            example_batch=_dense_data(), has_aux=True, accumulation_steps=accum)
        state = runner.init(_dense_params())
        _, (loss, aux) = runner.run(state, _dense_data())
        return jax.device_get(aux)

    a1, a4 = run(1), run(4)
    assert a1.shape == a4.shape == (3,)
    # Mean-of-micro-means equals the full mean for equal micro sizes.
    np.testing.assert_allclose(a4[0], a1[0], rtol=2e-6, atol=2e-6)


def test_longer_aux_leaf_is_not_mistaken_for_batch():
    """A sampled-softmax-style auxiliary leaf LONGER than the batch (and itself
    divisible by accum*dp) must never be silently micro-split in place of the
    true batch: two splittable dims is an explicit ambiguity error, and
    batch_size= resolves it to a value-exact accumulation (the long leaf stays
    whole in every micro-step)."""
    rng = np.random.RandomState(11)
    neg = rng.randn(64, 1).astype(np.float32)  # longer than BATCH=32

    def loss_fn(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        # Every example is scored against ALL negatives every micro-step; if
        # b["neg"] were micro-sliced the penalty term would change value.
        penalty = jnp.mean((pred[:, None, :] - b["neg"][None, :, :]) ** 2)
        return jnp.mean((b["y"] - pred) ** 2) + 0.1 * penalty

    def run(accum, batch_size=None):
        ad = AutoDist(strategy_builder=AllReduce())
        batch = dict(_dense_data(), neg=neg)
        runner = ad.create_distributed_session(
            loss_fn, _dense_params(), optax.sgd(0.05), example_batch=batch,
            accumulation_steps=accum, batch_size=batch_size)
        state = runner.init(_dense_params())
        state, loss = runner.run(state, batch)
        return float(loss), jax.device_get(runner.logical_params(state))

    with pytest.raises(ValueError, match="[Aa]mbiguous"):
        run(2)  # both 32 and 64 are splittable: refuse to guess

    (l1, p1), (l2, p2) = run(1, batch_size=BATCH), run(2, batch_size=BATCH)
    assert l1 == pytest.approx(l2, rel=1e-6)
    for k in p1:
        np.testing.assert_allclose(p2[k], p1[k], rtol=2e-6, atol=2e-6)


def test_ambiguous_batch_dim_raises_and_batch_size_resolves():
    """Two equally-common, equally-splittable leading dims: refuse to guess;
    an explicit batch_size= disambiguates."""
    rng = np.random.RandomState(5)
    batch = {"x": rng.randn(BATCH, 4).astype(np.float32),
             "neg": rng.randn(2 * BATCH, 4).astype(np.float32)}

    def loss_fn(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        return jnp.mean(pred ** 2) + jnp.mean((b["neg"] @ p["w"]) ** 2)

    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(
        loss_fn, _dense_params(), optax.sgd(0.05), example_batch=batch,
        accumulation_steps=2)
    state = runner.init(_dense_params())
    with pytest.raises(ValueError, match="[Aa]mbiguous"):
        runner.run(state, batch)

    ad2 = AutoDist(strategy_builder=AllReduce())
    runner2 = ad2.create_distributed_session(
        loss_fn, _dense_params(), optax.sgd(0.05), example_batch=batch,
        accumulation_steps=2, batch_size=BATCH)
    state2 = runner2.init(_dense_params())
    state2, loss = runner2.run(state2, batch)
    assert np.isfinite(float(loss))


def test_splittable_outlier_does_not_hijack_indivisible_batch():
    """When the true (modal) batch dim is NOT divisible by accum*dp but an
    auxiliary leaf is, the aux leaf must not be silently micro-split in the
    batch's place: the inference refuses and names both dims."""
    rng = np.random.RandomState(13)
    batch = {"x": rng.randn(24, 4).astype(np.float32),   # 24 % (2*8) != 0
             "y": rng.randn(24, 1).astype(np.float32),
             "neg": rng.randn(32, 1).astype(np.float32)}  # 32 % 16 == 0

    def loss_fn(p, b):
        pred = b["x"] @ p["w"] + p["b"]
        pen = jnp.mean((pred[:, None, :] - b["neg"][None, :, :]) ** 2)
        return jnp.mean((b["y"] - pred) ** 2) + 0.1 * pen

    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(
        loss_fn, _dense_params(), optax.sgd(0.05), example_batch=batch,
        accumulation_steps=2)
    state = runner.init(_dense_params())
    with pytest.raises(ValueError, match="most common leading dim"):
        runner.run(state, batch)


def test_indivisible_batch_raises():
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(
        _dense_loss, _dense_params(), optax.sgd(0.1),
        example_batch=_dense_data(), accumulation_steps=3)
    state = runner.init(_dense_params())
    with pytest.raises(ValueError, match="accumulation_steps"):
        runner.run(state, _dense_data())  # 32 splits by dp=8 but not by 3*8


def test_run_many_composes_with_accumulation_bit_exact():
    """In-window canary for the fused multi-step path (the full suite in
    tests/test_unrolled.py sorts past the tier-1 time budget): run_many over
    an accumulating runner must be BIT-identical to the sequential steps —
    the scan is a dispatch transform, not a numeric one."""
    def run(fused):
        ad = AutoDist(strategy_builder=AllReduce())
        runner = ad.create_distributed_session(
            _dense_loss, _dense_params(), optax.adam(1e-2),
            example_batch=_dense_data(), accumulation_steps=2)
        state = runner.init(_dense_params())
        batches = [_dense_data(seed=i) for i in range(3)]
        if fused:
            state, losses = runner.run_many(state, batches)
            losses = list(jax.device_get(losses))
        else:
            losses = []
            for b in batches:
                state, loss = runner.run(state, b)
                losses.append(jax.device_get(loss))
        return jax.device_get(runner.logical_params(state)), losses

    p_seq, l_seq = run(fused=False)
    p_fused, l_fused = run(fused=True)
    np.testing.assert_array_equal(np.stack(l_fused), np.stack(l_seq))
    for k in p_seq:
        np.testing.assert_array_equal(p_fused[k], p_seq[k])


def test_async_regime_rejects_accumulation():
    ad = AutoDist(strategy_builder=PS(sync=False))
    with pytest.raises(ValueError, match="synchronous"):
        ad.create_distributed_session(
            _dense_loss, _dense_params(), optax.sgd(0.1),
            example_batch=_dense_data(), accumulation_steps=2)
