"""Host transport for the async/bounded-stale parameter service.

The reference's non-synchronous PS regimes spanned worker *processes*: each
re-executed user script pushed gradients to PS-device accumulators over TF's
grpc session plane and the chief-side token queues gated staleness
(``ps_synchronizer.py:387-458``, ``:556-633``). The TPU-native async design
keeps the regimes host-driven (``parallel/staleness.py``); this module puts the
chief-owned :class:`ParameterService` + :class:`StalenessController` behind a
small TCP transport so workers in OTHER processes (launched by the Coordinator)
pull parameters and push gradients exactly like the reference's PS plane:

- :class:`PSServer` — runs on the chief next to its AsyncPSRunner; each request
  is handled on its own thread so a blocking ``start_step`` gate (the token
  queue) does not stall other workers.
- :class:`RemotePSWorker` — a worker process's handle: ``step(batch)`` gates on
  the chief's staleness bound, pulls the current parameters, computes local
  gradients on its own devices, and pushes them back.

Wire format: length-prefixed TYPED messages (``parallel/wire.py`` — tag-based
scalars/containers + dtype/shape-headed raw tensor bytes). Nothing on the
socket is ever unpickled, so a hostile peer gets no code execution — the same
property the reference's protobuf-over-grpc plane had (its servers were
unauthenticated but typed). The SPMD data plane is untouched — this is the
host-side control/parameter plane that has no XLA equivalent.

The bytes-on-the-wire hot path is native (``native/transport.cc``, built
lazily like the data loader): one writev per message and a single-buffer
receive, syscalls made with the GIL released — measured 1.9x the Python
socket path at 8 MB gradient messages. The Python fallback speaks the same
framing, so endpoints mix freely; sockets carrying a timeout always use the
Python path to keep timeout semantics.
"""

import os
import socket
import socketserver
import struct
import threading
from typing import Any, Optional, Tuple

import jax
import numpy as np

from autodist_tpu.parallel import wire
from autodist_tpu.utils import logging

PyTree = Any

_HDR = struct.Struct("!Q")

# ---------------------------------------------------------------- native plane
# The bytes-on-the-wire hot path compiles to native/transport.cc (writev send,
# one-buffer recv, GIL released during the syscalls) — the reference's PS plane
# was likewise native (TF's C++ grpc, SURVEY.md §2.4). The Python fallback
# below speaks the identical framing, so mixed endpoints interoperate.
_TR_LIB = None
_TR_FAILED = False
_TR_LOCK = threading.Lock()


def _native_transport():
    global _TR_LIB, _TR_FAILED
    if _TR_LIB is not None or _TR_FAILED:
        return _TR_LIB
    with _TR_LOCK:
        if _TR_LIB is not None or _TR_FAILED:
            return _TR_LIB
        import ctypes

        from autodist_tpu.utils.native_build import build_native_lib
        if os.environ.get("AUTODIST_NATIVE_TRANSPORT", "1") in ("0", "false"):
            _TR_FAILED = True
            return None
        src = os.path.join(os.path.dirname(__file__), "native", "transport.cc")
        lib = build_native_lib(src, "transport")
        if lib is None:
            _TR_FAILED = True
            return None
        lib.tr_send.restype = ctypes.c_int
        lib.tr_send.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64]
        lib.tr_recv.restype = ctypes.c_int64
        lib.tr_recv.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_void_p)]
        lib.tr_free.restype = None
        lib.tr_free.argtypes = [ctypes.c_void_p]
        lib.tr_last_errno.restype = ctypes.c_int
        lib.tr_last_errno.argtypes = []
        _TR_LIB = lib
        return _TR_LIB


def _native_error(lib, what: str) -> ConnectionError:
    """ConnectionError carrying the native layer's errno (the C functions
    collapse failures to -1; tr_last_errno() preserves the diagnostic the
    Python fallback's OSError would have shown)."""
    err = lib.tr_last_errno()
    if err == 0:
        return ConnectionError(f"PS transport {what}: connection closed by peer")
    return ConnectionError(
        f"PS transport {what} failed (errno {err}: {os.strerror(err)})")


def _send_msg(sock: socket.socket, obj) -> int:
    """Send one framed message; returns the payload byte count (for the
    client's wire accounting)."""
    return _send_payload(sock, wire.encode(obj))


def _send_payload(sock: socket.socket, payload: bytes) -> int:
    """Send an already-encoded payload with framing (the server pre-encodes
    replies so an encode failure can be reported instead of dropping the
    connection)."""
    # Native path only for plain blocking sockets: a socket timeout must keep
    # Python's timeout semantics, which raw-fd syscalls would bypass.
    lib = _native_transport() if sock.gettimeout() is None else None
    if lib is not None:
        while True:
            rc = lib.tr_send(sock.fileno(), payload, len(payload))
            if rc == 0:
                return len(payload)
            if rc == -2:
                # Signal before any byte moved: the ctypes-call boundary has
                # run pending Python signal handlers (KeyboardInterrupt raises
                # here); otherwise retry the send.
                continue
            raise _native_error(lib, "send")
    sock.sendall(_HDR.pack(len(payload)) + payload)
    return len(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("PS transport connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket):
    """Receive one framed message; returns ``(obj, payload_bytes)``."""
    lib = _native_transport() if sock.gettimeout() is None else None
    if lib is not None:
        import ctypes
        out = ctypes.c_void_p()
        while True:
            n = lib.tr_recv(sock.fileno(), ctypes.byref(out))
            if n != -2:  # -2 = signal at a message boundary -> handlers ran; retry
                break
        if n < 0:
            raise _native_error(lib, "recv")
        try:
            # Zero-copy view over the malloc'd buffer; wire.decode copies
            # tensor data out, so freeing right after is safe.
            view = memoryview((ctypes.c_char * n).from_address(out.value or 0))
            return wire.decode(view), n
        finally:
            lib.tr_free(out)
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    return wire.decode(_recv_exact(sock, n)), n


def _to_host(tree: PyTree) -> PyTree:
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)


class PSServer:
    """Serve a chief AsyncPSRunner's service + controller to remote workers.

    ``host`` defaults to loopback; pass the coordinator address for real
    multi-node runs. The wire is typed (no unpickling — a hostile peer gets
    data parsing, not code execution), but the protocol is unauthenticated
    like the reference's tf.Servers, so binding wider than the cluster's
    trust domain is still the caller's explicit choice."""

    def __init__(self, runner, host: str = "127.0.0.1", port: int = 0,
                 listen_sock: Optional[socket.socket] = None):
        """``listen_sock``: an already-bound listening socket to adopt — the
        launcher binds it BEFORE shipping the address to workers, so the port is
        reserved rather than guessed (no bind race at init time)."""
        if runner.service is None:
            raise RuntimeError("Call runner.init(params) before serving")
        self._runner = runner
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # The worker id this connection drives (from its gate or
                # register messages) + the slot generation it observed:
                # needed to free the gate if the worker dies mid-step, and to
                # make that retire a no-op if a replacement has re-registered
                # the slot since (a stale socket's death must not retire the
                # live occupant).
                self.worker_id = None
                self.worker_gen = 0
                controller = outer._runner.controller
                try:
                    while True:
                        msg, _ = _recv_msg(self.request)
                        reply = outer._dispatch(msg)
                        is_protocol = isinstance(msg, tuple) and bool(msg)
                        op = msg[0] if is_protocol else "<malformed>"
                        try:
                            payload = wire.encode(reply)
                        except wire.WireError as e:
                            # OUR reply is unencodable (e.g. the user's params
                            # tree contains an unregistered pytree node) —
                            # a server-side limitation, not a hostile peer:
                            # tell the worker instead of dropping it.
                            logging.warning(
                                "PS transport: reply to %r is not "
                                "wire-encodable (%s)", op, e)
                            payload = wire.encode((
                                "error", "WireError",
                                f"server reply to {op!r} is not "
                                f"wire-encodable: {e}"))
                        # The generation token rides in the dispatch reply,
                        # read inside the controller's own critical section —
                        # a separate generation() read here could race a
                        # concurrent re-registration and adopt the REPLACEMENT
                        # occupant's token (whose retire would then kill the
                        # live worker when this connection dies).
                        if op in ("start_step", "finish_step") \
                                and reply[0] == "ok":
                            # Capture ONCE, at the connection's first bind to
                            # this worker id. Refreshing on every message would
                            # let a zombie connection that sends one more gate
                            # message AFTER a replacement re-registered the
                            # slot adopt the new generation.
                            if self.worker_id != msg[1]:
                                self.worker_id = msg[1]
                                self.worker_gen = reply[1]
                        elif op == "register" and reply[0] == "ok":
                            # register DOES refresh: this connection's own
                            # registration bumped the slot's generation, so the
                            # old token is stale by construction.
                            # Covers a replacement that registers and dies
                            # before its first step (and worker_id=None
                            # allocations, whose id only the reply knows).
                            self.worker_id = reply[1]
                            self.worker_gen = reply[2]
                        _send_payload(self.request, payload)
                except wire.WireError as e:
                    # Malformed/out-of-vocabulary bytes (a broken or hostile
                    # peer): drop the connection. Decoding allocates data only
                    # — nothing on the socket can execute — so the worst such
                    # a peer achieves is its own disconnect.
                    logging.warning("PS transport: dropping connection with "
                                    "malformed payload (%s)", e)
                    if self.worker_id is not None:
                        controller.retire(self.worker_id,
                                          generation=self.worker_gen)
                except (ConnectionError, OSError):
                    # A vanished worker must not freeze the staleness gate for
                    # everyone else (its step count would pin min(steps) forever).
                    if self.worker_id is not None:
                        logging.warning(
                            "PS worker %s disconnected; retiring it from the "
                            "staleness gate", self.worker_id)
                        controller.retire(self.worker_id,
                                          generation=self.worker_gen)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        if listen_sock is not None:
            self._server = Server(listen_sock.getsockname(), Handler,
                                  bind_and_activate=False)
            self._server.socket.close()
            self._server.socket = listen_sock
            self._server.server_activate()
        else:
            self._server = Server((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        logging.info("PSServer listening on %s:%d", *self._server.server_address)

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def _dispatch(self, msg):
        # The wire codec's vocabulary is wider than the protocol's: a peer
        # can legally encode a bare dict/int/None, which would raise at
        # msg[0] OUTSIDE the per-op try below and skip the gate retire.
        if not isinstance(msg, tuple) or not msg \
                or not isinstance(msg[0], str):
            return ("error", "PSClientError",
                    f"malformed protocol message: expected (op, ...) tuple, "
                    f"got {type(msg).__name__}")
        op = msg[0]
        r = self._runner
        try:
            if op == "start_step":
                _, worker_id, timeout = msg
                gen = r.controller.start_step(worker_id, timeout)
                return ("ok", gen)
            if op == "read":
                params, ef_state, version = r.service.read()
                return ("ok", _to_host(params), _to_host(ef_state), version)
            if op == "read_if_newer":
                params, ef_state, version = r.service.read_if_newer(msg[1])
                if params is None:  # not modified: version-only reply, no tree
                    return ("ok", None, None, version)
                return ("ok", _to_host(params), _to_host(ef_state), version)
            if op == "apply":
                version = r.service.apply(msg[1])
                return ("ok", version)
            if op == "finish_step":
                gen = r.controller.finish_step(msg[1])
                return ("ok", gen)
            if op == "register":
                # Through add_worker, not the bare controller: the chief-side
                # runner's num_workers / handle table must track the gate.
                # with_generation captures the retire token atomically with
                # the registration (see register_with_generation).
                worker, gen = r.add_worker(msg[1], with_generation=True)
                return ("ok", worker.worker_id, gen)
            if op == "version":
                return ("ok", r.service.version)
            return ("error", "PSClientError", f"unknown op {op!r}")
        except Exception as e:  # ship the failure to the worker, keep serving
            return ("error", type(e).__name__, str(e))

    def close(self):
        self._server.shutdown()
        self._server.server_close()


class PSClientError(RuntimeError):
    """A server-side failure reported over the transport."""


class _PSClient:
    def __init__(self, address, connect_timeout: float = 60.0):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host, int(port))
        # The chief serves only after its runner.init(); a worker process that
        # starts faster retries until the server is up.
        import time
        deadline = time.monotonic() + connect_timeout
        while True:
            try:
                self._sock = socket.create_connection(address, timeout=10)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        self._sock.settimeout(None)
        self._lock = threading.Lock()
        # Wire accounting (payload bytes, both directions) — lets callers and
        # tests measure what a protocol change (e.g. read_if_newer) saves.
        self.bytes_sent = 0
        self.bytes_received = 0

    def call(self, *msg):
        with self._lock:
            self.bytes_sent += _send_msg(self._sock, msg)
            reply, nbytes = _recv_msg(self._sock)
            self.bytes_received += nbytes
        if reply[0] != "ok":
            # Re-raise gate timeouts under their real type so callers written
            # against the AsyncWorker contract (`except StalenessTimeout`) keep
            # working across the transport.
            kind, detail = reply[1], reply[2]
            if kind == "StalenessTimeout":
                from autodist_tpu.parallel.staleness import StalenessTimeout
                raise StalenessTimeout(detail)
            raise PSClientError(f"{kind}: {detail}")
        return reply[1:]

    def close(self):
        self._sock.close()


class RemotePSWorker:
    """A worker process's handle onto the chief's parameter service.

    Mirrors :class:`~autodist_tpu.parallel.staleness.AsyncWorker` but with the
    service/controller calls crossing the transport; gradient computation runs on
    this process's own devices through the runner's jitted grad fn.
    """

    def __init__(self, address, runner, worker_id: int):
        self._client = _PSClient(address)
        self._runner = runner
        self.worker_id = worker_id
        self.steps_completed = 0
        self.last_version_read = -1
        # Register up front: idempotent for a live slot (the server keeps its
        # count), and for a RETIRED slot — e.g. a Coordinator-relaunched worker
        # reusing its AUTODIST_PROCESS_ID — it re-admits the slot so stepping
        # is gated again. Without this, a relaunched process would step a
        # retired slot the live workers no longer wait for, silently making
        # the staleness bound one-sided.
        self.register()
        # Cache of the last pulled (params, ef_state): the conditional pull in
        # step() reuses it when the service version is unchanged, so a worker
        # whose gate opened with no intervening applies ships no parameter
        # bytes (the reference's proxy-variable cache served the same purpose,
        # proxy_variable.py:74-114).
        self._cached_pull = None

    @property
    def wire_bytes(self) -> Tuple[int, int]:
        """(sent, received) payload bytes over this worker's transport."""
        return self._client.bytes_sent, self._client.bytes_received

    def register(self) -> int:
        """(Re-)admit this worker to the chief's staleness gate — the elastic
        rejoin for a replacement process after the original disconnected and
        was retired. Seeds the gate at the slowest live worker's step count;
        returns the admitted id (may differ when ``worker_id`` was None)."""
        wid = self._client.call("register", self.worker_id)[0]
        self.worker_id = wid
        return wid

    def warmup(self, batch: PyTree) -> None:
        """Compile this worker's gradient program without applying an update
        (pull params, compile, discard) — keeps process-startup compile time out
        of the staleness-gated stepping. The pull seeds the conditional-read
        cache, so the first step() skips re-downloading an unchanged tree."""
        params, ef_state, _ = self._pull()
        sharded = self._runner.shard_batch(batch)
        with self._runner.mesh:
            jax.block_until_ready(self._runner.grad_fn(params, sharded, ef_state)[0])

    def _pull(self):
        """Current (params, ef_state, version), skipping the parameter payload
        when the service hasn't advanced past the cached version."""
        if self._cached_pull is None:
            params, ef_state, version = self._client.call("read")
        else:
            params, ef_state, version = self._client.call(
                "read_if_newer", self.last_version_read)
            if params is None:  # not modified: the cached tree IS current
                params, ef_state = self._cached_pull
        self._cached_pull = (params, ef_state)
        self.last_version_read = version
        return params, ef_state, version

    def step(self, batch: PyTree, timeout: Optional[float] = None):
        r = self._runner
        self._client.call("start_step", self.worker_id, timeout)
        params, ef_state, _ = self._pull()
        sharded = r.shard_batch(batch)
        with r.mesh:
            grads, loss, aux, _ef = r.grad_fn(params, sharded, ef_state)
        self._client.call("apply", _to_host(grads))
        self._client.call("finish_step", self.worker_id)
        self.steps_completed += 1
        if r.has_aux:
            return loss, aux
        return loss

    @property
    def version(self) -> int:
        return self._client.call("version")[0]

    def close(self):
        self._client.close()
