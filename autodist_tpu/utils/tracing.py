"""Tracing and compilation-stage snapshots.

Parity with reference §5.1:

- Chrome-trace timelines (``runner.py:66-75``, ``/tmp/autodist/traces/...``) map to
  :func:`trace`, a ``jax.profiler.trace`` wrapper writing a Perfetto/TensorBoard
  trace under the working dir's ``traces/``.
- Graph-evolution snapshots (``utils/visualization_util.py:24-36`` wrote the graph
  at each transform stage) map to :func:`dump_stage`: the jaxpr and StableHLO text
  of the train step at each compilation stage, written under ``graphs/<tag>/``.
"""

import contextlib
import os
import time
from typing import Optional

from autodist_tpu import const
from autodist_tpu.utils import logging


@contextlib.contextmanager
def trace(name: str = "trace", trace_dir: Optional[str] = None):
    """Profile the enclosed steps: ``with tracing.trace(): runner.run(...)``.

    Produces a Perfetto-compatible trace viewable in TensorBoard or ui.perfetto.dev
    (the chrome-trace timeline counterpart)."""
    import jax
    trace_dir = trace_dir or os.path.join(const.DEFAULT_TRACE_DIR,
                                          f"{name}_{int(time.time())}")
    os.makedirs(trace_dir, exist_ok=True)
    logging.info("Writing profiler trace to %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        yield trace_dir


def dump_stage(tag: str, stage: str, fn, *example_args,
               dump_dir: Optional[str] = None) -> Optional[str]:
    """Write the jaxpr + StableHLO of ``fn(*example_args)`` for one build stage.

    Stages mirror the reference's four snapshots (0-original, 1-after-partition,
    2-after-in-graph, 3-transformed): here typically "0-original" (user loss fn)
    and "1-distributed" (the sharded train step).
    """
    import jax
    dump_dir = dump_dir or os.path.join(const.DEFAULT_GRAPH_DUMP_DIR, tag)
    os.makedirs(dump_dir, exist_ok=True)
    base = os.path.join(dump_dir, stage)
    try:
        jaxpr = jax.make_jaxpr(fn)(*example_args)
        with open(base + ".jaxpr.txt", "w") as f:
            f.write(str(jaxpr))
        lowered = jax.jit(fn).lower(*example_args)
        with open(base + ".stablehlo.txt", "w") as f:
            f.write(lowered.as_text())
        logging.debug("Dumped %s stage %s", tag, stage)
        return base
    except Exception as e:  # diagnostics must never break training
        logging.warning("Stage dump %s/%s failed: %s", tag, stage, e)
        return None
