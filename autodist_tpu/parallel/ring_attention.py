"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability beyond the reference (which has no sequence parallelism,
SURVEY.md §5.7): the sequence dimension is sharded over the ``seq`` axis; each
device holds its local Q/K/V shard, and K/V shards rotate around the ring via
``jax.lax.ppermute`` while every device accumulates its queries' attention with the
online-softmax merge (:mod:`autodist_tpu.ops.blockwise_attention`). After
``seq_size`` steps every query has attended to every key, with peak activation
memory O(L/seq_size) per device and communication overlapping compute the XLA way
(each ppermute is independent of the current step's FLOPs).

Causality is preserved globally: each ring step knows the global offset of the K/V
shard it currently holds and masks accordingly.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.ops.blockwise_attention import (blockwise_attention_with_carry as _bw_carry, finalize as _bw_finalize)


# Measured crossover on a TPU v5e chip (b=4 h=8 d=64 bf16, 512 blocks, causal
# carry step): pallas flash vs pure-JAX blockwise per local step — 0.68x at
# L_local=2048, 1.43x at 4096, 1.85x at 8192. Short shards are grid/DMA-overhead
# bound, exactly like the plain kernel's 128-block regime.
_FLASH_MIN_LOCAL_LEN = 3072


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   causal: bool = True, axis_name: str = const.MESH_AXIS_SEQ,
                   block_size: int = 512, impl: str = "auto") -> jax.Array:
    """Attention with K/V rotating around the ``axis_name`` ring.

    Must run inside a ``shard_map`` (or any SPMD context) where ``axis_name`` is a
    mesh axis and the inputs' sequence dimension (axis 1 of [B, L_local, H, D]) is
    the local shard of the global sequence in ring order: device r holds global
    positions [r*L_local, (r+1)*L_local).

    ``impl='flash'`` runs the local step as the pallas carry kernel — the same
    online-softmax state the kernel already carries across k-blocks is the ring
    merge state — with a two-ring-pass custom VJP (dk/dv accumulators rotate
    with their K/V shard). ``impl='blockwise'`` keeps the pure-JAX scan
    (XLA-differentiated), the reference semantics for the kernel. The default
    ``'auto'`` picks flash for long local shards (the long-context regime ring
    attention exists for) and blockwise below the measured crossover.
    """
    if impl == "auto":
        # The crossover was measured at 512 blocks; a caller-tuned smaller block
        # puts the kernel in its overhead-bound regime, so auto only picks flash
        # when both the shard length and the block size are in its winning
        # regime — block_size is always honored as given.
        if q.shape[1] >= _FLASH_MIN_LOCAL_LEN and block_size >= 512:
            return _ring_flash(q, k, v, causal, axis_name, block_size)
        impl = "blockwise"
    if impl == "flash":
        return _ring_flash(q, k, v, causal, axis_name, block_size)
    if impl != "blockwise":
        raise ValueError(f"Unknown ring attention impl {impl!r}")
    _, l_local, _, _ = q.shape

    def attend(src, kv, carry):
        k_cur, v_cur = kv
        return kv, _bw_carry(q, k_cur, v_cur, carry, causal=causal,
                             block_size=block_size,
                             q_offset=jax.lax.axis_index(axis_name) * l_local,
                             k_offset=src * l_local)

    b, lq, h, d = q.shape
    carry0 = (jnp.zeros((b, h, lq, d), jnp.float32),
              jnp.full((b, h, lq), -1e30, jnp.float32),
              jnp.zeros((b, h, lq), jnp.float32))
    _, acc = _ring_loop(axis_name, causal, (k, v), carry0, attend)

    out = _bw_finalize(*acc)                         # [B, H, Lq, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# ------------------------------------------------------------- ring scheduling

def _ring_perm(ring_size):
    return [(i, (i + 1) % ring_size) for i in range(ring_size)]


def _ring_loop(axis_name, causal, rotating, carry, body):
    """The ring schedule shared by forward and backward passes.

    ``rotating`` (a pytree) circulates via ppermute each step; ``body(src,
    rotating, carry) -> (rotating, carry)`` runs the local work against the shard
    that originated on device ``src``. Under a causal mask, steps whose shard is
    strictly future are skipped entirely (identity on both trees) — but rotation
    still happens, keeping the ring in lockstep. The final step does not rotate
    (the backward separately sends its traveling accumulators the last hop
    home)."""
    ring_size = jax.lax.axis_size(axis_name)
    my_index = jax.lax.axis_index(axis_name)
    perm = _ring_perm(ring_size)
    for step in range(ring_size):
        src = (my_index - step) % ring_size

        def run(operands):
            return body(src, *operands)

        if step == 0 or not causal:
            # Step 0 is always our own shard (src == my_index): never skipped.
            rotating, carry = run((rotating, carry))
        else:
            rotating, carry = jax.lax.cond(src <= my_index, run,
                                           lambda operands: operands,
                                           (rotating, carry))
        if step != ring_size - 1:
            rotating = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis_name, perm), rotating)
    return rotating, carry


# --------------------------------------------------------------- flash local step

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, causal, axis_name, block_size):
    out, _ = _ring_flash_fwd(q, k, v, causal, axis_name, block_size)
    return out


def _ring_flash_fwd(q, k, v, causal, axis_name, block_size):
    from autodist_tpu.ops.flash_attention import flash_attention_with_carry

    b, l_local, h, d = q.shape
    q_offset = jax.lax.axis_index(axis_name) * l_local

    def attend(src, kv, carry):
        k_cur, v_cur = kv
        return kv, flash_attention_with_carry(
            q, k_cur, v_cur, carry, causal=causal, q_offset=q_offset,
            k_offset=src * l_local, q_block=block_size, k_block=block_size)

    carry0 = (jnp.zeros((b, h, l_local, d), jnp.float32),
              jnp.full((b, h, l_local), -1e30, jnp.float32),
              jnp.zeros((b, h, l_local), jnp.float32))
    _, (acc, m, l) = _ring_loop(axis_name, causal, (k, v), carry0, attend)

    out = _bw_finalize(acc, m, l)                       # [B, H, Lq, D] f32
    lse = m + jnp.log(jnp.maximum(l, 1e-30))            # [B, H, Lq]
    out_t = out.transpose(0, 2, 1, 3).astype(q.dtype)   # [B, Lq, H, D]
    return out_t, (q, k, v, out_t, lse)


def _ring_flash_bwd(causal, axis_name, block_size, residuals, g):
    """Second ring pass: each device accumulates dQ for its queries locally while
    (dK, dV) accumulators travel WITH their K/V shard — after a full circle
    (ring_size rotations) each shard's gradient arrives back at its home device
    complete."""
    import jax.experimental.pallas as pl

    from autodist_tpu.ops.flash_attention import (_flash_backward_kv,
                                                  _use_interpret,
                                                  prepare_backward_q_side)

    q, k, v, o, lse = residuals
    b, l_local, h, d = q.shape
    q_offset = jax.lax.axis_index(axis_name) * l_local

    # Query-side layout (transposes, dO padding, D_i row term) is shard-pair
    # independent: prepare once, reuse every ring step.
    qf, dof, dd, bq, n_q = prepare_backward_q_side(q, o, g, block_size)
    lse_flat = lse.reshape(b * h, l_local)
    if n_q * bq - l_local:
        lse_flat = jnp.pad(lse_flat, ((0, 0), (0, n_q * bq - l_local)))
    lse_plane = lse_flat.reshape(b * h, n_q, bq)
    interpret = _use_interpret()

    def bwd_step(src, kv_and_grads, dq):
        k_cur, v_cur, dk_acc, dv_acc = kv_and_grads
        # out_dtype=f32: per-step contributions accumulate unquantized (a bf16
        # round-trip per ring step would add noise proportional to ring size).
        dqc, dkc, dvc = _flash_backward_kv(
            qf, dof, lse_plane, dd, k_cur, v_cur, causal, bq, n_q, block_size,
            interpret, q.shape, q_offset=q_offset, k_offset=src * l_local,
            out_dtype=jnp.float32)
        return (k_cur, v_cur, dk_acc + dkc, dv_acc + dvc), dq + dqc

    rotating0 = (k, v, jnp.zeros(k.shape, jnp.float32),
                 jnp.zeros(v.shape, jnp.float32))
    (_, _, dk_acc, dv_acc), dq = _ring_loop(
        axis_name, causal, rotating0, jnp.zeros(q.shape, jnp.float32), bwd_step)

    # The accumulators are one hop short of home after ring_size-1 rotations;
    # send just them the final hop (the K/V shards themselves are done).
    perm = _ring_perm(jax.lax.axis_size(axis_name))
    dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)

    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def make_ring_attention_fn(mesh: Mesh, *, causal: bool = True,
                           block_size: int = 512, impl: str = "auto"):
    """Wrap :func:`ring_attention` in a shard_map over (data, seq): batch shards on
    the data axes, sequence on ``seq``, heads/depth replicated."""
    spec = P((const.MESH_AXIS_DATA, const.MESH_AXIS_REDUCE),
             const.MESH_AXIS_SEQ, None, None)

    def fn(q, k, v):
        return ring_attention(q, k, v, causal=causal, block_size=block_size,
                              impl=impl)

    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)
