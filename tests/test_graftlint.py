"""graftlint (autodist_tpu.analysis) — fixture tests per check + engine.

NAMED to sort inside the tier-1 alphabetical window (after test_generate,
before test_multiprocess — the convention GL008 itself enforces). Everything
here is pure-AST: no jax, no subprocesses, sub-second.

Each GL00x check gets at least one violating and one clean fixture; the
engine gets suppression / baseline / JSON / directive-error coverage; and a
meta-test asserts the REPO ITSELF is lint-clean against the committed
baseline, so a hazard regression fails tier-1, not just ci.sh's lint stage.
"""

import importlib.util
import json
import os
import textwrap

import pytest

from autodist_tpu.analysis import core

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Fixture flag names, concatenated so GL007's literal scan (full-match on
# AUTODIST_* string constants) does not read them as unregistered real flags
# of THIS file.
GOOD_FLAG = "AUTODIST_" + "GOOD"

_cli_spec = importlib.util.spec_from_file_location(
    "graftlint_cli", os.path.join(ROOT, "tools", "graftlint.py"))
cli = importlib.util.module_from_spec(_cli_spec)
_cli_spec.loader.exec_module(cli)


def lint(tmp_path, source, relname="mod.py", checks=None, known_flags=None):
    """Lint one dedented snippet written at ``tmp_path/relname``."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    ctx = core.Context(str(tmp_path), known_flags=known_flags)
    return core.lint_paths([str(path)], root=str(tmp_path), checks=checks,
                           context=ctx)


def codes(result):
    return [f.check for f in result.findings]


# --------------------------------------------------------------------- GL001

# The PR 2 deadlock pattern (acceptance regression): a multi-device program
# dispatched inside an AsyncPSRunner._collective_lock-style critical section
# — but as a NEW, unannotated site, i.e. without the reviewed serialization
# rationale the real _collective_lock carries.
PR2_DEADLOCK = """
    import threading

    class BadRunner:
        def __init__(self, runner):
            self._collective_lock = threading.Lock()
            self._runner = runner

        def step(self, state, batch):
            with self._collective_lock:
                new_state, loss = self._runner.run(state, batch)
            return new_state, loss
"""


def test_gl001_flags_pr2_deadlock_pattern(tmp_path):
    res = lint(tmp_path, PR2_DEADLOCK, checks=["GL001"])
    assert codes(res) == ["GL001"]
    (f,) = res.findings
    assert "_collective_lock" in f.message and "run" in f.message
    assert f.scope == "BadRunner.step"


def test_gl001_clean_when_dispatch_outside_lock(tmp_path):
    res = lint(tmp_path, """
        import threading

        class GoodRunner:
            def __init__(self, runner):
                self._lock = threading.Lock()
                self._runner = runner
                self._queue = []

            def step(self, state, batch):
                with self._lock:
                    self._queue.append(batch)
                return self._runner.run(state, batch)
    """, checks=["GL001"])
    assert res.ok


def test_gl001_sees_through_local_helpers_and_jitted_names(tmp_path):
    res = lint(tmp_path, """
        import threading
        import jax

        _lock = threading.Lock()

        def _push(sock, data):
            sock.sendall(data)

        def locked_send(sock, data):
            with _lock:
                _push(sock, data)

        def locked_jit(lock, x):
            f = jax.jit(lambda y: y * 2)
            with lock:
                return f(x)
    """, checks=["GL001"])
    assert codes(res) == ["GL001", "GL001"]
    assert "via _push" in res.findings[0].message
    assert "(jitted)" in res.findings[1].message


def test_gl001_ignores_deferred_code_defined_under_lock(tmp_path):
    """A callback merely DEFINED while the lock is held runs after release —
    no held-across-dispatch hazard, no finding (GL002 likewise)."""
    res = lint(tmp_path, """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._other_lock = threading.Lock()
                self._cbs = []

            def register(self, sock):
                with self._lock:
                    def cb(data):
                        sock.sendall(data)
                        with self._other_lock:
                            pass
                    self._cbs.append(cb)
    """, checks=["GL001", "GL002"])
    assert res.ok


def test_gl001_suppression_with_reason(tmp_path):
    suppressed = PR2_DEADLOCK.replace(
        "with self._collective_lock:",
        "# graftlint: disable=GL001(serializes execution on purpose)\n"
        "            with self._collective_lock:")
    res = lint(tmp_path, suppressed, checks=["GL001"])
    assert res.ok
    [(finding, reason)] = res.suppressed
    assert finding.check == "GL001"
    assert reason == "serializes execution on purpose"


# --------------------------------------------------------------------- GL002

ABBA = """
    import threading

    class Service:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def backward(self):
            with self._b_lock:
                with self._a_lock:
                    pass
"""


def test_gl002_flags_inversion_against_declared_order(tmp_path):
    res = lint(tmp_path, "# graftlint: lock-order=_a_lock->_b_lock\n"
               + textwrap.dedent(ABBA), checks=["GL002"])
    assert codes(res) == ["GL002"]
    (f,) = res.findings
    assert f.scope == "Service.backward"
    assert "conflicting" in f.message


def test_gl002_undeclared_nesting_is_flagged(tmp_path):
    res = lint(tmp_path, ABBA, checks=["GL002"])
    # Both nestings lack a declared order (and invert each other).
    assert len(res.findings) == 2
    assert all(f.check == "GL002" for f in res.findings)


def test_gl002_clean_with_declared_consistent_order(tmp_path):
    res = lint(tmp_path, """
        # graftlint: lock-order=_write_mutex->_lock
        import threading

        class PS:
            def __init__(self):
                self._write_mutex = threading.Lock()
                self._lock = threading.Condition()

            def reset(self):
                with self._write_mutex:
                    with self._lock:
                        self._lock.notify_all()
    """, checks=["GL002"])
    assert res.ok


# --------------------------------------------------------------------- GL003

def test_gl003_flags_read_after_donation(tmp_path):
    res = lint(tmp_path, """
        import jax

        def train(state, batch):
            step = jax.jit(lambda s, b: s, donate_argnums=(0,))
            new_state = step(state, batch)
            return state
    """, checks=["GL003"])
    assert codes(res) == ["GL003"]
    assert "`state`" in res.findings[0].message


def test_gl003_sees_donor_assigned_inside_a_branch(tmp_path):
    res = lint(tmp_path, """
        import jax

        def train(state, batch, donate):
            if donate:
                step = jax.jit(lambda s, b: s, donate_argnums=(0,))
                new_state = step(state, batch)
                return state
            return state
    """, checks=["GL003"])
    assert codes(res) == ["GL003"]


def test_gl003_clean_when_result_is_used(tmp_path):
    res = lint(tmp_path, """
        import jax

        def train(state, batch):
            step = jax.jit(lambda s, b: s, donate_argnums=(0,))
            state = step(state, batch)
            return state
    """, checks=["GL003"])
    assert res.ok


# --------------------------------------------------------------------- GL004

def test_gl004_flags_host_calls_and_captured_stores(tmp_path):
    res = lint(tmp_path, """
        import time
        import jax

        class Meter:
            pass

        meter = Meter()

        @jax.jit
        def step(x):
            print("stepping", x)
            meter.last = x
            t = time.time()
            return x * 2

        @jax.jit
        def builds_locally(y):
            local = Meter()
            local.value = y      # object created under trace: fine
            return y + 1
    """, checks=["GL004"])
    msgs = [f.message for f in res.findings]
    assert codes(res).count("GL004") == 3
    assert any("`print`" in m for m in msgs)
    assert any("meter.last" in m for m in msgs)
    assert any("time.time" in m for m in msgs)
    assert not any("local.value" in m for m in msgs)


def test_gl004_clean_pure_jitted_fn(tmp_path):
    res = lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(params, batch):
            loss = jnp.mean((params - batch) ** 2)
            return loss
    """, checks=["GL004"])
    assert res.ok


# --------------------------------------------------------------------- GL005

def test_gl005_flags_unbounded_wait_in_package_code(tmp_path):
    res = lint(tmp_path, """
        import threading

        class Gate:
            def __init__(self):
                self._cond = threading.Condition()

            def wait_open(self):
                with self._cond:
                    self._cond.wait_for(lambda: True)

            def pause(self):
                with self._cond:
                    self._cond.wait(timeout=None)
    """, relname="autodist_tpu/gate.py", checks=["GL005"])
    assert codes(res) == ["GL005", "GL005"]


def test_gl005_clean_with_timeout_and_outside_package(tmp_path):
    clean = """
        import threading

        class Gate:
            def __init__(self):
                self._cond = threading.Condition()

            def wait_open(self, timeout):
                with self._cond:
                    return self._cond.wait_for(lambda: True, timeout)
    """
    assert lint(tmp_path, clean, relname="autodist_tpu/gate.py",
                checks=["GL005"]).ok
    unbounded_but_test_code = """
        import threading
        cond = threading.Condition()
        with cond:
            cond.wait_for(lambda: True)
    """
    assert lint(tmp_path, unbounded_but_test_code,
                relname="tests/helper.py", checks=["GL005"]).ok


# --------------------------------------------------------------------- GL006

def test_gl006_flags_opcode_without_dispatch_arm(tmp_path):
    res = lint(tmp_path, """
        class Client:
            def push(self, grads):
                return self._client.call("aply", grads)

            def pull(self):
                return self._client.call("read")

        def _dispatch(msg):
            op = msg[0]
            if op == "apply":
                return ("ok",)
            if op == "read":
                return ("ok", 1)
            return ("error", "unknown")
    """, checks=["GL006"])
    assert codes(res) == ["GL006"]
    assert "'aply'" in res.findings[0].message


def test_gl006_flags_asymmetric_codec_tags_and_unchecked_version(tmp_path):
    res = lint(tmp_path, """
        import struct

        _HDR = struct.Struct("!Q")
        _FRAME_VERSION = 0

        def _enc(out, obj):
            out += b"z"

        def _dec(r):
            tag = r.take(1)
            if tag == b"y":
                return 1

        def _frame_len(header):
            (word,) = _HDR.unpack(header)
            if word >> 56 != _FRAME_VERSION:
                raise ValueError(word)
            return word

        def sloppy_len(header):
            (word,) = _HDR.unpack(header)
            return word
    """, checks=["GL006"])
    msgs = " | ".join(f.message for f in res.findings)
    assert codes(res).count("GL006") == 3
    assert "b'z'" in msgs and "b'y'" in msgs and "sloppy_len" in msgs


def test_gl006_flags_serving_op_without_dispatch_arm(tmp_path):
    """Serving-transport shape: the dispatcher is a server-class METHOD and
    several server classes may share the module — a client op must match an
    arm in ANY of them, and a missing arm is flagged (the PR 7 serving wire
    gets the same exhaustiveness guarantee as the PS wire)."""
    res = lint(tmp_path, """
        class InferenceServer:
            def _dispatch(self, msg):
                op = msg[0]
                if op == "generate":
                    return ("ok",)
                if op == "stats":
                    return ("ok", {})
                return ("error", "ServeError", "unknown")

        class AdminServer:
            def _dispatch(self, msg):
                op = msg[0]
                if op == "drain":
                    return ("ok",)
                return ("error", "ServeError", "unknown")

        class ServeClient:
            def generate(self, prompt):
                return self._client.call("generate", prompt)

            def infer(self, example):
                return self._client.call("infer", example)

            def drain(self):
                return self._client.call("drain")
    """, checks=["GL006"])
    assert codes(res) == ["GL006"]
    # 'generate' and 'drain' resolve across the two dispatchers; only the
    # armless 'infer' is a finding.
    assert "'infer'" in res.findings[0].message


def test_gl006_clean_serving_protocol(tmp_path):
    """The real serving vocabulary (generate/infer/stats/status/record/ping),
    method-style dispatcher, every op armed — clean."""
    res = lint(tmp_path, """
        class InferenceServer:
            def _dispatch(self, msg):
                op = msg[0]
                if op == "generate":
                    return ("ok",)
                if op == "infer":
                    return ("ok",)
                if op == "stats":
                    return ("ok", {})
                if op == "status":
                    return ("ok", {})
                if op == "record":
                    return ("ok", "/tmp/snap")
                if op == "ping":
                    return ("ok", None)
                return ("error", "ServeError", "unknown")

        class ServeClient:
            def generate(self, prompt):
                return self._client.call("generate", prompt)

            def infer(self, example):
                return self._client.call("infer", example)

            def stats(self):
                return self._client.call("stats")[0]

            def status(self):
                return self._client.call("status")[0]

            def record(self, reason):
                return self._client.call("record", reason)[0]

            def ping(self):
                return self._client.call("ping")
    """, checks=["GL006"])
    assert res.ok


def test_gl006_clean_symmetric_protocol(tmp_path):
    res = lint(tmp_path, """
        class Client:
            def push(self, grads):
                return self._client.call("apply", grads)

        def _dispatch(msg):
            op = msg[0]
            if op == "apply":
                return ("ok",)
            return ("error", "unknown")
    """, checks=["GL006"])
    assert res.ok


# --------------------------------------------------------------------- GL007

def test_gl007_direct_env_read_in_package_and_typo_flag(tmp_path):
    res = lint(tmp_path, """
        import os

        good = os.environ.get("AUTODIST_GOOD")
        typo = os.environ.get("AUTODIST_GOOOD")
    """, relname="autodist_tpu/mod.py", checks=["GL007"],
        known_flags={GOOD_FLAG})
    # Two direct package reads + one unknown name.
    assert codes(res).count("GL007") == 3
    assert sum("unknown flag" in f.message for f in res.findings) == 1


def test_gl007_known_flag_outside_package_is_clean(tmp_path):
    res = lint(tmp_path, """
        import os

        flag = os.environ.get("AUTODIST_GOOD", "")
        env = dict(os.environ)
        env["AUTODIST_GOOD"] = "1"
    """, relname="tests/helper.py", checks=["GL007"],
        known_flags={GOOD_FLAG})
    assert res.ok


def test_known_flags_parsed_from_real_const_py():
    flags = core.Context(ROOT).known_flags()
    assert flags is not None
    assert "AUTODIST_PS_OVERLAP" in flags
    assert "AUTODIST_MATRIX_PROCS" in flags


# --------------------------------------------------------------------- GL008

def test_gl008_unmarked_subprocess_file_inside_window(tmp_path):
    res = lint(tmp_path, """
        import subprocess

        def test_spawns():
            subprocess.run(["echo", "hi"], check=True)
    """, relname="tests/test_aaa.py", checks=["GL008"])
    assert codes(res) == ["GL008"]
    assert "tier-1 window" in res.findings[0].message


def test_gl008_clean_when_marked_slow_or_after_edge(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        '[tool.pytest.ini_options]\nmarkers = ["slow: slow tests"]\n')
    marked = """
        import subprocess
        import pytest

        @pytest.mark.slow
        def test_spawns():
            subprocess.run(["echo", "hi"], check=True)
    """
    assert lint(tmp_path, marked, relname="tests/test_aaa.py",
                checks=["GL008"]).ok
    after_edge = """
        import subprocess

        def test_spawns():
            subprocess.run(["echo", "hi"], check=True)
    """
    assert lint(tmp_path, after_edge, relname="tests/test_zz_dist.py",
                checks=["GL008"]).ok


def test_gl008_detects_mp_env_harness_import_forms(tmp_path):
    res = lint(tmp_path, """
        from tests.mp_env import mp_env

        def test_cluster():
            mp_env(2)
    """, relname="tests/test_bbb.py", checks=["GL008"])
    assert codes(res) == ["GL008"]
    assert "mp_env" in res.findings[0].message


def test_gl008_bad_filename_and_unregistered_marker(tmp_path):
    res = lint(tmp_path, """
        import pytest

        @pytest.mark.slow
        def test_x():
            pass
    """, relname="tests/test_CamelCase.py", checks=["GL008"])
    msgs = " | ".join(f.message for f in res.findings)
    assert codes(res).count("GL008") == 2
    assert "does not match" in msgs and "not registered" in msgs


# ----------------------------------------------------------- engine behavior

def test_reasonless_suppression_is_a_gl000_finding(tmp_path):
    res = lint(tmp_path, """
        import threading

        _lock = threading.Lock()

        def locked_send(sock, data):
            with _lock:  # graftlint: disable=GL001
                sock.sendall(data)
    """, checks=["GL001"])
    assert sorted(codes(res)) == ["GL000", "GL001"]  # suppression rejected
    assert "no reason" in next(
        f.message for f in res.findings if f.check == "GL000")


def test_unknown_directive_is_flagged(tmp_path):
    res = lint(tmp_path, "# graftlint: disbale=GL001(oops)\nx = 1\n",
               checks=["GL001"])
    assert codes(res) == ["GL000"]


def test_syntax_error_is_reported_not_crashed(tmp_path):
    res = lint(tmp_path, "def broken(:\n", checks=["GL001"])
    assert codes(res) == ["GL000"]
    assert "does not parse" in res.findings[0].message


def test_baseline_grandfathers_old_findings_only(tmp_path):
    res = lint(tmp_path, PR2_DEADLOCK, relname="old.py", checks=["GL001"])
    assert len(res.findings) == 1
    baseline_path = tmp_path / "baseline.json"
    core.write_baseline(str(baseline_path), res.findings)
    baseline = core.load_baseline(str(baseline_path))

    # Same findings + baseline => clean, reported as baselined.
    ctx = core.Context(str(tmp_path))
    res2 = core.lint_paths([str(tmp_path / "old.py")], root=str(tmp_path),
                           baseline=baseline, checks=["GL001"], context=ctx)
    assert res2.ok and len(res2.baselined) == 1

    # A NEW violation in another file still fails.
    (tmp_path / "new.py").write_text(textwrap.dedent(PR2_DEADLOCK))
    res3 = core.lint_paths([str(tmp_path)], root=str(tmp_path),
                           baseline=baseline, checks=["GL001"], context=ctx)
    assert [f.path for f in res3.findings] == ["new.py"]

    # Fixing the old finding surfaces the stale baseline entry.
    (tmp_path / "old.py").write_text("x = 1\n")
    res4 = core.lint_paths([str(tmp_path / "old.py")], root=str(tmp_path),
                           baseline=baseline, checks=["GL001"], context=ctx)
    assert res4.ok and len(res4.stale_baseline) == 1


def test_baseline_never_grandfathers_gl000(tmp_path):
    """--write-baseline must not become a side door around the 'GL000
    cannot be suppressed' invariant: meta-findings (reasonless directives,
    parse errors) are excluded from writing AND from matching."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import threading

        _lock = threading.Lock()

        def locked_send(sock, data):
            with _lock:  # graftlint: disable=GL001
                sock.sendall(data)
    """))
    ctx = core.Context(str(tmp_path))
    res = core.lint_paths([str(bad)], root=str(tmp_path), checks=["GL001"],
                          context=ctx)
    assert sorted(codes(res)) == ["GL000", "GL001"]
    baseline_path = tmp_path / "baseline.json"
    core.write_baseline(str(baseline_path), res.findings)
    baseline = core.load_baseline(str(baseline_path))
    assert all("GL000" not in fp.split("|")[0] for fp in baseline)
    # Even a hand-edited baseline containing the GL000 fingerprint is inert.
    gl000 = next(f for f in res.findings if f.check == "GL000")
    res2 = core.lint_paths([str(bad)], root=str(tmp_path), checks=["GL001"],
                           baseline=baseline | {gl000.fingerprint},
                           context=ctx)
    assert "GL000" in codes(res2)


def test_cli_json_output_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(PR2_DEADLOCK))
    rc = cli.main(["--format", "json", "--no-baseline", "--check", "GL001",
                   str(bad)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["ok"] is False
    assert payload["findings"][0]["check"] == "GL001"

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    rc = cli.main(["--format", "json", "--no-baseline", str(good)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["ok"] is True


def test_nonexistent_path_is_an_error_not_a_green_gate(tmp_path, capsys):
    """A typo'd/renamed CI path must fail loudly — linting 0 files and
    exiting 0 would green-light every hazard class the gate exists for."""
    with pytest.raises(FileNotFoundError):
        core.lint_paths([str(tmp_path / "nope")], root=str(tmp_path),
                        context=core.Context(str(tmp_path)))
    assert cli.main([str(tmp_path / "nope_dir")]) == 2
    capsys.readouterr()


def test_cli_explain_documents_real_bug_provenance(capsys):
    assert cli.main(["--explain", "GL001"]) == 0
    out = capsys.readouterr().out
    assert "PR 2" in out and "rendezvous" in out
    assert cli.main(["--explain", "GL999"]) == 2


def test_all_eight_checks_are_registered():
    ids = set(core.all_checks())
    assert ids == {f"GL00{i}" for i in range(1, 9)}


# ------------------------------------------------------------ self-cleanness

def test_repo_is_lint_clean_against_committed_baseline(capsys):
    """The acceptance gate, in-suite: a reintroduced hazard (or a stale
    suppression/baseline edit) fails tier-1 here, not just ci.sh's lint
    stage. Runs the real CLI with the real committed baseline."""
    rc = cli.main([])
    out = capsys.readouterr().out
    assert rc == 0, f"graftlint found new findings:\n{out}"
