"""User API: the AutoDist class.

Surface parity with reference ``autodist/autodist.py``:

- ``AutoDist(resource_spec_file, strategy_builder)`` with PSLoadBalancing as the
  default builder (reference ``autodist.py:70``).
- ``scope()`` context manager around single-device model code (``:309-322``). In JAX
  nothing needs monkey patching (the reference patched optimizers/Keras inside the
  scope, ``patch.py``); the scope sets the process-default instance and marks the
  capture phase.
- ``build_strategy()`` / the chief-build-or-worker-load handshake keyed by
  ``AUTODIST_STRATEGY_ID`` (``:100-109``) — the serialized strategy is what ships to
  worker processes.
- ``create_distributed_session(...)`` -> :class:`DistributedRunner` (``:191-198``).
- ``function(...)`` -> a cached step callable (``:269-289``), the TF2-style path the
  lm1b example uses.
"""

import contextlib
from typing import Any, Callable, Optional, Sequence, Union

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.runner import DistributedRunner
from autodist_tpu.strategy.base import Strategy, StrategyBuilder, StrategyCompiler
from autodist_tpu.utils import logging

_default_autodist = None


def set_default_autodist(ad: "AutoDist"):
    global _default_autodist
    _default_autodist = ad


def get_default_autodist() -> Optional["AutoDist"]:
    return _default_autodist


class AutoDist:
    """Entry point: resource spec + strategy builder -> distributed execution."""

    def __init__(self, resource_spec_file: Union[str, ResourceSpec, None] = None,
                 strategy_builder: Union[StrategyBuilder, str, None] = None):
        """``resource_spec_file``: YAML path, inline YAML text, an already-parsed
        :class:`ResourceSpec`, or None for the local-devices default.

        ``strategy_builder``: a builder instance, or the string
        ``"autotune"`` — the first ``create_distributed_session`` then runs
        the plan autotuner (:mod:`autodist_tpu.strategy.autotune`) and
        applies the winning builder + execution knobs (warm plan-cache
        launches skip the search entirely)."""
        from autodist_tpu.strategy import PSLoadBalancing
        if isinstance(resource_spec_file, ResourceSpec):
            self._resource_spec = resource_spec_file
        else:
            self._resource_spec = ResourceSpec(resource_spec_file)
        self._autotune = False
        if isinstance(strategy_builder, str):
            if strategy_builder != "autotune":
                raise ValueError(
                    f"unknown strategy name {strategy_builder!r}; the only "
                    f"string strategy is 'autotune' (pass a StrategyBuilder "
                    f"instance otherwise)")
            self._autotune = True
            strategy_builder = None
        self._tuned_plan = None
        self._strategy_builder = strategy_builder or PSLoadBalancing()
        self._strategy: Optional[Strategy] = None
        self._compiled: Optional[Strategy] = None
        self._model_signature = None
        self._cluster = None
        self._coordinator = None
        set_default_autodist(self)

    @property
    def resource_spec(self) -> ResourceSpec:
        return self._resource_spec

    @property
    def is_chief(self) -> bool:
        """Chief/worker role split via AUTODIST_WORKER env (reference autodist.py:40-41)."""
        return not const.ENV.AUTODIST_WORKER.val

    @contextlib.contextmanager
    def scope(self):
        """Graph-capture scope (reference autodist.py:309-322). In JAX the model code
        inside needs no rewriting; the scope installs this instance as the process
        default so library code can find it."""
        prev = get_default_autodist()
        set_default_autodist(self)
        try:
            yield self
        finally:
            set_default_autodist(prev)

    # ----------------------------------------------------------------- strategy
    def build_strategy(self, model_spec: ModelSpec) -> Strategy:
        """Build (chief) or load (worker) the strategy (reference autodist.py:91-109)."""
        if self._strategy is not None:
            return self._strategy
        if self.is_chief:
            self._strategy = self._strategy_builder.build(model_spec, self._resource_spec)
            path = self._strategy.serialize()
            logging.info("Built strategy %s -> %s", self._strategy.id, path)
        else:
            strategy_id = const.ENV.AUTODIST_STRATEGY_ID.val
            if not strategy_id:
                raise RuntimeError(
                    "Worker process has no AUTODIST_STRATEGY_ID; the coordinator "
                    "must ship the chief's strategy id")
            self._strategy = Strategy.deserialize(strategy_id)
            logging.info("Loaded strategy %s (worker)", strategy_id)
        return self._strategy

    def _compile(self, model_spec: ModelSpec) -> Strategy:
        # One model per AutoDist instance, like the reference's single cached graph
        # (autodist.py:280-287): reusing a strategy built for a different model would
        # silently mis-distribute it, so that is an error.
        signature = tuple(sorted((n, p.shape) for n, p in model_spec.trainable.items()))
        if self._compiled is not None and signature != self._model_signature:
            raise RuntimeError(
                "This AutoDist instance already compiled a strategy for a different "
                "model; create a new AutoDist per model (one-model-per-instance, as "
                "in the reference)")
        if self._compiled is None:
            strategy = self.build_strategy(model_spec)
            self._compiled = StrategyCompiler(model_spec, self._resource_spec).compile(strategy)
            self._model_signature = signature
        return self._compiled

    # ------------------------------------------------------------------ session
    def _setup(self, strategy, async_mode: bool):
        """Multi-node setup on first session creation (reference autodist.py:120-128).

        Synchronous strategies: every process joins one jax.distributed SPMD
        program. Non-synchronous (async / bounded-stale PS) strategies: processes
        stay independent JAX programs joined only by the chief's parameter-service
        transport — the reference's async workers were likewise joined only by the
        grpc PS plane, never by collectives."""
        if self._cluster is not None or self._resource_spec.num_nodes <= 1:
            return
        from autodist_tpu.cluster import Cluster
        from autodist_tpu.coordinator import Coordinator
        from autodist_tpu.parallel.multihost import maybe_initialize_multihost
        self._cluster = Cluster(self._resource_spec)
        self._cluster.start()
        if self.is_chief:
            self._coordinator = Coordinator(strategy, self._cluster)
            extra_env = None
            if async_mode:
                # Reserve the PS transport port NOW (the server itself starts
                # after runner.init): binding before shipping the address means
                # workers never connect to a guessed, possibly-taken port.
                import socket as _socket
                host = self._resource_spec.chief_address
                sock = _socket.socket()
                sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
                sock.bind((host, 0))
                self._ps_listen_sock = sock
                self._ps_address = f"{host}:{sock.getsockname()[1]}"
                extra_env = {const.ENV.AUTODIST_PS_ADDR.name: self._ps_address}
            self._coordinator.launch_clients(extra_env=extra_env)
        if not async_mode:
            maybe_initialize_multihost(self._cluster)
        import atexit
        atexit.register(self._teardown)

    def _teardown(self):
        """Teardown ordering parity (reference autodist.py:178-183): coordinator
        join (bounded — an abnormal chief exit must not deadlock on workers stuck in
        a collective), then cluster terminate."""
        try:
            if self._coordinator is not None:
                self._coordinator.join(timeout=10.0)
        finally:
            session = getattr(self, "_session", None)
            if session is not None and hasattr(session, "close"):
                session.close()
            if self._cluster is not None:
                self._cluster.terminate()

    def create_distributed_session(self, loss_fn: Callable, params: Any, optimizer,
                                   example_batch: Any = None,
                                   sparse_names: Optional[Sequence[str]] = None,
                                   has_aux: bool = False,
                                   num_workers: Optional[int] = None,
                                   accumulation_steps: int = 1,
                                   batch_size: Optional[int] = None,
                                   zero: Optional[Any] = None,
                                   health: Optional[bool] = None,
                                   tune: Optional[bool] = None) -> DistributedRunner:
        """Compile the strategy for this model and return the runner
        (reference autodist.py:191-198 returned the wrapped session).

        Strategies requesting a non-synchronous PS regime (``sync=False`` or
        ``staleness>0``) return the host-driven :class:`AsyncPSRunner` instead of the
        SPMD runner — the reference switched regimes inside PSSynchronizer
        (``ps_synchronizer.py:335-458``); here the regime selects the runner.
        ``num_workers`` sizes the async worker pool. Default: one slot per
        launched process on a multi-node cluster (slot 0 = the chief's drop-in
        ``run()``; each worker process steps its own slot over the PS transport),
        or a single slot on single-node runs — an in-process phantom worker that
        never steps would deadlock the staleness gate. Pass it explicitly when
        driving multiple in-process worker handles.

        ``zero`` enables ZeRO-style weight-update sharding (default: the
        ``AUTODIST_ZERO`` flag): the synchronous runner shards optimizer state
        and the update over the data-parallel axes (reduce-scatter ->
        shard-local update -> all-gather); the async regime shards the chief's
        server-side apply over N concurrent param shards (``zero=N``). See
        docs/usage/performance.md "Weight-update sharding (ZeRO)".

        ``health`` enables the training-health monitors (default: the
        ``AUTODIST_HEALTH`` flag) on the synchronous runner: the jitted step
        additionally emits the fused numerics bundle ``train()``'s monitors
        consume at log boundaries. See docs/usage/observability.md
        "Training health monitors".

        ``tune`` runs the plan autotuner before the session is built
        (default: ``AutoDist(strategy_builder="autotune")`` or the
        ``AUTODIST_TUNE`` flag): the predict-prune-probe search
        (:func:`autodist_tpu.strategy.autotune.autotune`) picks the builder
        plus ``unroll``/``zero``/``accumulation_steps`` and this session
        applies them — explicit ``zero``/``accumulation_steps`` arguments
        win over the tuned values. The winner lands in the
        ``AUTODIST_PLAN_CACHE`` file, so a warm relaunch of the same job
        applies the tuned plan with zero search cost; the applied plan is
        recorded in the profile/flight-recorder manifests and on
        ``runner.tuned_plan`` (``train()`` adopts its ``unroll`` when none
        is passed). See docs/usage/performance.md "Plan autotuning".
        """
        self._maybe_autotune(tune, loss_fn, params, optimizer, example_batch,
                             sparse_names, has_aux)
        plan_knobs = self._tuned_plan
        if plan_knobs is not None:
            if accumulation_steps == 1:
                accumulation_steps = plan_knobs.accumulation_steps
            if zero is None and plan_knobs.zero:
                zero = plan_knobs.zero
        model_spec = self._model_spec_for(loss_fn, params, example_batch, sparse_names)
        # Builders that model memory (AutoStrategy) get the session's optimizer
        # so regime decisions use exact state bytes, not an Adam-class guess.
        observe = getattr(self._strategy_builder, "observe_optimizer", None)
        if observe is not None:
            observe(optimizer)
        strategy = self.build_strategy(model_spec)
        # Compile BEFORE multi-node setup: the plan's is_async is the single
        # source of truth for which communication plane _setup wires (pure proto
        # work — touches no backend, so it is safe pre-jax.distributed).
        compiled = self._compile(model_spec)
        from autodist_tpu.parallel.plan import ShardingPlan
        plan = ShardingPlan.from_strategy(compiled, model_spec)
        if plan.is_async and accumulation_steps > 1:
            # Before _setup: failing after Cluster.start() would leave launched
            # worker processes behind on a call that returns nothing.
            raise ValueError(
                "accumulation_steps > 1 is a synchronous-runner feature; the "
                "async/bounded-stale regime steps micro-batches as ordinary steps")
        self._setup(strategy, async_mode=plan.is_async)
        if plan.is_async:
            from autodist_tpu.parallel.staleness import AsyncPSRunner
            # Multi-node async: one worker slot per launched process (each steps
            # through the PS transport), else the documented single-slot default.
            if num_workers:
                workers = num_workers
            elif self._cluster is not None:
                workers = self._cluster.num_processes
            else:
                workers = 1
            runner = AsyncPSRunner(compiled, model_spec, loss_fn, optimizer,
                                   has_aux=has_aux, num_workers=workers, plan=plan,
                                   ps_address=getattr(self, "_ps_address", None)
                                   or (const.ENV.AUTODIST_PS_ADDR.val or None),
                                   zero=zero)
            runner._ps_listen_sock = getattr(self, "_ps_listen_sock", None)
            runner.tuned_plan = self._tuned_plan
            self._session = runner  # _teardown closes its transport endpoints
            return runner
        runner = DistributedRunner(compiled, model_spec, loss_fn, optimizer,
                                   has_aux=has_aux, plan=plan,
                                   accumulation_steps=accumulation_steps,
                                   batch_size=batch_size, zero=zero,
                                   health=health)
        runner.tuned_plan = self._tuned_plan
        return runner

    def _maybe_autotune(self, tune: Optional[bool], loss_fn, params, optimizer,
                        example_batch, sparse_names, has_aux):
        """Run the plan autotuner once per instance (before the first
        strategy build) and install the winning builder; later sessions on
        this instance reuse the already-built strategy. No-ops off the
        chief, without an example batch, or on multi-node specs (the search
        measures locally — same contract as ``tune_strategy``)."""
        if tune is None:
            tune = self._autotune or const.ENV.AUTODIST_TUNE.val
        if not tune or self._tuned_plan is not None:
            return
        if self._strategy is not None or self._compiled is not None:
            logging.warning("AutoDist: tune requested after a strategy was "
                            "already built; keeping the existing strategy")
            return
        if not self.is_chief:
            return   # workers load the chief's strategy id as usual
        import jax
        if jax.process_count() > 1:
            # A multi-process SPMD program must compile IDENTICAL step
            # programs everywhere, but only the builder travels via the
            # strategy id — a chief-tuned zero/unroll knob would diverge
            # from the workers' defaults and wedge the collectives. Tune a
            # single-process launch and ship the winning knobs explicitly.
            logging.warning(
                "AutoDist: tune=True in a multi-process SPMD program — the "
                "tuned execution knobs (zero/unroll/accumulation) cannot "
                "ship to the other processes, so the search is skipped; "
                "tune single-process and pass the winning knobs explicitly")
            return
        if example_batch is None:
            logging.warning("AutoDist: tune=True needs an example_batch to "
                            "probe candidate plans; skipping the search")
            return
        if self._resource_spec.num_nodes > 1:
            logging.warning(
                "AutoDist: tune=True on a multi-node spec — the autotuner "
                "measures on local devices only and would mis-rank "
                "cross-node plans; skipping the search (tune on a "
                "single-node spec and ship the winning builder)")
            return
        from autodist_tpu.strategy.autotune import autotune as _search
        from autodist_tpu.telemetry import profiling as _profiling
        try:
            plan = _search(loss_fn, params, optimizer, example_batch,
                           resource_spec=self._resource_spec,
                           sparse_names=sparse_names, has_aux=has_aux)
        except Exception as e:  # noqa: BLE001 — a failed search must degrade
            # Same contract as the other skip paths above: tuning is an
            # optimization, so a backend with no cost analysis (or every
            # probe failing) falls back to the default builder with a
            # warning instead of killing the launch.
            logging.warning("AutoDist: plan autotune failed (%s: %s); "
                            "keeping the default strategy builder",
                            type(e).__name__, e)
            return
        self._tuned_plan = plan
        self._strategy_builder = plan.make_builder()
        # The applied plan travels with every diagnostic artifact: profile
        # JSONs and flight-recorder manifests name which plan a run was
        # executing (cache key + knobs + predicted vs measured).
        _profiling.set_applied_plan(dict(plan.to_dict(), name=plan.name))
        logging.info("AutoDist: applying tuned plan %s (%s)", plan.name,
                     "cache hit" if plan.from_cache else
                     f"searched in {plan.search_s:.2f}s")

    def _model_spec_for(self, loss_fn, params, example_batch, sparse_names) -> ModelSpec:
        if sparse_names is not None:
            return ModelSpec(params, sparse_names=sparse_names)
        if example_batch is not None:
            return ModelSpec.from_loss_fn(loss_fn, params, example_batch)
        return ModelSpec(params)

    # ----------------------------------------------------------------- function
    def function(self, loss_fn: Callable, params: Any, optimizer,
                 example_batch: Any = None, sparse_names: Optional[Sequence[str]] = None,
                 has_aux: bool = False, accumulation_steps: int = 1,
                 batch_size: Optional[int] = None,
                 zero: Optional[Any] = None,
                 health: Optional[bool] = None,
                 tune: Optional[bool] = None) -> Callable:
        """TF2-style stepping: returns ``step(batch) -> loss`` carrying state
        internally (reference autodist.py:252-289 cached a built runner the same
        way: first call builds, later calls reuse).

        Async strategies: the ``step`` closure is one worker's loop (the reference
        ran one such loop per process); the worker pool is sized by the cluster —
        one slot per launched process, or a single slot for single-node runs (an
        in-process phantom worker that never steps would deadlock the gate)."""
        runner = self.create_distributed_session(
            loss_fn, params, optimizer, example_batch, sparse_names, has_aux,
            accumulation_steps=accumulation_steps, batch_size=batch_size,
            zero=zero, health=health, tune=tune)
        state = runner.init(params)

        def step(batch, fetches=None):
            nonlocal state
            if fetches is None:
                state, fetched = runner.run(state, batch)
            else:
                # Synchronous runners only; the async regime has no in-step
                # fetch point (its TypeError names the unsupported keyword).
                state, fetched = runner.run(state, batch, fetches=fetches)
            return fetched

        step.runner = runner
        step.get_state = lambda: state
        if not runner.plan.is_async:
            # Sync runner only: the async regime's worker-side local state is a
            # pass-through template (the chief's PS state is authoritative), so
            # an inherited evaluate would silently score untrained params.
            step.evaluate = lambda batch, fn=None: runner.evaluate(state, batch, fn)
        return step
