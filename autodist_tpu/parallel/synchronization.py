"""Gradient synchronization: the synchronizer kernels, TPU-native.

Reference counterparts:

- ``kernel/synchronization/all_reduce_synchronizer.py:102-130`` wrapped each gradient
  in ``collective_ops.all_reduce`` through a Compressor. Here the uncompressed path
  is simply the implicit psum XLA inserts for a sharded-batch ``value_and_grad``;
  the compressed path uses ``jax.shard_map`` so the cross-replica mean really rides
  the compressed (bfloat16 or low-rank) representation over ICI.
- ``kernel/synchronization/compressor.py``: ``NoneCompressor`` (:146-166),
  ``HorovodCompressor`` (:169-201, a dtype-cast codec) and ``HorovodCompressorEF``
  (:120-143, error feedback) map to NONE / BF16 / BF16_EF. ``PowerSGDCompressor``
  — which the reference drafted but left disabled (:208-284) — is implemented here
  as POWER_SGD: rank-r factorization M ~= P Q^T with one power iteration per step
  warm-started from the previous Q, QR orthogonalization, and error feedback; only
  the [m, r] and [n, r] factors cross the wire.
- Error-feedback residuals are **per data-parallel replica** (each worker keeps its
  own residual in the reference, ``compressor.py:120-143``): they are stored with a
  leading ``dp`` dimension sharded over the data axes, so in SPMD each device owns
  exactly its own residual slice.
- PS synchronizers need no explicit code here: weight-update sharding is expressed
  entirely through the plan's opt-state shardings (XLA emits the reduce-scatter /
  all-gather), replacing accumulators and token queues (``ps_synchronizer.py``).
- ZeRO weight-update sharding (``ShardingPlan.with_zero_update``, arXiv
  2004.13336) composes with everything here without code changes: the grad fn's
  outputs stay replicated-spec'd and the runner's step body reshards them at
  the constraint points, while the error-feedback residuals below were ALREADY
  ZeRO-form — a ``[dp, ...]`` leading dim sharded over the data axes, so each
  device owns exactly its 1/dp residual slice (``init_ef_state``/
  ``ef_partition_specs`` are the same treatment applied to compressor state).
"""

import dataclasses
import zlib
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.parallel import plan as plan_lib
from autodist_tpu.parallel.plan import (COMP_BF16, COMP_BF16_EF, COMP_NONE,
                                        COMP_POWER_SGD, ShardingPlan)

PyTree = Any


@dataclasses.dataclass
class EFState:
    """Per-replica error-feedback residual for BF16_EF: ``error[i]`` is replica i's
    residual (leading dim = dp size, sharded over the data axes)."""

    error: jax.Array


@dataclasses.dataclass
class PowerSGDState:
    """PowerSGD carry: per-replica EF residual plus the shared Q factor.

    ``q`` is [n, r] and identical on every replica (it is rebuilt each step from the
    pmean'd factor), so it stays replicated; warm-starting it across steps is what
    makes one power iteration per step enough (reference draft compressor.py:208-284
    kept ``rank`` + a persistent Q the same way).
    """

    error: jax.Array   # [dp, *param_shape]
    q: jax.Array       # [n, r] where n = prod(param_shape[1:])


jax.tree_util.register_dataclass(EFState, data_fields=["error"], meta_fields=[])
jax.tree_util.register_dataclass(
    PowerSGDState, data_fields=["error", "q"], meta_fields=[])

# Compressor state crosses the PS transport (read/read_if_newer replies); the
# typed wire codec reconstructs these nodes through its registry, never by
# importing names off the socket (parallel/wire.py).
from autodist_tpu.parallel.wire import register_wire_dataclass  # noqa: E402

register_wire_dataclass(EFState)
register_wire_dataclass(PowerSGDState)


_WARNED: set = set()


def _warn_once(key: str, message: str) -> None:
    """Log a knob-downgrade warning once per process (grad fns rebuild per
    runner; the user needs the diagnostic, not a log flood)."""
    if key in _WARNED:
        return
    _WARNED.add(key)
    from autodist_tpu.utils import logging
    logging.warning(message)


def mesh_dp_size(mesh: Mesh) -> int:
    """Actual data-parallel size of a mesh: product of the DP axes it carries.

    The plan's ``dp_size`` reflects the device count the strategy was *built* for;
    the runner may legally rebuild a smaller mesh when running on fewer local chips
    (``DistributedRunner._mesh_from_plan``), so anything sized per-replica must use
    the mesh the state actually lives on."""
    return int(np.prod([mesh.shape[a] for a in plan_lib.DP_AXES if a in mesh.shape]))


def _powersgd_applies(shape) -> bool:
    # Like the reference draft, only matrix-shaped (rank >= 2) tensors are
    # factorized; vectors/scalars all-reduce exactly.
    return len(shape) >= 2


def _powersgd_rank(shape, rank: int) -> int:
    m, n = shape[0], int(np.prod(shape[1:]))
    return max(1, min(rank, m, n))


# --------------------------------------------------------------------- compressors

def compress(x: jax.Array, kind: int) -> jax.Array:
    if kind in (COMP_BF16, COMP_BF16_EF):
        return x.astype(jnp.bfloat16)
    return x


def decompress(x: jax.Array, dtype) -> jax.Array:
    return x.astype(dtype)


class _SyncResult:
    """One parameter's synchronized gradient + its new compressor state. A plain
    (non-pytree) object so a tree of these keeps the parameter-tree structure."""

    __slots__ = ("synced", "state")

    def __init__(self, synced, state):
        self.synced = synced
        self.state = state


def _powersgd_sync(g: jax.Array, ef: PowerSGDState, pmean=None) -> _SyncResult:
    """One PowerSGD round inside shard_map: M = g + e; P = pmean(M Q); P_hat = QR(P);
    Q' = pmean(M^T P_hat); synced = P_hat Q'^T; e' = M - synced (local).
    ``pmean`` injects the spec-aware (possibly hierarchical) reduce — the factors
    ARE the dominant transfers, so the ICI/DCN knob must apply to them."""
    if pmean is None:
        pmean = lambda x: jax.lax.pmean(x, plan_lib.DP_AXES)  # noqa: E731
    shape = g.shape
    m, n = shape[0], int(np.prod(shape[1:]))
    err = ef.error[0]                               # this replica's residual slice
    mat = (g + err).reshape(m, n).astype(jnp.float32)
    p_fac = pmean(mat @ ef.q)                       # [m, r] on the wire
    p_hat, _ = jnp.linalg.qr(p_fac)                 # orthonormal [m, r]
    q_new = pmean(mat.T @ p_hat)                    # [n, r] on the wire
    approx = p_hat @ q_new.T                        # identical everywhere
    new_err = (mat - approx).reshape(shape).astype(g.dtype)
    synced = approx.reshape(shape).astype(g.dtype)
    return _SyncResult(synced, PowerSGDState(error=new_err[None],
                                             q=q_new.astype(ef.q.dtype)))


# ------------------------------------------------------------------ grad functions

def make_grad_fn(sharding_plan: ShardingPlan, model_spec: ModelSpec, mesh: Mesh,
                 loss_fn: Callable, has_aux: bool = False) -> Callable:
    """Build ``grad_fn(params, batch, ef_state) -> (grads, loss, aux, new_ef_state)``.

    Two lowerings:

    - **Implicit** (no compressor anywhere): plain ``value_and_grad`` of the global
      loss; the batch is sharded over the data axes, so XLA inserts the gradient
      all-reduce (and, with sharded opt state, the reduce-scatter) itself.
    - **Explicit** (a compressor somewhere, or a sparse param with a known index
      source): ``jax.shard_map`` over the data axes — each shard computes a local
      gradient, then per parameter either compresses + ``lax.pmean``s (bfloat16 /
      PowerSGD factors on the wire), or for sparse params all-gathers
      (indices, touched rows) and rebuilds the dense gradient by segment-sum — the
      reference's sparse all-gather wire path
      (``all_reduce_synchronizer.py:132-173``): for a large embedding the wire
      carries ~batch rows instead of the whole matrix. Error feedback keeps a
      per-replica residual: x = g + ef; send compress(x);
      ef' = x - decompress(compress(x)).
    """
    dp = mesh_dp_size(mesh)
    sparse_wire = sharding_plan.sparse_wire_params if dp > 1 else {}
    spec_dcn = plan_lib.strategy_pb2.AllReduceSynchronizer.DCN
    # Two-phase reduce needs both DP axes populated (inner = intra-slice tier).
    hierarchical_ok = all(mesh.shape.get(a, 1) > 1 for a in plan_lib.DP_AXES)
    requested_dcn = any(p.spec == spec_dcn
                        for p in sharding_plan.params.values())
    # A DCN (hierarchical-reduce) request is itself a reason to take the
    # explicit lowering: on the implicit path XLA owns the reduction schedule
    # and the knob would silently do nothing.
    honor_dcn = (requested_dcn and dp > 1 and hierarchical_ok
                 and sharding_plan.all_params_replicated)
    use_explicit = (sharding_plan.has_compression or bool(sparse_wire)
                    or honor_dcn)
    if requested_dcn and dp > 1 and not honor_dcn:
        msg = ("spec=DCN (hierarchical two-phase reduce) was requested but "
               "cannot be honored on this mesh/strategy (%s); gradients use a "
               "single-phase reduce" % (
                   "mesh lacks a populated inner DP axis" if not hierarchical_ok
                   else "partitioned parameters use the implicit SPMD lowering"))
        _warn_once(msg, msg)  # keyed by the full message: distinct downgrade
        # reasons in one process each get their own diagnostic

    def implicit(params, batch, ef_state):
        if has_aux:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            aux = ()
        return grads, loss, aux, ef_state

    # Which lowering a grad fn took, as an attribute: callers (and the test
    # suite's `requires_shard_map` guard — the explicit path is the one thing
    # here that needs `jax.shard_map`, absent from some jax builds) can ask
    # without re-deriving the decision.
    implicit.uses_shard_map = False

    if not use_explicit:
        return implicit

    if not sharding_plan.all_params_replicated:
        if sharding_plan.has_compression:
            raise NotImplementedError(
                "Gradient compression currently requires replicated parameters "
                "(AllReduce-family strategies); partitioned parameters with a "
                "compressor are not supported in one strategy")
        # Sparse wire rides the shard_map path, which needs every parameter
        # replicated; partitioned models keep the implicit SPMD lowering.
        from autodist_tpu.utils import logging
        logging.info("Sparse all-gather wire disabled: model has partitioned "
                     "parameters; using implicit dense synchronization")
        return implicit

    from autodist_tpu.model_spec import _path_name as name_of
    plans_by_name = dict(sharding_plan.params)

    def _pmean(x, spec: int):
        """Cross-replica mean, honoring the network-tier knob: DCN requests a
        hierarchical two-phase reduce — inner DP axis first (lay it out on ICI
        within a slice), then the outer axis (DCN across slices) — the TPU-native
        reading of the reference's NCCL/RING communication hint
        (all_reduce_synchronizer.py:102-130). AUTO/ICI is one joint reduce."""
        if spec == spec_dcn and hierarchical_ok:
            x = jax.lax.pmean(x, plan_lib.DP_AXES[1])  # intra-slice (ICI)
            return jax.lax.pmean(x, plan_lib.DP_AXES[0])  # cross-slice (DCN)
        return jax.lax.pmean(x, plan_lib.DP_AXES)

    def local_fn(params, batch, ef_state):
        if has_aux:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            aux = ()

        # ---- collect leaves in traversal order so buckets can span the tree ----
        collected = []

        def _collect(path, g, ef):
            collected.append((path, g, ef))
            return 0

        jax.tree_util.tree_map_with_path(_collect, grads, ef_state)

        # ---- gradient bucketing: params sharing a fusion group id reduce as one
        # concatenated buffer (the reference fused CollectiveReduce via
        # ScopedAllocator with these same group ids, runner.py:41-46). Stateless
        # and EF codecs bucket; PowerSGD (matrix-structured) and the sparse wire
        # stay per-leaf. ----
        buckets = {}
        for path, g, ef in collected:
            pp = plans_by_name.get(name_of(path))
            kind = pp.compressor if pp else COMP_NONE
            if pp is None or pp.name in sparse_wire or kind == COMP_POWER_SGD:
                continue
            if kind == COMP_BF16_EF and not isinstance(ef, EFState):
                continue  # per-leaf path raises the diagnostic TypeError
            if not getattr(g, "ndim", None):
                continue
            buckets.setdefault((pp.group, kind, pp.spec, g.dtype),
                               []).append((path, g, ef))

        bucketed_results = {}  # keyed by leaf path name
        for (group, kind, spec, dtype), members in buckets.items():
            if len(members) < 2:
                continue
            xs = [g + ef.error[0] if kind == COMP_BF16_EF else g
                  for _, g, ef in members]
            flat = jnp.concatenate([x.reshape(-1) for x in xs])
            synced_flat = decompress(_pmean(compress(flat, kind), spec), dtype)
            offset = 0
            for (path, g, ef), x in zip(members, xs):
                part = synced_flat[offset:offset + x.size].reshape(g.shape)
                offset += x.size
                if kind == COMP_BF16_EF:
                    new_err = x - decompress(compress(x, kind), g.dtype)
                    bucketed_results[name_of(path)] = _SyncResult(
                        part, EFState(error=new_err[None]))
                else:
                    bucketed_results[name_of(path)] = _SyncResult(part, ef)

        def sync_leaf(path, g, ef):
            param_plan = plans_by_name.get(name_of(path))
            kind = param_plan.compressor if param_plan else COMP_NONE
            spec = param_plan.spec if param_plan else 0
            if param_plan is not None and param_plan.name in sparse_wire:
                idx = _batch_leaf_by_name(batch, param_plan.index_leaf)
                if idx is not None:
                    return _SyncResult(_sparse_allgather_sync(g, idx, dp), ef)
            if kind == COMP_POWER_SGD and isinstance(ef, PowerSGDState):
                return _powersgd_sync(g, ef, pmean=lambda x: _pmean(x, spec))
            if kind == COMP_POWER_SGD and _powersgd_applies(g.shape):
                # A matrix-shaped POWER_SGD param must carry a PowerSGDState; falling
                # through would silently all-reduce the full gradient uncompressed.
                raise TypeError(
                    f"POWER_SGD parameter {name_of(path)!r} has no PowerSGDState "
                    f"(got {type(ef).__name__}); init_ef_state was bypassed")
            if kind == COMP_BF16_EF and isinstance(ef, EFState):
                x = g + ef.error[0]
                synced = decompress(_pmean(compress(x, kind), spec), g.dtype)
                new_err = x - decompress(compress(x, kind), g.dtype)
                return _SyncResult(synced, EFState(error=new_err[None]))
            if kind == COMP_BF16_EF:
                raise TypeError(
                    f"BF16_EF parameter {name_of(path)!r} has no EFState "
                    f"(got {type(ef).__name__}); init_ef_state was bypassed")
            if kind == COMP_BF16:
                # Plain cast codec, reference HorovodCompressor semantics.
                synced = decompress(_pmean(compress(g, COMP_BF16), spec), g.dtype)
                return _SyncResult(synced, ef)
            # NONE, or POWER_SGD on a vector/scalar: exact all-reduce.
            return _SyncResult(_pmean(g, spec), ef)

        def finalize(path, g, ef):
            return bucketed_results.get(name_of(path)) or sync_leaf(path, g, ef)

        results = jax.tree_util.tree_map_with_path(finalize, grads, ef_state)
        synced = jax.tree_util.tree_map(lambda r: r.synced, results)
        new_ef = jax.tree_util.tree_map(lambda r: r.state, results)
        loss = jax.lax.pmean(loss, plan_lib.DP_AXES)
        aux = jax.tree_util.tree_map(lambda a: jax.lax.pmean(a, plan_lib.DP_AXES), aux)
        return synced, loss, aux, new_ef

    batch_spec_fn = _batch_spec_maker(sharding_plan, dp=mesh_dp_size(mesh))

    def explicit(params, batch, ef_state):
        batch_specs = jax.tree_util.tree_map(batch_spec_fn, batch)
        replicated = jax.tree_util.tree_map(lambda _: P(), params)
        ef_specs = ef_partition_specs(ef_state)
        out = jax.shard_map(
            local_fn, mesh=mesh,
            in_specs=(replicated, batch_specs, ef_specs),
            out_specs=(replicated, P(), P(), ef_specs),
            check_vma=False,
        )(params, batch, ef_state)
        return out

    explicit.uses_shard_map = True
    return explicit


def _batch_leaf_by_name(batch: PyTree, leaf_name: str):
    from autodist_tpu.model_spec import _path_name
    for path, leaf in jax.tree_util.tree_flatten_with_path(batch)[0]:
        if _path_name(path) == leaf_name:
            return leaf
    return None


def _sparse_allgather_sync(g: jax.Array, idx: jax.Array, dp: int) -> jax.Array:
    """Sparse gradient sync: ship (indices, touched rows), not the dense matrix.

    ``g`` is this replica's dense scatter-add gradient of an embedding used only
    via gather, so it is nonzero only on rows its local indices touch. Each
    duplicate index contributes 1/k of its row so the local scatter-sum of the
    shipped contributions reconstructs ``g`` exactly; the all-gather then carries
    [global_batch, dim] + [global_batch] over the wire instead of [vocab, dim]
    (reference all_reduce_synchronizer.py:132-173 gathered IndexedSlices the same
    way). Result equals ``pmean(g)`` bit-for-bit up to float summation order.
    """
    vocab = g.shape[0]
    flat_idx = idx.reshape(-1).astype(jnp.int32)
    # Reproduce jnp.take's negative wrap (the detected provenance allows exactly
    # the {idx, idx+vocab} select pattern); out-of-range indices contributed no
    # gradient (FILL_OR_DROP), so mask them out of the reconstruction too.
    flat_idx = jnp.where(flat_idx < 0, flat_idx + vocab, flat_idx)
    valid = (flat_idx >= 0) & (flat_idx < vocab)
    safe_idx = jnp.where(valid, flat_idx, 0)
    rows = jnp.take(g, safe_idx, axis=0)
    counts = jax.ops.segment_sum(valid.astype(jnp.float32), safe_idx,
                                 num_segments=vocab)
    inv = jnp.where(valid, 1.0 / jnp.maximum(counts[safe_idx], 1.0), 0.0)
    contrib = rows * inv.astype(g.dtype).reshape((-1,) + (1,) * (rows.ndim - 1))
    all_idx = jax.lax.all_gather(safe_idx, plan_lib.DP_AXES, tiled=True)
    all_contrib = jax.lax.all_gather(contrib, plan_lib.DP_AXES, tiled=True)
    summed = jax.ops.segment_sum(all_contrib, all_idx, num_segments=vocab)
    return (summed / dp).astype(g.dtype)


def _batch_spec_maker(sharding_plan: ShardingPlan, dp: int):

    def spec_for(leaf):
        shape = getattr(leaf, "shape", ())
        if shape and shape[0] % dp == 0:
            return sharding_plan.batch_pspec(len(shape))
        return P()

    return spec_for


# ------------------------------------------------------------- compressor state

def init_ef_state(sharding_plan: ShardingPlan, params: PyTree,
                  mesh: Optional[Mesh] = None) -> PyTree:
    """Compressor state tree, shaped like ``params`` at the top level: an
    :class:`EFState` for BF16_EF parameters, a :class:`PowerSGDState` for matrix
    POWER_SGD parameters, and 0-d zeros elsewhere (so the tree rides the same
    sharding derivation). Residuals carry a leading ``dp`` dimension — one slice per
    data-parallel replica (the reference kept the residual as per-worker Python
    state inside the compressor object, ``compressor.py:120-143``). This IS the
    ZeRO sharding treatment for compressor state: residual memory is already
    ``size/dp`` per device whether or not the plan enables
    ``with_zero_update`` for the optimizer state (PowerSGD's ``q`` must stay
    replicated — every replica contracts against the full factor each step).

    With ``mesh``, the residuals are allocated directly with their sharding (a
    ``[dp, ...]`` residual materialized replicated first would cost dp× parameter
    memory on one device — exactly the scale compression targets)."""
    from autodist_tpu.model_spec import _path_name
    dp = mesh_dp_size(mesh) if mesh is not None else sharding_plan.dp_size
    plans = sharding_plan.params

    def leaf(path, x):
        param_plan = plans.get(_path_name(path))
        kind = param_plan.compressor if param_plan else COMP_NONE
        if kind == COMP_BF16_EF:
            return EFState(error=jnp.zeros((dp,) + x.shape, dtype=x.dtype))
        if kind == COMP_POWER_SGD and _powersgd_applies(x.shape):
            r = _powersgd_rank(x.shape, param_plan.power_sgd_rank)
            n = int(np.prod(x.shape[1:]))
            # Deterministic orthonormal warm start, seeded by the parameter name so
            # every process initializes identically without coordination.
            key = jax.random.PRNGKey(zlib.crc32(param_plan.name.encode()))
            q0, _ = jnp.linalg.qr(jax.random.normal(key, (n, r), jnp.float32))
            return PowerSGDState(error=jnp.zeros((dp,) + x.shape, dtype=x.dtype), q=q0)
        return jnp.zeros((), dtype=x.dtype)

    # Only shapes/dtypes matter: build from metadata so no parameter is ever
    # transferred (a params operand would commit a fully-replicated copy of the
    # model to every device before the plan's shardings are applied).
    meta = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)), params)

    def build():
        return jax.tree_util.tree_map_with_path(leaf, meta)

    if mesh is None:
        return build()
    abstract = jax.eval_shape(build)
    shardings = ef_sharding_tree(mesh, abstract)
    with mesh:
        return jax.jit(build, out_shardings=shardings)()


def ef_partition_specs(ef_state: PyTree) -> PyTree:
    """PartitionSpecs for a compressor-state tree: ``error`` leaves shard their
    leading (replica) dim over the data axes; everything else replicates."""

    def spec(path, x):
        last = path[-1] if path else None
        if (isinstance(last, jax.tree_util.GetAttrKey) and last.name == "error"
                and getattr(x, "ndim", 0) >= 1):
            return P(plan_lib.DP_AXES, *([None] * (x.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, ef_state)


def ef_sharding_tree(mesh: Mesh, ef_state: PyTree) -> PyTree:
    """NamedSharding pytree for the compressor state (used for jit in/out shardings)."""
    from jax.sharding import NamedSharding
    specs = ef_partition_specs(ef_state)
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s), specs,
                                  is_leaf=lambda x: isinstance(x, P))


# --------------------------------------------------------- wire-push compression

@dataclasses.dataclass
class SparseRows:
    """Row-sparse gradient wire frame: only the TOUCHED rows of an
    embedding-style gradient cross the PS wire.

    ``indices`` [k] are the unique touched row ids, ``rows`` [k, ...] the
    gradient rows at those ids, ``shape`` the dense shape the server
    scatter-applies into. Registered with the wire codec (rides as an ``o``
    frame whose array fields borrow like any other) but deliberately NOT
    registered as a jax pytree node: the server's densify pass must see it
    as a tree LEAF, not recurse into its fields."""

    indices: Any
    rows: Any
    shape: Any


register_wire_dataclass(SparseRows)


def densify_sparse_rows(tree: PyTree) -> PyTree:
    """Server-side scatter-apply: expand every :class:`SparseRows` leaf back
    to its dense gradient (zeros off the touched rows — exact, because a
    gather-only embedding's dense gradient IS zero off the touched rows;
    that provenance is what lets the plan mark the param sparse at all).
    Scatter-ADD, so duplicate indices — which a well-formed push never
    ships — still sum rather than silently last-write-wins."""

    def leaf(x):
        if not isinstance(x, SparseRows):
            return x
        rows = np.asarray(x.rows)
        dense = np.zeros(tuple(int(d) for d in x.shape), rows.dtype)
        if rows.size:
            np.add.at(dense, np.asarray(x.indices).reshape(-1), rows)
        return dense

    return jax.tree_util.tree_map(
        leaf, tree, is_leaf=lambda x: isinstance(x, SparseRows))


class WirePushCompressor:
    """Host-side gradient compressor for the remote PS push path.

    Sits between ``grads = _to_host(grads)`` and ``call("apply", ...)`` in
    :class:`~autodist_tpu.parallel.ps_transport.RemotePSWorker` — purely a
    transport concern: the server dequantizes/densifies on decode, so its
    apply path (and the plan's in-graph compressors) never change.

    Three regimes per leaf, mirroring the reference draft's rank gate:

    - **sparse push** (exact): params the plan marked row-sparse ship as
      :class:`SparseRows` — only the rows the batch's index leaf touched.
      No quantization, no residual; byte-for-byte the dense apply's result.
    - **quantized push** (lossy + error feedback): float leaves with
      ``ndim >= 2`` and at least ``min_bytes`` ship as ``wire.quantize``
      frames. The quantization residual ``x - dequantize(quantize(x))`` is
      kept per leaf in the existing :class:`EFState` machinery and added
      back before the NEXT quantize, so the compressed run tracks the exact
      run (int8 without EF is the documented divergent negative control).
    - **bypass** (exact): vectors, scalars, ints, and anything under the
      size floor ship untouched — the size floor is where compression's
      scale bytes and host cost stop paying for themselves.

    Cumulative ``bytes_in`` / ``bytes_out`` / ``bytes_saved`` /
    ``quantize_s`` stats mirror into the ``ps.wire.*`` registry counters
    when telemetry is on (the adtop/adfleet compression line and the
    profile block the cost model's ``quantize_bytes_per_s`` fit reads)."""

    def __init__(self, wire_dtype: str = "", *, min_bytes: Optional[int] = None,
                 error_feedback: bool = True,
                 sparse_params: Optional[dict] = None):
        from autodist_tpu import const
        from autodist_tpu.parallel import wire as wire_lib
        wire_dtype = str(wire_dtype or "").lower()
        if wire_dtype in ("off", "none", "0"):
            wire_dtype = ""
        if wire_dtype and wire_dtype not in wire_lib.WIRE_DTYPES:
            raise ValueError(f"unknown wire dtype {wire_dtype!r}; valid: "
                             f"off, {', '.join(wire_lib.WIRE_DTYPES)}")
        self.wire_dtype = wire_dtype
        self.min_bytes = int(const.ENV.AUTODIST_COMPRESS_MIN_BYTES.val
                             if min_bytes is None else min_bytes)
        self.error_feedback = bool(error_feedback)
        # param name -> batch index-leaf name (plan.sparse_wire_params)
        self.sparse_params = dict(sparse_params or {})
        self._residuals: dict = {}   # param name -> EFState
        self.bytes_in = 0            # dense bytes of every compressed leaf
        self.bytes_out = 0           # wire bytes those leaves actually ship
        self.bytes_saved = 0
        self.quantize_s = 0.0

    @property
    def active(self) -> bool:
        return bool(self.wire_dtype) or bool(self.sparse_params)

    def _sparse_leaf(self, name: str, g: np.ndarray, batch):
        from autodist_tpu.parallel import wire as wire_lib  # noqa: F401
        idx = _batch_leaf_by_name(batch, self.sparse_params[name]) \
            if batch is not None else None
        if idx is None:
            return None
        vocab = g.shape[0]
        flat = np.asarray(idx).reshape(-1).astype(np.int64)
        flat = np.where(flat < 0, flat + vocab, flat)   # jnp.take's wrap
        uniq = np.unique(flat[(flat >= 0) & (flat < vocab)])
        return SparseRows(indices=uniq, rows=np.ascontiguousarray(g[uniq]),
                          shape=tuple(int(d) for d in g.shape))

    def compress(self, grads: PyTree, batch: PyTree = None):
        """Returns ``(wire_tree, has_sparse)`` — the tree to push (leaves
        replaced by :class:`SparseRows` / ``wire.QuantizedArray` where the
        regime applies) and whether any leaf went sparse (the worker then
        uses the ``apply_sparse`` opcode)."""
        import time as _time
        from autodist_tpu import telemetry
        from autodist_tpu.model_spec import _path_name
        from autodist_tpu.parallel import wire as wire_lib
        t0 = _time.perf_counter()
        saved = quantized = 0
        has_sparse = False

        def leaf(path, g):
            nonlocal saved, quantized, has_sparse
            g = np.asarray(g)
            name = _path_name(path)
            if name in self.sparse_params and g.ndim >= 2:
                sp = self._sparse_leaf(name, g, batch)
                if sp is not None:
                    has_sparse = True
                    out_b = sp.rows.nbytes + sp.indices.nbytes
                    self.bytes_in += g.nbytes
                    self.bytes_out += out_b
                    saved += max(0, g.nbytes - out_b)
                    return sp
            if (self.wire_dtype and np.issubdtype(g.dtype, np.floating)
                    and g.ndim >= 2 and g.nbytes >= self.min_bytes):
                x = g
                prev = self._residuals.get(name)
                if prev is not None:
                    x = g + np.asarray(prev.error[0])
                qa = wire_lib.quantize(x, self.wire_dtype)
                if self.error_feedback:
                    # Residual rides the existing EFState carrier (leading
                    # [dp] dim) — one state shape across every compressor.
                    self._residuals[name] = EFState(
                        error=(x - wire_lib.dequantize(qa))[None])
                self.bytes_in += g.nbytes
                self.bytes_out += qa.wire_nbytes
                saved += max(0, g.nbytes - qa.wire_nbytes)
                quantized += g.nbytes
                return qa
            return g

        out = jax.tree_util.tree_map_with_path(leaf, grads)
        dt = _time.perf_counter() - t0
        self.bytes_saved += saved
        self.quantize_s += dt
        if telemetry.enabled():
            telemetry.counter("ps.wire.bytes_saved").inc(saved)
            telemetry.counter("ps.wire.bytes_quantized").inc(quantized)
            telemetry.counter("wire.quantize_s").inc(dt)
        return out, has_sparse
