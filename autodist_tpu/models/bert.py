"""BERT encoder with masked-LM pretraining loss.

Counterpart of the reference BERT pretraining benchmark (``examples/benchmark/
bert.py:41-47,194-215`` + ``utils/modeling``). Encoder-only Transformer sharing the
TPU-first layout of :mod:`transformer_lm` (bf16 activations, f32 params, static
shapes); the MLM objective gathers prediction positions statically.
"""

import dataclasses
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.models.transformer_lm import dot_product_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_len: int = 512
    type_vocab: int = 2
    dtype: Any = jnp.bfloat16


class EncoderBlock(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x, pad_mask):
        cfg = self.config
        head_dim = cfg.d_model // cfg.n_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(cfg.n_heads, head_dim), axis=-1, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name, use_bias=True)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_attn")(x)
        q, k, v = dense("query")(h), dense("key")(h), dense("value")(h)
        ctx = dot_product_attention(q, k, v, pad_mask, cfg.dtype)
        attn = nn.DenseGeneral(features=cfg.d_model, axis=(-2, -1), dtype=cfg.dtype,
                               param_dtype=jnp.float32, name="out")(ctx)
        x = x + attn
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln_mlp")(x)
        h = nn.gelu(nn.Dense(cfg.d_ff, dtype=cfg.dtype, param_dtype=jnp.float32,
                             name="mlp_in")(h))
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlp_out")(h)
        return x + h


class Bert(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, tokens, token_types, mlm_positions=None):
        cfg = self.config
        _, length = tokens.shape
        emb = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="embed")
        type_emb = nn.Embed(cfg.type_vocab, cfg.d_model, dtype=cfg.dtype,
                            param_dtype=jnp.float32, name="type_embed")
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (cfg.max_len, cfg.d_model), jnp.float32)
        x = emb(tokens) + type_emb(token_types) + pos[None, :length, :].astype(cfg.dtype)
        # Additive pad mask: 0 where attendable, -1e9 at pad columns ([B,1,1,L] is
        # broadcast over heads and query positions).
        pad = (tokens == 0)
        pad_mask = jnp.where(pad[:, None, None, :], jnp.full((), -1e9, cfg.dtype),
                             jnp.zeros((), cfg.dtype))
        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"layer_{i}")(x, pad_mask)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        if mlm_positions is not None:
            # Gather the prediction slots BEFORE the vocab projection (the
            # reference's gather_indexes): the tied head then runs on [B, P, d]
            # instead of [B, L, d] — at P=20, L=128 that is 6.4x fewer head
            # FLOPs and a [B, P, V] logits tensor instead of [B, L, V].
            x = jnp.take_along_axis(x, mlm_positions[..., None], axis=1)
        # Head matmul in compute dtype; the loss upcasts for the softmax.
        return emb.attend(x)  # tied MLM logits


def make_mlm_loss_fn(model: Bert) -> Callable:
    """Masked-LM loss; batch = tokens, token_types, mlm_positions, mlm_targets,
    mlm_weights (static-count prediction slots, TPU-friendly like the reference's
    fixed max_predictions_per_seq)."""

    def loss_fn(params, batch):
        logits_at = model.apply({"params": params}, batch["tokens"],
                                batch["token_types"],
                                mlm_positions=batch["mlm_positions"])  # [B, P, V]
        logprobs = jax.nn.log_softmax(logits_at.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logprobs, batch["mlm_targets"][..., None],
                                   axis=-1)[..., 0]
        w = batch["mlm_weights"].astype(nll.dtype)
        return (nll * w).sum() / jnp.maximum(w.sum(), 1.0)

    return loss_fn


def synthetic_batch(config: BertConfig, batch_size: int, seq_len: int = 128,
                    n_predictions: int = 20, seed: int = 0):
    rng = np.random.RandomState(seed)
    return {
        "tokens": rng.randint(1, config.vocab_size, size=(batch_size, seq_len)).astype(np.int32),
        "token_types": np.zeros((batch_size, seq_len), np.int32),
        "mlm_positions": rng.randint(0, seq_len, size=(batch_size, n_predictions)).astype(np.int32),
        "mlm_targets": rng.randint(1, config.vocab_size, size=(batch_size, n_predictions)).astype(np.int32),
        "mlm_weights": np.ones((batch_size, n_predictions), np.float32),
    }
