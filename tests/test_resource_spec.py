"""ResourceSpec YAML parsing — parity with reference tests/test_resource_spec.py."""

import pytest

from autodist_tpu.resource_spec import (DEFAULT_NETWORK_BANDWIDTH_GBPS, DeviceType,
                                        ResourceSpec)

TWO_NODE_YAML = """
nodes:
  - address: 10.0.0.1
    tpus: 4
    chief: true
    ssh_config: conf
    network_bandwidth: 100
  - address: 10.0.0.2
    tpus: 4
    ssh_config: conf
ssh:
  conf:
    username: me
    key_file: /tmp/id_rsa
    port: 2222
    python_venv: source /env/bin/activate
    shared_envs:
      LD_LIBRARY_PATH: /usr/lib
"""


def test_two_node_parse(tmp_path):
    p = tmp_path / "spec.yml"
    p.write_text(TWO_NODE_YAML)
    spec = ResourceSpec(str(p))
    assert spec.num_nodes == 2
    assert spec.chief_address == "10.0.0.1"
    assert spec.num_accelerators == 8
    assert [d for _, d in spec.tpu_devices][0].device_type is DeviceType.TPU
    # bandwidth default (reference resource_spec.py:209-215)
    assert spec.node_bandwidth("10.0.0.2") == DEFAULT_NETWORK_BANDWIDTH_GBPS
    assert spec.node_bandwidth("10.0.0.1") == 100
    ssh = spec.ssh_config_for("10.0.0.2")
    assert ssh.username == "me" and ssh.port == 2222
    assert ssh.shared_envs["LD_LIBRARY_PATH"] == "/usr/lib"


def test_inline_yaml_string():
    spec = ResourceSpec("nodes: [{address: localhost, tpus: 2}]")
    assert spec.num_nodes == 1
    # single node becomes chief implicitly
    assert spec.chief_address == "localhost"


def test_sorted_nodes_chief_first_then_lexicographic():
    spec = ResourceSpec("nodes: [{address: b, tpus: 1}, {address: c, tpus: 1, chief: true}, {address: a, tpus: 1}]")
    assert [n.address for n in spec.sorted_nodes] == ["c", "a", "b"]


def test_two_chiefs_rejected():
    with pytest.raises(ValueError, match="chief"):
        ResourceSpec("nodes: [{address: a, chief: true}, {address: b, chief: true}]")


def test_multi_node_without_chief_rejected():
    with pytest.raises(ValueError, match="chief"):
        ResourceSpec("nodes: [{address: a}, {address: b}]")


def test_duplicate_addresses_rejected():
    with pytest.raises(ValueError, match="Duplicate"):
        ResourceSpec("nodes: [{address: a, chief: true}, {address: a}]")


def test_cpu_only_node_contributes_cpu_replica():
    spec = ResourceSpec("nodes: [{address: a, tpus: 2, chief: true}, {address: b}]")
    reps = spec.replica_devices
    # reference ps_strategy.py:37-56: GPU-less (here TPU-less) nodes replicate on CPU
    assert len(reps) == 3
    assert reps[-1].device_type is DeviceType.CPU


def test_local_default_spec_matches_visible_devices():
    import jax
    spec = ResourceSpec()
    assert spec.num_accelerators == len(jax.devices())


def test_mesh_section_parsed():
    spec = ResourceSpec("{nodes: [{address: a, tpus: 8}], mesh: {data: 2, model: 4}}")
    assert spec.mesh_config == {"data": 2, "model": 4}


def test_env_members_are_distinct(monkeypatch):
    """Guard against enum aliasing: members with equal values would silently read
    each other's env vars."""
    from autodist_tpu.const import ENV, _ENV_DEFAULTS
    assert len(list(ENV)) == len(_ENV_DEFAULTS)
    monkeypatch.setenv("AUTODIST_WORKER", "1.2.3.4")
    assert ENV.AUTODIST_STRATEGY_ID.val == ""
    assert ENV.AUTODIST_WORKER.val == "1.2.3.4"
    assert ENV.AUTODIST_NUM_PROCESSES.val == 1
