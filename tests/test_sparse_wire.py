"""Sparse gradient wire path: all-gather (indices, rows) + segment-sum.

The reference synced sparse (IndexedSlices) gradients as an all-gather of
indices+values (``all_reduce_synchronizer.py:132-173``) so an embedding gradient
crossed the wire at ~rows-touched size, not the full matrix. These tests prove
the TPU-native equivalent: value-exactness vs the dense path (including
duplicate indices), and — by HLO inspection — that the collective carries
batch-sized rows while no vocab-sized all-reduce remains.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.parallel import synchronization
from autodist_tpu.parallel.mesh import build_mesh
from autodist_tpu.parallel.plan import ShardingPlan
from autodist_tpu.strategy import AllReduce, Parallax
from shardmap_compat import requires_shard_map

VOCAB, DIM, BATCH = 793, 8, 32
LR = 0.1


def _params():
    rng = np.random.RandomState(0)
    return {"emb": jnp.asarray(rng.randn(VOCAB, DIM), jnp.float32),
            "w": jnp.asarray(rng.randn(DIM, 1), jnp.float32)}


def _batch(seed=3, with_duplicates=False):
    rng = np.random.RandomState(seed)
    idx = rng.randint(0, VOCAB, size=(BATCH,))
    if with_duplicates:
        idx[::3] = idx[0]  # force cross-shard duplicate rows
    return {"idx": idx, "y": rng.randn(BATCH, 1).astype(np.float32)}


def _loss(p, b):
    e = jnp.take(p["emb"], b["idx"], axis=0)
    return jnp.mean((b["y"] - e @ p["w"]) ** 2)


def _plan_and_mesh(builder):
    from autodist_tpu.resource_spec import ResourceSpec
    spec = ResourceSpec("nodes: [{address: localhost, tpus: 8, chief: true}]")
    model = ModelSpec.from_loss_fn(_loss, _params(), _batch())
    strategy = builder.build(model, spec)
    plan = ShardingPlan.from_strategy(strategy, model)
    mesh = build_mesh(axes=dict(plan.mesh_axes))
    return plan, model, mesh


def test_index_leaf_detected_and_wire_enabled():
    plan, _, _ = _plan_and_mesh(Parallax())
    p = plan.params["emb"]
    assert p.sparse
    assert p.index_leaf == "idx"
    assert "emb" in plan.sparse_wire_params
    assert "w" not in plan.sparse_wire_params


@requires_shard_map
@pytest.mark.parametrize("builder_cls", [Parallax, AllReduce])
@pytest.mark.parametrize("dup", [False, True], ids=["unique", "duplicates"])
def test_sparse_sync_value_exact(builder_cls, dup):
    """The (indices, rows) wire reconstructs exactly the dense pmean gradient."""
    plan, model, mesh = _plan_and_mesh(builder_cls())
    params, batch = _params(), _batch(with_duplicates=dup)
    grad_fn = synchronization.make_grad_fn(plan, model, mesh, _loss)

    ef = synchronization.init_ef_state(plan, params, mesh=mesh)
    from jax.sharding import NamedSharding
    batch_sharded = {k: jax.device_put(v, NamedSharding(mesh, plan.batch_pspec(np.ndim(v))))
                     for k, v in batch.items()}
    with mesh:
        grads, loss, _, _ = jax.jit(grad_fn)(params, batch_sharded, ef)

    dense = jax.grad(_loss)(params, batch)
    np.testing.assert_allclose(np.asarray(grads["emb"]), np.asarray(dense["emb"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(grads["w"]), np.asarray(dense["w"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(loss), float(_loss(params, batch)), rtol=1e-5)


@requires_shard_map
def test_wire_carries_rows_not_matrix():
    """HLO proof of wire volume: the embedding gradient crosses as batch rows
    (all-gather of [local_batch, DIM] + indices); no vocab-sized all-reduce."""
    plan, model, mesh = _plan_and_mesh(Parallax())
    params, batch = _params(), _batch()
    grad_fn = synchronization.make_grad_fn(plan, model, mesh, _loss)
    ef = synchronization.init_ef_state(plan, params, mesh=mesh)
    hlo = jax.jit(grad_fn).lower(params, batch, ef).compile().as_text()

    collective_lines = [l for l in hlo.splitlines()
                        if "all-reduce" in l or "all-gather" in l]
    assert any("all-gather" in l for l in collective_lines), hlo[:2000]
    # No collective touches a [VOCAB, DIM] operand.
    for line in collective_lines:
        assert f"{VOCAB},{DIM}" not in line.replace(" ", ""), line


@requires_shard_map
def test_end_to_end_parallax_training_with_sparse_wire():
    params, batch = _params(), _batch(with_duplicates=True)
    ad = AutoDist(strategy_builder=Parallax())
    step = ad.function(_loss, params, optax.sgd(LR), example_batch=batch)
    l0 = float(step(batch))
    for _ in range(5):
        l1 = float(step(batch))
    assert l1 < l0
    # One-step parity against the hand-computed dense update.
    want = jax.tree_util.tree_map(
        lambda p, g: np.asarray(p) - LR * np.asarray(g),
        params, jax.grad(_loss)(params, batch))
    ad2 = AutoDist(strategy_builder=Parallax())
    step2 = ad2.function(_loss, params, optax.sgd(LR), example_batch=batch)
    step2(batch)
    got = step2.get_state().params
    np.testing.assert_allclose(np.asarray(got["emb"]), want["emb"], rtol=1e-5, atol=1e-6)


def test_transformed_indices_disable_sparse_wire():
    """idx+1 is not value-equal to the batch leaf: provenance must drop the
    mapping so the dense (always-correct) path is used."""
    from autodist_tpu.model_spec import detect_sparse_index_sources

    def loss(p, b):
        e = jnp.take(p["emb"], b["idx"] + 1, axis=0)
        return jnp.mean((b["y"] - e @ p["w"]) ** 2)

    params = _params()
    batch = _batch()
    assert detect_sparse_index_sources(loss, params, batch) == {}
    # And the full pipeline stays value-exact via the dense fallback.
    spec_model = ModelSpec.from_loss_fn(loss, params, batch)
    assert spec_model.params["emb"].index_leaf is None


def test_two_index_leaves_disable_sparse_wire():
    """A table gathered with two different batch leaves cannot use the single-leaf
    wire format; the mapping must be dropped entirely."""
    from autodist_tpu.model_spec import detect_sparse_index_sources

    def loss(p, b):
        e1 = jnp.take(p["emb"], b["idx"], axis=0)
        e2 = jnp.take(p["emb"], b["idx2"], axis=0)
        return jnp.mean(((e1 + e2) @ p["w"]) ** 2)

    params = _params()
    batch = {"idx": np.zeros((BATCH,), np.int32),
             "idx2": np.ones((BATCH,), np.int32),
             "y": np.zeros((BATCH, 1), np.float32)}
    assert detect_sparse_index_sources(loss, params, batch) == {}


@requires_shard_map
def test_negative_indices_value_exact():
    """jnp.take wraps negative indices; the wire format reproduces the wrap."""
    plan, model, mesh = _plan_and_mesh(Parallax())
    params = _params()
    rng = np.random.RandomState(11)
    batch = {"idx": rng.randint(-VOCAB, VOCAB, size=(BATCH,)),
             "y": rng.randn(BATCH, 1).astype(np.float32)}
    assert "emb" in plan.sparse_wire_params
    grad_fn = synchronization.make_grad_fn(plan, model, mesh, _loss)
    ef = synchronization.init_ef_state(plan, params, mesh=mesh)
    from jax.sharding import NamedSharding
    sharded = {k: jax.device_put(v, NamedSharding(mesh, plan.batch_pspec(np.ndim(v))))
               for k, v in batch.items()}
    with mesh:
        grads, _, _, _ = jax.jit(grad_fn)(params, sharded, ef)
    dense = jax.grad(_loss)(params, batch)
    np.testing.assert_allclose(np.asarray(grads["emb"]), np.asarray(dense["emb"]),
                               rtol=1e-5, atol=1e-6)
