"""Serving transport: request/response opcodes on the PR 2 zero-copy wire.

The PS transport's plane is reused wholesale — typed codec
(``parallel/wire.py``: nothing on the socket is ever unpickled),
scatter-gather ``sendmsg`` sends, recycled ``recv_into`` buffers, 8-byte
version-validated framing — with a new opcode vocabulary for online
inference:

- ``generate`` — LM generation: ``(op, prompt int32[P], max_new_tokens,
  seed, timeout)`` -> ``("ok", tokens int32[T], timing)``. The handler
  thread enqueues into the continuous batcher and parks (bounded) on the
  request's completion event; the socket is idle while the batch cooks, so
  a slow generation never blocks other connections (thread-per-connection,
  the same property the PS gate relies on).
- ``infer`` — stateless model apply: ``(op, example-pytree, timeout)`` ->
  ``("ok", output-pytree, timing)``.
- ``stats`` — the serving SLO snapshot (telemetry registry + queue/batch
  state), remote observability without grepping the server's log.
- ``ping`` — health/liveness echo.

Every arm is covered by graftlint GL006 (client-op/dispatch-arm symmetry):
an opcode the client sends without a server arm fails lint, same as the PS
wire. Malformed payloads (wrong types, oversize prompts, full queue) get an
``("error", kind, detail)`` reply — a hostile peer achieves data parsing and
its own rejection, nothing more.
"""

import socket
import socketserver
import threading
import time
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from autodist_tpu import telemetry
from autodist_tpu.telemetry import reqtrace as _reqtrace
from autodist_tpu.parallel import wire
from autodist_tpu.parallel.ps_transport import (_PSClient, _RecvBuffer,
                                                _recv_msg, _send_payload,
                                                PSClientError)
from autodist_tpu.serving.batcher import ServeBusy, ServeError
from autodist_tpu.utils import logging
from autodist_tpu.utils.metrics import WireCounters
from autodist_tpu.testing.sanitizer import san_lock

# Hard ceiling on one request's server-side completion wait: a vanished
# batcher must not park a handler thread forever (GL005's rule at the trust
# boundary); a single generation this long is operationally dead anyway.
MAX_WAIT_S = 600.0

# Completed-reply dedup entries kept per server (see the ``generate`` arm):
# the router's replay window is one in-flight set, so a small bound holds.
DEDUP_KEEP = 512


def _wire_server(host: str, port: int, owner) -> socketserver.TCPServer:
    """The shared thread-per-connection wire loop behind both serving
    endpoints (:class:`InferenceServer` and the fleet ``RouterServer``):
    recv typed message -> ``owner._dispatch(msg, span)`` -> send typed
    reply. ``owner`` provides ``wire`` (counters), ``_dispatch`` and
    ``_conns`` (live handler sockets, so ``kill()`` can sever them)."""

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            pool = _RecvBuffer()
            owner._conns.add(self.request)
            try:
                while True:
                    msg, _ = _recv_msg(self.request, pool=pool,
                                       counters=owner.wire)
                    is_protocol = isinstance(msg, tuple) and bool(msg)
                    op = msg[0] if is_protocol else "<malformed>"
                    with telemetry.span("serve.request", op=str(op)) as sp:
                        # The dispatch stamps the request id it assigns
                        # onto this span (sp.set(rid=...)) so one id ties
                        # the transport span, the batcher's prefill/
                        # decode spans, and the reply timing together.
                        reply = owner._dispatch(msg, sp)
                    try:
                        payload = wire.encode_parts(reply)
                    except wire.WireError as e:
                        # OUR reply is unencodable (e.g. a model output
                        # pytree with an unregistered node) — a server
                        # limitation, not a hostile peer: report it.
                        logging.warning(
                            "serve transport: reply to %r is not "
                            "wire-encodable (%s)", op, e)
                        payload = wire.encode_parts((
                            "error", "WireError",
                            f"server reply to {op!r} is not "
                            f"wire-encodable: {e}"))
                    n = _send_payload(self.request, payload)
                    owner.wire.add_sent(n)
                    # Drop aliases into the recv buffer before the next
                    # recv so the pool can recycle it.
                    msg = reply = payload = None
            except wire.WireError as e:
                logging.warning("serve transport: dropping connection "
                                "with malformed payload (%s)", e)
            except (ConnectionError, OSError):
                pass  # client went away; its requests complete unobserved
            finally:
                owner._conns.discard(self.request)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    return Server((host, port), Handler)


def _env_address() -> Tuple[str, int]:
    """The ``AUTODIST_SERVE_ADDR`` default: ``host:port`` when the flag is
    set, else loopback on an ephemeral port. Server bind and client target
    share it, so one exported flag points both ends at the same place."""
    from autodist_tpu import const
    addr = str(const.ENV.AUTODIST_SERVE_ADDR.val)
    if not addr:
        return "127.0.0.1", 0
    host, sep, port = addr.rpartition(":")
    if not sep:
        return addr, 0
    return host, int(port)


class InferenceServer:
    """Serve a batcher (LM :class:`~autodist_tpu.serving.batcher.Batcher` or
    :class:`~autodist_tpu.serving.batcher.ApplyBatcher`) to remote clients.

    Same trust model as the PS transport: the wire is typed (no code
    execution on decode) but unauthenticated — binding wider than loopback /
    the cluster's trust domain is the caller's explicit choice (defaults:
    ``AUTODIST_SERVE_ADDR`` when set, else loopback on an ephemeral port)."""

    def __init__(self, batcher, host: Optional[str] = None,
                 port: Optional[int] = None):
        env_host, env_port = _env_address()
        host = env_host if host is None else host
        port = env_port if port is None else port
        self._batcher = batcher
        self._t_started = time.monotonic()
        self.wire = WireCounters()
        # Request-id dedup for the fleet router's replay path (GL011: the
        # ``generate`` op is NOT wire-retried — replay happens one level up,
        # made idempotent here): a completed rid's reply is cached, so a
        # router that re-sends an in-flight request after a replica death
        # can never double-generate on a replica that already finished it.
        self._dedup: "OrderedDict[str, tuple]" = OrderedDict()
        self._dedup_lock = san_lock()
        self._conns: set = set()
        self._server = _wire_server(host, port, self)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        # Scrape endpoint: AUTODIST_METRICS_PORT attaches /metrics+/healthz
        # to the serving process (process-global; no-op when the flag is off).
        from autodist_tpu.telemetry import openmetrics as _openmetrics
        _openmetrics.maybe_serve()
        logging.info("InferenceServer (%s batcher, %s mode) listening on "
                     "%s:%d", batcher.kind, batcher.config.mode,
                     *self._server.server_address)

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def stats_snapshot(self) -> dict:
        """Wire-encodable serving snapshot: the telemetry registry (the
        ``serve.*`` SLO families live there), queue/batch state, uptime,
        and the structured event ring (so anomaly records survive the
        serving process — the stats plane is their offline exit)."""
        return {"registry": telemetry.snapshot(),
                "wire": self.wire.snapshot(),
                "uptime_s": round(time.monotonic() - self._t_started, 3),
                "mode": self._batcher.config.mode,
                "kind": self._batcher.kind,
                "capacity": self._batcher._engine.capacity,
                "queue_depth": self._batcher.queue_depth(),
                "events": telemetry.events()}

    def status_snapshot(self) -> dict:
        """The live-ops view the ``status`` opcode ships (``tools/adtop.py``
        polls it): :meth:`stats_snapshot` plus the per-request IN-FLIGHT
        table (request id, slot, age, tokens decoded) and a ``kind``
        discriminator (``serve``) so one console renders PS and serving
        endpoints alike."""
        snap = self.stats_snapshot()
        snap["kind"] = "serve"
        snap["engine"] = self._batcher.kind
        snap["in_flight"] = self._batcher.in_flight_snapshot()
        # Alert plane: same section (and same empty-shell contract) as the
        # PS status — one console renders both endpoint kinds.
        from autodist_tpu.telemetry import alerts as _alerts
        snap["alerts"] = _alerts.alerts_snapshot()
        # Recovery plane: same section as the PS status (a serving process
        # normally has no membership actions — the stable empty shell — but
        # a co-located trainer's records render identically either way).
        from autodist_tpu.parallel import recovery as _recovery
        snap["recovery"] = _recovery.recovery_snapshot()
        # Memory plane: the serving census is the paged-KV pool claim plus
        # pressure — the ratio the admission holdback reflex reads.
        from autodist_tpu.telemetry import memplane as _memplane
        snap["memory"] = _memplane.memory_snapshot()
        return snap

    def _wait(self, req, timeout) -> tuple:
        """Park this handler thread (bounded) until the batcher completes the
        request, then build the reply."""
        limit = self._batcher.config.request_timeout_s
        if timeout is not None:
            limit = min(float(timeout), limit)
        if not req.done.wait(timeout=min(limit, MAX_WAIT_S)):
            # Nobody will read this result: tell the batcher to drop the
            # request at its next scheduling round instead of decoding a
            # full generation into the void.
            req.abandon()
            return ("error", "ServeTimeout",
                    f"request {req.rid} did not complete within {limit:.1f}s")
        if req.error is not None:
            return ("error", "ServeError", req.error)
        if self._batcher.kind == "lm":
            return ("ok", np.asarray(req.tokens, np.int32), req.timing())
        return ("ok", req.output, req.timing())

    def _dispatch(self, msg, sp=None):
        # A peer can legally encode a bare dict/int/None; reject it as a
        # protocol error instead of raising outside the per-op try.
        if not isinstance(msg, tuple) or not msg \
                or not isinstance(msg[0], str):
            return ("error", "ServeError",
                    f"malformed protocol message: expected (op, ...) tuple, "
                    f"got {type(msg).__name__}")
        op = msg[0]
        try:
            if op == "generate":
                if self._batcher.kind != "lm":
                    raise ServeError("this server hosts a stateless apply "
                                     "batcher; use the 'infer' op")
                # Optional trailing elements: the router's replay-dedup
                # token, optionally extended into the full trace context
                # ``(rid, send_wall_ns, hop, offset_ns)`` when the request
                # plane is armed. Plain clients send the 5-tuple; arity
                # stays backward compatible either way.
                _, prompt, max_new, seed, timeout, *rest = msg
                rid_token = str(rest[0]) if rest else None
                wire_s = 0.0
                if len(rest) >= 4 and _reqtrace.enabled():
                    # Wire-vs-queue decomposition: the router stamped its
                    # send wall-ns and its estimate of OUR clock minus its
                    # own (ntp_offset over ping round-trips), so
                    # now - send - offset is time spent on the wire, not
                    # in our queue. Clamped: a noisy offset estimate must
                    # never produce negative wire time.
                    send_wall, hop, offset = (int(rest[1]), int(rest[2]),
                                              int(rest[3]))
                    wire_ns = max(0, time.time_ns() - send_wall - offset)
                    wire_s = wire_ns / 1e9
                    _reqtrace.mark(rid_token, "received", hop=hop,
                                   wire_ns=wire_ns)
                if rid_token is not None:
                    with self._dedup_lock:
                        cached = self._dedup.get(rid_token)
                    if cached is not None:
                        return cached
                req = self._batcher.submit(prompt, max_new, seed=int(seed),
                                           rid_token=rid_token,
                                           wire_s=wire_s)
                if sp is not None:
                    # Both ids ride the span: the local rid joins the
                    # prefill/decode spans, the fleet-scope token joins
                    # the router's records and the reqtrace plane.
                    sp.set(rid=req.rid)
                    if rid_token is not None:
                        sp.set(rid_token=rid_token)
                reply = self._wait(req, timeout)
                if rid_token is not None and reply[0] == "ok":
                    with self._dedup_lock:
                        self._dedup[rid_token] = reply
                        while len(self._dedup) > DEDUP_KEEP:
                            self._dedup.popitem(last=False)
                return reply
            if op == "infer":
                if self._batcher.kind != "apply":
                    raise ServeError("this server hosts an LM batcher; use "
                                     "the 'generate' op")
                _, example, timeout = msg
                req = self._batcher.submit(example)
                if sp is not None:
                    sp.set(rid=req.rid)
                return self._wait(req, timeout)
            if op == "stats":
                return ("ok", self.stats_snapshot())
            if op == "status":
                # Live-ops console plane (tools/adtop.py): stats plus the
                # in-flight request table.
                return ("ok", self.status_snapshot())
            if op == "trace":
                # Span-ring pull (same columnar blob as the PS wire's arm)
                # so tools/adtrace.py merges replica spans into the fleet
                # timeline without a PS transport up.
                since = msg[1] if len(msg) > 1 else None
                return ("ok", telemetry.local_trace_state(since_ns=since))
            if op == "reqtrace":
                # Request-lifecycle pull: this process's reqtrace ring as
                # a columnar blob (rebased + merged by telemetry.cluster).
                since = msg[1] if len(msg) > 1 else None
                return ("ok",
                        telemetry.local_reqtrace_state(since_ns=since))
            if op == "ping":
                return ("ok", msg[1] if len(msg) > 1 else None,
                        time.time_ns())
            return ("error", "ServeError", f"unknown op {op!r}")
        except Exception as e:  # ship the failure to the client, keep serving
            return ("error", type(e).__name__, str(e))

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._batcher.close()
        if self.wire.msgs_received:
            logging.info("InferenceServer closed: %s | up %.1fs",
                         self.wire.format_line(),
                         time.monotonic() - self._t_started)

    def kill(self):
        """Simulate abrupt process death (fault injection — the router's
        kill-a-replica path and ``testing/faults`` ``worker_crash``): stop
        accepting, SEVER every live connection mid-reply, stop the batcher.
        Clients observe connection resets — exactly what a killed replica
        process produces — and the router replays their in-flight requests
        on a surviving replica (rid dedup makes the replay idempotent)."""
        self._server.shutdown()
        self._server.server_close()
        for s in list(self._conns):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        # Drain-and-fail is harmless here: the severed sockets mean nobody
        # reads these replies; it just stops the scheduler thread.
        self._batcher.close()


class ServeClient:
    """A client handle onto an :class:`InferenceServer`.

    One in-flight request per client (the underlying connection pairs one
    request with one reply); concurrency = one client per thread, each its
    own connection — the loopback examples and the serving bench do exactly
    that."""

    def __init__(self, address=None, connect_timeout: float = 60.0):
        if address is None:
            address = _env_address()   # the AUTODIST_SERVE_ADDR default
        self._client = _PSClient(address, connect_timeout=connect_timeout)

    @property
    def wire(self) -> WireCounters:
        return self._client.wire

    def generate(self, prompt, max_new_tokens: int, seed: int = 0,
                 timeout: Optional[float] = None):
        """``prompt`` (1-D int array-like) -> ``(tokens int32[T], timing)``
        where timing is the server's ``{queue,prefill,decode,total}_s``
        breakdown. Raises :class:`ServeBusy` on an overload rejection
        (retryable — the queue or page pool is full right now) and
        :class:`ServeError` on any other rejection."""
        prompt = np.ascontiguousarray(np.asarray(prompt, np.int32))
        try:
            tokens, timing = self._client.call(
                "generate", prompt, int(max_new_tokens), int(seed), timeout)
        except PSClientError as e:
            # The wire ships ("error", type-name, detail); re-type the
            # busy rejection so callers (the router's shed cascade) can
            # branch on it without string matching.
            if str(e).startswith("ServeBusy:"):
                raise ServeBusy(str(e)) from None
            raise ServeError(str(e)) from None
        return np.asarray(tokens), timing

    def infer(self, example, timeout: Optional[float] = None):
        """One stateless-apply request: ``example`` (pytree of ndarrays,
        no batch dim) -> ``(output, timing)``."""
        try:
            output, timing = self._client.call("infer", example, timeout)
        except PSClientError as e:
            raise ServeError(str(e)) from None
        return output, timing

    def stats(self) -> dict:
        return self._client.call("stats")[0]

    def status(self) -> dict:
        """The server's live-ops status (:meth:`InferenceServer.
        status_snapshot`): SLO registry + queue depth + in-flight request
        ids — what ``tools/adtop.py`` renders."""
        return self._client.call("status")[0]

    def ping(self) -> float:
        """Round-trip seconds to the server (health check)."""
        t0 = time.perf_counter()
        self._client.call("ping", time.time_ns())
        return time.perf_counter() - t0

    def close(self):
        self._client.close()
