"""Host data pipeline: native threaded prefetch with a pure-Python fallback.

The reference delegated its input pipeline to TF's C++ runtime (queues,
iterators, staging — SURVEY.md §2.4 "host data plane"); this module owns the
equivalent native capability in-tree. ``DataLoader`` serves shuffled, fixed-size
batches from in-memory arrays:

- **Native path** (default): ``native/loader.cc`` is compiled once with g++ into
  the working dir and driven via ctypes. A C++ worker thread reshuffles indices
  per epoch and gathers rows into a prefetch ring off the GIL, so batch assembly
  overlaps the TPU step.
- **Fallback path**: the same semantics in numpy (used when no C++ toolchain is
  available, and as the reference implementation in tests).

``device_prefetch`` composes either path with the runner's feed remapping: it
keeps ``prefetch`` batches in flight on-device (``shard_batch`` = device_put
with the batch sharding) so host->HBM transfer also overlaps the step.
"""

import ctypes
import os
import threading
from typing import Dict, Iterator, Optional

import numpy as np

from autodist_tpu import const
from autodist_tpu.utils import logging

_BUILD_LOCK = threading.Lock()
_LIB = None
_LIB_FAILED = False


def _source_path() -> str:
    return os.path.join(os.path.dirname(__file__), "native", "loader.cc")


def _build_native() -> Optional[ctypes.CDLL]:
    """Compile and load the native loader; None when unavailable."""
    global _LIB, _LIB_FAILED
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        from autodist_tpu.utils.native_build import build_native_lib
        lib = build_native_lib(_source_path(), "loader",
                               extra_flags=("-O3", "-lpthread"))
        if lib is None:
            _LIB_FAILED = True
            return None
        lib.dl_create.restype = ctypes.c_void_p
        lib.dl_create.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64]
        lib.dl_next.restype = ctypes.c_int
        lib.dl_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_void_p)]
        lib.dl_epochs_completed.restype = ctypes.c_uint64
        lib.dl_epochs_completed.argtypes = [ctypes.c_void_p]
        lib.dl_destroy.restype = None
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


class DataLoader:
    """Shuffled fixed-size batches over a dict of same-length arrays.

    Continuous stream: iteration never ends (epochs reshuffle internally,
    drop-last semantics — static batch shapes only, the TPU constraint).
    ``native=None`` auto-selects; ``native=False`` forces the numpy fallback.
    """

    def __init__(self, arrays: Dict[str, np.ndarray], batch_size: int,
                 shuffle: bool = True, seed: int = 0, prefetch: int = 2,
                 native: Optional[bool] = None):
        if not arrays:
            raise ValueError("DataLoader needs at least one array")
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"All arrays must share a leading dim, got {lengths}")
        self._keys = list(arrays)
        # C-contiguous row-major so a row is one contiguous memcpy.
        self._arrays = {k: np.ascontiguousarray(v) for k, v in arrays.items()}
        self.n_rows = next(iter(lengths.values()))
        if batch_size < 1 or batch_size > self.n_rows:
            raise ValueError(f"batch_size {batch_size} out of range "
                             f"[1, {self.n_rows}]")
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = max(1, prefetch)

        self._lib = _build_native() if native in (None, True) else None
        if native is True and self._lib is None:
            raise RuntimeError("native=True but the native loader failed to build")
        self._handle = None
        if self._lib is not None:
            self._handle = self._create_native()
            if not self._handle:
                raise RuntimeError("dl_create rejected the loader configuration")
        else:
            self._rng = np.random.RandomState(seed)
            self._perm = None
            self._cursor = 0
            self._epochs = 0

    # ------------------------------------------------------------------ native
    def _create_native(self):
        n = len(self._keys)
        ptrs = (ctypes.c_void_p * n)(
            *[self._arrays[k].ctypes.data for k in self._keys])
        row_bytes = (ctypes.c_uint64 * n)(
            *[self._arrays[k].nbytes // self.n_rows for k in self._keys])
        return self._lib.dl_create(
            n, ptrs, row_bytes, self.n_rows, self.batch_size, self.prefetch,
            int(self.shuffle), self.seed)

    @property
    def is_native(self) -> bool:
        return self._handle is not None

    @property
    def epochs_completed(self) -> int:
        """Epoch wraps so far. Native path: producer-side (the prefetch worker
        runs up to ``prefetch`` batches ahead of consumption, so this can read
        ahead of what ``next()`` has returned). Fallback: consumer-side."""
        if self._handle is not None:
            return int(self._lib.dl_epochs_completed(self._handle))
        return self._epochs

    def next(self) -> Dict[str, np.ndarray]:
        """The next batch (blocks on the prefetch ring in the native path)."""
        out = {k: np.empty((self.batch_size,) + self._arrays[k].shape[1:],
                           self._arrays[k].dtype) for k in self._keys}
        if self._handle is not None:
            ptrs = (ctypes.c_void_p * len(self._keys))(
                *[out[k].ctypes.data for k in self._keys])
            if self._lib.dl_next(self._handle, ptrs) != 0:
                raise RuntimeError("Native loader was shut down")
            return out
        # numpy fallback: same drop-last/reshuffle-on-wrap semantics.
        if self._perm is None or self.n_rows - self._cursor < self.batch_size:
            if self._perm is not None:
                self._epochs += 1
            self._perm = (self._rng.permutation(self.n_rows) if self.shuffle
                          else np.arange(self.n_rows))
            self._cursor = 0
        idx = self._perm[self._cursor:self._cursor + self.batch_size]
        self._cursor += self.batch_size
        for k in self._keys:
            out[k][...] = self._arrays[k][idx]
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.next()

    def close(self):
        if self._handle is not None:
            self._lib.dl_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def device_prefetch(loader: DataLoader, runner, depth: int = 2):
    """Iterator of on-device sharded batches, ``depth`` transfers ahead.

    ``runner.shard_batch`` is the feed remapping (split over data axes /
    replicate); issuing it ahead of consumption overlaps host->HBM transfer with
    the running step — the TPU analogue of the reference's staged input queues.
    """
    import collections
    pending = collections.deque()
    it = iter(loader)
    while True:
        while len(pending) < max(1, depth):
            pending.append(runner.shard_batch(next(it)))
        yield pending.popleft()
