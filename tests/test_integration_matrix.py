"""Strategy x model-case x mesh-shape integration matrix.

The reference's integration tier ran the cartesian product {2 resource specs} x
{10 strategies} x {9 model cases} (``tests/integration/test_all.py:20-70``), with
cases covering placeholders, CNNs, sparse embeddings, ``while_loop`` models, and
dynamic RNNs. Same product here on the 8-device CPU-sim mesh: every strategy
family must train every case shape — dense MLP, conv net, sparse embedding,
PARTITIONED sparse embedding (uneven rows), ``lax.scan`` recurrence (the
while_loop analog), LSTM-style gated recurrence — on BOTH mesh shapes (pure
data-parallel, and a TP-capable ``{model: 2}`` mesh), each combo value-exact
against the single-process jit loss at step 0 and descending thereafter. No
forked processes needed: each combo builds a fresh AutoDist (the reference
needed a process per combo because its runtime was one-instance-per-process,
``test_all.py:49-70``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.strategy import (AllReduce, AutoStrategy, Parallax, PartitionedAR,
                                   PartitionedPS, PS, PSLoadBalancing,
                                   RandomAxisPartitionAR, UnevenPartitionedPS)
from shardmap_compat import skip_unless_shard_map

BATCH = 16


# --------------------------------------------------------------------- cases

def _case_mlp():
    """Dense MLP on random regression (reference c0/c3: placeholder + numpy feeds)."""
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(12, 16) * 0.1, jnp.float32),
        "b1": jnp.zeros((16,)),
        "w2": jnp.asarray(rng.randn(16, 1) * 0.1, jnp.float32),
    }
    batch = {"x": rng.randn(BATCH, 12).astype(np.float32),
             "y": rng.randn(BATCH, 1).astype(np.float32)}

    def loss(p, b):
        h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - b["y"]) ** 2)

    return params, batch, loss


def _case_cnn():
    """Tiny conv classifier (reference c1/c7: Keras image models)."""
    rng = np.random.RandomState(1)
    params = {
        "conv": jnp.asarray(rng.randn(3, 3, 1, 4) * 0.1, jnp.float32),
        "w": jnp.asarray(rng.randn(8 * 8 * 4, 10) * 0.1, jnp.float32),
        "b": jnp.zeros((10,)),
    }
    batch = {"x": rng.randn(BATCH, 8, 8, 1).astype(np.float32),
             "y": rng.randint(0, 10, size=(BATCH,))}

    def loss(p, b):
        h = jax.lax.conv_general_dilated(
            b["x"], p["conv"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h = jax.nn.relu(h).reshape(b["x"].shape[0], -1)
        logits = h @ p["w"] + p["b"]
        return -jnp.mean(jax.nn.log_softmax(logits)[jnp.arange(logits.shape[0]), b["y"]])

    return params, batch, loss


def _case_embedding():
    """Sparse embedding lookup (reference c2: sentiment / sparse grads)."""
    rng = np.random.RandomState(2)
    params = {
        "emb": jnp.asarray(rng.randn(40, 8) * 0.1, jnp.float32),
        "w": jnp.asarray(rng.randn(8, 1) * 0.1, jnp.float32),
    }
    batch = {"idx": rng.randint(0, 40, size=(BATCH, 5)),
             "y": rng.randn(BATCH, 1).astype(np.float32)}

    def loss(p, b):
        e = jnp.take(p["emb"], b["idx"], axis=0).mean(axis=1)
        return jnp.mean((e @ p["w"] - b["y"]) ** 2)

    return params, batch, loss


def _case_scan_rnn():
    """lax.scan recurrence — the while_loop model (reference c4)."""
    rng = np.random.RandomState(3)
    params = {
        "w_in": jnp.asarray(rng.randn(4, 8) * 0.3, jnp.float32),
        "w_rec": jnp.asarray(rng.randn(8, 8) * 0.1, jnp.float32),
        "w_out": jnp.asarray(rng.randn(8, 1) * 0.3, jnp.float32),
    }
    batch = {"x": rng.randn(BATCH, 6, 4).astype(np.float32),
             "y": rng.randn(BATCH, 1).astype(np.float32)}

    def loss(p, b):
        def cell(h, x_t):
            h = jnp.tanh(x_t @ p["w_in"] + h @ p["w_rec"])
            return h, None

        h0 = jnp.zeros((b["x"].shape[0], 8))
        h, _ = jax.lax.scan(cell, h0, b["x"].transpose(1, 0, 2))
        return jnp.mean((h @ p["w_out"] - b["y"]) ** 2)

    return params, batch, loss


def _case_lstm():
    """Gated (LSTM-style) recurrence (reference c6: dynamic LSTM)."""
    rng = np.random.RandomState(4)
    d_in, d_h = 4, 8
    params = {
        "w": jnp.asarray(rng.randn(d_in + d_h, 4 * d_h) * 0.2, jnp.float32),
        "b": jnp.zeros((4 * d_h,)),
        "w_out": jnp.asarray(rng.randn(d_h, 1) * 0.3, jnp.float32),
    }
    batch = {"x": rng.randn(BATCH, 5, d_in).astype(np.float32),
             "y": rng.randn(BATCH, 1).astype(np.float32)}

    def loss(p, b):
        def cell(carry, x_t):
            h, c = carry
            z = jnp.concatenate([x_t, h], axis=-1) @ p["w"] + p["b"]
            i, f, g, o = jnp.split(z, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), None

        h0 = jnp.zeros((b["x"].shape[0], d_h))
        (h, _), _ = jax.lax.scan(cell, (h0, h0), b["x"].transpose(1, 0, 2))
        return jnp.mean((h @ p["w_out"] - b["y"]) ** 2)

    return params, batch, loss


def _case_partitioned_embedding():
    """LARGE sparse embedding with a prime row count (reference c2 at the
    partitioner's scale): partitioning strategies must split the table —
    unevenly, 1031 doesn't divide — while the gradient stays sparse."""
    rng = np.random.RandomState(5)
    params = {
        "emb": jnp.asarray(rng.randn(1031, 16) * 0.1, jnp.float32),
        "w": jnp.asarray(rng.randn(16, 1) * 0.1, jnp.float32),
    }
    batch = {"idx": rng.randint(0, 1031, size=(BATCH, 6)),
             "y": rng.randn(BATCH, 1).astype(np.float32)}

    def loss(p, b):
        e = jnp.take(p["emb"], b["idx"], axis=0).mean(axis=1)
        return jnp.mean((e @ p["w"] - b["y"]) ** 2)

    return params, batch, loss


CASES = {
    "mlp": _case_mlp,
    "cnn": _case_cnn,
    "embedding": _case_embedding,
    "part_embedding": _case_partitioned_embedding,
    "scan_rnn": _case_scan_rnn,
    "lstm": _case_lstm,
}

STRATEGIES = [
    PS, PSLoadBalancing, PartitionedPS, UnevenPartitionedPS,
    AllReduce, PartitionedAR, RandomAxisPartitionAR, Parallax, AutoStrategy,
]

# Two mesh shapes, the reference's {2 resource specs} dimension: the default
# pure-data mesh, and a TP-capable mesh with a non-trivial model axis.
MESHES = {
    "data8": None,
    "model2": "{nodes: [{address: localhost, tpus: 8}], mesh: {model: 2}}",
}


@pytest.mark.parametrize("mesh_name", list(MESHES), ids=str)
@pytest.mark.parametrize("case_name", list(CASES), ids=str)
@pytest.mark.parametrize("builder_cls", STRATEGIES, ids=lambda c: c.__name__)
def test_strategy_times_case(builder_cls, case_name, mesh_name):
    params, batch, loss = CASES[case_name]()
    # Value-exactness anchor: whatever the strategy/mesh does, step 0's loss
    # must equal the plain single-process jit loss on the same params/batch
    # (the reference's c0 criterion).
    expected0 = float(jax.jit(loss)(params, {k: jnp.asarray(v)
                                             for k, v in batch.items()}))
    ad = AutoDist(MESHES[mesh_name], strategy_builder=builder_cls())
    step = ad.function(loss, params, optax.adam(3e-2), example_batch=batch)
    skip_unless_shard_map(step.runner)  # sparse-wire combos need the explicit path
    losses = [float(step(batch)) for _ in range(8)]
    np.testing.assert_allclose(losses[0], expected0, rtol=1e-5, atol=1e-6,
                               err_msg=f"{builder_cls.__name__}/{case_name}/"
                                       f"{mesh_name}")
    assert np.all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], (builder_cls.__name__, case_name, losses)
    final = step.get_state().params
    assert all(np.all(np.isfinite(np.asarray(v)))
               for v in jax.tree_util.tree_leaves(final))


@pytest.mark.parametrize("case_name", list(CASES), ids=str)
@pytest.mark.parametrize("builder_cls", [AllReduce, PartitionedPS, Parallax],
                         ids=lambda c: c.__name__)
def test_strategy_times_case_with_accumulation(builder_cls, case_name):
    """The micro-batch scan must compose with every case shape (BATCH=16 splits
    into 2 micro-batches over the 8-device mesh)."""
    params, batch, loss = CASES[case_name]()
    ad = AutoDist(strategy_builder=builder_cls())
    step = ad.function(loss, params, optax.adam(3e-2), example_batch=batch,
                       accumulation_steps=2)
    skip_unless_shard_map(step.runner)  # sparse-wire combos need the explicit path
    losses = [float(step(batch)) for _ in range(8)]
    assert np.all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], (builder_cls.__name__, case_name, losses)
