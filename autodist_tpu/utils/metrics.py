"""Throughput instrumentation.

Counterparts of the reference's benchmark-side observability (SURVEY.md §5.1):
``TimeHistory`` (``examples/benchmark/imagenet.py:84-133``, examples/sec per log
period + run average) and ``ExamplesPerSecondHook``
(``examples/benchmark/utils/logs/hooks.py:28-130``). These live in the framework
here (the reference kept them in examples) so every example/benchmark shares one
implementation.
"""

import time
from typing import List, Optional

from autodist_tpu.utils import logging


class ThroughputMeter:
    """examples/sec (or tokens/sec) per log period plus a run average."""

    def __init__(self, batch_size: int, log_every: int = 100,
                 unit: str = "examples", warmup_steps: int = 1,
                 log: bool = True):
        self._batch_size = batch_size
        self._log_every = log_every
        self._unit = unit
        self._warmup = warmup_steps
        self._log = log  # False when the caller emits its own period log line
        self._step = 0
        now = time.perf_counter()
        # warmup_steps=0 means "count from construction"; otherwise these restart
        # when the last warmup step lands.
        self._period_start: float = now
        self._run_start: float = now
        self._run_steps = 0
        self.history: List[float] = []

    def step(self, sync=None) -> Optional[float]:
        """Record one completed step; returns the period rate when a period ends.

        Pass the step's fetched value (e.g. the loss array) as ``sync``: dispatch is
        asynchronous, so at period boundaries the meter forces a device->host read
        of it before taking the clock — otherwise rates measure dispatch, not
        compute."""
        self._step += 1
        at_boundary = (self._step > self._warmup
                       and (self._run_steps + 1) % self._log_every == 0)
        if (at_boundary or self._step == self._warmup) and sync is not None:
            try:
                import jax
                jax.device_get(sync)
            except Exception:
                pass
        now = time.perf_counter()
        if self._step <= self._warmup:
            # Exclude compile/warmup from rates (reference TimeHistory did the same
            # by starting timers on_batch_begin after the first epoch).
            self._period_start = now
            self._run_start = now
            self._run_steps = 0
            return None
        self._run_steps += 1
        if self._run_steps % self._log_every == 0:
            rate = self._log_every * self._batch_size / (now - self._period_start)
            self.history.append(rate)
            if self._log:
                logging.info("step %d: %.1f %s/sec", self._step, rate, self._unit)
            self._period_start = now
            return rate
        return None

    @property
    def average(self) -> Optional[float]:
        """Run-average rate excluding warmup (reference logged the same)."""
        if not self._run_steps:
            return None
        elapsed = time.perf_counter() - self._run_start
        return self._run_steps * self._batch_size / elapsed
