"""train(): checkpoint/resume loop — an interrupted run continues exactly.

Mirrors the reference's resumability contract (chief-gated saver on a shared
filesystem, ``tests/integration/cases/c10.py``) at the API level: a run killed
after a save and restarted with the same command must land on the same final
state as the uninterrupted run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist, train
from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.strategy import AllReduce


def _loss(p, b):
    return jnp.mean((b["y"] - (b["x"] @ p["w"] + p["b"])) ** 2)


def _params():
    rng = np.random.RandomState(7)
    return {"w": rng.randn(4, 1).astype(np.float32), "b": np.zeros((1,), np.float32)}


def _batch_fn(i):
    rng = np.random.RandomState(100 + i)   # deterministic per-step batches
    return {"x": rng.randn(32, 4).astype(np.float32),
            "y": rng.randn(32, 1).astype(np.float32)}


def _runner():
    ad = AutoDist(strategy_builder=AllReduce())
    return ad.create_distributed_session(_loss, _params(), optax.adam(1e-2),
                                         example_batch=_batch_fn(0))


def test_uninterrupted_vs_resumed_identical(tmp_path):
    direct = train(_runner(), _params(), _batch_fn, steps=10, log_every=0)

    ckpt = str(tmp_path / "ckpts")
    first = train(_runner(), _params(), _batch_fn, steps=4, checkpoint_dir=ckpt,
                  log_every=0)
    assert int(first.step) == 4
    assert Saver.latest_checkpoint(ckpt) is not None

    resumed = train(_runner(), _params(), _batch_fn, steps=10,
                    checkpoint_dir=ckpt, log_every=0)
    assert int(resumed.step) == 10
    d, r = jax.device_get(direct.params), jax.device_get(resumed.params)
    for k in d:
        np.testing.assert_allclose(r[k], d[k], rtol=1e-6, atol=1e-6)


def test_resume_skips_completed_run(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    done = train(_runner(), _params(), _batch_fn, steps=5, checkpoint_dir=ckpt,
                 log_every=0)
    again = train(_runner(), _params(), _batch_fn, steps=5, checkpoint_dir=ckpt,
                  log_every=0)
    assert int(again.step) == 5
    d, a = jax.device_get(done.params), jax.device_get(again.params)
    for k in d:
        np.testing.assert_allclose(a[k], d[k], rtol=1e-6, atol=1e-6)


def test_periodic_saves_and_rotation(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    train(_runner(), _params(), _batch_fn, steps=9, checkpoint_dir=ckpt,
          save_every=2, max_to_keep=3, log_every=0)
    import glob
    kept = sorted(glob.glob(f"{ckpt}/model-*.npz"))
    assert len(kept) == 3  # rotation caps the kept set
    assert Saver.latest_checkpoint(ckpt).endswith("model-9")


def test_async_periodic_saves_match_sync(tmp_path):
    """async_save=True: periodic writes ride the background thread but the
    on-disk result — rotation, latest pointer, resumability — is identical
    to synchronous saving, and train() returns with everything durable."""
    sync_dir, async_dir = str(tmp_path / "sync"), str(tmp_path / "async")
    train(_runner(), _params(), _batch_fn, steps=9, checkpoint_dir=sync_dir,
          save_every=2, max_to_keep=3, log_every=0)
    train(_runner(), _params(), _batch_fn, steps=9, checkpoint_dir=async_dir,
          save_every=2, max_to_keep=3, log_every=0, async_save=True)
    import glob
    import os
    names = lambda d: sorted(os.path.basename(p)  # noqa: E731
                             for p in glob.glob(f"{d}/model-*.npz"))
    assert names(sync_dir) == names(async_dir)
    assert Saver.latest_checkpoint(async_dir).endswith("model-9")
    resumed = train(_runner(), _params(), _batch_fn, steps=12,
                    checkpoint_dir=async_dir, log_every=0, async_save=True)
    direct = train(_runner(), _params(), _batch_fn, steps=12, log_every=0)
    d, r = jax.device_get(direct.params), jax.device_get(resumed.params)
    for k in d:
        np.testing.assert_allclose(r[k], d[k], rtol=1e-6, atol=1e-6)


def test_iterator_batches_end_early():
    batches = [_batch_fn(i) for i in range(4)]
    state = train(_runner(), _params(), iter(batches), steps=100, log_every=0)
    assert int(state.step) == 4


def test_iterator_resume_fast_forwards(tmp_path):
    """Resumed iterable runs must not replay already-consumed batches."""
    direct = train(_runner(), _params(), [_batch_fn(i) for i in range(8)],
                   steps=8, log_every=0)
    ckpt = str(tmp_path / "ckpts")
    train(_runner(), _params(), [_batch_fn(i) for i in range(8)], steps=4,
          checkpoint_dir=ckpt, log_every=0)
    resumed = train(_runner(), _params(), [_batch_fn(i) for i in range(8)],
                    steps=8, checkpoint_dir=ckpt, log_every=0)
    assert int(resumed.step) == 8
    d, r = jax.device_get(direct.params), jax.device_get(resumed.params)
    for k in d:
        np.testing.assert_allclose(r[k], d[k], rtol=1e-6, atol=1e-6)


def test_two_names_share_directory_without_cross_talk(tmp_path):
    """GAN-style: two models checkpoint into one directory under different
    names; each resumes its own line and never rotates the other's files."""
    ckpt = str(tmp_path / "ckpts")
    a = train(_runner(), _params(), _batch_fn, steps=3, checkpoint_dir=ckpt,
              checkpoint_name="gen", log_every=0)
    b = train(_runner(), _params(), _batch_fn, steps=5, checkpoint_dir=ckpt,
              checkpoint_name="disc", save_every=2, max_to_keep=2, log_every=0)
    # Resume "gen" to 6: must restore gen-3 (not disc-5) and extend it.
    a2 = train(_runner(), _params(), _batch_fn, steps=6, checkpoint_dir=ckpt,
               checkpoint_name="gen", log_every=0)
    assert int(a2.step) == 6
    direct = train(_runner(), _params(), _batch_fn, steps=6, log_every=0)
    d, r = jax.device_get(direct.params), jax.device_get(a2.params)
    for k in d:
        np.testing.assert_allclose(r[k], d[k], rtol=1e-6, atol=1e-6)
    import glob
    # disc's rotation (max_to_keep=2) never deleted gen's files.
    assert sorted(p.split("/")[-1] for p in glob.glob(f"{ckpt}/gen-*.npz")) \
        == ["gen-3.npz", "gen-6.npz"]
    assert len(glob.glob(f"{ckpt}/disc-*.npz")) == 2


def test_dash_prefix_names_do_not_collide(tmp_path):
    """name="gen" must never resume from "gen-ema" checkpoints."""
    ckpt = str(tmp_path / "ckpts")
    train(_runner(), _params(), _batch_fn, steps=3, checkpoint_dir=ckpt,
          checkpoint_name="gen", log_every=0)
    train(_runner(), _params(), _batch_fn, steps=7, checkpoint_dir=ckpt,
          checkpoint_name="gen-ema", log_every=0)  # saves last -> owns state file
    assert Saver.latest_checkpoint(ckpt, name="gen").endswith("/gen-3")
    assert Saver.latest_checkpoint(ckpt, name="gen-ema").endswith("/gen-ema-7")
    resumed = train(_runner(), _params(), _batch_fn, steps=5, checkpoint_dir=ckpt,
                    checkpoint_name="gen", log_every=0)
    assert int(resumed.step) == 5  # resumed gen-3, not gen-ema-7


def test_eval_hook_fires_on_current_params(tmp_path):
    """eval_every runs the forward-only evaluate on the training state; the
    held-out loss decreases as training progresses."""
    evals = []
    held_out = _batch_fn(999)
    train(_runner(), _params(), _batch_fn, steps=9, log_every=0,
          eval_every=3, eval_batch=held_out,
          on_eval=lambda step, val: evals.append((step, float(val))))
    assert [s for s, _ in evals] == [3, 6, 9]
    assert evals[-1][1] < evals[0][1]


def test_eval_every_without_batch_raises():
    import pytest as _pytest
    with _pytest.raises(ValueError, match="eval_batch"):
        train(_runner(), _params(), _batch_fn, steps=2, eval_every=1)


def test_train_consumes_dataloader():
    """The native/fallback DataLoader's iterator plugs into train() directly
    (the host data pipeline and the loop compose)."""
    from autodist_tpu.data.loader import DataLoader
    rng = np.random.RandomState(5)
    loader = DataLoader({"x": rng.randn(96, 4).astype(np.float32),
                         "y": rng.randn(96, 1).astype(np.float32)},
                        batch_size=32)
    try:
        state = train(_runner(), _params(), iter(loader), steps=6, log_every=0)
        assert int(state.step) == 6  # continuous stream: never exhausts
    finally:
        loader.close()


def test_metrics_callback_fires():
    seen = []
    train(_runner(), _params(), _batch_fn, steps=7, log_every=3,
          on_metrics=lambda step, loss, rate: seen.append((step, loss, rate)))
    # The meter's first step is warmup (excluded), so periods end at 1+3k.
    assert [s for s, _, _ in seen] == [4, 7]
    assert all(rate > 0 for _, _, rate in seen)
