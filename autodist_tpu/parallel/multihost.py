"""Multi-host bootstrap: jax.distributed initialization from the coordinator env.

Replaces the reference's per-node ``tf.Server`` startup (``utils/server_starter.py:
48-75``): instead of a grpc server per node, every host joins one SPMD program via
``jax.distributed.initialize`` pointed at the chief's coordination service. The env
variables are set by the Coordinator on workers; the chief derives its own values
from the cluster spec.
"""

from typing import Optional

from autodist_tpu import const
from autodist_tpu.utils import logging

_initialized = False


def maybe_initialize_multihost(cluster=None) -> bool:
    """Initialize jax.distributed when a multi-process env is configured.

    Returns True if distributed init ran (or already had). Single-process runs
    (no coordinator env, single-node spec) skip initialization entirely.
    """
    global _initialized
    if _initialized:
        return True

    coordinator = const.ENV.AUTODIST_COORDINATOR_ADDR.val
    num_processes = const.ENV.AUTODIST_NUM_PROCESSES.val
    process_id = const.ENV.AUTODIST_PROCESS_ID.val

    if not coordinator and cluster is not None and cluster.num_processes > 1:
        # Chief in a multi-node spec: derive from the cluster spec.
        coordinator = cluster.cluster_spec["coordinator"]
        num_processes = cluster.num_processes
        process_id = 0

    if not coordinator or num_processes <= 1:
        return False

    import jax
    if _externally_initialized():
        logging.info("jax.distributed already initialized outside AutoDist; reusing")
        _initialized = True
        return True
    _enable_cpu_collectives(jax)
    logging.info("jax.distributed.initialize(%s, num_processes=%d, process_id=%d)",
                 coordinator, num_processes, process_id)
    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        if "must be called before" in str(e):
            raise RuntimeError(
                "Multi-node AutoDist must bootstrap jax.distributed before any "
                "JAX computation, but this process already initialized the XLA "
                "backend (e.g. via jnp array creation or jax.devices()). Keep "
                "model setup in numpy until create_distributed_session(), or "
                "call jax.distributed.initialize() yourself at program start."
            ) from e
        raise
    _initialized = True
    return True


def _enable_cpu_collectives(jax) -> None:
    """Multiprocess SPMD on the CPU backend needs a cross-process collectives
    implementation, and jax's default is ``none`` — every cross-process program
    would fail with "Multiprocess computations aren't implemented on the CPU
    backend". Select gloo (bundled with jaxlib) before the backend
    initializes; a user's explicit choice (mpi, or an older jax without the
    flag) is left alone."""
    try:
        if jax.config.read("jax_cpu_collectives_implementation") == "none":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            logging.info("CPU backend: enabled gloo cross-process collectives")
    except AttributeError:  # jax build without the flag: nothing to select
        pass


def _externally_initialized() -> bool:
    """True when the user already ran jax.distributed.initialize themselves (the
    standard pattern at the top of pod scripts) — calling it twice raises."""
    try:
        from jax._src import distributed
        return distributed.global_state.client is not None
    except Exception:
        return False
