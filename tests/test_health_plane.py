"""Training-health plane + flight recorder + live ops console.

Covers the PR 8 contract end to end (docs/usage/observability.md "Training
health monitors" / "Flight recorder" / "Live console"):

- the fused on-device numerics bundle (NaN/Inf probes, grad/update/param
  norms) and its unroll-block reduction;
- the host monitor's EWMA loss-spike z-score and non-finite detection;
- the end-to-end trigger proof: an induced NaN batch inside ``train()``
  produces a complete flight-recorder snapshot dir with NO human action,
  and an induced watchdog stall does the same;
- the snapshot dir schema (manifest/metrics/events/trace), ring eviction at
  K, debounce vs manual bypass;
- ``halt`` raising :class:`telemetry.HealthHalt` with the live state intact;
- health PARITY: enabling monitors changes no trained params (bit-identical
  step outputs, per-step AND ``unroll=K``);
- the ``status``/``record`` wire opcodes on a loopback PSServer and
  ``tools/adtop.py --once`` rendering against it;
- ``dump_events_jsonl`` + ``tracedump --events`` instant-marker merge;
- the new ``AUTODIST_HEALTH*`` / ``AUTODIST_RECORDER*`` flag registrations.

Pure in-process host tests — no subprocess spawns (GL008-clean), named to
sort inside the tier-1 window (before test_image_data).
"""

import importlib.util
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist, const, telemetry, train  # noqa: E402
from autodist_tpu.strategy import AllReduce  # noqa: E402
from autodist_tpu.telemetry import health, recorder  # noqa: E402


@pytest.fixture(autouse=True)
def _telemetry_reset():
    """Leave process-global telemetry as found: disabled, empty span ring,
    empty EVENT ring (anomaly records from one test must not bleed into the
    next test's snapshot/adtop/tracedump output), no installed recorder
    (instruments stay — the registry is additive-only and shared)."""
    telemetry.disable()
    telemetry.clear()
    telemetry.registry().clear_events()
    recorder.set_recorder(None)
    yield
    telemetry.disable()
    telemetry.clear()
    telemetry.registry().clear_events()
    recorder.set_recorder(None)


# ------------------------------------------------------------------ fixtures

def _loss(p, b):
    return jnp.mean((b["y"] - b["x"] @ p["w"]) ** 2)


def _params():
    return {"w": np.random.RandomState(0).randn(4, 1).astype(np.float32)}


def _batch(i, nan_at=None):
    rng = np.random.RandomState(100 + i)
    b = {"x": rng.randn(32, 4).astype(np.float32),
         "y": rng.randn(32, 1).astype(np.float32)}
    if nan_at is not None and i == nan_at:
        b["x"] = b["x"] * np.nan
    return b


def _session(health_on):
    ad = AutoDist(strategy_builder=AllReduce())
    return ad.create_distributed_session(
        _loss, _params(), optax.adam(1e-2), example_batch=_batch(0),
        health=health_on)


@pytest.fixture(scope="module")
def runner_off():
    return _session(False)


@pytest.fixture(scope="module")
def runner_on():
    return _session(True)


# ------------------------------------------------------- device-side bundle

def test_device_bundle_values_and_nonfinite_probe():
    g = {"w": jnp.array([3.0, 4.0])}
    u = {"w": jnp.array([0.3, 0.4])}
    p = {"w": jnp.array([1.0, 0.0]), "n_steps": jnp.array([7, 8])}  # ints skip
    b = np.asarray(jax.jit(health.device_bundle)(g, u, p, jnp.float32(0.5)))
    assert list(b.shape) == [4]
    assert b[0] == 0.0
    assert b[1] == pytest.approx(5.0)       # grad L2
    assert b[2] == pytest.approx(0.5)       # update L2
    assert b[3] == pytest.approx(1.0)       # param L2 (int leaf skipped)
    # Any NaN in a tree propagates into its squared norm -> probe flags.
    g_bad = {"w": jnp.array([np.nan, 1.0])}
    b2 = np.asarray(jax.jit(health.device_bundle)(g_bad, u, p,
                                                  jnp.float32(0.5)))
    assert b2[0] >= 1.0 and not np.isfinite(b2[1])
    # A non-finite loss flags even with clean trees.
    b3 = np.asarray(jax.jit(health.device_bundle)(g, u, p,
                                                  jnp.float32(np.inf)))
    assert b3[0] >= 1.0


def test_reduce_bundle_sums_flags_and_maxes_norms():
    stacked = jnp.array([[0.0, 1.0, 0.2, 5.0],
                         [2.0, 3.0, 0.1, 4.0],
                         [1.0, 2.0, 0.3, 6.0]], jnp.float32)
    out = np.asarray(jax.jit(health.reduce_bundle)(stacked))
    assert out[0] == 3.0                    # nonfinite flags SUM
    assert out[1] == 3.0 and out[2] == pytest.approx(0.3) and out[3] == 6.0


# ------------------------------------------------------------- host monitor

def test_monitor_loss_spike_zscore_and_gauges():
    mon = health.HealthMonitor(health.HealthConfig(action="warn", z_max=4.0))
    bundle = np.array([0.0, 1.0, 0.01, 2.0], np.float32)
    rng = np.random.RandomState(3)
    for step in range(1, 30):               # steady plateau builds the EWMA
        assert mon.observe(step, [1.0 + 0.01 * rng.randn()], bundle) == []
    found = mon.observe(30, [50.0], bundle)
    assert [a["kind"] for a in found] == ["loss_spike"]
    assert found[0]["z"] > 4.0
    snap = telemetry.snapshot()
    assert snap["train.health.grad_norm"] == 1.0
    assert snap["train.health.update_ratio"] == pytest.approx(0.005)
    assert snap["train.health.loss_z"] > 4.0
    assert snap["train.health.anomalies"] >= 1
    # The grad-norm distribution resolves the NORM_BUCKETS family.
    assert "le:0.001" in snap["train.health.grad_norm.dist"]
    # The anomaly is a structured event too.
    assert any(e["name"] == "health.anomaly" and e["kind"] == "loss_spike"
               for e in telemetry.events())


def test_monitor_nonfinite_bundle_triggers_recorder(tmp_path):
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=4,
                                  min_interval_s=0.0)
    mon = health.HealthMonitor(health.HealthConfig(action="record"),
                               recorder=rec)
    found = mon.observe(7, [1.0], np.array([1.0, np.nan, 0.1, 2.0]))
    assert [a["kind"] for a in found] == ["nonfinite"]
    snaps = rec.snapshots()
    assert len(snaps) == 1 and "health.nonfinite" in snaps[0]
    # NaN losses flag as nonfinite even without a bundle (async/PS loops).
    mon2 = health.HealthMonitor(health.HealthConfig(action="warn"))
    assert [a["kind"] for a in mon2.observe(1, [np.nan], None)] \
        == ["nonfinite"]


# ------------------------------------------------- flight recorder mechanics

def test_snapshot_dir_schema_pinned(tmp_path):
    telemetry.enable()
    with telemetry.span("work.unit", idx=1):
        pass
    telemetry.event("health.anomaly", kind="loss_spike", step=9, z=7.1)
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=4,
                                  min_interval_s=0.0)
    path = rec.record("schema_pin")
    assert sorted(os.listdir(path)) == sorted(recorder.SNAPSHOT_FILES)
    manifest = json.load(open(os.path.join(path, "manifest.json")))
    for key in ("reason", "seq", "t_wall_s", "host", "pid", "flags",
                "versions", "files", "memory"):
        assert key in manifest
    assert manifest["reason"] == "schema_pin"
    # The PR 20 memory section: snapshot shell + forensics extras, present
    # in EVERY manifest (a stable empty shell when the plane never armed).
    assert {"owned", "live_bytes", "pressure", "budget_bytes",
            "budget_source", "devices", "programs",
            "history"} <= set(manifest["memory"])
    metrics = json.load(open(os.path.join(path, "metrics.json")))
    assert isinstance(metrics, dict)
    doc = json.load(open(os.path.join(path, "trace.json")))
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert "work.unit" in names             # the local ring made it in
    marks = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert any(m["name"] == "health.anomaly" for m in marks)
    events = telemetry.load_events_jsonl(os.path.join(path, "events.jsonl"))
    assert any(e["name"] == "health.anomaly" and e["kind"] == "loss_spike"
               for e in events)


def test_snapshot_ring_evicts_at_k(tmp_path):
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=3,
                                  min_interval_s=0.0)
    for i in range(5):
        assert rec.record(f"r{i}") is not None
    snaps = rec.snapshots()
    assert len(snaps) == 3
    assert [os.path.basename(s) for s in snaps] == \
        ["snap-0002-w0-r2", "snap-0003-w0-r3", "snap-0004-w0-r4"]


def test_snapshot_ring_numeric_order_past_five_digits(tmp_path):
    """Eviction order is NUMERIC seq order: snap-10000 is newer than
    snap-9999 (a lexicographic sort would evict the newest dir the moment
    the counter grows a digit)."""
    base = tmp_path / "fr"
    for name in ("snap-10000-w0-r", "snap-9999-w0-r"):
        (base / name).mkdir(parents=True)
    rec = recorder.FlightRecorder(str(base), keep=8, min_interval_s=0.0)
    assert [os.path.basename(p) for p in rec.snapshots()] == \
        ["snap-9999-w0-r", "snap-10000-w0-r"]
    assert rec._seq == 10001


def test_debounce_blocks_auto_but_not_manual(tmp_path):
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=8,
                                  min_interval_s=3600.0)
    assert rec.maybe_record("first") is not None
    assert rec.maybe_record("second") is None        # inside the window
    assert rec.record("manual") is not None          # bypasses the debounce
    assert len(rec.snapshots()) == 2


def test_maybe_record_is_noop_unarmed(tmp_path, monkeypatch):
    assert recorder.maybe_record("nothing") is None  # no recorder, flag off
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=2,
                                  min_interval_s=0.0)
    recorder.set_recorder(rec)
    assert recorder.maybe_record("armed") is not None


# ------------------------------------------------------ end-to-end in train()

def test_induced_nan_writes_snapshot_with_no_human_action(runner_on,
                                                          tmp_path):
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=8,
                                  min_interval_s=0.0)
    mon = health.HealthMonitor(health.HealthConfig(action="record"),
                               recorder=rec)
    state = train(runner_on, _params(), lambda i: _batch(i, nan_at=2),
                  steps=5, log_every=1, health_monitor=mon)
    assert int(state.step) == 5             # record does not stop the run
    assert any(a["kind"] == "nonfinite" for a in mon.anomalies)
    snaps = rec.snapshots()
    assert snaps, "the induced NaN produced no flight-recorder snapshot"
    assert sorted(os.listdir(snaps[0])) == sorted(recorder.SNAPSHOT_FILES)
    assert json.load(open(os.path.join(snaps[0], "trace.json")))


def test_halt_raises_cleanly_with_state_intact(runner_on):
    mon = health.HealthMonitor(health.HealthConfig(action="halt"))
    with pytest.raises(health.HealthHalt) as ei:
        train(runner_on, _params(), lambda i: _batch(i, nan_at=2),
              steps=8, log_every=1, health_monitor=mon)
    err = ei.value
    # The NaN enters at step index 2; the boundary observing it is step 3+.
    assert 3 <= err.step <= 8
    assert int(err.state.step) == err.step  # the LIVE state rides the raise
    assert jax.device_get(err.state.params)["w"].shape == (4, 1)
    assert any(a["kind"] == "nonfinite" for a in err.anomalies)


def test_health_parity_params_bit_identical(runner_off, runner_on):
    s_off, s_on = runner_off.init(_params()), runner_on.init(_params())
    for i in range(4):
        s_off, _ = runner_off.run(s_off, _batch(i))
        s_on, _ = runner_on.run(s_on, _batch(i))
    np.testing.assert_array_equal(jax.device_get(s_off.params)["w"],
                                  jax.device_get(s_on.params)["w"])
    # unroll=K: the scanned body with the bundle stays bit-identical too.
    blocks = [_batch(i) for i in range(4, 8)]
    s_off, _ = runner_off.run_many(s_off, blocks)
    s_on, _ = runner_on.run_many(s_on, blocks)
    np.testing.assert_array_equal(jax.device_get(s_off.params)["w"],
                                  jax.device_get(s_on.params)["w"])


def test_tail_partial_period_still_observed(runner_on):
    """steps NOT a multiple of log_every: a NaN in the final partial period
    must still reach the monitor (end-of-run flush) — the last boundary
    would otherwise silently drop it."""
    mon = health.HealthMonitor(health.HealthConfig(action="warn"))
    state = train(runner_on, _params(), lambda i: _batch(i, nan_at=4),
                  steps=5, log_every=4, health_monitor=mon)
    assert int(state.step) == 5
    assert any(a["kind"] == "nonfinite" for a in mon.anomalies)


def test_unroll_block_reduce_surfaces_mid_block_nan(runner_on):
    state = runner_on.init(_params())
    blocks = [_batch(i, nan_at=1) for i in range(3)]   # NaN mid-block
    state, _ = runner_on.run_many(state, blocks)
    bundle = np.asarray(jax.device_get(runner_on.last_health))
    assert bundle.shape == (4,)
    assert bundle[0] >= 1.0                 # the reduction kept the flag


# ------------------------------------------- status/record wire ops + adtop

class _StubPSRunner:
    """The minimal surface PSServer._dispatch drives (the test_cluster_trace
    pattern): a real gate + numpy-only ParameterService, no compilation."""

    def __init__(self, num_workers=1, staleness=2):
        from autodist_tpu.parallel.staleness import (ParameterService,
                                                     StalenessController)
        from autodist_tpu.runner import TrainState
        state = TrainState(step=np.zeros((), np.int32),
                           params={"w": np.ones((16,), np.float32)},
                           opt_state=(), ef_state=())
        self.service = ParameterService(state, lambda s, grads: s)
        self.controller = StalenessController(num_workers,
                                              staleness=staleness)

    def add_worker(self, worker_id=None, with_generation=False):
        wid, gen = self.controller.register_with_generation(worker_id)
        handle = type("H", (), {"worker_id": wid})()
        return (handle, gen) if with_generation else handle


def _loopback(num_workers=1, staleness=2, **server_kw):
    from autodist_tpu.parallel.ps_transport import PSServer
    server = PSServer(_StubPSRunner(num_workers, staleness),
                      host="127.0.0.1", **server_kw)
    return server, "%s:%d" % server.address


def test_status_and_record_opcodes_over_loopback(tmp_path):
    from autodist_tpu.parallel.ps_transport import RemotePSWorker

    recorder.set_recorder(recorder.FlightRecorder(
        str(tmp_path / "fr"), keep=2, min_interval_s=0.0))
    server, addr = _loopback(watchdog=False)
    remote = RemotePSWorker(addr, runner=None, worker_id=0, overlap=False)
    try:
        remote._client.call("start_step", 0, 5.0)
        remote._client.call("finish_step", 0)
        # Attribution-plane gauges ride the shared registry, so stats and
        # status ship them with no transport change.
        telemetry.gauge("train.mfu").set(0.28)
        telemetry.gauge("train.attr.compute").set(0.61)
        status = remote.status()
        assert status["kind"] == "ps"
        assert status["staleness_bound"] == 2
        assert status["per_worker"][0]["lag"] == 0
        assert isinstance(status["events"], list)
        # The PR 8 rename contract: `status` ships the event ring ONCE as
        # `events` — re-aliasing it under `anomalies` would double the poll.
        assert "anomalies" not in status
        assert status["registry"]["train.mfu"] == 0.28
        assert status["registry"]["train.attr.compute"] == 0.61
        # The PR 11 alerts section: a stable empty shell when the alert
        # plane never armed, the live active/resolved records when it did.
        assert status["alerts"] == {"active": [], "resolved": [],
                                    "rules": 0, "action": ""}
        # The recovery section (same stable-shell contract; pinned by SHAPE
        # — earlier suites' disconnect retires legitimately book records in
        # the process-global log, so emptiness is not the invariant).
        assert set(status["recovery"]) == {
            "evictions", "rejoins", "rollbacks", "respawns", "counts",
            "generations"}
        assert set(status["recovery"]["counts"]) == {
            "evicted", "rejoined", "rollbacks", "respawns"}
        # The PR 20 memory section (same stable-shell contract — pinned by
        # SHAPE: armed runs fill the values, unarmed ones ship zeros).
        assert set(status["memory"]) == {
            "owned", "live_bytes", "pressure", "budget_bytes",
            "budget_source", "devices"}
        from autodist_tpu.telemetry import alerts as _alerts
        eng = _alerts.AlertEngine(rules=[_alerts.AlertRule(
            name="pin", kind="threshold", metric="train.mfu", op=">",
            value=0.1)], action="warn")
        _alerts.set_engine(eng)
        try:
            from autodist_tpu.telemetry import history as _history
            h = _history.MetricsHistory(out_dir="", min_interval_s=0.0,
                                        engine=eng)
            h.sample()
            status = remote.status()
            assert [a["rule"] for a in status["alerts"]["active"]] == ["pin"]
            assert status["alerts"]["action"] == "warn"
            assert "anomalies" not in status    # still renamed, not aliased
        finally:
            _alerts.set_engine(None)
        json.dumps(status)                  # crossed the wire: plain data
        path = remote.record("operator_asked")
        assert path and os.path.isdir(path)
        assert "operator_asked" in path
        assert sorted(os.listdir(path)) == sorted(recorder.SNAPSHOT_FILES)
    finally:
        remote.close()
        server.close()


def test_watchdog_stall_triggers_recorder(tmp_path):
    import time as _time
    rec = recorder.FlightRecorder(str(tmp_path / "fr"), keep=4,
                                  min_interval_s=0.0)
    recorder.set_recorder(rec)
    server, _ = _loopback(watchdog=True, watchdog_interval=60.0)
    try:
        server._runner.controller.register(0)
        server._stats_for(0)                # create the entry OUTSIDE the lock
        with server._worker_stats_lock:
            server._worker_stats[0].last_seen = _time.monotonic() - 9999.0
        server._watchdog._sample()          # deterministic direct tick
        assert 0 in server._watchdog.flagged
        snaps = rec.snapshots()
        assert snaps and "ps.stall.w0" in snaps[0]
    finally:
        server.close()


def _adtop():
    spec = importlib.util.spec_from_file_location(
        "adtop_cli", os.path.join(os.path.dirname(__file__), os.pardir,
                                  "tools", "adtop.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_adtop_once_renders_loopback_status(capsys):
    from autodist_tpu.telemetry import alerts as _alerts
    from autodist_tpu.telemetry import history as _history
    telemetry.gauge("train.health.grad_norm").set(2.5)
    telemetry.gauge("train.mfu").set(0.283)
    telemetry.gauge("train.attr.compute").set(0.61)
    telemetry.gauge("train.attr.data_wait").set(0.07)
    telemetry.event("ps.anomaly.stall", worker=0, last_seen_s=42.0)
    # An active alert must render on its own console line (the PR 11
    # status-section satellite).
    eng = _alerts.AlertEngine(rules=[_alerts.AlertRule(
        name="mfu_floor", kind="threshold", metric="train.mfu", op=">",
        value=0.1)], action="warn")
    _alerts.set_engine(eng)
    _history.MetricsHistory(out_dir="", min_interval_s=0.0,
                            engine=eng).sample()
    server, addr = _loopback(watchdog=False)
    try:
        server._runner.controller.register(0)
        server._stats_for(0)
        ad = _adtop()
        assert ad.main([addr, "--once"]) == 0
        out = capsys.readouterr().out
        assert "adtop — ps server" in out
        assert "w0" in out and "bound 2" in out
        assert "grad_norm 2.5" in out
        # The attribution plane's roofline + phase-share gauges render on
        # the perf line.
        assert "mfu 28.3%" in out
        assert "comp .61" in out and "data .07" in out
        assert "ps.anomaly.stall" in out
        assert "alerts   1 active" in out and "mfu_floor" in out
        # --raw ships the JSON payload verbatim.
        assert ad.main([addr, "--raw"]) == 0
        assert json.loads(capsys.readouterr().out)["kind"] == "ps"
    finally:
        _alerts.set_engine(None)
        server.close()


def test_adtop_errors_cleanly_without_server(capsys):
    ad = _adtop()
    assert ad.main(["127.0.0.1:1", "--once"]) == 1
    assert "cannot read status" in capsys.readouterr().err


# --------------------------------------------- events JSONL + tracedump leg

def test_dump_events_jsonl_roundtrip_and_tracedump_merge(tmp_path):
    telemetry.enable()
    with telemetry.span("spanned"):
        pass
    telemetry.event("health.anomaly", kind="loss_spike", step=3, z=9.9)
    ring = str(tmp_path / "w0.jsonl")
    evs = str(tmp_path / "events.jsonl")
    telemetry.dump_spans_jsonl(ring, worker_id=0)
    telemetry.dump_events_jsonl(evs)
    loaded = telemetry.load_events_jsonl(evs)
    assert loaded and loaded[-1]["kind"] == "loss_spike"

    spec = importlib.util.spec_from_file_location(
        "tracedump_cli", os.path.join(os.path.dirname(__file__), os.pardir,
                                      "tools", "tracedump.py"))
    td = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(td)
    out = str(tmp_path / "merged.json")
    assert td.main([out, ring, "--events", evs]) == 0
    doc = json.load(open(out))
    marks = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert [m["name"] for m in marks] == ["health.anomaly"]
    assert marks[0]["args"]["z"] == 9.9
    # Instant markers get their own labeled lane.
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert any("events" in l for l in lanes)

    bad = tmp_path / "bad.jsonl"
    bad.write_text('["not", "an", "event"]\n')
    with pytest.raises(ValueError, match="event record"):
        telemetry.load_events_jsonl(str(bad))


# ----------------------------------------------------------- flag registry

def test_new_flags_registered_and_typed(monkeypatch):
    for flag in ("AUTODIST_HEALTH", "AUTODIST_HEALTH_ACTION",
                 "AUTODIST_HEALTH_ZMAX", "AUTODIST_RECORDER",
                 "AUTODIST_RECORDER_DIR", "AUTODIST_RECORDER_KEEP",
                 "AUTODIST_RECORDER_MIN_S"):
        assert flag in const.KNOWN_FLAGS
        assert hasattr(const.ENV, flag)
    assert const.ENV.AUTODIST_HEALTH.val is False
    assert const.ENV.AUTODIST_HEALTH_ACTION.val == "warn"
    assert health.HealthMonitor.from_env() is None     # flag off -> no cost
    monkeypatch.setenv("AUTODIST_HEALTH", "1")
    monkeypatch.setenv("AUTODIST_HEALTH_ACTION", "halt")
    monkeypatch.setenv("AUTODIST_HEALTH_ZMAX", "3.5")
    mon = health.HealthMonitor.from_env()
    assert mon is not None and mon.config.action == "halt"
    assert mon.config.z_max == 3.5
    with pytest.raises(ValueError, match="action"):
        health.HealthConfig(action="explode")
