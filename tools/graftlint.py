#!/usr/bin/env python
"""graftlint — project-specific static analysis for autodist_tpu.

Usage:
    python tools/graftlint.py [paths...]           # text output, baseline on
    python tools/graftlint.py --format json ...    # machine-readable (CI)
    python tools/graftlint.py --explain GL001      # why a check exists
    python tools/graftlint.py --list-checks
    python tools/graftlint.py --write-baseline ... # re-grandfather findings

Default paths mirror the CI gate: autodist_tpu tests examples bench.py.
Exit status: 0 = clean (only suppressed/baselined findings), 1 = new
findings, 2 = usage error. Findings are suppressed inline with
``# graftlint: disable=GLnnn(reason)`` — the reason is mandatory — and
grandfathered via tools/graftlint_baseline.json (new findings fail, old ones
don't). See docs/usage/static_analysis.md for the check catalog.
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from autodist_tpu.analysis import core  # noqa: E402

DEFAULT_PATHS = ["autodist_tpu", "tests", "examples", "bench.py"]
DEFAULT_BASELINE = os.path.join(ROOT, "tools", "graftlint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as failures too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ap.add_argument("--explain", metavar="GLnnn",
                    help="print a check's rationale and exit")
    ap.add_argument("--list-checks", action="store_true")
    ap.add_argument("--check", action="append", metavar="GLnnn",
                    help="run only these checks (repeatable)")
    args = ap.parse_args(argv)

    checks = core.all_checks()
    if args.list_checks:
        for cid in sorted(checks):
            print(f"{cid}  {checks[cid].title}")
        return 0
    if args.explain:
        check = checks.get(args.explain)
        if check is None:
            print(f"unknown check {args.explain!r}; known: "
                  f"{', '.join(sorted(checks))}", file=sys.stderr)
            return 2
        print(f"{check.id} — {check.title}\n")
        print((check.doc or "(no documentation)").strip())
        return 0
    if args.check:
        unknown = [c for c in args.check if c not in checks]
        if unknown:
            print(f"unknown check(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    paths = args.paths or DEFAULT_PATHS
    baseline = set() if (args.no_baseline or args.write_baseline) \
        else core.load_baseline(args.baseline)
    try:
        result = core.lint_paths(paths, root=ROOT, baseline=baseline,
                                 checks=args.check)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2

    if args.write_baseline:
        core.write_baseline(args.baseline, result.findings)
        print(f"graftlint: wrote {len(result.findings)} grandfathered "
              f"finding(s) to {os.path.relpath(args.baseline, ROOT)}")
        return 0

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "files_checked": result.files_checked,
            "findings": [f.to_json() for f in result.findings],
            "baselined": [f.to_json() for f in result.baselined],
            "suppressed": [{"finding": f.to_json(), "reason": r}
                           for f, r in result.suppressed],
            "stale_baseline": result.stale_baseline,
            "ok": result.ok,
        }, indent=1))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    tail = (f"graftlint: {len(result.findings)} new finding(s) over "
            f"{result.files_checked} file(s)"
            f" ({len(result.suppressed)} suppressed, "
            f"{len(result.baselined)} baselined)")
    if result.stale_baseline:
        tail += (f"; {len(result.stale_baseline)} stale baseline entr"
                 f"{'y' if len(result.stale_baseline) == 1 else 'ies'} "
                 f"(fixed findings — prune with --write-baseline)")
    print(tail)
    if result.findings:
        print("explain a check: python tools/graftlint.py --explain GLnnn; "
              "suppress with `# graftlint: disable=GLnnn(reason)`")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
