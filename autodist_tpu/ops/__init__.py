"""Hot-op kernels: pallas TPU kernels with pure-JAX blockwise fallbacks."""

from autodist_tpu.ops.blockwise_attention import blockwise_attention
from autodist_tpu.ops.flash_attention import flash_attention
from autodist_tpu.ops.fused_xent import fused_softmax_xent, matmul_logsumexp


def mosaic_compiles() -> bool:
    """True when pallas kernels compile natively on this backend (TPU-class
    platforms). The single backend gate for callers choosing kernel-backed
    configs — elsewhere pallas falls back to interpret mode, orders of
    magnitude slower."""
    from autodist_tpu.ops.flash_attention import _use_interpret
    return not _use_interpret()


__all__ = ["blockwise_attention", "flash_attention", "fused_softmax_xent",
           "matmul_logsumexp", "mosaic_compiles"]
