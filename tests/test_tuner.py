"""Empirical strategy tuner: measures candidates, ranks, survives failures."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from shardmap_compat import requires_shard_map
from autodist_tpu.strategy import (AllReduce, PSLoadBalancing, Strategy,
                                   StrategyBuilder, TuneResult, tune_strategy)


def _loss(p, b):
    return jnp.mean((b["y"] - (b["x"] @ p["w"] + p["b"])) ** 2)


def _params():
    rng = np.random.RandomState(0)
    return {"w": rng.randn(4, 1).astype(np.float32), "b": np.zeros((1,), np.float32)}


def _batch():
    rng = np.random.RandomState(1)
    return {"x": rng.randn(32, 4).astype(np.float32),
            "y": rng.randn(32, 1).astype(np.float32)}


class ExplodingBuilder(StrategyBuilder):
    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        raise RuntimeError("boom")


def test_tuner_ranks_candidates():
    result = tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                           candidates=[AllReduce(), PSLoadBalancing()],
                           warmup_steps=1, measure_steps=3)
    assert isinstance(result, TuneResult)
    assert len(result.results) == 2
    assert all(r.steps_per_sec and r.steps_per_sec > 0 for r in result.results)
    assert result.best in [r.builder for r in result.results]
    report = result.report()
    assert "AllReduce" in report and "PSLoadBalancing" in report
    assert "<- best" in report


def test_tuner_skips_failing_candidate():
    result = tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                           candidates=[ExplodingBuilder(), AllReduce()],
                           warmup_steps=1, measure_steps=2)
    failed = [r for r in result.results if r.steps_per_sec is None]
    assert len(failed) == 1 and "boom" in failed[0].error
    assert type(result.best).__name__ == "AllReduce"
    assert "FAILED" in result.report()


def test_tuner_all_failing_raises():
    with pytest.raises(RuntimeError, match="every candidate failed"):
        tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                      candidates=[ExplodingBuilder()])


def test_tuner_with_accumulation():
    result = tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                           candidates=[AllReduce()], warmup_steps=1,
                           measure_steps=2, accumulation_steps=2)
    assert result.results[0].steps_per_sec > 0


def test_tuner_with_aux_loss():
    def loss_aux(p, b):
        err = b["y"] - (b["x"] @ p["w"] + p["b"])
        return jnp.mean(err ** 2), {"mae": jnp.mean(jnp.abs(err))}

    result = tune_strategy(loss_aux, _params(), optax.sgd(0.1), _batch(),
                           candidates=[AllReduce(), PSLoadBalancing()],
                           warmup_steps=1, measure_steps=2, has_aux=True)
    assert all(r.steps_per_sec for r in result.results)


def test_tuner_restores_default_autodist():
    from autodist_tpu import AutoDist, get_default_autodist
    mine = AutoDist(strategy_builder=AllReduce())
    tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                  candidates=[PSLoadBalancing()], warmup_steps=1, measure_steps=2)
    assert get_default_autodist() is mine


def test_tuner_rejects_multinode_spec():
    """Ranking is sync-local: a multi-node spec must be rejected up front, not
    silently measured on local devices."""
    spec = ResourceSpec(
        "nodes: [{address: 10.0.0.1, tpus: 4, chief: true}, "
        "{address: 10.0.0.2, tpus: 4}]")
    with pytest.raises(ValueError, match="multi-node"):
        tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                      candidates=[AllReduce()], resource_spec=spec)


def test_tuner_skips_async_candidate():
    """An async candidate is recorded as skipped (gate-dominated wall-clock is
    not comparable to a sync step), and a sync candidate still wins."""
    from autodist_tpu.strategy import PS
    result = tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                           candidates=[PS(sync=False), AllReduce()],
                           warmup_steps=1, measure_steps=2)
    skipped = [r for r in result.results if r.steps_per_sec is None]
    assert len(skipped) == 1 and "async" in skipped[0].error
    assert type(result.best).__name__ == "AllReduce"


def test_tuner_sweeps_accumulation_steps():
    result = tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                           candidates=[AllReduce()], warmup_steps=1,
                           measure_steps=2, accumulation_steps=[1, 2])
    names = {r.name for r in result.results}
    assert names == {"AllReduce[accum=1]", "AllReduce[accum=2]"}
    assert result.best_accumulation_steps in (1, 2)
    assert "<- best" in result.report()


def test_tuner_rejects_zero_warmup():
    with pytest.raises(ValueError, match="warmup_steps"):
        tune_strategy(_loss, _params(), optax.sgd(0.1), _batch(),
                      candidates=[AllReduce()], warmup_steps=0)


@requires_shard_map
def test_tuner_default_candidates_include_parallax_for_sparse():
    rng = np.random.RandomState(2)
    params = {"emb": rng.randn(50, 4).astype(np.float32),
              "w": rng.randn(4, 1).astype(np.float32)}
    batch = {"idx": rng.randint(0, 50, (32,)),
             "y": rng.randn(32, 1).astype(np.float32)}

    def loss(p, b):
        return jnp.mean((b["y"] - jnp.take(p["emb"], b["idx"], axis=0) @ p["w"]) ** 2)

    result = tune_strategy(loss, params, optax.sgd(0.1), batch,
                           warmup_steps=1, measure_steps=2)
    names = {r.name for r in result.results}
    assert "Parallax" in names and "AllReduce" in names and "AutoStrategy" in names
