"""Execution runtime: DistributedRunner (reference WrappedSession + Remapper).

The reference's steady-state step (``runner.py:117-132``) ran a grpc session with a
feed/fetch remapper splitting the host batch across replicas (``remapper.py:81-123``)
and contracting fetches (``:125-185``). Here the step is one jitted SPMD program over
the mesh:

- feeds: host arrays whose leading dim is divisible by the data-parallel size are
  device_put with the batch sharding (the split); everything else replicates (the
  duplicate) — same polymorphism as the reference's Remapper.
- fetches: the loss (and aux metrics) come back as replicated scalars — the
  "master replica value" contraction is a no-op in SPMD.
- initializers-at-construction (reference ``runner.py:97-100``) becomes
  ``init(params)``: placing params/opt-state/EF-state onto the mesh per the plan.
"""

import dataclasses
import time
import weakref
import zlib
from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu import telemetry
from autodist_tpu.telemetry import profiling as _profiling
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.parallel import synchronization
from autodist_tpu.parallel.mesh import build_mesh
from autodist_tpu.parallel.plan import ShardingPlan
from autodist_tpu.utils import logging

PyTree = Any


def place_host_value(leaf, sharding) -> jax.Array:
    """Place a host value with ``sharding``, tolerating heterogeneous processes.

    ``jax.device_put`` onto a non-fully-addressable sharding runs a cross-process
    value check built on ``process_allgather``, which requires every process to
    have the same local device count — exactly what a heterogeneous cluster
    (reference ``resource_specs/r4.yml``, 2+1 GPUs) violates. Building the array
    from per-shard callbacks sidesteps the check; every process holds the same
    full host value by construction (same batch protocol as the reference's
    per-worker re-execution)."""
    if sharding.is_fully_addressable:
        return jax.device_put(leaf, sharding)
    arr = np.asarray(leaf)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


@dataclasses.dataclass(frozen=True)
class FeedLayout:
    """The static description of a runner's feed remapping — what the
    input-data plane (:mod:`autodist_tpu.data.prefetch`) keys per-host
    sharding and async transfers off, so a prefetch pipeline can never
    place a batch differently than :meth:`DistributedRunner.shard_batch`
    would. ``dp`` is the data-parallel extent, ``accum`` the micro-batch
    split, ``batch_pspec(ndim)`` the plan's batch partition spec."""

    mesh: Any
    plan: Any
    dp: int
    accum: int

    def batch_pspec(self, ndim: int):
        return self.plan.batch_pspec(ndim)


@jax.tree_util.register_pytree_node_class
class MicroBatched:
    """Marker wrapping a batch leaf laid out ``[accum_steps, micro_batch, ...]``.

    Produced by ``shard_batch`` when gradient accumulation is on; the step scans
    axis 0. Being a pytree *node* (not a bare array) makes "which leaves are
    micro-batched" part of the jit cache key, so a batch structure change can
    never silently reuse a stale compiled step.
    """

    def __init__(self, value):
        self.value = value

    def tree_flatten(self):
        return (self.value,), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0])


def _is_micro(leaf) -> bool:
    return isinstance(leaf, MicroBatched)


class BatchBlock:
    """K pre-sharded batches stacked along a leading step axis.

    Built by :meth:`DistributedRunner.shard_block` (or
    ``data.loader.device_prefetch(..., unroll=K)``) and consumed by
    :meth:`DistributedRunner.run_many`, which scans the step body over the
    leading axis — one compiled dispatch for K optimizer steps. A host-side
    handle, not a pytree: ``tree`` is the on-device stacked batch pytree and
    ``length`` the number of steps it carries."""

    __slots__ = ("tree", "length")

    def __init__(self, tree, length: int):
        self.tree = tree
        self.length = length

    def __len__(self) -> int:
        return self.length


@dataclasses.dataclass
class TrainState:
    """One training step's carried state (a pytree)."""

    step: jax.Array
    params: PyTree
    opt_state: PyTree
    ef_state: PyTree   # error-feedback residuals (zeros-scalars when unused)
    # Static (untraced) reference to the ShardingPlan that shaped this state, so the
    # Saver can slice padded uneven-partition storage back to logical shapes without
    # the caller having to remember which runner the state came from. Compared by
    # identity for jit caching — one runner always reuses one plan object.
    plan: Any = None


jax.tree_util.register_dataclass(
    TrainState, data_fields=["step", "params", "opt_state", "ef_state"],
    meta_fields=["plan"])


class _CompileProbe:
    """Times one first-of-its-signature dispatch and books it as compilation.

    jit compiles synchronously inside the first call for a new input
    signature (tracing + lowering + XLA compile happen before the program is
    enqueued), so that call's wall time IS the compile cost to within one
    async dispatch. Wraps the would-be dispatch span with a ``jit.compile``
    span and, on exit, bumps ``jit.cache_miss`` and accumulates
    ``jit.compile_s`` in the telemetry registry — and, when the profiling
    plane armed a ``cost_cb``, hands it the compile seconds so the program's
    static cost record (XLA cost analysis) lands in the per-signature cache.
    Constructed only in enabled mode
    (:meth:`DistributedRunner._dispatch_span`)."""

    __slots__ = ("_inner", "_t0", "_cost_cb")

    def __init__(self, inner, cost_cb=None):
        self._inner = inner
        self._t0 = 0.0
        self._cost_cb = cost_cb

    def __enter__(self):
        self._inner.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        telemetry.counter("jit.cache_miss").inc()
        telemetry.counter("jit.compile_s").inc(dt)
        if self._cost_cb is not None and exc[0] is None:
            self._cost_cb(dt)
        return self._inner.__exit__(*exc)


class DistributedRunner:
    """Compiles and runs the distributed train step for one (strategy, model).

    Counterpart of reference ``WrappedSession`` (``runner.py:78-132``): constructed
    from the *compiled* strategy, owns the mesh, shards state, steps batches.
    """

    # Whether run_many's fused multi-step scan is available. The async/remote
    # regimes override to False: their parameter service applies gradients
    # host-step by host-step, so there is no on-device K-step program to fuse.
    supports_run_many = True

    def __init__(self, compiled_strategy, model_spec: ModelSpec, loss_fn: Callable,
                 optimizer, mesh: Optional[Mesh] = None, has_aux: bool = False,
                 donate_state: bool = True, plan: Optional[ShardingPlan] = None,
                 accumulation_steps: int = 1, batch_size: Optional[int] = None,
                 zero: Optional[Any] = None, health: Optional[bool] = None):
        if accumulation_steps < 1:
            raise ValueError("accumulation_steps must be >= 1")
        # Training-health monitors (``health=None`` reads AUTODIST_HEALTH):
        # when on, the step body additionally computes the fused numerics
        # bundle (telemetry/health.py) — four f32 scalars in the SAME
        # compiled program, read back only at the train loop's log
        # boundaries. Off (the default) leaves the program byte-identical.
        if health is None:
            from autodist_tpu import const
            health = const.ENV.AUTODIST_HEALTH.val
        self.health = bool(health)
        # The most recent step's device-side health bundle (float32[4] per
        # telemetry.health.BUNDLE_FIELDS; an unroll block arrives reduced).
        # A device array — callers device_get it at their own sync points.
        self.last_health = None
        # ZeRO-style weight-update sharding (arXiv 2004.13336; ``zero=None``
        # reads AUTODIST_ZERO): 0/False off, 1/True on, N>1 on with N
        # server-side PS apply shards (the async regime's knob). On the
        # synchronous path "on" reshards the plan's opt-state specs over the
        # data-parallel axes and constrains grads/updates/params in the step
        # body, so XLA lowers the update into reduce-scatter -> shard-local
        # optimizer.update -> all-gather.
        if zero is None:
            from autodist_tpu import const
            zero = const.ENV.AUTODIST_ZERO.val
        self.zero = int(zero)
        # Explicit global batch size for micro-batch splitting; when None it is
        # inferred per batch as the modal leading dim (see shard_batch).
        self._batch_size = batch_size
        self._model_spec = model_spec
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._has_aux = has_aux
        self._donate = donate_state
        self._accum = accumulation_steps
        self.plan = plan if plan is not None \
            else ShardingPlan.from_strategy(compiled_strategy, model_spec)
        self.mesh = mesh if mesh is not None else self._mesh_from_plan()
        if self.zero and not self.plan.is_async and not self.plan.zero:
            # Synchronous regimes take the SPMD lowering; the async/PS regime
            # keeps its plan and shards the server-side apply instead
            # (parallel/staleness.py) — its opt state lives on the chief only.
            self.plan = self.plan.with_zero_update(self.mesh)
        # Uneven partitioning: state leaves live padded (XLA needs even tiles); the
        # user's loss fn sees logical shapes. Differentiating through the unpad
        # slice zero-fills the pad region of the gradient, so padded rows never
        # receive updates (the masked-update half of pad-and-mask).
        if self.plan.has_padding:
            unpad = self.plan.unpad_params
            self._step_loss_fn = lambda p, b: loss_fn(unpad(p), b)
        else:
            self._step_loss_fn = loss_fn
        self._grad_fn = synchronization.make_grad_fn(
            self.plan, model_spec, self.mesh, self._step_loss_fn, has_aux=has_aux)
        # Compiled steps keyed by fetch fn (None = plain step); reference cached
        # one built runner per graph the same way (autodist.py:280-287).
        self._step_fns: dict = {}
        self._many_fns: dict = {}   # fused K-step scans, same keying
        self._eval_fns: dict = {}
        self._state_shardings = None
        # Dispatch signatures (kind + fetch-fn token + batch shapes/dtypes)
        # already seen: a NEW signature means jit will retrace+recompile
        # inside the next call — the compile-telemetry key (_dispatch_span).
        # Fetch fns get a NEVER-REUSED token via a weak map: a bare id()
        # could be recycled by a new fn after the old one (evicted from the
        # step cache) is collected, silently suppressing its compile record.
        self._compile_sigs: set = set()
        self._mem_analysis_warned: set = set()
        self._fetch_tokens: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._fetch_token_next = 0

    def _mesh_from_plan(self) -> Mesh:
        axes = dict(self.plan.mesh_axes)
        n = len(jax.devices())
        if int(np.prod(list(axes.values()))) != n:
            # Strategy was built for a different device count (e.g. compiled on the
            # chief for the full pod, now dry-running on fewer chips): refill data,
            # then shrink the largest remaining axis until the product divides n.
            logging.warning(
                "Strategy mesh %s does not match %d local devices; refilling data axis",
                axes, n)
            axes.pop("data", None)
            axes = {a: s for a, s in axes.items() if s > 1}
            while axes and n % int(np.prod(list(axes.values()))) != 0:
                largest = max(axes, key=axes.__getitem__)
                axes.pop(largest)
            axes["data"] = -1
        return build_mesh(axes=axes)

    # ------------------------------------------------------------------- state
    def init(self, params: PyTree, rng: Optional[jax.Array] = None) -> TrainState:
        """Place initial state onto the mesh (reference ran initializers at session
        construction, runner.py:97-100). Params arrive at logical shapes; unevenly
        partitioned ones are zero-padded to their physical storage shape here."""
        params = self.plan.pad_params(params)
        opt_state = self._optimizer.init(params)
        ef_state = synchronization.init_ef_state(self.plan, params, mesh=self.mesh)
        state = TrainState(step=np.zeros((), np.int32), params=params,
                           opt_state=opt_state, ef_state=ef_state, plan=self.plan)
        self._state_shardings = None   # rebuild for THIS init's trees
        self._ensure_state_shardings(state)
        # Jitted identity with out_shardings: places the state on the mesh AND
        # guarantees fresh buffers (a plain device_put may alias caller-owned arrays,
        # which step donation would then delete out from under the caller).
        place = jax.jit(lambda s: s, out_shardings=self._state_shardings)
        with self.mesh:
            return place(state)

    # -------------------------------------------------------------------- step
    def _make_step_body(self, fetch_fn: Optional[Callable] = None):
        """The pure (untraced) one-step function ``(state, batch) -> (state,
        (loss, aux, fetched, bundle))`` — ``bundle`` is the fused health
        numerics float32[4] when monitors are on, an empty tuple (nothing in
        the compiled program) when off. Single source of the step math:
        ``_build_step`` jits it directly and ``_build_many`` scans it — so the
        fused multi-step path can never drift numerically from the per-step
        path."""
        import jax.numpy as jnp

        optimizer = self._optimizer
        grad_fn = self._grad_fn
        accum = self._accum
        # ZeRO update sharding: constraint points for the jitted step. Captured
        # as (plan, mesh) statics so the body stays a pure function of state.
        zero_plan = self.plan if self.plan.zero else None
        mesh = self.mesh
        # Health bundle: a TRACE-TIME static — the disabled program carries
        # nothing (an empty tuple output), the enabled one a few fused
        # reductions over intermediates the step already has.
        health_on = self.health

        def accumulate(params, batch, ef_state):
            """Gradient accumulation: scan grad_fn over the micro axis, summing
            gradients and threading error-feedback state; one optimizer update per
            outer step. Micro-batches are equal-sized, so the mean of per-micro
            (already data-synced) gradients equals the full-batch gradient for
            mean-reduced losses — value-exact vs one big batch."""
            def select(i):
                return jax.tree_util.tree_map(
                    lambda l: jax.lax.dynamic_index_in_dim(
                        l.value, i, axis=0, keepdims=False) if _is_micro(l) else l,
                    batch, is_leaf=_is_micro)

            def micro(carry, i):
                gsum, ef = carry
                grads, loss, aux, ef = grad_fn(params, select(i), ef)
                gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
                return (gsum, ef), (loss, aux)

            zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
            (gsum, ef_state), (losses, auxes) = jax.lax.scan(
                micro, (zeros, ef_state), jnp.arange(accum))
            grads = jax.tree_util.tree_map(lambda g: g / accum, gsum)
            # Aux contraction matches the accum=1 shapes: per-example aux — leading
            # dim == the micro-batch size — folds back to [B, ...] (same examples,
            # same params, so values are identical to full-batch evaluation);
            # everything else (scalars, per-class vectors, ...) averages across
            # micros. A non-per-example aux whose leading dim happens to equal the
            # micro-batch size is indistinguishable and gets folded.
            micro_b = next((l.value.shape[1] for l in jax.tree_util.tree_leaves(
                batch, is_leaf=_is_micro) if _is_micro(l)), None)
            aux = jax.tree_util.tree_map(
                lambda a: a.reshape((-1,) + a.shape[2:])
                if a.ndim >= 2 and a.shape[1] == micro_b
                else jnp.mean(a, axis=0), auxes)
            return grads, jnp.mean(losses), aux, ef_state

        def step_fn(state: TrainState, batch: PyTree):
            if accum > 1:
                grads, loss, aux, ef_state = accumulate(state.params, batch,
                                                        state.ef_state)
            else:
                grads, loss, aux, ef_state = grad_fn(state.params, batch,
                                                     state.ef_state)
            if zero_plan is not None:
                # ZeRO weight-update sharding (arXiv 2004.13336): constraining
                # the gradient to the opt-state shards makes XLA materialize it
                # as a reduce-scatter; the optimizer update then runs on 1/dp
                # of each parameter per device.
                grads = zero_plan.constrain_update(mesh, grads)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            if zero_plan is not None:
                updates = zero_plan.constrain_update(mesh, updates)
                opt_state = zero_plan.constrain_opt(mesh, opt_state)
            params = optax.apply_updates(state.params, updates)
            if zero_plan is not None:
                # Back to the storage sharding — the all-gather closing the
                # sharded update.
                params = zero_plan.constrain_params(mesh, params)
            new_state = TrainState(step=state.step + 1, params=params,
                                   opt_state=opt_state, ef_state=ef_state,
                                   plan=state.plan)
            # Arbitrary fetches (reference remapper.py:125-185 fetched any graph
            # tensor with per-kind contraction): computed in the same compiled
            # step from the pre-update params. SPMD supplies the contractions —
            # per-example outputs come back as the (logically concatenated)
            # global batch-sharded array, scalars as the replicated value the
            # reference took from the master replica.
            if fetch_fn is not None:
                # Fetches see the logical batch: micro-batched leaves fold back to
                # [B, ...] (row-major reshape restores the original example order).
                logical = jax.tree_util.tree_map(
                    lambda l: l.value.reshape((-1,) + l.value.shape[2:])
                    if _is_micro(l) else l,
                    batch, is_leaf=_is_micro)
                fetched = fetch_fn(state.params, logical)
            else:
                fetched = ()
            if health_on:
                from autodist_tpu.telemetry import health as _health
                # Pre-update params: the ratio convention is update magnitude
                # relative to the weights it applies to.
                bundle = _health.device_bundle(grads, updates, state.params,
                                               loss)
            else:
                bundle = ()   # empty pytree: nothing in the compiled program
            return new_state, (loss, aux, fetched, bundle)

        return step_fn

    def _cap_fn_cache(self, cache: dict, where: str):
        if len(cache) > 8:
            # Fetch callables are cache keys by identity: per-call lambdas would
            # recompile the full step every run and pin executables forever.
            evict = next(k for k in cache if k is not None)
            del cache[evict]
            logging.warning(
                "More than 8 distinct fetch callables compiled; pass a stable "
                "function to %s instead of per-call lambdas "
                "(each new identity recompiles the whole training step)", where)

    def _build_step(self, fetch_fn: Optional[Callable] = None):
        donate = (0,) if self._donate else ()
        jitted = jax.jit(
            self._make_step_body(fetch_fn),
            in_shardings=(self._state_shardings, None),
            out_shardings=(self._state_shardings, None),
            donate_argnums=donate,
        )
        self._step_fns[fetch_fn] = jitted
        self._cap_fn_cache(self._step_fns, "runner.run(fetches=...)")
        return jitted

    def _build_many(self, fetch_fn: Optional[Callable] = None):
        """Fused multi-step program: one ``lax.scan`` of the step body over a
        stacked batch block. Compiled once per (fetch fn, block length) — jit
        retraces per scan length, so varying block sizes (cadence-clipped tail
        blocks) reuse their own executables."""
        step_fn = self._make_step_body(fetch_fn)
        health_on = self.health

        def many_fn(state: TrainState, block: PyTree):
            state, (losses, auxes, fetched, bundles) = jax.lax.scan(
                step_fn, state, block)
            if health_on:
                # Reduce the [K, 4] per-step bundles ON DEVICE (nonfinite
                # sums, norms max) — a K-step block still reads back four
                # scalars at the log boundary.
                from autodist_tpu.telemetry import health as _health
                bundles = _health.reduce_bundle(bundles)
            return state, (losses, auxes, fetched, bundles)

        donate = (0,) if self._donate else ()
        jitted = jax.jit(
            many_fn,
            in_shardings=(self._state_shardings, None),
            out_shardings=(self._state_shardings, None),
            donate_argnums=donate,
        )
        self._many_fns[fetch_fn] = jitted
        self._cap_fn_cache(self._many_fns, "runner.run_many(fetches=...)")
        return jitted

    def _leading_dims(self, batch: PyTree):
        """Counter of leading dims over the batch's array leaves (MicroBatched
        leaves count at their logical ``k * micro`` size). The single
        shape-extraction rule shared by batch-dim inference and the explicit-
        batch_size sanity check, so the two cannot drift apart."""
        from collections import Counter
        dims: Counter = Counter()
        for leaf in jax.tree_util.tree_leaves(batch, is_leaf=_is_micro):
            if _is_micro(leaf):
                # Already laid out [k, B/k, ...] by a previous shard_batch.
                dims[leaf.value.shape[0] * leaf.value.shape[1]] += 1
                continue
            shape = getattr(leaf, "shape", None)
            if shape is None:
                shape = np.asarray(leaf).shape
            if len(shape) >= 1:
                dims[shape[0]] += 1
        return dims

    def _infer_batch_dim(self, dims, split: int) -> int:
        """The global batch size for micro-splitting: the explicit ``batch_size``
        if the runner was given one, else the unique splittable leading dim —
        provided it is also the most common one (the likeliest batch).

        There is no structural rule that can tell a batch leaf from an
        auxiliary leaf that happens to be splittable (sampled-softmax negatives
        longer than the batch, per-class vectors shorter than it — either can
        outnumber or outweigh the true batch leaves), and guessing wrong
        silently changes the loss. So anything other than the clean case — one
        splittable dim, and it is the modal one — refuses and asks for
        ``batch_size=``."""
        if self._batch_size is not None:
            return self._batch_size
        if not dims:
            return 0
        top = max(dims.values())
        modal = {d for d, c in dims.items() if c == top}
        splittable = sorted(d for d in dims if d % split == 0)
        if len(splittable) == 1 and modal == {splittable[0]}:
            return splittable[0]
        if len(splittable) > 1:
            raise ValueError(
                f"Ambiguous batch dimension for gradient accumulation: leading "
                f"dims {splittable} are all divisible by accumulation_steps*dp="
                f"{split}, and micro-splitting the wrong one would silently "
                f"change the loss; pass batch_size= to the runner (or "
                f"AutoDist.function / create_distributed_session) to pick one")
        if len(splittable) == 1:
            # The one splittable dim is NOT the most common leading dim: the
            # likeliest batch was excluded only by divisibility. Micro-splitting
            # the outlier would silently change the loss; make the user decide.
            raise ValueError(
                f"Cannot infer the batch dimension for gradient accumulation: "
                f"the only leading dim divisible by accumulation_steps*dp="
                f"{split} is {splittable[0]}, but the most common leading dim "
                f"is {sorted(modal)}; pass batch_size= (or make the batch "
                f"divisible) to pick one")
        # Nothing splittable: report against the most common leading dim (the
        # likeliest batch) so the divisibility error below names it.
        return max(modal)

    def _micro_batch_dim(self, batch: PyTree, k: int, dp: int) -> int:
        """The leading dim that micro-splits for accumulation (0 when off).
        Shared by shard_batch and shard_block so the per-step and fused paths
        can never infer different batch dims for the same runner."""
        if k <= 1:
            return 0
        dims = self._leading_dims(batch)
        batch_dim = self._infer_batch_dim(dims, k * dp)
        if batch_dim not in dims:
            # A typo'd explicit batch_size would otherwise silently disable
            # micro-splitting while the accumulation scan still runs k
            # identical full-batch micro-steps.
            raise ValueError(
                f"batch_size={batch_dim} matches no leaf's leading dim "
                f"(present: {sorted(dims)}); nothing would be "
                f"micro-split for accumulation_steps={k}")
        return batch_dim

    @staticmethod
    def _require_micro_divisible(n: int, k: int, dp: int):
        if n % (k * dp) != 0:
            raise ValueError(
                f"Global batch {n} is not divisible into "
                f"accumulation_steps={k} micro-batches over {dp} data "
                f"replicas; make it divisible by {k * dp} (or drop "
                f"accumulation)")

    def feed_layout(self) -> FeedLayout:
        """This runner's feed remapping as data (:class:`FeedLayout`) —
        the input-data plane's key for per-host sharded loading and
        prefetch placement (one layout source, shared with
        :meth:`shard_batch`/:meth:`shard_block`)."""
        return FeedLayout(mesh=self.mesh, plan=self.plan,
                          dp=synchronization.mesh_dp_size(self.mesh),
                          accum=self._accum)

    def shard_batch(self, batch: PyTree,
                    accumulation: Optional[int] = None) -> PyTree:
        """Feed remapping: split batch leaves across data replicas, duplicate the
        rest (reference remapper.py:81-123 semantics, with the polymorphic dim now
        'leading dim divisible by dp_size').

        With gradient accumulation (``accumulation_steps=k``), splittable leaves
        are additionally laid out ``[k, B/k, ...]`` (wrapped in ``MicroBatched``)
        so the compiled step can scan micro-batches; the reshape happens on the
        host, before placement, so it moves no device data. ``accumulation``
        overrides the runner's setting (evaluate() passes 1 — the micro layout
        only shapes the training scan)."""
        dp = synchronization.mesh_dp_size(self.mesh)
        k = self._accum if accumulation is None else accumulation

        # Which leaves are *batch* leaves for micro-splitting: those whose leading
        # dim equals the global batch size. The batch size is the modal (most
        # common) leading dim across the pytree, not the largest — an auxiliary
        # leaf longer than the batch (e.g. sampled-softmax negatives with
        # num_sampled > batch_size) must NOT be mistaken for the batch, or each
        # micro-step would see the full batch with a slice of the negatives.
        # Ambiguity (two splittable dims equally common) raises rather than
        # guessing; ``batch_size=`` on the runner resolves it explicitly.
        batch_dim = self._micro_batch_dim(batch, k, dp)

        def put(leaf):
            if _is_micro(leaf):
                return leaf  # already laid out by a previous shard_batch
            shape = getattr(leaf, "shape", None)
            if shape is None:
                leaf = np.asarray(leaf)
                shape = leaf.shape
            if k > 1 and len(shape) >= 1 and shape[0] == batch_dim:
                self._require_micro_divisible(shape[0], k, dp)
                micro = leaf.reshape((k, shape[0] // k) + tuple(shape[1:]))
                spec = P(None, *self.plan.batch_pspec(len(shape)))
                return MicroBatched(
                    place_host_value(micro, NamedSharding(self.mesh, spec)))
            if len(shape) >= 1 and shape[0] % dp == 0:
                spec = self.plan.batch_pspec(len(shape))
            else:
                spec = P()
            sharding = NamedSharding(self.mesh, spec)
            if isinstance(leaf, jax.Array) and leaf.sharding == sharding:
                return leaf  # already resident with the right layout — no transfer
            return place_host_value(leaf, sharding)

        return jax.tree_util.tree_map(put, batch, is_leaf=_is_micro)

    def shard_block(self, batches) -> BatchBlock:
        """Stack K host batches into one on-device :class:`BatchBlock` for
        :meth:`run_many`.

        The feed remapping is ``shard_batch``'s, shifted one axis right: every
        leaf gains a leading (unsharded) step axis of length K, batch leaves
        shard their *second* dim over the data axes, non-batch leaves
        replicate, and micro-batched leaves (gradient accumulation) lay out
        ``[K, accum, B/accum, ...]``. Stacking happens on the host before one
        placement per leaf, so a block costs the same number of host->device
        transfers as a single batch."""
        batches = list(batches)
        if not batches:
            raise ValueError("shard_block needs at least one batch")
        treedef = jax.tree_util.tree_structure(batches[0], is_leaf=_is_micro)
        for i, b in enumerate(batches[1:], 1):
            td = jax.tree_util.tree_structure(b, is_leaf=_is_micro)
            if td != treedef:
                raise ValueError(
                    f"shard_block: batch {i}'s pytree structure {td} does not "
                    f"match batch 0's {treedef}; a block scans one compiled "
                    f"step over uniformly-shaped batches")
        K = len(batches)
        dp = synchronization.mesh_dp_size(self.mesh)
        k = self._accum
        batch_dim = self._micro_batch_dim(batches[0], k, dp)

        def put(*leaves):
            import jax.numpy as jnp
            # Device-resident leaves (HBM-cached records, re-fed fetches) stack
            # on-device: stack/reshape dispatch asynchronously and device_put
            # relayouts without the host round-trip np.asarray would force —
            # the block analogue of shard_batch's already-resident fast path.
            # Mixed host/device leaves fall back to host stacking.
            resident = all(isinstance(l.value if _is_micro(l) else l, jax.Array)
                           for l in leaves)
            xp = jnp if resident else np
            arrs = []
            for leaf in leaves:
                if _is_micro(leaf):
                    # Fold a pre-sharded [k, B/k, ...] layout back to logical.
                    v = leaf.value if resident else np.asarray(leaf.value)
                    leaf = v.reshape((-1,) + v.shape[2:])
                arrs.append(leaf if resident else np.asarray(leaf))
            shape = tuple(arrs[0].shape)
            ragged = {tuple(a.shape) for a in arrs}
            if len(ragged) > 1:
                # The per-step path tolerates shape drift by recompiling; a
                # block scans ONE compiled step, so name the problem instead
                # of letting stack() raise a bare shape error mid-training.
                raise ValueError(
                    f"shard_block: batches disagree on a leaf's shape "
                    f"{sorted(ragged)}; a fused block scans one compiled step "
                    f"over uniformly-shaped batches — pad the ragged batch "
                    f"(or use unroll=1 / per-step run() for shape-bucketed "
                    f"data)")
            stacked = xp.stack(arrs)

            def place(value, spec):
                sharding = NamedSharding(self.mesh, spec)
                if resident:
                    return jax.device_put(value, sharding)
                return place_host_value(value, sharding)

            if k > 1 and len(shape) >= 1 and shape[0] == batch_dim:
                self._require_micro_divisible(shape[0], k, dp)
                micro = stacked.reshape((K, k, shape[0] // k) + shape[1:])
                return MicroBatched(place(
                    micro, P(None, None, *self.plan.batch_pspec(len(shape)))))
            if len(shape) >= 1 and shape[0] % dp == 0:
                spec = P(None, *self.plan.batch_pspec(len(shape)))
            else:
                spec = P()
            return place(stacked, spec)

        tree = jax.tree_util.tree_map(put, *batches, is_leaf=_is_micro)
        return BatchBlock(tree, K)

    def _fetch_token(self, fetch_fn) -> str:
        """A stable, never-reused token for a fetch fn (monotonic counter
        behind a weak map — a collected fn's token is never handed to a new
        one, unlike a recycled ``id()``)."""
        if fetch_fn is None:
            return "-"
        try:
            token = self._fetch_tokens.get(fetch_fn)
            if token is None:
                self._fetch_token_next += 1
                token = self._fetch_tokens[fetch_fn] = self._fetch_token_next
        except TypeError:          # non-weakref-able callable: best effort
            return f"id{id(fetch_fn)}"
        return str(token)

    def _compile_signature(self, kind: str, fetch_fn, batch: PyTree) -> str:
        """Shape signature of one dispatch: the (kind, fetch-fn token,
        per-leaf dtype/shape, treedef) tuple jit keys its executable cache
        by, flattened to a string. Two calls with equal signatures hit the
        same compiled program; a fresh signature recompiles — which is what
        the compile telemetry counts."""
        parts = [kind, self._fetch_token(fetch_fn)]
        leaves, treedef = jax.tree_util.tree_flatten(batch, is_leaf=_is_micro)
        parts.append(str(treedef))
        for leaf in leaves:
            v = leaf.value if _is_micro(leaf) else leaf
            parts.append(f"{'m' if _is_micro(leaf) else ''}"
                         f"{getattr(v, 'dtype', type(v).__name__)}"
                         f"{getattr(v, 'shape', ())}")
        return "|".join(parts)

    def _extract_program_cost(self, jitted, args, steps: int = 1):
        """XLA's static cost analysis for ``jitted`` at ``args`` as a plain
        ``{"flops", "bytes_accessed", "output_bytes"}`` dict, or None when
        the backend reports nothing. Called right after the first dispatch
        of a signature compiled, so ``lower().compile()`` hits the
        executable cache (the same contract ``utils/flops.train_step_flops``
        relies on); accounting must never break a step, hence the broad
        guard.

        ``steps`` scales flops/bytes for the fused K-step block program:
        HloCostAnalysis visits each instruction ONCE and does not model
        loop trip counts, so a ``lax.scan``-of-K-steps program reports its
        body's cost, not K of them — the runner knows K and restores it
        (verified on this backend: the K=4 block reports ~1x the
        single-step program's flops). The gradient-accumulation scan inside
        the step body (``accumulate``'s micro loop) is the same shape of
        under-count, so ``self._accum`` scales too — a slight over-count of
        the once-per-step optimizer update, accepted because the gradient
        pass dominates any program accumulation is worth using on."""
        try:
            with self.mesh:
                compiled = jitted.lower(*args).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            if not cost:
                return None
            k = max(1, int(steps)) * max(1, int(self._accum))
            # Backends report -1 for properties they don't know (the same
            # sentinel utils/flops._flops_from_cost guards): only POSITIVE
            # counts are real.
            flops = float(cost.get("flops", 0.0) or 0.0)
            bytes_acc = float(cost.get("bytes accessed", 0.0) or 0.0)
            if flops <= 0:
                return None
            out: dict = {"flops": k * flops,
                         "bytes_accessed":
                             k * bytes_acc if bytes_acc > 0 else None}
            # The memory ledger: the full memory_analysis() record (bytes a
            # dispatch pins while running — UNscaled by k: the block's
            # working set does not multiply with its trip count). Optional
            # on some backends, but named when absent — a silently-None
            # ledger is how the memory plane goes dark.
            for field in ("output_bytes", "argument_bytes", "temp_bytes",
                          "generated_code_bytes"):
                out[field] = None
            try:
                mem = compiled.memory_analysis()
                out["output_bytes"] = int(mem.output_size_in_bytes)
                out["argument_bytes"] = int(mem.argument_size_in_bytes)
                out["temp_bytes"] = int(mem.temp_size_in_bytes)
                out["generated_code_bytes"] = \
                    int(mem.generated_code_size_in_bytes)
            except Exception as e:  # noqa: BLE001 — optional on some backends
                backend = jax.default_backend()
                if backend not in self._mem_analysis_warned:
                    self._mem_analysis_warned.add(backend)
                    logging.debug(
                        "memory_analysis() unavailable on the %r backend "
                        "(%s); the per-program memory ledger will be empty",
                        backend, e)
            return out
        except Exception:  # noqa: BLE001
            return None

    def _maybe_record_oom(self, where: str, exc: BaseException) -> None:
        """OOM forensics at the dispatch sites: when a step died of
        RESOURCE_EXHAUSTED, book the ``mem.oom`` event and trigger the
        (debounced) flight recorder — whose manifest ``memory`` section is
        the autopsy: census, program ledger, predicted-vs-live peak. The
        caller re-raises the real error either way; forensics never mask
        it (and never fire on non-memory failures)."""
        try:
            from autodist_tpu.telemetry import memplane as _memplane
            if _memplane.is_oom_error(exc):
                _memplane.record_oom(where, exc)
        except Exception:  # noqa: BLE001 — diagnostics must never mask
            pass

    def _dispatch_span(self, name: str, kind: str, fetch_fn, batch: PyTree,
                       cost_probe=None, **span_args):
        """The span wrapping a compiled-step dispatch. Enabled mode only: the
        first dispatch of a NEW shape signature becomes a ``jit.compile``
        span (carrying a crc32 of the signature) whose exit books
        ``jit.cache_miss``/``jit.compile_s`` — so "why was step N slow"
        answers itself as "a new batch shape recompiled". Every dispatch
        additionally counts against its signature's
        :class:`telemetry.profiling.ProgramCost` record, and — with the
        profiling plane active — the first dispatch pulls the compiled
        program's XLA cost analysis through ``cost_probe`` (the jitted fn
        plus its args) into that record. Disabled mode short-circuits to
        the shared no-op span."""
        if not telemetry.enabled():
            return telemetry.span(name)
        sig = self._compile_signature(kind, fetch_fn, batch)
        digest = format(zlib.crc32(sig.encode()), "08x")
        steps = int(span_args.get("steps", 1))
        _profiling.note_dispatch(digest, kind, steps)
        if sig in self._compile_sigs:
            return telemetry.span(name, **span_args)
        self._compile_sigs.add(sig)
        cost_cb = None
        if cost_probe is not None and _profiling.active():
            jitted, jit_args = cost_probe

            def cost_cb(compile_s, _d=digest, _k=kind, _s=steps,
                        _fn=jitted, _a=jit_args):
                _profiling.record_program_cost(
                    _d, _k, _s,
                    self._extract_program_cost(_fn, _a, steps=_s),
                    compile_s=compile_s)
        return _CompileProbe(telemetry.span(
            "jit.compile", kind=kind, sig=digest, **span_args), cost_cb)

    # ------------------------------------------------- compile-only cost probe
    def _abstract_state(self, params: PyTree) -> TrainState:
        """The :class:`TrainState` this runner's ``init(params)`` would build,
        as a ``ShapeDtypeStruct`` pytree via ``jax.eval_shape`` — no device
        allocation, no dispatch. The probe path's stand-in for real state."""
        import jax.numpy as jnp

        def build(p):
            p = self.plan.pad_params(p)
            opt_state = self._optimizer.init(p)
            ef_state = synchronization.init_ef_state(self.plan, p)
            return TrainState(step=jnp.zeros((), jnp.int32), params=p,
                              opt_state=opt_state, ef_state=ef_state,
                              plan=self.plan)

        return jax.eval_shape(build, params)

    def _ensure_state_shardings(self, state: TrainState):
        """Derive the jit in/out shardings from a (possibly abstract) state
        tree — the ONE sharding-tree construction, shared by ``init``
        (concrete trees) and the compile-only probe (ShapeDtypeStructs; the
        derivation only reads leaf paths), so the probe can never lower a
        program with different shardings than the real run."""
        if self._state_shardings is not None:
            return
        self._state_shardings = TrainState(
            step=NamedSharding(self.mesh, P()),
            params=self.plan.param_sharding_tree(self.mesh, state.params),
            opt_state=self.plan.opt_sharding_tree(self.mesh, state.opt_state),
            ef_state=synchronization.ef_sharding_tree(self.mesh,
                                                      state.ef_state),
            plan=self.plan)

    def _abstract_batch(self, batch: PyTree, block: int = 0) -> PyTree:
        """The ShapeDtypeStruct mirror of ``shard_batch`` (``block=0``) /
        ``shard_block`` (``block=K``)'s layout — same micro-batch wrapping and
        leading axes, no placement. Feeds :meth:`plan_costs`' lowering."""
        dp = synchronization.mesh_dp_size(self.mesh)
        k = self._accum
        batch_dim = self._micro_batch_dim(batch, k, dp)

        def abs_leaf(leaf):
            micro = _is_micro(leaf)
            if micro:
                leaf = leaf.value           # already laid out [k, B/k, ...]
            arr = leaf if hasattr(leaf, "shape") else np.asarray(leaf)
            shape, dtype = tuple(arr.shape), np.dtype(arr.dtype)
            if (not micro and k > 1 and len(shape) >= 1
                    and shape[0] == batch_dim):
                self._require_micro_divisible(shape[0], k, dp)
                shape = (k, shape[0] // k) + shape[1:]
                micro = True
            if block:
                shape = (block,) + shape
            struct = jax.ShapeDtypeStruct(shape, dtype)
            return MicroBatched(struct) if micro else struct

        return jax.tree_util.tree_map(abs_leaf, batch, is_leaf=_is_micro)

    def plan_costs(self, params: PyTree, example_batch: PyTree,
                   unroll: int = 1) -> Optional[dict]:
        """Compile-only static cost probe of this runner's step program.

        Lowers + compiles the (``unroll=K`` fused or single-step) training
        program at abstract args — state via :meth:`_abstract_state`, batch
        via :meth:`_abstract_batch` — and returns XLA's cost analysis as a
        ``{"flops", "bytes_accessed", "output_bytes", "steps", "dispatches",
        "source"}`` record (flops/bytes PER DISPATCH, the shape
        ``telemetry.costmodel.predict`` consumes), or None when the backend
        reports nothing. **No step executes and no state is allocated**: the
        probe's only cost is one compilation, which lands in jit's executable
        cache so a later real first step of the same signature reuses it.
        This is the predict-stage interface the plan autotuner
        (:mod:`autodist_tpu.strategy.autotune`) ranks candidates with."""
        if unroll < 1:
            raise ValueError("unroll must be >= 1")
        if unroll > 1 and not self.supports_run_many:
            raise RuntimeError(
                f"{type(self).__name__} has no fused multi-step program to "
                f"probe at unroll={unroll}; probe unroll=1")
        state = self._abstract_state(params)
        self._ensure_state_shardings(state)
        if unroll > 1:
            jitted = self._many_fns.get(None)
            if jitted is None:
                jitted = self._build_many(None)
            batch = self._abstract_batch(example_batch, block=unroll)
        else:
            jitted = self._step_fns.get(None)
            if jitted is None:
                jitted = self._build_step(None)
            batch = self._abstract_batch(example_batch)
        cost = self._extract_program_cost(jitted, (state, batch), steps=unroll)
        if cost is None:
            return None
        return dict(cost, steps=unroll, dispatches=1, source="xla")

    def logical_params(self, state_or_params) -> PyTree:
        """The parameter tree at its original (user-facing, unpadded) shapes."""
        params = state_or_params.params if isinstance(state_or_params, TrainState) \
            else state_or_params
        return self.plan.unpad_params(params)

    def run(self, state: TrainState, batch: PyTree,
            fetches: Optional[Callable] = None) -> Tuple[TrainState, Any]:
        """One synchronized training step. Returns ``(new_state, fetched)``.

        ``fetched`` defaults to the loss (or ``(loss, aux)`` with has_aux). With
        ``fetches=fn`` — any ``fn(params, batch) -> pytree`` — it becomes
        ``(default_fetches, fn_result)``, computed inside the same compiled step
        from the pre-update parameters (the reference fetched arbitrary session
        tensors the same way, remapper.py:125-185). Per-example leaves return as
        global batch-sharded arrays (the concat contraction); scalars return
        replicated (the master-replica contraction).
        """
        if self._state_shardings is None:
            raise RuntimeError("Call init(params) before run()")
        step_fn = self._step_fns.get(fetches)
        first_build = step_fn is None
        if first_build:
            step_fn = self._build_step(fetches)
        with telemetry.span("runner.shard_batch"):
            sharded = self.shard_batch(batch)
        if first_build and not self._step_fns.keys() - {fetches}:
            self._maybe_dump_graphs(state, sharded, step_fn)
        # The dispatch span closes when the program is ENQUEUED (dispatch is
        # asynchronous); the wait for results shows up in the caller's
        # readback span (metrics._sync / device_get), and device execution in
        # the jax.profiler trace. A long dispatch span means compilation or a
        # full dispatch queue — and the first dispatch of a new shape
        # signature is recorded AS compilation (jit.compile span +
        # jit.cache_miss/jit.compile_s counters, see _dispatch_span).
        try:
            with self._dispatch_span("runner.run.dispatch", "step", fetches,
                                     sharded, cost_probe=(step_fn,
                                                          (state, sharded))):
                with self.mesh:
                    new_state, (loss, aux, fetched, bundle) = step_fn(state,
                                                                      sharded)
        except Exception as e:  # noqa: BLE001 — OOM forensics, then re-raise
            self._maybe_record_oom("runner.run", e)
            raise
        if self.health:
            self.last_health = bundle
        default = (loss, aux) if self._has_aux else loss
        if fetches is not None:
            return new_state, (default, fetched)
        return new_state, default

    def run_many(self, state: TrainState, batches,
                 fetches: Optional[Callable] = None) -> Tuple[TrainState, Any]:
        """K fused training steps in ONE compiled dispatch.

        ``batches`` is a sequence of K host batches, or a pre-sharded
        :class:`BatchBlock` from :meth:`shard_block` /
        ``device_prefetch(unroll=K)``. The step body is scanned on-device, so
        Python dispatch, feed remapping, and fetch materialization are paid
        once per K steps — and the result is bit-identical to K sequential
        :meth:`run` calls (same body, same shardings; test-pinned).

        The fetch contract is :meth:`run`'s with a leading ``[K]`` step axis:
        losses return as a ``[K]`` stack, aux and ``fetches=fn`` results stack
        per step (each slice computed from that step's pre-update params)."""
        if not self.supports_run_many:
            raise RuntimeError(
                f"{type(self).__name__} does not support run_many: the async "
                f"regime's parameter service applies gradients step-by-step; "
                f"use run() (or train(..., unroll=1))")
        if self._state_shardings is None:
            raise RuntimeError("Call init(params) before run_many()")
        if isinstance(batches, BatchBlock):
            block = batches
        else:
            with telemetry.span("runner.shard_block"):
                block = self.shard_block(batches)
        many_fn = self._many_fns.get(fetches)
        if many_fn is None:
            many_fn = self._build_many(fetches)
        try:
            with self._dispatch_span("runner.run_many.dispatch", "many",
                                     fetches, block.tree, steps=block.length,
                                     cost_probe=(many_fn,
                                                 (state, block.tree))):
                with self.mesh:
                    new_state, (losses, auxes, fetched, bundle) = many_fn(
                        state, block.tree)
        except Exception as e:  # noqa: BLE001 — OOM forensics, then re-raise
            self._maybe_record_oom("runner.run_many", e)
            raise
        if self.health:
            self.last_health = bundle
        default = (losses, auxes) if self._has_aux else losses
        if fetches is not None:
            return new_state, (default, fetched)
        return new_state, default

    def evaluate(self, state: TrainState, batch: PyTree,
                 fn: Optional[Callable] = None):
        """Forward-only compiled evaluation — no gradients, no update, no
        donation; ``state`` stays valid and unchanged.

        ``fn(params, batch) -> pytree`` defaults to the loss function. Params
        are presented at logical (unpadded) shapes, like the training step.
        The reference evaluated by session-running non-train fetches
        (remapper.py:125-185 master-replica contraction); here it is its own
        tiny compiled program, cached per ``fn`` identity.
        """
        if self._state_shardings is None:
            raise RuntimeError("Call init(params) before evaluate()")
        fn = fn if fn is not None else self._loss_fn
        jitted = self._eval_fns.get(fn)
        if jitted is None:
            unpad = self.plan.unpad_params if self.plan.has_padding else (lambda t: t)
            jitted = jax.jit(lambda p, b: fn(unpad(p), b),
                             in_shardings=(self._state_shardings.params, None))
            self._eval_fns[fn] = jitted
            if len(self._eval_fns) > 8:
                # Never evict the default (loss) entry — it is the hot path.
                evict = next(k for k in self._eval_fns if k is not self._loss_fn)
                del self._eval_fns[evict]
                logging.warning(
                    "More than 8 distinct evaluate() callables compiled; pass a "
                    "stable function instead of per-call lambdas")
        # A batch pre-sharded for an accumulating run() carries MicroBatched
        # [k, B/k, ...] leaves — fold them back to the logical layout first.
        batch = jax.tree_util.tree_map(
            lambda l: l.value.reshape((-1,) + l.value.shape[2:]) if _is_micro(l)
            else l, batch, is_leaf=_is_micro)
        sharded = self.shard_batch(batch, accumulation=1)
        with self.mesh:
            return jitted(state.params, sharded)

    def _maybe_dump_graphs(self, state: TrainState, sharded_batch: PyTree,
                           step_fn: Callable):
        """Stage snapshots (reference dumped the graph at each transform stage,
        graph_transformer.py:62-90): 0-original = the user's loss fn, 1-distributed
        = the sharded train step. ``sharded_batch`` is already on-device."""
        from autodist_tpu import const
        if not const.ENV.AUTODIST_DUMP_GRAPHS.val:
            return
        from autodist_tpu.utils import tracing
        # The user's loss fn sees the logical batch: fold micro-batched leaves back.
        logical_batch = jax.tree_util.tree_map(
            lambda l: l.value.reshape((-1,) + l.value.shape[2:]) if _is_micro(l)
            else l, sharded_batch, is_leaf=_is_micro)
        with self.mesh:
            tracing.dump_stage("train_step", "0-original", self._step_loss_fn,
                               state.params, logical_batch)
            tracing.dump_stage("train_step", "1-distributed",
                               lambda s, b: step_fn(s, b), state, sharded_batch)

    # Convenience parity alias: session.run(...)
    __call__ = run
