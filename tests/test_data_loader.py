"""Native + fallback data loader: batch semantics, shuffle, prefetch, device feed."""

import numpy as np
import pytest

from autodist_tpu.data import DataLoader, device_prefetch


def _dataset(n=64, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "x": rng.randn(n, 5).astype(np.float32),
        "y": rng.randint(0, 10, size=(n,)).astype(np.int32),
    }


def test_native_loader_builds_and_serves_correct_rows():
    data = _dataset()
    dl = DataLoader(data, batch_size=16, shuffle=True, seed=3, native=True)
    assert dl.is_native
    row_lookup = {tuple(np.round(r, 5)): i for i, r in enumerate(data["x"])}
    seen = set()
    for _ in range(4):  # one epoch: 64/16 batches
        batch = dl.next()
        assert batch["x"].shape == (16, 5) and batch["y"].shape == (16,)
        for bx, by in zip(batch["x"], batch["y"]):
            i = row_lookup[tuple(np.round(bx, 5))]     # row exists in the dataset
            assert data["y"][i] == by                  # arrays stay row-aligned
            seen.add(i)
    assert len(seen) == 64  # a full epoch covers every row exactly once
    dl.close()


def test_native_matches_fallback_semantics_unshuffled():
    data = _dataset(n=20)
    native = DataLoader(data, batch_size=8, shuffle=False, native=True)
    fallback = DataLoader(data, batch_size=8, shuffle=False, native=False)
    assert native.is_native and not fallback.is_native
    for _ in range(5):  # crosses the drop-last boundary (20 = 2*8 + 4 dropped)
        nb, fb = native.next(), fallback.next()
        np.testing.assert_array_equal(nb["x"], fb["x"])
        np.testing.assert_array_equal(nb["y"], fb["y"])
    # Epoch counting: fallback counts consumed wraps exactly; the native counter
    # is producer-side and may run up to `prefetch` batches ahead.
    assert fallback.epochs_completed == 2
    assert native.epochs_completed >= 2
    native.close()


def test_shuffle_is_seed_deterministic():
    data = _dataset()
    a = DataLoader(data, batch_size=16, shuffle=True, seed=7, native=True)
    b = DataLoader(data, batch_size=16, shuffle=True, seed=7, native=True)
    for _ in range(6):
        np.testing.assert_array_equal(a.next()["x"], b.next()["x"])
    a.close(), b.close()


def test_loader_validates_inputs():
    data = _dataset(n=8)
    with pytest.raises(ValueError, match="batch_size"):
        DataLoader(data, batch_size=9)
    with pytest.raises(ValueError, match="leading dim"):
        DataLoader({"x": np.zeros((4, 2)), "y": np.zeros((5,))}, batch_size=2)
    with pytest.raises(ValueError, match="at least one"):
        DataLoader({}, batch_size=1)


def test_device_prefetch_feeds_training():
    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.strategy import AllReduce

    rng = np.random.RandomState(0)
    w_true = rng.randn(5, 1).astype(np.float32)
    x = rng.randn(64, 5).astype(np.float32)
    data = {"x": x, "y": (x @ w_true + 0.01 * rng.randn(64, 1)).astype(np.float32)}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    params = {"w": np.zeros((5, 1), np.float32)}
    dl = DataLoader(data, batch_size=16, shuffle=True, seed=0)
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(loss_fn, params, optax.sgd(0.1),
                       example_batch=dl.next())
    feed = device_prefetch(dl, step.runner, depth=2)
    losses = [float(step(next(feed))) for _ in range(20)]
    assert losses[-1] < 0.1 * losses[0]
    dl.close()
