"""Shard-count policies shared by the partitioned strategy builders.

Ports of the reference's pure algorithms: smallest divisor of dim0 for even
partitioning (``partitioned_ps_strategy.py:125-135``) and smallest *non*-divisor for
the uneven variant (``uneven_partition_ps_strategy.py:125-135``), which deliberately
exercises remainder handling (on TPU: pad-and-mask shards).
"""

from typing import Optional, Tuple

from autodist_tpu.model_spec import ParamSpec


def smallest_divisor_at_least_2(n: int, cap: Optional[int] = None) -> Optional[int]:
    """Smallest k >= 2 dividing n (None if n < 2 or no divisor <= cap)."""
    if n < 2:
        return None
    k = 2
    while k * k <= n:
        if n % k == 0:
            break
        k += 1
    else:
        k = n  # n is prime: its smallest divisor >= 2 is itself
    if cap is not None and k > cap:
        return None
    return k


def smallest_non_divisor_at_least_2(n: int, cap: Optional[int] = None) -> Optional[int]:
    """Smallest k >= 2 NOT dividing n (None if n < 2 or k exceeds cap)."""
    if n < 2:
        return None
    k = 2
    while n % k == 0:
        k += 1
    if cap is not None and k > cap:
        return None
    return k


def partitionable_axis(spec: ParamSpec) -> Optional[int]:
    """The tensor axis eligible for partitioning, or None.

    Like the reference (one active axis, ``kernel/partitioner.py:51-70``), axis 0 is
    the default; sparse (embedding) parameters must partition axis 0 so row updates
    stay shard-local (reference forced axis 0 for sparse,
    ``random_axis_partition_all_reduce_strategy.py:118-141``).
    """
    if not spec.shape or spec.shape[0] < 2:
        return None
    return 0


def make_num_shards(rank: int, axis: int, k: int) -> Tuple[int, ...]:
    """Per-axis shard counts with one active axis (reference partitioner str "k,1,..")."""
    return tuple(k if i == axis else 1 for i in range(max(rank, 1)))
