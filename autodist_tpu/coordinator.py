"""Coordinator: the chief re-executes the user's script on every host.

Parity with reference ``autodist/coordinator.py``:

- ``launch_clients()`` ships the serialized strategy to each worker host, then runs
  the user's own command (``python + sys.argv``) there with the role env set
  (``AUTODIST_WORKER=<ip>``, ``AUTODIST_STRATEGY_ID=<id>``, reference ``:66-90``),
  plus the TPU-native bootstrap env (coordinator address, process count/id) that
  ``jax.distributed.initialize`` consumes on each host.
- A watchdog thread per remote process fail-fasts the chief on any nonzero worker
  exit (``os._exit(1)``, reference ``:98-110``).
"""

import os
import sys
import threading
from typing import List, Optional

from autodist_tpu import const
from autodist_tpu.cluster import Cluster, is_local_address
from autodist_tpu.utils import logging


class Coordinator:
    def __init__(self, strategy, cluster: Cluster,
                 argv: Optional[List[str]] = None):
        self._strategy = strategy
        self._cluster = cluster
        self._argv = argv if argv is not None else sys.argv
        self._procs = []
        self._watchdogs: List[threading.Thread] = []

    def launch_clients(self, extra_env: Optional[dict] = None):
        """Ship strategy + relaunch the user script on every non-chief host.

        ``extra_env``: additional env vars for the workers (e.g. the async PS
        transport address, ``AUTODIST_PS_ADDR``)."""
        strategy_path = self._strategy.serialize()
        spec = self._cluster.cluster_spec
        coordinator_addr = spec["coordinator"]
        n = self._cluster.num_processes

        for proc_info in spec["processes"]:
            address = proc_info["address"]
            if proc_info["process_id"] == 0:
                continue  # the chief is this process
            if not is_local_address(address):
                self._cluster.remote_copy(strategy_path, const.DEFAULT_SERIALIZATION_DIR,
                                          address)
            env = {
                const.ENV.AUTODIST_WORKER.name: address,
                const.ENV.AUTODIST_STRATEGY_ID.name: self._strategy.id,
                const.ENV.AUTODIST_COORDINATOR_ADDR.name: coordinator_addr,
                const.ENV.AUTODIST_COORDINATOR_PORT.name:
                    str(const.ENV.AUTODIST_COORDINATOR_PORT.val),
                const.ENV.AUTODIST_NUM_PROCESSES.name: str(n),
                const.ENV.AUTODIST_PROCESS_ID.name: str(proc_info["process_id"]),
                const.ENV.AUTODIST_MIN_LOG_LEVEL.name: const.ENV.AUTODIST_MIN_LOG_LEVEL.val,
            }
            if const.ENV.AUTODIST_IS_TESTING.val:
                env[const.ENV.AUTODIST_IS_TESTING.name] = "1"
            # The reference propagated its path env vars to every worker
            # (coordinator.py:70-79); a user script driven by SYS_RESOURCE_PATH /
            # SYS_DATA_PATH must resolve them identically when re-executed.
            for var in (const.ENV.SYS_RESOURCE_PATH, const.ENV.SYS_DATA_PATH):
                if var.val:
                    env[var.name] = var.val
            if extra_env:
                env.update({k: str(v) for k, v in extra_env.items()})
            cmd = [sys.executable] + self._argv
            logging.info("Launching worker on %s (process %d/%d)",
                         address, proc_info["process_id"], n)
            proc = self._cluster.remote_exec(cmd, address, env=env)
            self._procs.append(proc)
            self._watch(proc, address)

    def _on_worker_failure(self, address: str, code: int):
        """Fail-fast: kill the chief (reference coordinator.py:98-110). Overridable
        for tests and for future elastic policies."""
        logging.error("Worker %s exited with code %s; terminating chief", address, code)
        os._exit(1)

    def _watch(self, proc, address: str):
        def wait():
            code = proc.wait()
            if code != 0:
                self._on_worker_failure(address, code)

        thread = threading.Thread(target=wait, daemon=True)
        thread.start()
        self._watchdogs.append(thread)

    def join(self, timeout: Optional[float] = None):
        """Wait for all workers. With a timeout, returns False if any worker is
        still running when it expires (the caller decides whether to terminate)."""
        import subprocess
        done = True
        for proc in self._procs:
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                done = False
        return done
