"""SavedModel-equivalent export for serving.

Counterpart of reference ``checkpoint/saved_model_builder.py:24-64`` (a
SavedModelBuilder that exported the transformed graph's variables under original
names for vanilla-TF serving). The TPU-native serving artifact is a directory with:

- ``params.npz`` — full unsharded parameters under original names (via Saver),
- ``model_config.json`` — user-provided model metadata (enough to rebuild the
  apply function),
- optionally ``apply.hlo`` — the StableHLO text of the jitted apply function, a
  framework-independent serving graph (what a SavedModel's GraphDef was to TF).
"""

import json
import os
from typing import Any, Callable, Optional

import jax

from autodist_tpu.checkpoint.saver import Saver
from autodist_tpu.utils import logging


class SavedModelBuilder:
    def __init__(self, export_dir: str):
        self._export_dir = export_dir
        os.makedirs(export_dir, exist_ok=True)

    def save(self, params: Any, model_config: Optional[dict] = None,
             apply_fn: Optional[Callable] = None, example_args: tuple = ()) -> str:
        saver = Saver(max_to_keep=1)
        saver.save(params, os.path.join(self._export_dir, "params"), global_step=0)
        # Rename to the stable serving name (no step suffix) and drop the Saver's
        # latest-pointer state file, which would point at the renamed-away prefix.
        for suffix in (".npz", ".json"):
            src = os.path.join(self._export_dir, "params-0" + suffix)
            dst = os.path.join(self._export_dir, "params" + suffix)
            if os.path.exists(src):
                os.replace(src, dst)
        state_file = os.path.join(self._export_dir, "checkpoint")
        if os.path.exists(state_file):
            os.remove(state_file)

        with open(os.path.join(self._export_dir, "model_config.json"), "w") as f:
            json.dump(model_config or {}, f, indent=1, sort_keys=True)

        if apply_fn is not None:
            lowered = jax.jit(apply_fn).lower(params, *example_args)
            with open(os.path.join(self._export_dir, "apply.hlo"), "w") as f:
                f.write(lowered.as_text())

        logging.info("Exported serving artifact to %s", self._export_dir)
        return self._export_dir

    @staticmethod
    def load_params(export_dir: str):
        return Saver().restore_params(os.path.join(export_dir, "params"))
