"""Cross-process sequence parallelism (driver in test_multiprocess.py).

Long-context is first-class (SURVEY.md §5.7): this script runs the ring-
attention sequence-parallel session over a REAL 2-process mesh — a 4-way
``seq`` axis spanning the process boundary, so the ring's K/V ``ppermute``
hops cross between OS processes (the gloo wire on CPU, ICI/DCN on a pod).
Same protocol as the strategy matrix: the chief runs this script, the
Coordinator re-executes it as the worker, and ``AUTODIST_MATRIX_SINGLE=1``
produces the single-process 4-device reference the 2-process run must match
value-exactly (identical global mesh => identical shard count and rounding).

The chief writes per-step losses + final params to argv[1].
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist  # noqa: E402
from autodist_tpu.models import transformer_lm  # noqa: E402
from autodist_tpu.parallel.sequence import (  # noqa: E402
    create_sequence_parallel_session)
from autodist_tpu.resource_spec import ResourceSpec  # noqa: E402
from autodist_tpu.strategy import SequenceParallel  # noqa: E402

SEQ = 32
BATCH = 4
STEPS = 3

SINGLE = os.environ.get("AUTODIST_MATRIX_SINGLE") == "1"


def _spec():
    if SINGLE:
        nodes = [{"address": "localhost", "tpus": 4, "chief": True}]
    else:
        nodes = [{"address": "localhost", "tpus": 2, "chief": True},
                 {"address": "127.0.0.1", "tpus": 2}]
    return ResourceSpec(resource_info={"nodes": nodes})


def main(out_path: str):
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=128, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_len=SEQ, dtype=jnp.float32, tied_output=False,
        attention_impl="ring")
    # Multi-host constraint: jax.distributed must bootstrap before the first
    # backend touch, but the session needs the model's parameter SHAPES.
    # jax.eval_shape is backend-free, so abstract params drive the strategy
    # build and real params materialize only after the session (and therefore
    # the multihost init) exists.
    model = transformer_lm.TransformerLM(cfg)
    abstract_params = jax.eval_shape(
        lambda k, t: model.init(k, t)["params"],
        jax.ShapeDtypeStruct((2,), jnp.uint32),
        jax.ShapeDtypeStruct((1, SEQ), jnp.int32))

    ad = AutoDist(_spec(), SequenceParallel(seq_axis_size=4))
    runner = create_sequence_parallel_session(ad, model, abstract_params,
                                              optax.adam(1e-2))
    if not SINGLE:
        assert jax.process_count() == 2, f"process_count={jax.process_count()}"
    assert jax.device_count() == 4
    assert dict(runner.mesh.shape)["seq"] == 4  # spans the process boundary

    _, params = transformer_lm.init_params(cfg)
    state = runner.init(params)
    losses = []
    for step in range(STEPS):
        batch = transformer_lm.synthetic_batch(cfg, batch_size=BATCH,
                                               seq_len=SEQ, seed=step)
        state, loss = runner.run(state, batch)
        losses.append(float(loss))

    if jax.process_index() == 0:
        logical = jax.device_get(runner.logical_params(state))
        flat = {jax.tree_util.keystr(p): np.asarray(l).ravel()[:8].tolist()
                for p, l in jax.tree_util.tree_flatten_with_path(logical)[0]}
        result = {
            "losses": losses,
            "params_sample": flat,
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
            "mesh": {k: int(v) for k, v in dict(runner.mesh.shape).items()},
        }
        with open(out_path, "w") as f:
            json.dump(result, f)


def run_single_reference(out_path: str, workdir: str, timeout: int = 300):
    """Run this script once, single-process, on a 4-device sim mesh (the
    strategy matrix's shared env recipe, ``tests/mp_env.py``)."""
    import subprocess

    from tests.mp_env import repo_root, single_reference_env
    env = single_reference_env(workdir, device_count=4)
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), out_path],
        env=env, cwd=repo_root(), capture_output=True, text=True,
        timeout=timeout)


if __name__ == "__main__":
    main(sys.argv[1])
