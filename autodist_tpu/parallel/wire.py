"""Typed binary wire codec for the PS transport — the pickle replacement.

The reference's PS plane spoke protobuf over grpc: typed messages, no code
execution on decode (``SURVEY.md`` §2.4). The first TPU-native transport
pickled pytrees, which made every socket byte a potential
``pickle.loads`` RCE. This codec closes that: a small tag-based binary
format covering exactly the protocol's value vocabulary —

- ``None``/bool/int/float/str/bytes,
- tuple/list/dict (the protocol messages and pytree containers),
- numpy ndarrays as ``dtype name + shape + raw C-order bytes`` (the typed
  tensor framing; custom float dtypes like bfloat16 ride as their true dtype
  name, decoded via ml_dtypes),
- REGISTERED dataclass pytree nodes (compressor state such as ``EFState``),
  encoded as a registry key + field dict and reconstructed only through the
  registry — never by importing attacker-chosen names.

Decoding allocates plain Python/numpy objects; there is no reduce protocol,
no module import, no callable evaluation. Unknown tags or registry keys
raise :class:`WireError`. By default arrays are copied out of the input
buffer so the caller may free it; ``decode(buf, copy=False)`` instead
aliases array payloads into ``buf`` (read-only views) for receive paths
that keep the buffer alive — see :func:`decode`.

The encoder has two faces over one code path: :func:`encode` returns one
``bytes`` object, and :func:`encode_parts` returns a scatter-gather list of
buffers whose concatenation is byte-identical to ``encode``'s output — large
C-contiguous ndarrays ride as BORROWED views of their own memory (no
``tobytes()`` copy, no concat copy), so a multi-MB gradient push serializes
without touching the tensor bytes. Old and new endpoints therefore
interoperate freely: the bytes on the wire are the same either way.

Ints use a fixed 8-byte signed encoding with a decimal-string escape for
arbitrary precision; dict keys may be any encodable value (the protocol uses
str keys, but pytrees may legally carry int keys).
"""

import struct
from typing import Any, Callable, Dict, List, Tuple

import numpy as np

__all__ = ["encode", "encode_parts", "decode", "register_wire_dataclass",
           "WireError"]


class WireError(ValueError):
    """Malformed or out-of-vocabulary wire data."""


_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1
_u32 = struct.Struct("!I")
_u64 = struct.Struct("!Q")
_i64 = struct.Struct("!q")
_f64 = struct.Struct("!d")

# Registered dataclass nodes: key -> (cls, field_names). The key is the
# class's registration name, agreed by both endpoints at import time; decode
# can only ever construct classes something in THIS process registered.
_REGISTRY: Dict[str, Tuple[type, Tuple[str, ...]]] = {}
_CLS_KEY: Dict[type, str] = {}


def register_wire_dataclass(cls: type, key: str = None) -> type:
    """Allow ``cls`` (a field-constructible dataclass used as a pytree node)
    across the wire. Both endpoints must register it — which they do by
    importing the defining module. Returns ``cls`` (decorator-friendly)."""
    import dataclasses
    if not dataclasses.is_dataclass(cls):
        raise TypeError(f"{cls!r} is not a dataclass")
    key = key or f"{cls.__module__}:{cls.__qualname__}"
    _REGISTRY[key] = (cls, tuple(f.name for f in dataclasses.fields(cls)))
    _CLS_KEY[cls] = key
    return cls


# ---------------------------------------------------------------------- encode

# Arrays at or above this many bytes are emitted as borrowed buffers by
# encode_parts; smaller ones are inlined into the adjacent header segment
# (a dedicated iovec per 8-byte scalar would cost more than the copy saves).
_BORROW_MIN_BYTES = 1024


class _PartSink:
    """bytearray-compatible accumulator that can split out borrowed buffers.

    ``_enc`` only ever does ``out += <bytes-like>``, so the same encoder body
    serves both faces: with a plain ``bytearray`` it produces one contiguous
    message (:func:`encode`); with a ``_PartSink`` large array payloads are
    appended as zero-copy views between the accumulated header segments
    (:func:`encode_parts`)."""

    __slots__ = ("parts", "tail")

    def __init__(self):
        self.parts: List[Any] = []
        self.tail = bytearray()

    def __iadd__(self, data):
        self.tail += data
        return self

    def borrow(self, view):
        """Append ``view`` (a memoryview over caller-owned memory) without
        copying; the caller must keep the backing memory unchanged until the
        parts have been sent."""
        if self.tail:
            self.parts.append(self.tail)
            self.tail = bytearray()
        self.parts.append(view)

    def finish(self) -> List[Any]:
        if self.tail:
            self.parts.append(self.tail)
            self.tail = bytearray()
        return self.parts


def _enc_str(out, s: str):
    b = s.encode("utf-8")
    out += _u32.pack(len(b))
    out += b


def _enc(out, obj: Any):
    if obj is None:
        out += b"N"
    elif obj is True:
        out += b"T"
    elif obj is False:
        out += b"F"
    elif type(obj) is int:  # exact: bool is handled above, np ints below
        if _I64_MIN <= obj <= _I64_MAX:
            out += b"i"
            out += _i64.pack(obj)
        else:
            out += b"I"
            _enc_str(out, str(obj))
    elif type(obj) is float:
        out += b"f"
        out += _f64.pack(obj)
    elif type(obj) is str:
        out += b"s"
        _enc_str(out, obj)
    elif type(obj) is bytes:
        out += b"b"
        out += _u64.pack(len(obj))
        out += obj
    elif isinstance(obj, (np.ndarray, np.generic)):
        # asarray, NOT ascontiguousarray: the latter promotes 0-d to 1-d,
        # silently reshaping scalar gradients. tobytes() below serializes in
        # C order whatever the memory layout.
        arr = np.asarray(obj)
        if arr.dtype.hasobject:
            # tobytes() on an object array would serialize raw heap POINTERS
            # — a memory-address leak the peer cannot decode anyway. Refuse
            # at encode time so the server's reply-encode error path reports
            # it as a server-side limitation.
            raise WireError("object-dtype arrays are not wire-encodable")
        out += b"a"
        _enc_str(out, str(arr.dtype))
        out += bytes([arr.ndim])
        for d in arr.shape:
            out += _u64.pack(d)
        if (type(out) is _PartSink and arr.nbytes >= _BORROW_MIN_BYTES
                and arr.flags.c_contiguous):
            # Zero-copy: the payload is the array's own memory. A C-contiguous
            # buffer viewed as flat uint8 is exactly tobytes()'s C-order
            # output, so the concatenated parts stay byte-identical to
            # encode(). (reshape(-1)/view are views here, never copies.)
            out += _u64.pack(arr.nbytes)
            out.borrow(memoryview(arr.reshape(-1).view(np.uint8)))
        else:
            raw = arr.tobytes()  # C-order buffer; works for custom dtypes too
            out += _u64.pack(len(raw))
            out += raw
    elif type(obj) is tuple:
        out += b"t"
        out += _u32.pack(len(obj))
        for item in obj:
            _enc(out, item)
    elif type(obj) is list:
        out += b"l"
        out += _u32.pack(len(obj))
        for item in obj:
            _enc(out, item)
    elif type(obj) is dict:
        out += b"d"
        out += _u32.pack(len(obj))
        for k, v in obj.items():
            _enc(out, k)
            _enc(out, v)
    elif type(obj) in _CLS_KEY:
        out += b"o"
        _enc_str(out, _CLS_KEY[type(obj)])
        fields = _REGISTRY[_CLS_KEY[type(obj)]][1]
        out += _u32.pack(len(fields))
        for name in fields:
            _enc_str(out, name)
            _enc(out, getattr(obj, name))
    else:
        # jax Arrays must be host-converted (_to_host) before sending; any
        # other type is outside the protocol vocabulary by design.
        raise WireError(
            f"type {type(obj).__name__} is not wire-encodable; convert device "
            f"arrays to numpy first or register the dataclass")


def encode(obj: Any) -> bytes:
    """Serialize a protocol message to bytes."""
    out = bytearray()
    _enc(out, obj)
    return bytes(out)


def encode_parts(obj: Any) -> List[Any]:
    """Serialize a protocol message as a scatter-gather buffer list.

    ``b"".join(encode_parts(obj)) == encode(obj)`` always holds — the parts
    are the SAME wire bytes, merely not concatenated. Large C-contiguous
    ndarray payloads come back as borrowed read-views of the arrays' own
    memory, so the caller (``ps_transport._send_payload``) can hand the list
    to ``socket.sendmsg`` and ship a multi-MB pytree with zero serialization
    copies. The views borrow: do not mutate the source arrays until the
    parts have been fully sent."""
    sink = _PartSink()
    _enc(sink, obj)
    return sink.finish()


# ---------------------------------------------------------------------- decode

class _Reader:
    __slots__ = ("buf", "pos", "copy")

    def __init__(self, buf, copy: bool = True):
        self.buf = memoryview(buf)
        self.pos = 0
        self.copy = copy

    def take(self, n: int) -> memoryview:
        if self.pos + n > len(self.buf):
            raise WireError("truncated wire message")
        v = self.buf[self.pos:self.pos + n]
        self.pos += n
        return v

    def u32(self) -> int:
        return _u32.unpack(self.take(4))[0]

    def u64(self) -> int:
        return _u64.unpack(self.take(8))[0]

    def str_(self) -> str:
        return str(self.take(self.u32()), "utf-8")


def dtype_from_name(name: str) -> np.dtype:
    """Resolve a dtype by its string name, including ml_dtypes customs
    (bfloat16, float8_*). Raises ValueError for unknown names — the single
    resolver shared by the wire codec and the checkpoint manifest reader."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except (AttributeError, TypeError):
            raise ValueError(f"unknown array dtype {name!r}") from None


def _np_dtype(name: str):
    try:
        return dtype_from_name(name)
    except ValueError as e:
        raise WireError(str(e)) from None


def _dec(r: _Reader) -> Any:
    tag = bytes(r.take(1))
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return _i64.unpack(r.take(8))[0]
    if tag == b"I":
        return int(r.str_())
    if tag == b"f":
        return _f64.unpack(r.take(8))[0]
    if tag == b"s":
        return r.str_()
    if tag == b"b":
        return bytes(r.take(r.u64()))
    if tag == b"a":
        dtype = _np_dtype(r.str_())
        ndim = bytes(r.take(1))[0]
        shape = tuple(r.u64() for _ in range(ndim))
        nbytes = r.u64()
        want = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if nbytes != want:
            raise WireError(f"array payload {nbytes}B != shape/dtype {want}B")
        flat = np.frombuffer(r.take(nbytes), np.uint8)
        if r.copy:
            # Copy: the caller may free the receive buffer after decode.
            flat = flat.copy()
        else:
            # Alias: the array keeps the receive buffer alive through its
            # .base chain; mark it read-only so a caller mutating a pulled
            # tree cannot scribble over a recycled buffer.
            flat.flags.writeable = False
        return flat.view(dtype).reshape(shape)
    if tag == b"t":
        return tuple(_dec(r) for _ in range(r.u32()))
    if tag == b"l":
        return [_dec(r) for _ in range(r.u32())]
    if tag == b"d":
        n = r.u32()
        out = {}
        for _ in range(n):
            k = _dec(r)
            out[k] = _dec(r)
        return out
    if tag == b"o":
        key = r.str_()
        entry = _REGISTRY.get(key)
        if entry is None:
            raise WireError(f"unregistered wire dataclass {key!r}")
        cls, known = entry
        fields = {}
        for _ in range(r.u32()):
            name = r.str_()
            value = _dec(r)
            if name not in known:
                raise WireError(f"{key}: unexpected field {name!r}")
            fields[name] = value
        return cls(**fields)
    raise WireError(f"unknown wire tag {tag!r}")


def decode(buf, copy: bool = True) -> Any:
    """Deserialize one message (bytes/memoryview).

    ``copy=True`` (default): array data is copied out of ``buf``; the caller
    may free/reuse the buffer afterwards. ``copy=False``: arrays come back as
    READ-ONLY views aliasing ``buf`` — zero decode copies. The views keep the
    buffer alive (refcount), but a transport recycling the buffer (see
    ``ps_transport._RecvBuffer``) will overwrite it once every alias has been
    dropped, so only callers that consume the tree — e.g. feed it to a jitted
    function and drop it — before releasing their references should pass
    ``copy=False``.

    EVERY malformed-input failure surfaces as :class:`WireError` — including
    bad UTF-8, overflowing dims, unhashable dict keys, wrong dataclass
    fields, or absurd nesting — so a server can catch one exception type and
    treat it as 'broken peer' (anything else escaping decode is a server-side
    bug, not bad input)."""
    r = _Reader(buf, copy=copy)
    try:
        obj = _dec(r)
    except WireError:
        raise
    except Exception as e:
        raise WireError(f"malformed wire message: {type(e).__name__}: {e}") \
            from e
    if r.pos != len(r.buf):
        raise WireError(f"{len(r.buf) - r.pos} trailing bytes after message")
    return obj
