"""HBM memory plane: owner-attributed census, budget, pressure, OOM forensics.

The raw gauges existed before this module — ``device.mem.bytes_in_use.d<id>``
from the allocator, ``device.live_bytes`` from ``jax.live_arrays()`` — but
nothing said WHOSE bytes those were, whether a candidate plan would fit
before a compile probe was spent on it, or what was resident when an OOM
killed the run. This module closes those three gaps with one registry:

- **Tag registry** — :func:`tag` claims device bytes for a named owner
  (``params`` / ``opt_state`` / ``kv_pages`` / ``prefetch`` / ``snapshots``).
  A tree claim holds WEAK references to its ``jax.Array`` leaves, so a
  donated/freed tree's claim evaporates with it (no owner ever pins memory
  just by being observed); an integer claim is static until re-tagged.
  :func:`attribute` turns the claims plus the live-bytes gauge into
  ``mem.owned.*`` values, with ``other`` = live minus claimed, clamped at
  zero — the leak-hunting residual.
- **Budget** — :func:`device_budget` resolves the per-device usable budget
  from the first source that answers: the measured allocator limit
  (``bytes_limit`` x 0.8), the ``AUTODIST_MEM_BUDGET`` override, else the
  8 GiB default (with a one-time warning — a silently defaulted budget is
  how the async-PS memory rule ran blind on CPU). The winning source is
  booked as ``mem.budget_source`` (0 default / 1 env / 2 measured).
- **Pressure** — :func:`current_pressure` is the worst device's
  ``bytes_in_use / bytes_limit`` (the ratio the shipped ``mem_pressure``
  alert rule thresholds); on backends with no allocator stats it degrades
  to ``live_bytes / budget`` so an injected squeeze (a tiny
  ``AUTODIST_MEM_BUDGET``) still drives the same plane. Serving admission
  reads it through :func:`kv_admission_holdback`: past the threshold the
  paged-KV allocator holds back a fraction of its reservable pages, so the
  fleet sheds load before the allocator dies.
- **OOM forensics** — :func:`is_oom_error` recognizes RESOURCE_EXHAUSTED
  at the runner's dispatch sites; :func:`record_oom` books the ``mem.oom``
  counter + event and triggers the flight recorder (debounced), whose
  manifest carries :func:`memory_section`: the census, the per-program
  memory ledger, the last-K ``device.mem`` history samples, and the
  predicted-vs-live peak delta.

Everything degrades to a no-op shell: :func:`memory_snapshot` returns the
same keys armed or not (the ``status`` wire contract), and every sampling
failure is swallowed at debug — diagnostics must never break the run.
"""

import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from autodist_tpu import const
from autodist_tpu.telemetry import metrics as _metrics
from autodist_tpu.telemetry import spans as _spans
from autodist_tpu.utils import logging

__all__ = ["OWNERS", "tag", "untag", "census", "attribute", "device_budget",
           "pressure_threshold", "current_pressure", "kv_admission_holdback",
           "is_oom_error", "record_oom", "memory_snapshot", "memory_section",
           "reset"]

# The attribution vocabulary: every claim lands in one of these buckets, and
# the census books exactly these plus the ``other`` residual (a stable gauge
# family — scrapers see the same series whether an owner is present or not).
OWNERS = ("params", "opt_state", "kv_pages", "prefetch", "snapshots")

DEFAULT_BUDGET_BYTES = 8 << 30     # the historical auto-strategy fallback
BUDGET_FRACTION = 0.8              # usable share of the measured limit
KV_HOLDBACK_FRACTION = 0.25        # reservable pages withheld under pressure
_PRESSURE_CACHE_S = 1.0            # admission-path refresh throttle
_SOURCE_CODE = {"default": 0.0, "env": 1.0, "measured": 2.0}

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "Out of memory",
                "out of memory", "Allocation failure", "allocating")


class _Claim:
    """One owner's claim: either a static byte count or weakrefs to the
    ``jax.Array`` leaves of a tagged tree (dead/donated leaves drop out)."""

    __slots__ = ("nbytes", "refs")

    def __init__(self, nbytes: Optional[int] = None,
                 refs: Optional[List[Tuple[Any, int]]] = None):
        self.nbytes = nbytes
        self.refs = refs

    def live(self) -> Tuple[int, bool]:
        """(live bytes, any leaf still alive). Static claims are always
        alive; a tree claim whose every leaf died reports dead so the
        registry can prune it."""
        if self.refs is None:
            return int(self.nbytes or 0), True
        total, alive = 0, False
        for ref, nb in self.refs:
            leaf = ref()
            if leaf is None:
                continue
            try:
                if leaf.is_deleted():     # donated buffers keep the pyobject
                    continue
            except (AttributeError, RuntimeError, TypeError):
                pass
            alive = True
            total += nb
        return total, alive


_LOCK = threading.Lock()
_CLAIMS: Dict[str, Dict[str, _Claim]] = {}
_WARNED_DEFAULT = [False]
_PRESSURE = {"value": 0.0, "t": 0.0, "set": False}


def tag(owner: str, tree_or_nbytes: Any, key: str = "default") -> None:
    """Claim ``owner``'s device bytes for the census. An int/float claims a
    static byte count; anything else is treated as a pytree whose
    ``jax.Array`` leaves are weakly referenced (the claim follows the
    arrays' lifetime — re-tagging at each boundary replaces the claim, a
    freed tree's claim evaporates on its own). ``key`` scopes concurrent
    claimants of one owner (two paged engines in one process)."""
    if isinstance(tree_or_nbytes, (int, float)) \
            and not isinstance(tree_or_nbytes, bool):
        claim = _Claim(nbytes=int(tree_or_nbytes))
    else:
        try:
            import jax
            refs: List[Tuple[Any, int]] = []
            for leaf in jax.tree_util.tree_leaves(tree_or_nbytes):
                if not isinstance(leaf, jax.Array):
                    continue           # census vs device live_bytes: same unit
                nb = int(getattr(leaf, "nbytes", 0) or 0)
                if nb <= 0:
                    continue
                try:
                    refs.append((weakref.ref(leaf), nb))
                except TypeError:      # exotic leaf: skip, never pin
                    continue
            claim = _Claim(refs=refs)
        except Exception as e:  # noqa: BLE001 — a census tag must never fail
            logging.debug("memplane.tag(%s) skipped: %s", owner, e)
            return
    with _LOCK:
        entries = _CLAIMS.setdefault(str(owner), {})
        entries[str(key)] = claim
        # Opportunistic prune so churny taggers (prefetch) stay bounded.
        for k in [k for k, c in entries.items() if not c.live()[1]]:
            del entries[k]


def untag(owner: str, key: str = "default") -> None:
    """Drop one claim (idempotent)."""
    with _LOCK:
        entries = _CLAIMS.get(str(owner))
        if entries:
            entries.pop(str(key), None)


def reset() -> None:
    """Drop every claim and the pressure cache (tests)."""
    with _LOCK:
        _CLAIMS.clear()
    _PRESSURE.update(value=0.0, t=0.0, set=False)
    _WARNED_DEFAULT[0] = False


def census() -> Dict[str, int]:
    """Live claimed bytes per owner (dead tree claims pruned as a side
    effect). Owners with no claim are absent — :func:`attribute` restores
    the full stable vocabulary."""
    out: Dict[str, int] = {}
    with _LOCK:
        for owner, entries in list(_CLAIMS.items()):
            total = 0
            for key in list(entries):
                nbytes, alive = entries[key].live()
                if not alive:
                    del entries[key]
                    continue
                total += nbytes
            if entries:
                out[owner] = total
            else:
                del _CLAIMS[owner]
    return out


def attribute(live_bytes: int) -> Dict[str, int]:
    """The owner-attributed view of ``live_bytes``: every :data:`OWNERS`
    bucket (0 when unclaimed) plus ``other`` — live minus claimed, CLAMPED
    at zero (claims can overshoot the live gauge when an owner tags bytes
    the live census does not see; the residual is a leak detector, and a
    negative leak is a lie)."""
    counts = census()
    out = {owner: int(counts.get(owner, 0)) for owner in OWNERS}
    claimed = sum(out.values())
    out["other"] = max(0, int(live_bytes) - claimed)
    return out


# ------------------------------------------------------------------ budget

def device_budget() -> Tuple[int, str]:
    """Per-device usable memory budget and its source: ``measured``
    (``bytes_limit`` x 0.8 from the allocator), ``env``
    (``AUTODIST_MEM_BUDGET`` bytes), or ``default`` (8 GiB, warned once —
    a budget nobody chose should not be a budget nobody sees). Books
    ``mem.budget_bytes`` / ``mem.budget_source``."""
    budget, source = 0, ""
    try:
        import jax
        limit = min((int((d.memory_stats() or {}).get("bytes_limit", 0))
                     for d in jax.local_devices()), default=0)
        if limit > 0:
            budget, source = int(limit * BUDGET_FRACTION), "measured"
    except Exception as e:  # noqa: BLE001 — CPU/sim backends report nothing
        logging.debug("memory budget probe unavailable: %s", e)
    if not budget:
        try:
            env = int(const.ENV.AUTODIST_MEM_BUDGET.val)
        except (TypeError, ValueError):
            env = 0
        if env > 0:
            budget, source = env, "env"
    if not budget:
        budget, source = DEFAULT_BUDGET_BYTES, "default"
        if not _WARNED_DEFAULT[0]:
            _WARNED_DEFAULT[0] = True
            logging.warning(
                "memory plane: no allocator limit and no AUTODIST_MEM_BUDGET "
                "— memory rules (async-PS optimizer choice, autotune "
                "pre-flight) run on the %d GiB default",
                DEFAULT_BUDGET_BYTES >> 30)
    try:
        _metrics.gauge("mem.budget_bytes").set(budget)
        _metrics.gauge("mem.budget_source").set(_SOURCE_CODE[source])
    except Exception:  # noqa: BLE001 — booking is best-effort
        pass
    return budget, source


def pressure_threshold() -> float:
    """The ``AUTODIST_MEM_PRESSURE`` ratio past which the plane reacts
    (the shipped alert rule's value and the KV holdback trigger)."""
    try:
        value = float(const.ENV.AUTODIST_MEM_PRESSURE.val)
    except (TypeError, ValueError):
        return 0.92
    return value if value > 0 else 0.92


def _measure_pressure() -> float:
    """Worst device ``bytes_in_use / bytes_limit``; live-bytes over budget
    when no device reports allocator stats."""
    import jax
    worst = None
    try:
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except (RuntimeError, ValueError, TypeError, AttributeError):
                stats = None
            if not stats:
                continue
            limit = int(stats.get("bytes_limit", 0) or 0)
            if limit <= 0:
                continue
            ratio = int(stats.get("bytes_in_use", 0) or 0) / limit
            worst = ratio if worst is None else max(worst, ratio)
    except RuntimeError:
        pass
    if worst is None:
        live = sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.live_arrays())
        budget, _ = device_budget()
        worst = live / budget if budget > 0 else 0.0
    return float(worst)


def current_pressure(max_age_s: float = _PRESSURE_CACHE_S) -> float:
    """The pressure ratio, cached for ``max_age_s`` (the serving admission
    path reads it per request — one allocator probe per second, not per
    admission). Books ``mem.pressure`` on refresh; failures return the
    last value (diagnostics never gate admission on a backend hiccup)."""
    now = time.monotonic()
    if _PRESSURE["set"] and now - _PRESSURE["t"] < max_age_s:
        return _PRESSURE["value"]
    try:
        value = _measure_pressure()
        _metrics.gauge("mem.pressure").set(round(value, 6))
    except Exception as e:  # noqa: BLE001
        logging.debug("memory pressure sampling unavailable: %s", e)
        return _PRESSURE["value"]
    _PRESSURE.update(value=value, t=now, set=True)
    return value


def kv_admission_holdback(usable_pages: int) -> int:
    """Pages the paged-KV allocator should withhold from NEW reservations:
    0 below the pressure threshold, ``KV_HOLDBACK_FRACTION`` of the usable
    pool at/above it (in-flight requests keep their reservations — the
    engine sheds admissions, the allocator never dies mid-decode)."""
    if usable_pages <= 0:
        return 0
    if current_pressure() < pressure_threshold():
        return 0
    return max(1, int(usable_pages * KV_HOLDBACK_FRACTION))


# ------------------------------------------------------------------ OOM

def is_oom_error(exc: BaseException) -> bool:
    """Does this look like a device allocator exhaustion? XLA surfaces OOM
    as ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...`` (type match is on the
    NAME — the class moved across jaxlib versions)."""
    msg = str(exc)
    if any(marker in msg for marker in _OOM_MARKERS):
        return type(exc).__name__ == "XlaRuntimeError" \
            or "RESOURCE" in msg.upper() or "memory" in msg.lower()
    return False


def record_oom(where: str, exc: BaseException) -> None:
    """Book the OOM (``mem.oom`` counter + structured event), refresh the
    pressure gauge, and trigger the flight recorder THROUGH its debounce —
    the manifest's ``memory`` section is the autopsy. Never raises: the
    caller re-raises the real error and forensics must not mask it."""
    try:
        _metrics.counter("mem.oom").inc()
        _metrics.event("mem.oom", where=str(where), error=str(exc)[:300])
        current_pressure(max_age_s=0.0)
        from autodist_tpu.telemetry import recorder as _recorder
        _recorder.maybe_record(f"oom.{where}")
    except Exception as e:  # noqa: BLE001 — forensics never mask the OOM
        logging.debug("OOM forensics capture failed: %s", e)


# ------------------------------------------------------------- snapshots

def _armed() -> bool:
    """The plane is armed when telemetry records or anyone tagged bytes."""
    with _LOCK:
        has_claims = bool(_CLAIMS)
    return has_claims or _spans.enabled()


def memory_snapshot() -> Dict[str, Any]:
    """The ``status`` wire section: a STABLE shell (same keys armed or
    not), filled with the census / pressure / budget / per-device stats
    when the plane is armed. Cheap enough for a 2 s console poll."""
    shell: Dict[str, Any] = {"owned": {}, "live_bytes": 0, "pressure": 0.0,
                             "budget_bytes": 0, "budget_source": "",
                             "devices": {}}
    if not _armed():
        return shell
    try:
        import jax
        live = sum(int(getattr(a, "nbytes", 0) or 0)
                   for a in jax.live_arrays())
        shell["live_bytes"] = live
        shell["owned"] = attribute(live)
        budget, source = device_budget()
        shell["budget_bytes"], shell["budget_source"] = budget, source
        shell["pressure"] = round(current_pressure(), 6)
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except (RuntimeError, ValueError, TypeError, AttributeError):
                stats = None
            if not stats:
                continue
            shell["devices"][f"d{d.id}"] = {
                "bytes_in_use": int(stats.get("bytes_in_use", 0) or 0),
                "bytes_limit": int(stats.get("bytes_limit", 0) or 0)}
    except Exception as e:  # noqa: BLE001 — a status poll must not 500
        logging.debug("memory snapshot unavailable: %s", e)
    return shell


def memory_section(history_k: int = 8) -> Dict[str, Any]:
    """The flight-recorder manifest section: :func:`memory_snapshot` plus
    the per-program memory ledger, the last-``history_k`` ``device.mem`` /
    ``mem.*`` history samples, and the predicted-vs-live peak delta
    (resident claimed bytes + the ledger's worst program temp, against the
    worst live ``bytes_in_use`` — the number an OOM autopsy opens with)."""
    section = memory_snapshot()
    try:
        from autodist_tpu.telemetry import profiling as _profiling
        programs: Dict[str, Dict[str, Any]] = {}
        for sig, rec in _profiling.program_costs().items():
            programs[sig] = {
                "kind": rec.kind,
                "argument_bytes": rec.argument_bytes,
                "output_bytes": rec.output_bytes,
                "temp_bytes": rec.temp_bytes,
                "generated_code_bytes": rec.generated_code_bytes,
            }
        section["programs"] = programs
    except Exception:  # noqa: BLE001 — ledger is optional in the autopsy
        section["programs"] = {}
    try:
        from autodist_tpu.telemetry import history as _history
        hist = _history.get_history()
        tail: List[Dict[str, Any]] = []
        if hist is not None:
            for sample in hist.samples()[-max(1, history_k):]:
                row = {k: v for k, v in sample.items()
                       if k == "t_wall_s" or k == "step"
                       or k.startswith("device.mem.")
                       or k.startswith("device.live_")
                       or k.startswith("mem.")}
                tail.append(row)
        section["history"] = tail
    except Exception:  # noqa: BLE001
        section["history"] = []
    try:
        temps = [p.get("temp_bytes") or 0
                 for p in section.get("programs", {}).values()]
        resident = sum(section["owned"].get(o, 0) for o in OWNERS)
        predicted = resident + (max(temps) if temps else 0)
        live_peak = max(
            [d["bytes_in_use"] for d in section["devices"].values()]
            or [section["live_bytes"]])
        section["predicted_peak_bytes"] = int(predicted)
        section["live_peak_bytes"] = int(live_peak)
        section["peak_delta_bytes"] = int(live_peak - predicted)
    except Exception:  # noqa: BLE001
        pass
    return section
