"""Pipeline-parallel strategy: stacked layer weights sharded over the ``pipe`` axis.

Beyond reference parity (SURVEY.md §2.2: the reference scoped pipeline
parallelism out). Targets models whose block weights are stacked on a leading
layer dimension (``models/pipeline_lm.py``): those parameters get a partitioner
on tensor axis 0 mapped onto the ``pipe`` mesh axis — each device stores the
contiguous group of layers its pipeline stage runs — and everything else
(embedding, head, norms) falls back to AllReduce data parallelism. The compute
schedule itself lives in the model via ``parallel/pipeline.pipelined``; this
builder supplies the matching storage sharding and mesh.
"""

from typing import Callable, Optional

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.all_reduce_strategy import parse_ar_options
from autodist_tpu.strategy.base import Strategy, StrategyBuilder, num_devices


def _default_stage_filter(name: str) -> bool:
    return "blocks" in name.lower()


class Pipeline(StrategyBuilder):
    """AllReduce everywhere + pipe-axis sharding for layer-stacked parameters.

    ``n_stages`` sizes the mesh ``pipe`` axis (must divide the device count);
    layer-stacked parameters must have leading dim divisible by ``n_stages``.
    """

    def __init__(self, n_stages: int,
                 stage_filter: Optional[Callable[[str], bool]] = None,
                 chunk_size: int = 128, all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor"):
        if n_stages < 2:
            raise ValueError("n_stages must be >= 2")
        self._n_stages = n_stages
        self._stage_filter = stage_filter or _default_stage_filter
        self._chunk_size, self._spec, self._compressor = parse_ar_options(
            chunk_size, all_reduce_spec, compressor)

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        n = num_devices(resource_spec)
        if n % self._n_stages != 0:
            raise ValueError(
                f"n_stages={self._n_stages} does not divide {n} devices")

        def is_stage(spec):
            return (self._stage_filter(spec.name) and len(spec.shape) >= 1
                    and spec.shape[0] % self._n_stages == 0)

        return self._build_axis0_sharded(
            model_spec, resource_spec, const.MESH_AXIS_PIPE, self._n_stages,
            is_stage, self._spec, self._compressor, self._chunk_size)
