"""Package version."""

__version__ = "0.1.0"
