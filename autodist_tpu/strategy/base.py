"""Strategy wrapper, builder ABC, and compiler.

Parity with reference ``autodist/strategy/base.py``:

- :class:`Strategy` wraps the proto with a timestamped id and (de)serializes under the
  working dir's ``strategies/`` (reference ``:31-38, 78-99``) — this is what the chief
  ships to workers by id (``AUTODIST_STRATEGY_ID`` handshake, ``coordinator.py:66-90``).
- :class:`StrategyBuilder` is the policy ABC (reference ``:102-117``).
- :class:`StrategyCompiler` prunes configs for parameters without gradients and
  resolves device strings / fills mesh axis sizes against the actual device count
  (reference ``:137-168`` resolved ``ip:GPU:k`` to TF device names; here resolution
  targets mesh coordinates).
"""

import abc
import datetime
import os
from typing import Optional

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.parallel.mesh import standard_mesh_shape
from autodist_tpu.proto import strategy_pb2
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.utils import logging


class Strategy:
    """A built distribution strategy: proto + id + (de)serialization."""

    def __init__(self, proto: Optional[strategy_pb2.Strategy] = None):
        self._proto = proto or strategy_pb2.Strategy()
        if not self._proto.id:
            self._proto.id = datetime.datetime.now().strftime("%Y%m%dT%H%M%SM%f")

    @property
    def proto(self) -> strategy_pb2.Strategy:
        return self._proto

    @property
    def id(self) -> str:
        return self._proto.id

    @property
    def node_config(self):
        return self._proto.node_config

    @property
    def mesh_config(self):
        return self._proto.mesh_config

    def mesh_axes(self) -> dict:
        return {a.name: a.size for a in self._proto.mesh_config.axes}

    # --- serialization (reference strategy/base.py:78-99) ---

    @staticmethod
    def _path_for(strategy_id: str) -> str:
        return os.path.join(const.DEFAULT_SERIALIZATION_DIR, strategy_id)

    def serialize(self, path: Optional[str] = None) -> str:
        path = path or self._path_for(self.id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._proto.path = path
        with open(path, "wb") as f:
            f.write(self._proto.SerializeToString())
        return path

    @classmethod
    def deserialize(cls, strategy_id: Optional[str] = None, path: Optional[str] = None) -> "Strategy":
        if path is None:
            if not strategy_id:
                raise ValueError("Need a strategy id or path")
            path = cls._path_for(strategy_id)
        proto = strategy_pb2.Strategy()
        with open(path, "rb") as f:
            proto.ParseFromString(f.read())
        return cls(proto)

    def copy(self) -> "Strategy":
        dup = strategy_pb2.Strategy()
        dup.CopyFrom(self._proto)
        return Strategy(dup)

    def __str__(self):
        return f"Strategy(id={self.id}, nodes={len(self._proto.node_config)}, mesh={self.mesh_axes()})"


# Default mesh for the PS family: every device is both a data replica and a parameter
# shard (full weight-update sharding — batch shards over data*reduce jointly).
PS_DEFAULT_AXES = {const.MESH_AXIS_REDUCE: -1, const.MESH_AXIS_DATA: 1}
# Default mesh for the AllReduce family: pure data parallelism.
AR_DEFAULT_AXES = {const.MESH_AXIS_DATA: -1}


def num_devices(resource_spec: ResourceSpec) -> int:
    """Device count a strategy targets: accelerators if the spec lists any, else
    one slot per replica device, floor 1. Single source of truth for every
    builder's divisibility checks and the recorded mesh."""
    return max(1, resource_spec.num_accelerators
               or len(resource_spec.replica_devices))


class StrategyBuilder(abc.ABC):
    """Policy ABC: (ModelSpec, ResourceSpec) -> Strategy (reference base.py:102-117)."""

    @abc.abstractmethod
    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        ...

    @staticmethod
    def _resolved_axes(resource_spec: ResourceSpec, default_axes: dict) -> dict:
        """The full axis->size map this strategy will record — computed once per build
        so destination counts and the recorded mesh cannot drift apart."""
        n = num_devices(resource_spec)
        return dict(standard_mesh_shape(n, resource_spec.mesh_config or default_axes))

    @staticmethod
    def _build_axis0_sharded(model_spec: ModelSpec, resource_spec: ResourceSpec,
                             mesh_axis: str, axis_size: int, param_filter,
                             ar_spec, ar_compressor, chunk_size: int) -> Strategy:
        """Shared skeleton for single-purpose axis builders (ExpertParallel,
        Pipeline): parameters passing ``param_filter`` get a dim-0 partitioner of
        ``axis_size`` shards mapped onto ``mesh_axis``; everything else gets an
        AllReduce synchronizer. The mesh is {mesh_axis: axis_size, data: -1}
        unless the resource spec overrides it."""
        strategy = Strategy()
        for i, spec in enumerate(model_spec.trainable.values()):
            node = strategy.proto.node_config.add(var_name=spec.name)
            node.sparse = spec.sparse

            def fill_ar(cfg):
                ar = cfg.all_reduce_synchronizer
                ar.spec = ar_spec
                ar.compressor = ar_compressor
                ar.group = i // chunk_size

            if param_filter(spec):
                node.partitioner.num_shards.extend(
                    [axis_size] + [1] * (len(spec.shape) - 1))
                node.partitioner.mesh_axis = mesh_axis
                for k in range(axis_size):
                    fill_ar(node.part_config.add(var_name=f"{spec.name}/part_{k}"))
            else:
                fill_ar(node)
        axes = {mesh_axis: axis_size, const.MESH_AXIS_DATA: -1}
        StrategyBuilder._fill_mesh_config(
            strategy, resource_spec,
            StrategyBuilder._resolved_axes(resource_spec, axes))
        return strategy

    # Shared helper: record the mesh shape + replica devices in the graph-level config.
    @staticmethod
    def _fill_mesh_config(strategy: Strategy, resource_spec: ResourceSpec,
                          axes: Optional[dict] = None):
        n = num_devices(resource_spec)
        shape = standard_mesh_shape(n, axes if axes is not None else resource_spec.mesh_config)
        mc = strategy.proto.mesh_config
        del mc.axes[:]
        for name, size in shape.items():
            mc.axes.add(name=name, size=size)
        del mc.replica_devices[:]
        mc.replica_devices.extend(d.name_string for d in resource_spec.replica_devices)


class StrategyCompiler:
    """Prune + resolve pass over a built strategy (reference base.py:120-168)."""

    def __init__(self, model_spec: ModelSpec, resource_spec: ResourceSpec):
        self._model_spec = model_spec
        self._resource_spec = resource_spec

    def compile(self, strategy: Strategy) -> Strategy:
        out = strategy.copy()
        self._prune_nodes(out)
        self._resolve_mesh(out)
        self._resolve_destinations(out)
        return out

    def _prune_nodes(self, strategy: Strategy):
        """Drop configs for unknown or non-trainable parameters.

        Reference pruned node_configs whose variable had no update op
        (base.py:137-150); the functional analogue is a parameter that is not
        trainable (no gradient flows to it).
        """
        trainable = self._model_spec.trainable
        keep = [n for n in strategy.node_config if n.var_name in trainable]
        dropped = len(strategy.node_config) - len(keep)
        if dropped:
            logging.debug("StrategyCompiler pruned %d node config(s)", dropped)
        del strategy.proto.node_config[:]
        for n in keep:
            strategy.proto.node_config.add().CopyFrom(n)

    def _resolve_mesh(self, strategy: Strategy):
        """Fill/validate mesh axis sizes against the actual device count."""
        n = num_devices(self._resource_spec)
        axes = {a.name: a.size for a in strategy.mesh_config.axes}
        shape = standard_mesh_shape(n, axes or None)
        mc = strategy.proto.mesh_config
        del mc.axes[:]
        for name, size in shape.items():
            mc.axes.add(name=name, size=size)
        if not mc.replica_devices:
            mc.replica_devices.extend(
                d.name_string for d in self._resource_spec.replica_devices)

    def _resolve_destinations(self, strategy: Strategy):
        """Resolve PS reduction destinations to mesh coordinates.

        Reference resolved ``ip:CPU:0`` strings to ``/job:worker/task:n`` device names
        (resolver.py:38-67). Here a destination names a shard index along the
        ``reduce`` axis: device strings become ``reduce:<k>`` coordinates; already-
        resolved or empty (auto-balance) destinations pass through.
        """
        hosts = [n.address for n in self._resource_spec.sorted_nodes]
        reduce_size = dict((a.name, a.size) for a in strategy.mesh_config.axes).get(
            const.MESH_AXIS_REDUCE, 1)

        def resolve(node):
            ps = node.ps_synchronizer
            dest = ps.reduction_destination
            if not dest or dest.startswith("reduce:"):
                return
            host = dest.split(":")[0]
            idx = hosts.index(host) % reduce_size if host in hosts else 0
            ps.reduction_destination = f"reduce:{idx}"

        for node in strategy.node_config:
            if node.WhichOneof("synchronizer") == "ps_synchronizer":
                resolve(node)
            for part in node.part_config:
                if part.WhichOneof("synchronizer") == "ps_synchronizer":
                    resolve(part)
