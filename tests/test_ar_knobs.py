"""The AllReduce tuning knobs actually tune: group -> gradient bucketing,
spec -> hierarchical ICI/DCN reduce.

The reference wired ``group`` into ScopedAllocator fusion of CollectiveReduce
(``all_reduce_strategy.py:61-67``, ``runner.py:41-46``) and ``spec`` into the
collective implementation choice. TPU-native: in the explicit shard_map path,
params sharing a group id reduce as one concatenated buffer (fewer, larger
collectives — what ScopedAllocator bought), and spec=DCN lowers to a two-phase
reduce (intra-slice axis first, then cross-slice). Both are proven by HLO
inspection plus value-exactness against the unfused/flat lowering.
"""

import jax
import jax.numpy as jnp
import numpy as np

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.parallel import synchronization
from autodist_tpu.parallel.mesh import build_mesh
from autodist_tpu.parallel.plan import ShardingPlan
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import AllReduce
from shardmap_compat import requires_shard_map

BATCH = 16
SPEC_8 = ResourceSpec("nodes: [{address: localhost, tpus: 8, chief: true}]")
SPEC_HIER = ResourceSpec(resource_info={
    "nodes": [{"address": "localhost", "tpus": 8, "chief": True}],
    "mesh": {"data": 2, "reduce": 4}})


def _params():
    rng = np.random.RandomState(0)
    return {f"w{i}": jnp.asarray(rng.randn(8, 4), jnp.float32) for i in range(4)}


def _batch(seed=1):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(BATCH, 8).astype(np.float32),
            "y": rng.randn(BATCH, 4).astype(np.float32)}


def _loss(p, b):
    # Per-param scale keeps the four gradients distinct (identical grads would be
    # CSE'd into one collective, confounding the counts).
    out = sum((i + 1.0) * (b["x"] @ p[k]) for i, k in enumerate(sorted(p)))
    return jnp.mean((b["y"] - out) ** 2)


def _grads_and_lowered(builder, resource_spec=SPEC_8):
    params, batch = _params(), _batch()
    model = ModelSpec.from_loss_fn(_loss, params, batch)
    strategy = builder.build(model, resource_spec)
    plan = ShardingPlan.from_strategy(strategy, model)
    mesh = build_mesh(axes=dict(plan.mesh_axes))
    grad_fn = synchronization.make_grad_fn(plan, model, mesh, _loss)
    ef = synchronization.init_ef_state(plan, params, mesh=mesh)
    # Pre-optimization lowering: what OUR sync emits (the compiled module also
    # reflects XLA's own combiner, which would mask the knob under test).
    text = jax.jit(grad_fn).lower(params, batch, ef).as_text()
    with mesh:
        grads, *_ = jax.jit(grad_fn)(params, batch, ef)
    return grads, text


def _count_all_reduce(text):
    return sum("stablehlo.all_reduce" in l for l in text.splitlines())


@requires_shard_map
def test_group_bucketing_fuses_collectives():
    """chunk_size=4 puts all four 8x4 grads in one group: ONE concatenated
    collective (+1 for the loss) instead of four per-leaf ones."""
    _, flat = _grads_and_lowered(AllReduce(chunk_size=1, compressor="HorovodCompressor"))
    _, fused = _grads_and_lowered(AllReduce(chunk_size=4, compressor="HorovodCompressor"))
    assert _count_all_reduce(flat) == 5    # 4 grads + loss
    assert _count_all_reduce(fused) == 2   # 1 bucket + loss
    assert "tensor<128xbf16>" in fused     # 4 * (8*4) elements, bf16 on the wire


@requires_shard_map
def test_bucketing_is_value_exact():
    """The bf16 cast is elementwise, so bucketed and per-leaf lowerings produce
    identical gradients."""
    g_flat, _ = _grads_and_lowered(AllReduce(chunk_size=1, compressor="HorovodCompressor"))
    g_fused, _ = _grads_and_lowered(AllReduce(chunk_size=4, compressor="HorovodCompressor"))
    for k in g_flat:
        np.testing.assert_array_equal(np.asarray(g_flat[k]), np.asarray(g_fused[k]))


@requires_shard_map
def test_bucketing_with_error_feedback_value_exact():
    g_flat, _ = _grads_and_lowered(AllReduce(chunk_size=1, compressor="HorovodCompressorEF"))
    g_fused, text = _grads_and_lowered(AllReduce(chunk_size=4, compressor="HorovodCompressorEF"))
    assert _count_all_reduce(text) == 2
    for k in g_flat:
        np.testing.assert_array_equal(np.asarray(g_flat[k]), np.asarray(g_fused[k]))


@requires_shard_map
def test_dcn_spec_lowers_to_two_phase_reduce():
    """spec=DCN on a {data:2, reduce:4} mesh: the bucketed gradient reduce becomes
    two all-reduce phases (intra-slice then cross-slice); AUTO stays single-phase.
    Results identical."""
    g_auto, auto = _grads_and_lowered(
        AllReduce(chunk_size=4, compressor="HorovodCompressor"), SPEC_HIER)
    g_dcn, dcn = _grads_and_lowered(
        AllReduce(chunk_size=4, compressor="HorovodCompressor",
                  all_reduce_spec="DCN"), SPEC_HIER)

    assert _count_all_reduce(auto) == 2   # 1 joint bucket reduce + loss
    assert _count_all_reduce(dcn) == 3    # 2 hierarchical phases + loss
    for k in g_auto:
        # Each hierarchical phase rounds to bf16 on the wire, so the two
        # schedules agree only to bf16 precision (~3 decimal digits).
        np.testing.assert_allclose(np.asarray(g_auto[k]), np.asarray(g_dcn[k]),
                                   rtol=2e-2, atol=2e-2)


def test_no_compression_keeps_implicit_path():
    """NONE-only strategies stay on the implicit SPMD lowering (no shard_map):
    XLA's all-reduce combiner performs the fusion the group ids request, so the
    knob is honored without forcing a manual data path."""
    params, batch = _params(), _batch()
    model = ModelSpec.from_loss_fn(_loss, params, batch)
    strategy = AllReduce(chunk_size=4).build(model, SPEC_8)
    plan = ShardingPlan.from_strategy(strategy, model)
    mesh = build_mesh(axes=dict(plan.mesh_axes))
    grad_fn = synchronization.make_grad_fn(plan, model, mesh, _loss)
    hlo = jax.jit(grad_fn).lower(
        params, batch, synchronization.init_ef_state(plan, params, mesh=mesh)
    ).as_text()
    assert "shard_map" not in hlo
