"""End-to-end minimum slice — value-exact parity with reference c0.

The reference proved correctness by asserting the post-step variable equals the
hand-computed averaged-gradient update (``tests/integration/cases/c0.py:88-121``).
Same here: one SGD step over an 8-way sharded batch must produce exactly the update
computed from the full-batch gradient with numpy, for every strategy family.
"""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from shardmap_compat import requires_shard_map
from autodist_tpu.strategy import (AllReduce, Parallax, PartitionedAR, PartitionedPS,
                                   PS, PSLoadBalancing, RandomAxisPartitionAR,
                                   UnevenPartitionedPS)

LR = 0.1
BATCH = 16


def _data(seed=123):
    rng = np.random.RandomState(seed)
    x = rng.randn(BATCH).astype(np.float32)
    y = (3.0 * x + 2.0 + 0.1 * rng.randn(BATCH)).astype(np.float32)
    return {"x": x, "y": y}


def _loss(p, batch):
    pred = batch["x"] * p["w"] + p["b"]
    return jnp.mean((batch["y"] - pred) ** 2)


def _expected_after_one_step(batch, w0=0.0, b0=0.0):
    # d/dw mean((y - (wx+b))^2) = mean(-2x(y - wx - b)); at w0=b0=0: -2 mean(x*y)
    x, y = batch["x"], batch["y"]
    resid = y - (w0 * x + b0)
    gw = np.mean(-2.0 * x * resid)
    gb = np.mean(-2.0 * resid)
    return w0 - LR * gw, b0 - LR * gb


STRATEGIES = [
    PS, PSLoadBalancing, PartitionedPS, UnevenPartitionedPS,
    AllReduce, PartitionedAR, RandomAxisPartitionAR, Parallax,
]


@pytest.mark.parametrize("builder_cls", STRATEGIES, ids=lambda c: c.__name__)
def test_one_step_matches_hand_computed_update(builder_cls):
    batch = _data()
    ad = AutoDist(strategy_builder=builder_cls())
    params = {"w": jnp.zeros(()), "b": jnp.zeros(())}
    step = ad.function(_loss, params, optax.sgd(LR), example_batch=batch)
    step(batch)
    got = step.get_state().params
    want_w, want_b = _expected_after_one_step(batch)
    np.testing.assert_allclose(float(got["w"]), want_w, rtol=1e-5)
    np.testing.assert_allclose(float(got["b"]), want_b, rtol=1e-5)


def test_loss_decreases_over_ten_steps():
    batch = _data()
    ad = AutoDist(strategy_builder=AllReduce())
    params = {"w": jnp.zeros(()), "b": jnp.zeros(())}
    step = ad.function(_loss, params, optax.sgd(0.05), example_batch=batch)
    losses = [float(step(batch)) for _ in range(10)]
    assert losses[-1] < losses[0]
    assert losses == sorted(losses, reverse=True)  # monotone for this convex problem


@requires_shard_map
def test_bf16_compressor_approximates_dense_update():
    batch = _data()
    ad = AutoDist(strategy_builder=AllReduce(compressor="HorovodCompressor"))
    params = {"w": jnp.zeros(()), "b": jnp.zeros(())}
    step = ad.function(_loss, params, optax.sgd(LR), example_batch=batch)
    step(batch)
    got = step.get_state().params
    want_w, want_b = _expected_after_one_step(batch)
    # bf16 wire format: ~3 decimal digits
    np.testing.assert_allclose(float(got["w"]), want_w, rtol=2e-2)
    np.testing.assert_allclose(float(got["b"]), want_b, rtol=2e-2)


@requires_shard_map
def test_error_feedback_caught_up_after_many_steps():
    """EF compensates the bf16 rounding over time: parameters track the uncompressed
    run closely (reference compressor.py:120-143 semantics)."""
    batch = _data()
    params = {"w": jnp.zeros(()), "b": jnp.zeros(())}

    ad_ref = AutoDist(strategy_builder=AllReduce())
    step_ref = ad_ref.function(_loss, params, optax.sgd(0.05), example_batch=batch)
    ad_ef = AutoDist(strategy_builder=AllReduce(compressor="HorovodCompressorEF"))
    step_ef = ad_ef.function(_loss, params, optax.sgd(0.05), example_batch=batch)

    for _ in range(20):
        step_ref(batch)
        step_ef(batch)
    w_ref = float(step_ref.get_state().params["w"])
    w_ef = float(step_ef.get_state().params["w"])
    assert abs(w_ref - w_ef) < 5e-3


def test_linear_regression_example_runs():
    import examples.linear_regression as lr
    losses = lr.main()
    assert losses[-1] < losses[0]


@requires_shard_map
def test_multi_param_model_with_embedding_parallax():
    """Sparse embedding + dense layers under the Parallax hybrid, 2 steps."""
    rng = np.random.RandomState(0)
    vocab, dim = 50, 8
    params = {
        "emb": jnp.asarray(rng.randn(vocab, dim), jnp.float32),
        "w": jnp.asarray(rng.randn(dim, 1), jnp.float32),
        "b": jnp.zeros((1,)),
    }
    idx = rng.randint(0, vocab, size=(BATCH,))
    y = rng.randn(BATCH, 1).astype(np.float32)
    batch = {"idx": idx, "y": y}

    def loss(p, b):
        e = jnp.take(p["emb"], b["idx"], axis=0)
        pred = e @ p["w"] + p["b"]
        return jnp.mean((b["y"] - pred) ** 2)

    ad = AutoDist(strategy_builder=Parallax())
    step = ad.function(loss, params, optax.sgd(0.1), example_batch=batch)
    l0 = float(step(batch))
    l1 = float(step(batch))
    assert l1 < l0
    # the strategy actually routed the embedding to PS
    strat = ad._strategy
    kinds = {n.var_name: n.WhichOneof("synchronizer") for n in strat.node_config}
    assert kinds["emb"] == "ps_synchronizer"
    assert kinds["w"] == "all_reduce_synchronizer"
