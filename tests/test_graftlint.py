"""graftlint (autodist_tpu.analysis) — fixture tests per check + engine.

NAMED to sort inside the tier-1 alphabetical window (after test_generate,
before test_multiprocess — the convention GL008 itself enforces). Everything
here is pure-AST: no jax, no subprocesses, sub-second.

Each GL00x check gets at least one violating and one clean fixture; the
engine gets suppression / baseline / JSON / directive-error coverage; and a
meta-test asserts the REPO ITSELF is lint-clean against the committed
baseline, so a hazard regression fails tier-1, not just ci.sh's lint stage.
"""

import importlib.util
import json
import os
import textwrap

import pytest

from autodist_tpu.analysis import core

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Fixture flag names, concatenated so GL007's literal scan (full-match on
# AUTODIST_* string constants) does not read them as unregistered real flags
# of THIS file.
GOOD_FLAG = "AUTODIST_" + "GOOD"

_cli_spec = importlib.util.spec_from_file_location(
    "graftlint_cli", os.path.join(ROOT, "tools", "graftlint.py"))
cli = importlib.util.module_from_spec(_cli_spec)
_cli_spec.loader.exec_module(cli)


def lint(tmp_path, source, relname="mod.py", checks=None, known_flags=None):
    """Lint one dedented snippet written at ``tmp_path/relname``."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    ctx = core.Context(str(tmp_path), known_flags=known_flags)
    return core.lint_paths([str(path)], root=str(tmp_path), checks=checks,
                           context=ctx)


def lint_many(tmp_path, files, checks=None, known_flags=None):
    """Lint a MULTI-FILE fixture tree (``{relname: source}``) as one
    program — the cross-module ProgramIndex path."""
    for relname, source in files.items():
        path = tmp_path / relname
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    ctx = core.Context(str(tmp_path), known_flags=known_flags)
    return core.lint_paths([str(tmp_path)], root=str(tmp_path),
                           checks=checks, context=ctx)


def codes(result):
    return [f.check for f in result.findings]


# --------------------------------------------------------------------- GL001

# The PR 2 deadlock pattern (acceptance regression): a multi-device program
# dispatched inside an AsyncPSRunner._collective_lock-style critical section
# — but as a NEW, unannotated site, i.e. without the reviewed serialization
# rationale the real _collective_lock carries.
PR2_DEADLOCK = """
    import threading

    class BadRunner:
        def __init__(self, runner):
            self._collective_lock = threading.Lock()
            self._runner = runner

        def step(self, state, batch):
            with self._collective_lock:
                new_state, loss = self._runner.run(state, batch)
            return new_state, loss
"""


def test_gl001_flags_pr2_deadlock_pattern(tmp_path):
    res = lint(tmp_path, PR2_DEADLOCK, checks=["GL001"])
    assert codes(res) == ["GL001"]
    (f,) = res.findings
    assert "_collective_lock" in f.message and "run" in f.message
    assert f.scope == "BadRunner.step"


def test_gl001_clean_when_dispatch_outside_lock(tmp_path):
    res = lint(tmp_path, """
        import threading

        class GoodRunner:
            def __init__(self, runner):
                self._lock = threading.Lock()
                self._runner = runner
                self._queue = []

            def step(self, state, batch):
                with self._lock:
                    self._queue.append(batch)
                return self._runner.run(state, batch)
    """, checks=["GL001"])
    assert res.ok


def test_gl001_sees_through_local_helpers_and_jitted_names(tmp_path):
    res = lint(tmp_path, """
        import threading
        import jax

        _lock = threading.Lock()

        def _push(sock, data):
            sock.sendall(data)

        def locked_send(sock, data):
            with _lock:
                _push(sock, data)

        def locked_jit(lock, x):
            f = jax.jit(lambda y: y * 2)
            with lock:
                return f(x)
    """, checks=["GL001"])
    assert codes(res) == ["GL001", "GL001"]
    assert "via _push" in res.findings[0].message
    assert "(jitted)" in res.findings[1].message


def test_gl001_ignores_deferred_code_defined_under_lock(tmp_path):
    """A callback merely DEFINED while the lock is held runs after release —
    no held-across-dispatch hazard, no finding (GL002 likewise)."""
    res = lint(tmp_path, """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._other_lock = threading.Lock()
                self._cbs = []

            def register(self, sock):
                with self._lock:
                    def cb(data):
                        sock.sendall(data)
                        with self._other_lock:
                            pass
                    self._cbs.append(cb)
    """, checks=["GL001", "GL002"])
    assert res.ok


def test_gl001_suppression_with_reason(tmp_path):
    suppressed = PR2_DEADLOCK.replace(
        "with self._collective_lock:",
        "# graftlint: disable=GL001(serializes execution on purpose)\n"
        "            with self._collective_lock:")
    res = lint(tmp_path, suppressed, checks=["GL001"])
    assert res.ok
    [(finding, reason)] = res.suppressed
    assert finding.check == "GL001"
    assert reason == "serializes execution on purpose"


# --------------------------------------------------------------------- GL002

ABBA = """
    import threading

    class Service:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def forward(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def backward(self):
            with self._b_lock:
                with self._a_lock:
                    pass
"""


def test_gl002_flags_inversion_against_declared_order(tmp_path):
    res = lint(tmp_path, "# graftlint: lock-order=_a_lock->_b_lock\n"
               + textwrap.dedent(ABBA), checks=["GL002"])
    assert codes(res) == ["GL002"]
    (f,) = res.findings
    assert f.scope == "Service.backward"
    assert "conflicting" in f.message


def test_gl002_undeclared_nesting_is_flagged(tmp_path):
    res = lint(tmp_path, ABBA, checks=["GL002"])
    # Both nestings lack a declared order (and invert each other).
    assert len(res.findings) == 2
    assert all(f.check == "GL002" for f in res.findings)


def test_gl002_clean_with_declared_consistent_order(tmp_path):
    res = lint(tmp_path, """
        # graftlint: lock-order=_write_mutex->_lock
        import threading

        class PS:
            def __init__(self):
                self._write_mutex = threading.Lock()
                self._lock = threading.Condition()

            def reset(self):
                with self._write_mutex:
                    with self._lock:
                        self._lock.notify_all()
    """, checks=["GL002"])
    assert res.ok


# --------------------------------------------------------------------- GL003

def test_gl003_flags_read_after_donation(tmp_path):
    res = lint(tmp_path, """
        import jax

        def train(state, batch):
            step = jax.jit(lambda s, b: s, donate_argnums=(0,))
            new_state = step(state, batch)
            return state
    """, checks=["GL003"])
    assert codes(res) == ["GL003"]
    assert "`state`" in res.findings[0].message


def test_gl003_sees_donor_assigned_inside_a_branch(tmp_path):
    res = lint(tmp_path, """
        import jax

        def train(state, batch, donate):
            if donate:
                step = jax.jit(lambda s, b: s, donate_argnums=(0,))
                new_state = step(state, batch)
                return state
            return state
    """, checks=["GL003"])
    assert codes(res) == ["GL003"]


def test_gl003_clean_when_result_is_used(tmp_path):
    res = lint(tmp_path, """
        import jax

        def train(state, batch):
            step = jax.jit(lambda s, b: s, donate_argnums=(0,))
            state = step(state, batch)
            return state
    """, checks=["GL003"])
    assert res.ok


# --------------------------------------------------------------------- GL004

def test_gl004_flags_host_calls_and_captured_stores(tmp_path):
    res = lint(tmp_path, """
        import time
        import jax

        class Meter:
            pass

        meter = Meter()

        @jax.jit
        def step(x):
            print("stepping", x)
            meter.last = x
            t = time.time()
            return x * 2

        @jax.jit
        def builds_locally(y):
            local = Meter()
            local.value = y      # object created under trace: fine
            return y + 1
    """, checks=["GL004"])
    msgs = [f.message for f in res.findings]
    assert codes(res).count("GL004") == 3
    assert any("`print`" in m for m in msgs)
    assert any("meter.last" in m for m in msgs)
    assert any("time.time" in m for m in msgs)
    assert not any("local.value" in m for m in msgs)


def test_gl004_clean_pure_jitted_fn(tmp_path):
    res = lint(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(params, batch):
            loss = jnp.mean((params - batch) ** 2)
            return loss
    """, checks=["GL004"])
    assert res.ok


# --------------------------------------------------------------------- GL005

def test_gl005_flags_unbounded_wait_in_package_code(tmp_path):
    res = lint(tmp_path, """
        import threading

        class Gate:
            def __init__(self):
                self._cond = threading.Condition()

            def wait_open(self):
                with self._cond:
                    self._cond.wait_for(lambda: True)

            def pause(self):
                with self._cond:
                    self._cond.wait(timeout=None)
    """, relname="autodist_tpu/gate.py", checks=["GL005"])
    assert codes(res) == ["GL005", "GL005"]


def test_gl005_clean_with_timeout_and_outside_package(tmp_path):
    clean = """
        import threading

        class Gate:
            def __init__(self):
                self._cond = threading.Condition()

            def wait_open(self, timeout):
                with self._cond:
                    return self._cond.wait_for(lambda: True, timeout)
    """
    assert lint(tmp_path, clean, relname="autodist_tpu/gate.py",
                checks=["GL005"]).ok
    unbounded_but_test_code = """
        import threading
        cond = threading.Condition()
        with cond:
            cond.wait_for(lambda: True)
    """
    assert lint(tmp_path, unbounded_but_test_code,
                relname="tests/helper.py", checks=["GL005"]).ok


# --------------------------------------------------------------------- GL006

def test_gl006_flags_opcode_without_dispatch_arm(tmp_path):
    res = lint(tmp_path, """
        class Client:
            def push(self, grads):
                return self._client.call("aply", grads)

            def pull(self):
                return self._client.call("read")

        def _dispatch(msg):
            op = msg[0]
            if op == "apply":
                return ("ok",)
            if op == "read":
                return ("ok", 1)
            return ("error", "unknown")
    """, checks=["GL006"])
    assert codes(res) == ["GL006"]
    assert "'aply'" in res.findings[0].message


def test_gl006_flags_asymmetric_codec_tags_and_unchecked_version(tmp_path):
    res = lint(tmp_path, """
        import struct

        _HDR = struct.Struct("!Q")
        _FRAME_VERSION = 0

        def _enc(out, obj):
            out += b"z"

        def _dec(r):
            tag = r.take(1)
            if tag == b"y":
                return 1

        def _frame_len(header):
            (word,) = _HDR.unpack(header)
            if word >> 56 != _FRAME_VERSION:
                raise ValueError(word)
            return word

        def sloppy_len(header):
            (word,) = _HDR.unpack(header)
            return word
    """, checks=["GL006"])
    msgs = " | ".join(f.message for f in res.findings)
    assert codes(res).count("GL006") == 3
    assert "b'z'" in msgs and "b'y'" in msgs and "sloppy_len" in msgs


def test_gl006_flags_serving_op_without_dispatch_arm(tmp_path):
    """Serving-transport shape: the dispatcher is a server-class METHOD and
    several server classes may share the module — a client op must match an
    arm in ANY of them, and a missing arm is flagged (the PR 7 serving wire
    gets the same exhaustiveness guarantee as the PS wire)."""
    res = lint(tmp_path, """
        class InferenceServer:
            def _dispatch(self, msg):
                op = msg[0]
                if op == "generate":
                    return ("ok",)
                if op == "stats":
                    return ("ok", {})
                return ("error", "ServeError", "unknown")

        class AdminServer:
            def _dispatch(self, msg):
                op = msg[0]
                if op == "drain":
                    return ("ok",)
                return ("error", "ServeError", "unknown")

        class ServeClient:
            def generate(self, prompt):
                return self._client.call("generate", prompt)

            def infer(self, example):
                return self._client.call("infer", example)

            def drain(self):
                return self._client.call("drain")
    """, checks=["GL006"])
    assert codes(res) == ["GL006"]
    # 'generate' and 'drain' resolve across the two dispatchers; only the
    # armless 'infer' is a finding.
    assert "'infer'" in res.findings[0].message


def test_gl006_clean_serving_protocol(tmp_path):
    """The real serving vocabulary (generate/infer/stats/status/record/ping),
    method-style dispatcher, every op armed — clean."""
    res = lint(tmp_path, """
        class InferenceServer:
            def _dispatch(self, msg):
                op = msg[0]
                if op == "generate":
                    return ("ok",)
                if op == "infer":
                    return ("ok",)
                if op == "stats":
                    return ("ok", {})
                if op == "status":
                    return ("ok", {})
                if op == "record":
                    return ("ok", "/tmp/snap")
                if op == "ping":
                    return ("ok", None)
                return ("error", "ServeError", "unknown")

        class ServeClient:
            def generate(self, prompt):
                return self._client.call("generate", prompt)

            def infer(self, example):
                return self._client.call("infer", example)

            def stats(self):
                return self._client.call("stats")[0]

            def status(self):
                return self._client.call("status")[0]

            def record(self, reason):
                return self._client.call("record", reason)[0]

            def ping(self):
                return self._client.call("ping")
    """, checks=["GL006"])
    assert res.ok


def test_gl006_clean_symmetric_protocol(tmp_path):
    res = lint(tmp_path, """
        class Client:
            def push(self, grads):
                return self._client.call("apply", grads)

        def _dispatch(msg):
            op = msg[0]
            if op == "apply":
                return ("ok",)
            return ("error", "unknown")
    """, checks=["GL006"])
    assert res.ok


# --------------------------------------------------------------------- GL007

def test_gl007_direct_env_read_in_package_and_typo_flag(tmp_path):
    res = lint(tmp_path, """
        import os

        good = os.environ.get("AUTODIST_GOOD")
        typo = os.environ.get("AUTODIST_GOOOD")
    """, relname="autodist_tpu/mod.py", checks=["GL007"],
        known_flags={GOOD_FLAG})
    # Two direct package reads + one unknown name.
    assert codes(res).count("GL007") == 3
    assert sum("unknown flag" in f.message for f in res.findings) == 1


def test_gl007_known_flag_outside_package_is_clean(tmp_path):
    res = lint(tmp_path, """
        import os

        flag = os.environ.get("AUTODIST_GOOD", "")
        env = dict(os.environ)
        env["AUTODIST_GOOD"] = "1"
    """, relname="tests/helper.py", checks=["GL007"],
        known_flags={GOOD_FLAG})
    assert res.ok


def test_known_flags_parsed_from_real_const_py():
    flags = core.Context(ROOT).known_flags()
    assert flags is not None
    assert "AUTODIST_PS_OVERLAP" in flags
    assert "AUTODIST_MATRIX_PROCS" in flags


# --------------------------------------------------------------------- GL008

def test_gl008_unmarked_subprocess_file_inside_window(tmp_path):
    res = lint(tmp_path, """
        import subprocess

        def test_spawns():
            subprocess.run(["echo", "hi"], check=True)
    """, relname="tests/test_aaa.py", checks=["GL008"])
    assert codes(res) == ["GL008"]
    assert "tier-1 window" in res.findings[0].message


def test_gl008_clean_when_marked_slow_or_after_edge(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        '[tool.pytest.ini_options]\nmarkers = ["slow: slow tests"]\n')
    marked = """
        import subprocess
        import pytest

        @pytest.mark.slow
        def test_spawns():
            subprocess.run(["echo", "hi"], check=True)
    """
    assert lint(tmp_path, marked, relname="tests/test_aaa.py",
                checks=["GL008"]).ok
    after_edge = """
        import subprocess

        def test_spawns():
            subprocess.run(["echo", "hi"], check=True)
    """
    assert lint(tmp_path, after_edge, relname="tests/test_zz_dist.py",
                checks=["GL008"]).ok


def test_gl008_detects_mp_env_harness_import_forms(tmp_path):
    res = lint(tmp_path, """
        from tests.mp_env import mp_env

        def test_cluster():
            mp_env(2)
    """, relname="tests/test_bbb.py", checks=["GL008"])
    assert codes(res) == ["GL008"]
    assert "mp_env" in res.findings[0].message


def test_gl008_bad_filename_and_unregistered_marker(tmp_path):
    res = lint(tmp_path, """
        import pytest

        @pytest.mark.slow
        def test_x():
            pass
    """, relname="tests/test_CamelCase.py", checks=["GL008"])
    msgs = " | ".join(f.message for f in res.findings)
    assert codes(res).count("GL008") == 2
    assert "does not match" in msgs and "not registered" in msgs


# ------------------------------------------- cross-module (ProgramIndex) lift

def test_gl001_cross_module_lock_across_dispatch(tmp_path):
    """The seeded acceptance fixture: a `with lock:` body that reaches a
    socket send THROUGH ANOTHER MODULE — today's intra-module blind spot —
    must fail lint, with the hop path named."""
    res = lint_many(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/sender.py": """
            def push(sock, data):
                sock.sendall(data)
        """,
        "pkg/locked.py": """
            import threading

            from pkg.sender import push

            _lock = threading.Lock()

            def locked_send(sock, data):
                with _lock:
                    push(sock, data)
        """}, checks=["GL001"])
    assert codes(res) == ["GL001"]
    (f,) = res.findings
    assert f.path == "pkg/locked.py"
    assert "pkg.sender.push" in f.message and "sendall" in f.message


def test_gl001_cross_module_via_module_attribute_and_instance(tmp_path):
    """`mod.f()` chains and methods of locally-constructed imported-class
    instances both resolve across the import."""
    res = lint_many(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/runner.py": """
            class Runner:
                def run(self, state, batch):
                    return state
        """,
        "pkg/driver.py": """
            import threading

            from pkg.runner import Runner

            _lock = threading.Lock()

            def step(state, batch):
                r = Runner()
                with _lock:
                    return r.run(state, batch)
        """}, checks=["GL001"])
    assert codes(res) == ["GL001"]
    assert "run" in res.findings[0].message


def test_gl001_cross_module_clean_when_callee_does_not_block(tmp_path):
    res = lint_many(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/helper.py": """
            def tally(items):
                return sum(items)
        """,
        "pkg/locked.py": """
            import threading

            from pkg.helper import tally

            _lock = threading.Lock()

            def locked_count(items):
                with _lock:
                    return tally(items)
        """}, checks=["GL001"])
    assert res.ok


def test_gl002_cross_module_undeclared_nesting(tmp_path):
    """A module-global lock acquired inside a helper ANOTHER module calls
    under its own lock is an undeclared cross-module nesting."""
    files = {
        "pkg/__init__.py": "",
        "pkg/inner.py": """
            import threading

            _b_lock = threading.Lock()

            def guarded():
                with _b_lock:
                    return 1
        """,
        "pkg/outer.py": """
            import threading

            from pkg.inner import guarded

            _a_lock = threading.Lock()

            def run():
                with _a_lock:
                    return guarded()
        """}
    res = lint_many(tmp_path, dict(files), checks=["GL002"])
    assert codes(res) == ["GL002"]
    assert res.findings[0].path == "pkg/outer.py"
    assert "_a_lock` -> `_b_lock" in res.findings[0].message
    # Declaring the order in EITHER module involved silences it.
    files["pkg/outer.py"] = files["pkg/outer.py"].replace(
        "import threading",
        "# graftlint: lock-order=_a_lock->_b_lock\n"
        "            import threading", 1)
    assert lint_many(tmp_path, files, checks=["GL002"]).ok


def test_gl002_cross_module_abba_on_shared_locks(tmp_path):
    """Two modules importing the SAME lock pair from a shared module and
    nesting them in opposite orders (through each other's helpers) is a
    program-wide ABBA deadlock — identity-matched, so it fires."""
    res = lint_many(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/locks.py": """
            import threading

            a_lock = threading.Lock()
            b_lock = threading.Lock()

            def take_b():
                with b_lock:
                    return 1

            def take_a():
                with a_lock:
                    return 1
        """,
        "pkg/x.py": """
            from pkg.locks import a_lock, take_b

            def fx():
                with a_lock:
                    return take_b()
        """,
        "pkg/y.py": """
            from pkg.locks import b_lock, take_a

            def fy():
                with b_lock:
                    return take_a()
        """}, checks=["GL002"])
    assert any("program-wide ABBA" in f.message for f in res.findings)


def test_gl002_same_names_in_unrelated_modules_are_distinct_locks(tmp_path):
    """`_alpha_lock`/`_beta_lock` nested in opposite orders by two UNRELATED
    module pairs are four distinct locks — identity matching must not
    manufacture a program-wide ABBA (bare-name matching did)."""
    res = lint_many(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/b.py": """
            import threading

            _beta_lock = threading.Lock()

            def helper():
                with _beta_lock:
                    return 1
        """,
        "pkg/a.py": """
            # graftlint: lock-order=_alpha_lock->_beta_lock
            import threading

            from pkg import b

            _alpha_lock = threading.Lock()

            def fa():
                with _alpha_lock:
                    return b.helper()
        """,
        "pkg/d.py": """
            import threading

            _alpha_lock = threading.Lock()

            def helper2():
                with _alpha_lock:
                    return 1
        """,
        "pkg/c.py": """
            # graftlint: lock-order=_beta_lock->_alpha_lock
            import threading

            from pkg import d

            _beta_lock = threading.Lock()

            def fc():
                with _beta_lock:
                    return d.helper2()
        """}, checks=["GL002"])
    assert not any("ABBA" in f.message for f in res.findings)
    assert not any("opposite acquisition orders" in f.message
                   for f in res.findings)
    assert res.ok   # declared orders cover both modules' own edges


def test_gl001_deep_callee_reexplored_with_more_depth(tmp_path):
    """Depth-aware cycle guard: a callee FIRST reached near the hop limit
    (shallowly explored) must be re-explored when reached directly with
    budget to spare — the finding must not depend on statement order."""
    chain = "\n".join(
        f"def f{i}(sock):\n    return f{i + 1}(sock)" for i in range(6))
    res = lint_many(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/deep.py": f"""
{chain}

def f6(sock):
    return h(sock)

def h(sock):
    return g1(sock)

def g1(sock):
    return g2(sock)

def g2(sock):
    sock.sendall(b"x")
""",
        "pkg/locked.py": """
            import threading

            from pkg.deep import f0, h

            _lock = threading.Lock()

            def locked(sock):
                with _lock:
                    f0(sock)   # reaches h at the depth limit (shallow)
                    h(sock)    # direct: must still find sendall
        """}, checks=["GL001"])
    assert codes(res) == ["GL001"]


def test_gl002_direct_nesting_of_shared_locks_is_program_wide_abba(tmp_path):
    """Two modules DIRECTLY nesting the same imported lock pair in
    opposite orders (no call edge needed) is the simplest program-wide
    ABBA — and a module's own-direction declaration must not vouch for
    the other module's opposite acquisition."""
    files = {
        "pkg/__init__.py": "",
        "pkg/locks.py": """
            import threading

            a_lock = threading.Lock()
            b_lock = threading.Lock()
        """,
        "pkg/m1.py": """
            from pkg.locks import a_lock, b_lock

            def f1():
                with b_lock:
                    with a_lock:
                        return 1
        """,
        "pkg/m2.py": """
            # graftlint: lock-order=a_lock->b_lock
            from pkg.locks import a_lock, b_lock

            def f2():
                with a_lock:
                    with b_lock:
                        return 2
        """}
    res = lint_many(tmp_path, files, checks=["GL002"])
    assert any("program-wide ABBA" in f.message for f in res.findings)


def test_gl002_contradictory_declarations_across_modules(tmp_path):
    """Two modules PROMISING opposite orders for the SAME locks (same
    identity: both import them from one home module) are two subsystems
    one scheduler decision from deadlock — the program-wide declaration
    cross-check catches what per-module matching cannot. Declarations
    about unrelated same-named locks do not compare (identity-gated)."""
    res = lint_many(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/x.py": """
            # graftlint: lock-order=a_lock->b_lock
            import threading

            a_lock = threading.Lock()
            b_lock = threading.Lock()
        """,
        "pkg/y.py": """
            # graftlint: lock-order=b_lock->a_lock
            from pkg.x import a_lock, b_lock
        """}, checks=["GL002"])
    assert codes(res) == ["GL002"]
    assert "opposite acquisition orders" in res.findings[0].message


# --------------------------------------------------------------------- GL009

METRIC_PRODUCERS = """
    from autodist_tpu import telemetry

    def sample():
        telemetry.gauge("train.mfu").set(0.5)
        telemetry.counter("serve.requests.completed").inc()
        for phase in ("compute", "comm"):
            telemetry.gauge(f"train.attr.{phase}").set(0.1)
"""

# A fixture copy of alerts' DEFAULT_RULES shape: the acceptance scenario is
# deleting a booked metric name (the producer above books train.mfu but NOT
# train.attr.data_wait) and observing the dead-selector finding.
ALERT_DEFAULTS = """
    DEFAULT_RULES = [
        {"name": "mfu_collapse", "kind": "drift", "metric": "train.mfu",
         "ref_from": "window_max", "band": 0.5},
        {"name": "data_wait_drift", "kind": "drift",
         "metric": "train.attr.data_wait", "ref_from": "plan",
         "band": 0.25},
    ]
"""


def test_gl009_selector_with_no_producer_is_dead_on_arrival(tmp_path):
    res = lint_many(tmp_path, {
        "autodist_tpu/prod.py": METRIC_PRODUCERS.replace(
            'f"train.attr.{phase}"', '"train.other"'),
        "autodist_tpu/alerts.py": ALERT_DEFAULTS,
    }, checks=["GL009"])
    assert codes(res) == ["GL009"]
    assert "train.attr.data_wait" in res.findings[0].message
    assert "dead on arrival" in res.findings[0].message


def test_gl009_fstring_producers_book_prefix_patterns(tmp_path):
    """`f"train.attr.{phase}"` books `train.attr.*`, so the selector
    resolves — and the whole fixture is clean."""
    res = lint_many(tmp_path, {
        "autodist_tpu/prod.py": METRIC_PRODUCERS,
        "autodist_tpu/alerts.py": ALERT_DEFAULTS,
    }, checks=["GL009"])
    assert res.ok


def test_gl009_registry_lookup_of_unbooked_name(tmp_path):
    res = lint_many(tmp_path, {
        "autodist_tpu/prod.py": METRIC_PRODUCERS,
        "tools/console.py": """
            def render(reg):
                good = reg.get("train.mfu")
                bad = reg.get("train.mfuu")
                return good, bad
        """}, checks=["GL009"])
    assert codes(res) == ["GL009"]
    assert "train.mfuu" in res.findings[0].message


def test_gl009_plan_phase_vocabulary(tmp_path):
    """A ref_from='plan' drift rule whose phase suffix the plan never
    prices degrades to a 0 reference — flagged against the breakdown-key
    vocabulary harvested from the program."""
    phase_map = """
        def _reference(breakdown):
            return {"compute": breakdown.get("compute_s", 0.0),
                    "data_wait": breakdown.get("data_wait_s", 0.0)}
    """
    bad_rule = ALERT_DEFAULTS.replace("train.attr.data_wait",
                                      "train.attr.datawait")
    res = lint_many(tmp_path, {
        "autodist_tpu/prod.py": METRIC_PRODUCERS,
        "autodist_tpu/ref.py": phase_map,
        "autodist_tpu/alerts.py": bad_rule,
    }, checks=["GL009"])
    # The typo'd selector is BOTH unbooked (train.attr.* books it though —
    # the pattern matches any suffix) and an unpriced phase.
    assert codes(res) == ["GL009"]
    assert "not a plan-priced phase" in res.findings[0].message


def test_gl009_undocumented_package_metric(tmp_path):
    (tmp_path / "docs" / "usage").mkdir(parents=True)
    (tmp_path / "docs" / "usage" / "observability.md").write_text(
        "Metrics: `train.mfu`, the `train.attr.*` family.\n")
    res = lint_many(tmp_path, {
        "autodist_tpu/prod.py": METRIC_PRODUCERS,
    }, checks=["GL009"])
    assert codes(res) == ["GL009"]
    assert "serve.requests.completed" in res.findings[0].message
    assert "observability.md" in res.findings[0].message


def test_gl009_wrapper_functions_and_defaults_book_names(tmp_path):
    """One level of in-module wrapper forwarding and string parameter
    defaults both contribute to the producer registry."""
    res = lint_many(tmp_path, {
        "autodist_tpu/prod.py": """
            from autodist_tpu import telemetry

            def _counter(name):
                return telemetry.counter(name)

            def boot(metric_prefix="data"):
                _counter("recover.evicted")
                telemetry.gauge(f"{metric_prefix}.queue_depth").set(0)
        """,
        "tools/console.py": """
            def render(reg):
                return (reg.get("recover.evicted"),
                        reg.get("data.queue_depth"))
        """}, checks=["GL009"])
    assert res.ok


# --------------------------------------------------------------------- GL010

CLOSEABLE_DEF = """
    import threading

    class Producer:
        def __init__(self):
            self._t = threading.Thread(target=lambda: None, daemon=True)

        def close(self):
            pass

    def make_feed():
        return Producer()
"""


def test_gl010_unclosed_closeable_leaks(tmp_path):
    res = lint_many(tmp_path, {
        "autodist_tpu/res.py": CLOSEABLE_DEF,
        "examples/train.py": """
            from autodist_tpu.res import make_feed

            def main():
                feed = make_feed()
                for _ in range(3):
                    next(feed)
        """}, checks=["GL010"])
    assert codes(res) == ["GL010"]
    (f,) = res.findings
    assert f.path == "examples/train.py" and "never closed" in f.message
    assert "make_feed" in f.message   # the factory chain resolved


def test_gl010_straight_line_close_is_unprotected(tmp_path):
    res = lint_many(tmp_path, {
        "autodist_tpu/res.py": CLOSEABLE_DEF,
        "examples/train.py": """
            from autodist_tpu.res import Producer

            def main():
                feed = Producer()
                next(feed)
                feed.close()
        """}, checks=["GL010"])
    assert codes(res) == ["GL010"]
    assert "straight-line" in res.findings[0].message


def test_gl010_clean_with_finally_with_block_or_escape(tmp_path):
    res = lint_many(tmp_path, {
        "autodist_tpu/res.py": CLOSEABLE_DEF,
        "examples/train.py": """
            from autodist_tpu.res import Producer, make_feed

            def finally_path():
                feed = make_feed()
                try:
                    next(feed)
                finally:
                    feed.close()

            def with_path():
                with Producer() as feed:
                    next(feed)

            def escapes_by_return():
                feed = Producer()
                return feed

            def escapes_into_registry(registry):
                feed = Producer()
                registry.add(feed)
        """}, checks=["GL010"])
    assert res.ok


def test_gl010_store_on_object_or_container_transfers_ownership(tmp_path):
    """`self.x = feed` / `d[k] = feed` hand the resource to another owner
    — the documented escape rule, not a leak."""
    res = lint_many(tmp_path, {
        "autodist_tpu/res.py": CLOSEABLE_DEF,
        "examples/train.py": """
            from autodist_tpu.res import Producer

            class Holder:
                def attach(self):
                    feed = Producer()
                    self.feed = feed

            def stash(feeds):
                feed = Producer()
                feeds["main"] = feed
        """}, checks=["GL010"])
    assert res.ok


def test_gl009_test_fixture_producer_does_not_mask_dead_selector(tmp_path):
    """A metric booked ONLY by a test must not keep a production alert
    selector alive — producers are harvested from non-test code, symmetric
    with the consumer exemption."""
    res = lint_many(tmp_path, {
        "autodist_tpu/prod.py": METRIC_PRODUCERS.replace(
            '"train.mfu"', '"train.mfu_v2"'),
        "tests/test_old.py": """
            from autodist_tpu import telemetry

            def test_books_the_old_name():
                telemetry.gauge("train.mfu").set(0.5)
        """,
        "autodist_tpu/alerts.py": ALERT_DEFAULTS,
    }, checks=["GL009"])
    assert [f.message for f in res.findings
            if "train.mfu'" in f.message and "dead on arrival" in f.message]


def test_changed_only_refuses_write_baseline(capsys):
    assert cli.main(["--changed-only", "--write-baseline"]) == 2
    assert "partial file set" in capsys.readouterr().err


def test_partial_positional_paths_skip_registry_checks(capsys):
    """Linting a subset must not report every shipped selector as dead
    (GL009 over a partial producer set) — and must refuse to rewrite the
    baseline from partial findings."""
    rc = cli.main(["--no-cache", "autodist_tpu/telemetry/alerts.py",
                   "tools/adtop.py"])
    out = capsys.readouterr()
    assert rc == 0, out.out
    assert "registry checks (GL009/GL011) skipped" in out.err
    assert cli.main(["--no-cache", "--write-baseline",
                     "autodist_tpu/telemetry/alerts.py"]) == 2
    assert "partial path set" in capsys.readouterr().err


def test_changed_only_refuses_pure_full_program_check_set(capsys):
    """--changed-only --check GL009 would check NOTHING (the full-program
    checks are skipped there) — error loudly instead of a silent green."""
    assert cli.main(["--changed-only", "--check", "GL009"]) == 2
    assert "would check NOTHING" in capsys.readouterr().err


def test_gl009_doc_match_is_token_bounded(tmp_path):
    """A booked `train.flops` must not count as documented because
    `train.flops_per_s` appears in the doc's prose."""
    (tmp_path / "docs" / "usage").mkdir(parents=True)
    (tmp_path / "docs" / "usage" / "observability.md").write_text(
        "The roofline gauge `train.flops_per_s` and the family "
        "`serve.latency_s.*`.\n")
    res = lint_many(tmp_path, {
        "autodist_tpu/prod.py": """
            from autodist_tpu import telemetry

            def sample():
                telemetry.gauge("train.flops").set(1.0)
                telemetry.gauge("train.flops_per_s").set(1.0)
                telemetry.histogram("serve.latency_s.total").observe(0.1)
        """}, checks=["GL009"])
    assert codes(res) == ["GL009"]
    assert "'train.flops'" in res.findings[0].message


def test_gl010_close_of_earlier_binding_does_not_cover_a_rebinding(tmp_path):
    """Close-old-construct-new: the second Producer bound to the reused
    name is its own resource — the earlier `with feed:` must not mark it
    clean (position-sensitive tracing)."""
    res = lint_many(tmp_path, {
        "autodist_tpu/res.py": CLOSEABLE_DEF,
        "examples/train.py": """
            from autodist_tpu.res import Producer

            def main():
                feed = Producer()
                with feed:
                    next(feed)
                feed = Producer()
                next(feed)
        """}, checks=["GL010"])
    assert codes(res) == ["GL010"]
    assert res.findings[0].line == 8   # the REBINDING, not the first


def test_gl010_class_attribute_construction_is_instance_state(tmp_path):
    """`class Owner: feed = Feed()` is the class's state (closed through
    the instance lifecycle, like `self.feed = ...`) — not a scope leak."""
    res = lint_many(tmp_path, {
        "autodist_tpu/res.py": CLOSEABLE_DEF,
        "autodist_tpu/owner.py": """
            from autodist_tpu.res import Producer

            class Owner:
                feed = Producer()

                def close(self):
                    self.feed.close()
        """}, checks=["GL010"])
    assert res.ok


def test_gl010_tests_are_exempt(tmp_path):
    res = lint_many(tmp_path, {
        "autodist_tpu/res.py": CLOSEABLE_DEF,
        "tests/test_feed.py": """
            from autodist_tpu.res import Producer

            def test_leaky():
                feed = Producer()
                next(feed)
        """}, checks=["GL010"])
    assert res.ok


# --------------------------------------------------------------------- GL011

WIRE_MODULE = """
    IDEMPOTENT_OPS = frozenset({"read", "version", "register"})

    class PSClient:
        def call_raw(self, msg, counters):
            return msg

        def call(self, *msg):
            return self.call_raw(msg, None)

    def _dispatch(msg):
        op = msg[0]
        if op == "read":
            return ("ok", 1)
        if op == "version":
            return ("ok", 0)
        if op == "register":
            return ("ok",)
        if op == "apply":
            return ("ok",)
        return ("error", "unknown")
"""


def test_gl011_cross_module_nonidempotent_raw_retry(tmp_path):
    """The seeded acceptance fixture: a raw-path exchange in ANOTHER module
    sending an op outside IDEMPOTENT_OPS — the register(None)-replay class
    — fails lint."""
    res = lint_many(tmp_path, {
        "autodist_tpu/wiremod.py": WIRE_MODULE,
        "autodist_tpu/overlap.py": """
            from autodist_tpu.wiremod import PSClient

            def prefetch(counters):
                client = PSClient()
                good = client.call_raw(("read", 0), counters)
                bad = client.call_raw(("apply", 0), counters)
                return good, bad
        """}, checks=["GL011"])
    assert codes(res) == ["GL011"]
    (f,) = res.findings
    assert f.path == "autodist_tpu/overlap.py"
    assert "'apply'" in f.message and "IDEMPOTENT_OPS" in f.message


def test_gl011_table_member_without_dispatch_arm(tmp_path):
    # Typo the TABLE entry only (the dispatch arm keeps "register").
    res = lint_many(tmp_path, {
        "autodist_tpu/wiremod.py": WIRE_MODULE.replace(
            '"register"})', '"regster"})'),
    }, checks=["GL011"])
    assert codes(res) == ["GL011"]
    assert "'regster'" in res.findings[0].message


def test_gl011_cross_module_send_without_any_arm(tmp_path):
    """GL006 lifted: a `.call("op")` on a transport client in a module with
    NO local `_dispatch` is checked against the program-wide arm union."""
    res = lint_many(tmp_path, {
        "autodist_tpu/wiremod.py": WIRE_MODULE,
        "tools/console.py": """
            from autodist_tpu.wiremod import PSClient

            def fetch():
                client = PSClient()
                ok = client.call("version")
                bad = client.call("stats")
                return ok, bad
        """}, checks=["GL011"])
    assert codes(res) == ["GL011"]
    assert "'stats'" in res.findings[0].message


def test_gl011_unrelated_call_raw_method_is_not_a_wire_site(tmp_path):
    """A class that merely NAMES a method call_raw is not a transport
    client; its call sites are out of scope (receiver typing gates the
    raw-path rule)."""
    res = lint_many(tmp_path, {
        "autodist_tpu/wiremod.py": WIRE_MODULE,
        "autodist_tpu/mailbox.py": """
            class Mailbox:
                def call_raw(self, msg, prio):
                    return msg

            def post():
                box = Mailbox()
                return box.call_raw(("put", 1), 0)
        """}, checks=["GL011"])
    assert res.ok


def test_gl011_annotated_parameter_receiver_is_typed(tmp_path):
    """`client: PSClient` parameter annotations resolve cross-module — the
    real overlapped-prefetch helper's shape stays covered."""
    res = lint_many(tmp_path, {
        "autodist_tpu/wiremod.py": WIRE_MODULE,
        "autodist_tpu/overlap.py": """
            from autodist_tpu.wiremod import PSClient

            def exchange(client: PSClient, counters):
                return client.call_raw(("record", "why"), counters)
        """}, checks=["GL011"])
    assert codes(res) == ["GL011"]
    assert "'record'" in res.findings[0].message


def test_gl011_clean_program(tmp_path):
    res = lint_many(tmp_path, {
        "autodist_tpu/wiremod.py": WIRE_MODULE,
        "autodist_tpu/overlap.py": """
            from autodist_tpu.wiremod import PSClient

            def prefetch(counters):
                client = PSClient()
                return client.call_raw(("read", 0), counters)
        """}, checks=["GL011"])
    assert res.ok


def test_gl011_real_contract_is_joined():
    """The real repo's table, arms and raw-path sites satisfy the joined
    contract (the repo-wide gate asserts the same through the CLI; this
    pins the specific check)."""
    from autodist_tpu.parallel.ps_transport import IDEMPOTENT_OPS
    assert "read_min" in IDEMPOTENT_OPS   # the overlapped raw-path op


# ----------------------------------------------------------- engine behavior

def test_reasonless_suppression_is_a_gl000_finding(tmp_path):
    res = lint(tmp_path, """
        import threading

        _lock = threading.Lock()

        def locked_send(sock, data):
            with _lock:  # graftlint: disable=GL001
                sock.sendall(data)
    """, checks=["GL001"])
    assert sorted(codes(res)) == ["GL000", "GL001"]  # suppression rejected
    assert "no reason" in next(
        f.message for f in res.findings if f.check == "GL000")


def test_unknown_directive_is_flagged(tmp_path):
    res = lint(tmp_path, "# graftlint: disbale=GL001(oops)\nx = 1\n",
               checks=["GL001"])
    assert codes(res) == ["GL000"]


def test_syntax_error_is_reported_not_crashed(tmp_path):
    res = lint(tmp_path, "def broken(:\n", checks=["GL001"])
    assert codes(res) == ["GL000"]
    assert "does not parse" in res.findings[0].message


def test_baseline_grandfathers_old_findings_only(tmp_path):
    res = lint(tmp_path, PR2_DEADLOCK, relname="old.py", checks=["GL001"])
    assert len(res.findings) == 1
    baseline_path = tmp_path / "baseline.json"
    core.write_baseline(str(baseline_path), res.findings)
    baseline = core.load_baseline(str(baseline_path))

    # Same findings + baseline => clean, reported as baselined.
    ctx = core.Context(str(tmp_path))
    res2 = core.lint_paths([str(tmp_path / "old.py")], root=str(tmp_path),
                           baseline=baseline, checks=["GL001"], context=ctx)
    assert res2.ok and len(res2.baselined) == 1

    # A NEW violation in another file still fails.
    (tmp_path / "new.py").write_text(textwrap.dedent(PR2_DEADLOCK))
    res3 = core.lint_paths([str(tmp_path)], root=str(tmp_path),
                           baseline=baseline, checks=["GL001"], context=ctx)
    assert [f.path for f in res3.findings] == ["new.py"]

    # Fixing the old finding surfaces the stale baseline entry.
    (tmp_path / "old.py").write_text("x = 1\n")
    res4 = core.lint_paths([str(tmp_path / "old.py")], root=str(tmp_path),
                           baseline=baseline, checks=["GL001"], context=ctx)
    assert res4.ok and len(res4.stale_baseline) == 1


def test_baseline_never_grandfathers_gl000(tmp_path):
    """--write-baseline must not become a side door around the 'GL000
    cannot be suppressed' invariant: meta-findings (reasonless directives,
    parse errors) are excluded from writing AND from matching."""
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import threading

        _lock = threading.Lock()

        def locked_send(sock, data):
            with _lock:  # graftlint: disable=GL001
                sock.sendall(data)
    """))
    ctx = core.Context(str(tmp_path))
    res = core.lint_paths([str(bad)], root=str(tmp_path), checks=["GL001"],
                          context=ctx)
    assert sorted(codes(res)) == ["GL000", "GL001"]
    baseline_path = tmp_path / "baseline.json"
    core.write_baseline(str(baseline_path), res.findings)
    baseline = core.load_baseline(str(baseline_path))
    assert all("GL000" not in fp.split("|")[0] for fp in baseline)
    # Even a hand-edited baseline containing the GL000 fingerprint is inert.
    gl000 = next(f for f in res.findings if f.check == "GL000")
    res2 = core.lint_paths([str(bad)], root=str(tmp_path), checks=["GL001"],
                           baseline=baseline | {gl000.fingerprint},
                           context=ctx)
    assert "GL000" in codes(res2)


def test_cli_json_output_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(PR2_DEADLOCK))
    rc = cli.main(["--format", "json", "--no-baseline", "--check", "GL001",
                   str(bad)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload["ok"] is False
    assert payload["findings"][0]["check"] == "GL001"

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    rc = cli.main(["--format", "json", "--no-baseline", str(good)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["ok"] is True


def test_nonexistent_path_is_an_error_not_a_green_gate(tmp_path, capsys):
    """A typo'd/renamed CI path must fail loudly — linting 0 files and
    exiting 0 would green-light every hazard class the gate exists for."""
    with pytest.raises(FileNotFoundError):
        core.lint_paths([str(tmp_path / "nope")], root=str(tmp_path),
                        context=core.Context(str(tmp_path)))
    assert cli.main([str(tmp_path / "nope_dir")]) == 2
    capsys.readouterr()


def test_cli_explain_documents_real_bug_provenance(capsys):
    assert cli.main(["--explain", "GL001"]) == 0
    out = capsys.readouterr().out
    assert "PR 2" in out and "rendezvous" in out
    assert cli.main(["--explain", "GL999"]) == 2


def test_all_twelve_checks_are_registered():
    checks = core.all_checks()
    assert set(checks) == {f"GL{i:03d}" for i in range(1, 13)}
    # Interprocedural + registry checks run at program scope; the registry
    # checks additionally need the COMPLETE path set to be sound.
    assert {c for c, v in checks.items() if v.program} \
        == {"GL001", "GL002", "GL009", "GL010", "GL011", "GL012"}
    assert {c for c, v in checks.items() if v.full_program} \
        == {"GL009", "GL011"}


# ------------------------------------------------------ cache / sarif / CLI

def test_cache_program_warm_path_and_file_layer(tmp_path, capsys):
    """Second identical run must hit the whole-program cache; touching one
    file falls back to the per-file layer for the rest, with identical
    findings either way."""
    src_dir = tmp_path / "src"
    (src_dir / "a.py").parent.mkdir(parents=True, exist_ok=True)
    (src_dir / "a.py").write_text(textwrap.dedent(PR2_DEADLOCK))
    (src_dir / "b.py").write_text("x = 1\n")
    cache_dir = str(tmp_path / "cache")
    ctx = core.Context(str(src_dir))

    cache1 = core.LintCache(cache_dir)
    res1 = core.lint_paths([str(src_dir)], root=str(src_dir), cache=cache1,
                           checks=["GL001"], context=ctx)
    assert codes(res1) == ["GL001"] and not cache1.program_hit

    cache2 = core.LintCache(cache_dir)
    res2 = core.lint_paths([str(src_dir)], root=str(src_dir), cache=cache2,
                           checks=["GL001"], context=ctx)
    assert cache2.program_hit
    assert [f.fingerprint for f in res2.findings] \
        == [f.fingerprint for f in res1.findings]

    (src_dir / "b.py").write_text("y = 2\n")
    cache3 = core.LintCache(cache_dir)
    res3 = core.lint_paths([str(src_dir)], root=str(src_dir), cache=cache3,
                           checks=["GL001"], context=ctx)
    assert not cache3.program_hit
    assert cache3.hits == 1 and cache3.misses == 1   # a.py reused, b.py re-run
    assert [f.fingerprint for f in res3.findings] \
        == [f.fingerprint for f in res1.findings]


def test_cache_file_layer_invalidates_on_const_py_change(tmp_path):
    """GL007 reads the flag registry from const.py — a flag deleted THERE
    must invalidate every file's cached result, not just the program
    layer (the per-file key hashes CACHE_EXTRA_INPUTS too)."""
    src_dir = tmp_path / "src"
    const = src_dir / "autodist_tpu" / "const.py"
    const.parent.mkdir(parents=True)
    const.write_text('KNOWN_FLAGS = {"%s": "doc"}\n' % GOOD_FLAG)
    user = src_dir / "autodist_tpu" / "user.py"
    user.write_text('import os\nf = os.environ.get("%s")\n' % GOOD_FLAG)
    cache_dir = str(tmp_path / "cache")
    res1 = core.lint_paths([str(user)], root=str(src_dir),
                           cache=core.LintCache(cache_dir),
                           checks=["GL007"],
                           context=core.Context(str(src_dir)))
    # The direct package read is flagged; the flag NAME is known (1 finding).
    assert sum("unknown flag" in f.message for f in res1.findings) == 0
    # Delete the flag's registration (another stays: an EMPTY registry
    # disables the unknown-flag rule by design).
    const.write_text('KNOWN_FLAGS = {"%s": "doc"}\n' % ("AUTODIST_" + "KEPT"))
    cache2 = core.LintCache(cache_dir)
    res2 = core.lint_paths([str(user)], root=str(src_dir), cache=cache2,
                           checks=["GL007"],
                           context=core.Context(str(src_dir)))
    assert not cache2.program_hit and cache2.hits == 0
    assert sum("unknown flag" in f.message for f in res2.findings) == 1


def test_gl010_multi_target_closed_via_alias_is_clean(tmp_path):
    res = lint_many(tmp_path, {
        "autodist_tpu/res.py": CLOSEABLE_DEF,
        "examples/train.py": """
            from autodist_tpu.res import Producer

            def main():
                a = b = Producer()
                try:
                    next(a)
                finally:
                    b.close()
        """}, checks=["GL010"])
    assert res.ok


def test_doc_text_refuses_unhashed_repo_inputs(tmp_path):
    """A check reading a repo file the cache keys do not hash is a
    structural bug — Context refuses it outright."""
    ctx = core.Context(str(tmp_path))
    assert ctx.doc_text("docs/usage/observability.md") is None   # absent: ok
    with pytest.raises(ValueError, match="CACHE_EXTRA_INPUTS"):
        ctx.doc_text("docs/usage/serving.md")


def test_cache_program_layer_keeps_multiple_slots(tmp_path):
    """A --check-subset run must not evict the full run's warm program
    entry (the pre-commit --changed-only pattern)."""
    src_dir = tmp_path / "src"
    src_dir.mkdir()
    (src_dir / "a.py").write_text(textwrap.dedent(PR2_DEADLOCK))
    cache_dir = str(tmp_path / "cache")
    core.lint_paths([str(src_dir)], root=str(src_dir),
                    cache=core.LintCache(cache_dir), checks=["GL001"],
                    context=core.Context(str(src_dir)))
    # A different selection writes its own slot...
    core.lint_paths([str(src_dir)], root=str(src_dir),
                    cache=core.LintCache(cache_dir), checks=["GL002"],
                    context=core.Context(str(src_dir)))
    # ...and the original selection still hits warm.
    cache3 = core.LintCache(cache_dir)
    core.lint_paths([str(src_dir)], root=str(src_dir), cache=cache3,
                    checks=["GL001"], context=core.Context(str(src_dir)))
    assert cache3.program_hit


def test_cache_prunes_entries_for_deleted_files(tmp_path):
    src_dir = tmp_path / "src"
    src_dir.mkdir()
    a, b = src_dir / "a.py", src_dir / "b.py"
    a.write_text("x = 1\n")
    b.write_text("y = 2\n")
    cache_dir = str(tmp_path / "cache")
    core.lint_paths([str(src_dir)], root=str(src_dir),
                    cache=core.LintCache(cache_dir), checks=["GL001"],
                    context=core.Context(str(src_dir)))
    b.unlink()
    core.lint_paths([str(src_dir)], root=str(src_dir),
                    cache=core.LintCache(cache_dir), checks=["GL001"],
                    context=core.Context(str(src_dir)))
    data = json.loads((tmp_path / "cache" / "cache.json").read_text())
    assert "b.py" not in data["files"] and "a.py" in data["files"]


def test_cache_invalidates_on_baseline_change_without_invalidation(tmp_path):
    """Cached results are RAW (pre-baseline): grandfathering a finding
    takes effect on a fully-warm cache run."""
    src_dir = tmp_path / "src"
    src_dir.mkdir()
    (src_dir / "a.py").write_text(textwrap.dedent(PR2_DEADLOCK))
    cache_dir = str(tmp_path / "cache")
    ctx = core.Context(str(src_dir))
    res1 = core.lint_paths([str(src_dir)], root=str(src_dir),
                           cache=core.LintCache(cache_dir),
                           checks=["GL001"], context=ctx)
    baseline = {f.fingerprint for f in res1.findings}
    cache2 = core.LintCache(cache_dir)
    res2 = core.lint_paths([str(src_dir)], root=str(src_dir), cache=cache2,
                           baseline=baseline, checks=["GL001"], context=ctx)
    assert cache2.program_hit and res2.ok and len(res2.baselined) == 1


def test_skip_full_program_drops_registry_checks_only(tmp_path):
    """--changed-only's engine mode: GL009/GL011 (unsound on a partial
    file set) are skipped; the interprocedural GL001 still runs."""
    files = {
        "autodist_tpu/prod.py": METRIC_PRODUCERS,
        "autodist_tpu/alerts.py": ALERT_DEFAULTS.replace(
            "train.mfu", "train.mfuu"),
        "autodist_tpu/locked.py": PR2_DEADLOCK,
    }
    for relname, source in files.items():
        path = tmp_path / relname
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    ctx = core.Context(str(tmp_path))
    full = core.lint_paths([str(tmp_path)], root=str(tmp_path), context=ctx,
                           checks=["GL001", "GL009"])
    assert sorted(codes(full)) == ["GL001", "GL009"]
    partial = core.lint_paths([str(tmp_path)], root=str(tmp_path),
                              context=core.Context(str(tmp_path)),
                              checks=["GL001", "GL009"],
                              skip_full_program=True)
    assert codes(partial) == ["GL001"]


def test_changed_only_path_discovery():
    """The git-derived path set is repo-relative .py files under the lint
    roots (or None when git is unavailable) — the CLI falls back safely."""
    changed = cli.changed_py_files()
    assert changed is None or all(
        p.endswith(".py") and not os.path.isabs(p) for p in changed)


def test_sarif_output_round_trips(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(PR2_DEADLOCK))
    rc = cli.main(["--format", "sarif", "--no-baseline", "--no-cache",
                   "--check", "GL001", str(bad)])
    sarif = json.loads(capsys.readouterr().out)
    assert rc == 1 and sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "graftlint"
    (result,) = run["results"]
    assert result["ruleId"] == "GL001"
    loc = result["locations"][0]["physicalLocation"]

    # Round-trip: the SARIF location/message reproduces the JSON finding.
    rc = cli.main(["--format", "json", "--no-baseline", "--no-cache",
                   "--check", "GL001", str(bad)])
    payload = json.loads(capsys.readouterr().out)
    (finding,) = payload["findings"]
    assert loc["artifactLocation"]["uri"] == finding["path"]
    assert loc["region"]["startLine"] == finding["line"]
    assert loc["region"]["startColumn"] == finding["col"] + 1
    assert result["message"]["text"] == finding["message"]
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == {"GL001"}
    # The SARIF run is clean-parseable as a whole-file JSON document and
    # carries the schema pointer tools key on.
    assert sarif["$schema"].endswith("sarif-schema-2.1.0.json")


def test_json_output_reports_wall_time_and_cache(tmp_path, capsys):
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    rc = cli.main(["--format", "json", "--no-baseline", "--no-cache",
                   str(good)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["wall_time_s"] >= 0
    assert payload["cache"] == {"enabled": False}
    rc = cli.main(["--format", "json", "--no-baseline",
                   "--cache-dir", str(tmp_path / "c"), str(good)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["cache"]["enabled"] is True


# --------------------------------------------------------------------- GL012

# The Batcher._held shape: a guard inferred from one method's locked write,
# a bare write in the scheduling loop a Thread entry reaches.
GL012_MIXED = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            with self._lock:
                self._count += 1

        def read(self):
            return self._count
"""


def test_gl012_flags_mixed_guarded_bare_attr(tmp_path):
    res = lint(tmp_path, GL012_MIXED, checks=["GL012"])
    assert codes(res) == ["GL012"]
    (f,) = res.findings
    assert "Worker._count" in f.message and "_lock" in f.message
    assert f.scope == "Worker.read"


def test_gl012_thread_entry_reachability(tmp_path):
    # The bare write sits TWO self-calls below the Thread target: the
    # finding needs the intra-family reachability walk, not entry matching.
    res = lint(tmp_path, """
        import threading

        class Pipe:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = 0

            def start(self):
                t = threading.Thread(target=self._run, daemon=True)
                t.start()

            def _run(self):
                self._step()

            def _step(self):
                self._pending -= 1

            def submit(self):
                with self._lock:
                    self._pending += 1
    """, checks=["GL012"])
    assert codes(res) == ["GL012"]
    (f,) = res.findings
    assert "Pipe._pending" in f.message
    assert f.scope == "Pipe._step"


def test_gl012_suppression_with_reason_honored(tmp_path):
    res = lint(tmp_path, """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._count += 1

            def read(self):
                # graftlint: disable=GL012(monotonic progress gauge; one-round staleness is harmless)
                return self._count
    """, checks=["GL012"])
    assert codes(res) == []
    assert [r for _, r in res.suppressed] \
        == ["monotonic progress gauge; one-round staleness is harmless"]


def test_gl012_locked_helper_and_all_guarded_clean(tmp_path):
    # A method only ever CALLED under the guard is credited with it
    # (_inflight_locked idiom), and a fully-guarded class has no finding.
    res = lint(tmp_path, """
        import threading

        class Clean:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self._items += 1

            def _drain_locked(self):
                n = self._items
                self._items = 0
                return n

            def close(self):
                with self._lock:
                    return self._drain_locked()
    """, checks=["GL012"])
    assert codes(res) == []


def test_gl012_cross_class_typed_receiver(tmp_path):
    # Replica.in_flight shape: the guard and the bare read both live in
    # ANOTHER class, reaching the attr through an annotated parameter —
    # shared-object concurrency, no Thread() in sight.
    res = lint(tmp_path, """
        import threading

        class Rep:
            def __init__(self):
                self._lock = threading.Lock()
                self.busy = 0

        class Rt:
            def hit(self, rep: Rep):
                with rep._lock:
                    rep.busy += 1

            def peek(self, rep: Rep):
                return rep.busy
    """, checks=["GL012"])
    assert codes(res) == ["GL012"]
    (f,) = res.findings
    assert "Rep.busy" in f.message
    assert f.scope == "Rt.peek"


def test_gl012_inherited_entry_and_base_call_site(tmp_path):
    # The _BatcherBase shape: the Thread entry AND the guarded call site
    # live on the base class; the override's bare sibling access in the
    # subclass's own loop path must still be found.
    res = lint(tmp_path, """
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()
                self._held = None

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                self._admit()

            def _admit(self):
                raise NotImplementedError

            def close(self):
                with self._lock:
                    self._drain_locked()

            def _drain_locked(self):
                raise NotImplementedError

        class Impl(Base):
            def _drain_locked(self):
                held, self._held = self._held, None
                return held

            def _admit(self):
                self._held = object()
    """, checks=["GL012"])
    assert codes(res) == ["GL012"]
    (f,) = res.findings
    assert "._held" in f.message
    assert f.scope == "Impl._admit"


def test_gl012_ambiguous_guard_and_init_writes_skipped(tmp_path):
    # Two different locks guard writes -> discipline is ambiguous, skip;
    # __init__ self-writes are construction, never "bare" sites.
    res = lint(tmp_path, """
        import threading

        class TwoGuards:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self._n = 0

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._a_lock:
                    self._n += 1

            def other(self):
                with self._b_lock:
                    self._n += 1

            def read(self):
                return self._n
    """, checks=["GL012"])
    assert codes(res) == []


# ----------------------------------------------------------------- crosscheck

def _crosscheck_program(tmp_path, files):
    for relname, source in files.items():
        path = tmp_path / relname
        path.write_text(textwrap.dedent(source))
    mods = {rel: core.Module(str(tmp_path / rel), rel,
                             textwrap.dedent(src))
            for rel, src in files.items()}
    from autodist_tpu.analysis.program import ProgramIndex
    return ProgramIndex(mods)


CROSSCHECK_ORDERED = """
    import threading

    _a_lock = threading.Lock()
    _b_lock = threading.Lock()

    def both():
        with _a_lock:
            with _b_lock:
                pass
"""


def _obs(outer, inner, count=1):
    return {"outer": {"path": outer[0], "name": outer[1], "cls": None},
            "inner": {"path": inner[0], "name": inner[1], "cls": None},
            "count": count}


def test_crosscheck_dynamic_only_cycle_is_a_finding(tmp_path):
    from autodist_tpu.analysis.checks import concurrency
    prog = _crosscheck_program(tmp_path, {"mod.py": "x = 1\n"})
    observed = [_obs(("x.py", "A"), ("y.py", "B")),
                _obs(("y.py", "B"), ("x.py", "A"))]
    findings, unexercised = concurrency.crosscheck(prog, observed)
    assert len(findings) == 1
    assert "cycle" in findings[0].message
    assert "x.py:A" in findings[0].message and "y.py:B" in findings[0].message
    assert unexercised == []


def test_crosscheck_observed_reverse_of_static_edge(tmp_path):
    from autodist_tpu.analysis.checks import concurrency
    prog = _crosscheck_program(tmp_path, {"mod.py": CROSSCHECK_ORDERED})
    observed = [_obs(("mod.py", "_b_lock"), ("mod.py", "_a_lock"))]
    findings, unexercised = concurrency.crosscheck(prog, observed)
    assert len(findings) == 1
    assert "opposite" in findings[0].message
    assert findings[0].path == "mod.py"
    # the static a->b edge itself was never exercised forward
    assert len(unexercised) == 1
    assert unexercised[0]["outer"]["name"] == "_a_lock"


def test_crosscheck_exercised_edge_is_clean(tmp_path):
    from autodist_tpu.analysis.checks import concurrency
    prog = _crosscheck_program(tmp_path, {"mod.py": CROSSCHECK_ORDERED})
    observed = [_obs(("mod.py", "_a_lock"), ("mod.py", "_b_lock"), count=7)]
    findings, unexercised = concurrency.crosscheck(prog, observed)
    assert findings == []
    assert unexercised == []


def test_crosscheck_cli_consumes_sanitizer_artifact(tmp_path, capsys):
    # End-to-end over a REAL module: the staleness service's declared
    # _write_mutex -> _lock order, contradicted by a hand-built observed
    # file (meta header line included — the loader must skip it).
    obs = tmp_path / "observed.jsonl"
    rel = "autodist_tpu/parallel/staleness.py"
    obs.write_text(
        json.dumps({"meta": {"modes": ["locks"]}}) + "\n"
        + json.dumps(_obs((rel, "self._lock"), (rel, "self._write_mutex")))
        + "\n")
    rc = cli.main(["--crosscheck", "--observed", str(obs), rel])
    out = capsys.readouterr().out
    assert rc == 1
    assert "opposite of the static nesting" in out

    # meta-only artifact: nothing observed, static edges all unexercised,
    # still exit 0 (informational).
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"meta": {"modes": ["locks"]}}) + "\n")
    rc = cli.main(["--crosscheck", "--observed", str(empty), rel])
    out = capsys.readouterr().out
    assert rc == 0
    assert "unexercised" in out

    # a missing artifact is a usage error, not a silent green
    rc = cli.main(["--crosscheck", "--observed",
                   str(tmp_path / "nope.jsonl"), rel])
    assert rc == 2


# ------------------------------------------------------------ self-cleanness

def test_repo_is_lint_clean_against_committed_baseline(capsys):
    """The acceptance gate, in-suite: a reintroduced hazard (or a stale
    suppression/baseline edit) fails tier-1 here, not just ci.sh's lint
    stage. Runs the real CLI with the real committed baseline."""
    rc = cli.main([])
    out = capsys.readouterr().out
    assert rc == 0, f"graftlint found new findings:\n{out}"
