"""Pod-scale input-data plane: one async sharded prefetch pipeline.

MLPerf pod-scale experience (PAPERS.md 1909.09756) calls input stalls *the*
dominant bottleneck once compute and comms are tuned, and PR 9/11 made the
stall visible (``train.attr.data_wait``, the ``data_wait_drift`` alert) —
this module is the layer that *hides* it. One producer/queue core serves
every input path in the repo instead of three ad-hoc pipelines:

- :class:`BoundedQueue` — a bounded, closeable, thread-safe FIFO with
  GL005-clean bounded waits. The staging core: the prefetch producers emit
  into one, and the serving batchers' admission queues
  (:mod:`autodist_tpu.serving.batcher`) stage requests on the same class.
- :class:`PrefetchProducer` — a bounded-depth background producer
  (``workers`` threads; source pulls stay serialized and ordered, the
  transform — sharding, stacking, ``device_put`` — parallelizes) that
  re-raises producer exceptions at the consumer, ends cleanly on source
  exhaustion, and shuts down without leaking blocked threads. Telemetry:
  a ``data.producer_wait`` seconds counter (time spent blocked on the host
  loader — the slow loader stays *visible* even when the step no longer
  stalls), a ``data.queue_depth`` gauge, and ``data.prefetch`` spans.
- :func:`prefetch_to_device` — the producer composed with the runner's feed
  layout: pulls host batches, optionally reduces them to this process's
  shard of the global batch (:func:`host_shard` /
  :func:`assemble_global_batch`, keyed off :meth:`DistributedRunner.
  feed_layout`), and issues the async ``shard_batch``/``shard_block``
  transfers ``depth`` ahead so host loading AND host->HBM transfer overlap
  the running step. ``data.loader.device_prefetch`` is a thin wrapper;
  ``train(prefetch_depth=K)`` drives both loops through the same producer.

This module stays jax-free at import time (the serving batcher imports the
queue core and is deliberately jax-free); jax is imported lazily inside the
placement helpers.
"""

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from autodist_tpu import const, telemetry
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock, san_condition, san_event

__all__ = ["BoundedQueue", "QueueClosed", "EMPTY", "PrefetchProducer",
           "prefetch_to_device", "host_shard_rows", "host_shard",
           "assemble_global_batch", "default_prefetch_depth",
           "default_prefetch_workers"]


class QueueClosed(RuntimeError):
    """Raised by :meth:`BoundedQueue.try_put` / empty :meth:`BoundedQueue.get`
    after :meth:`BoundedQueue.close` — and by a consumer iterating a
    :class:`PrefetchProducer` that was closed under it."""


# get()/pop_nowait() "nothing there" sentinel — distinct from any item
# (queues legitimately carry None).
EMPTY = object()


def default_prefetch_depth() -> int:
    """The ``AUTODIST_PREFETCH_DEPTH`` flag's value (0 = synchronous feed)."""
    return max(0, int(const.ENV.AUTODIST_PREFETCH_DEPTH.val))


def default_prefetch_workers() -> int:
    """The ``AUTODIST_PREFETCH_WORKERS`` flag's value (>= 1)."""
    return max(1, int(const.ENV.AUTODIST_PREFETCH_WORKERS.val))


class BoundedQueue:
    """Bounded thread-safe FIFO with close semantics and bounded waits.

    The ONE staging core behind the input plane: prefetch producers emit
    into one, the serving batchers stage admissions on one. Semantics:

    - ``try_put`` never blocks: ``False`` when full, :class:`QueueClosed`
      once closed (better an instant rejection than an unbounded queue).
    - ``put`` blocks in bounded polls until space; returns ``False`` when
      the queue closes under it (a producer's exit signal, not an error).
    - ``get``/``pop_nowait`` DRAIN after close (items enqueued before the
      close are still delivered); an empty closed queue raises
      :class:`QueueClosed` from ``get`` so a consumer can't park forever.
    - every wait is bounded (GL005): waiters poll at :data:`POLL_S` and
      re-check the closed flag, so ``close()`` never strands a thread.
    """

    POLL_S = 0.2   # per-wait bound; loops re-check closed/deadline

    def __init__(self, capacity: int):
        # capacity 0 is a valid reject-everything queue (the serving
        # batcher's max_queue=0 drain configuration): try_put always
        # returns False, put blocks until close.
        if capacity < 0:
            raise ValueError(f"BoundedQueue capacity must be >= 0, got "
                             f"{capacity}")
        self.capacity = int(capacity)
        self._items: collections.deque = collections.deque()
        self._cond = san_condition()
        self._closed = False

    def __len__(self) -> int:
        with self._cond:
            return len(self._items)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def try_put(self, item) -> bool:
        """Non-blocking put: True on success, False when full; raises
        :class:`QueueClosed` once closed."""
        with self._cond:
            if self._closed:
                raise QueueClosed("queue is closed")
            if len(self._items) >= self.capacity:
                return False
            self._items.append(item)
            self._cond.notify_all()
            return True

    def put(self, item, timeout_s: Optional[float] = None) -> bool:
        """Blocking put (bounded polls). True on success; False when the
        queue closed while waiting, or ``timeout_s`` expired."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        with self._cond:
            while True:
                if self._closed:
                    return False
                if len(self._items) < self.capacity:
                    self._items.append(item)
                    self._cond.notify_all()
                    return True
                wait = self.POLL_S
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    wait = min(wait, remaining)
                self._cond.wait(wait)

    def get(self, timeout_s: Optional[float] = None):
        """Next item; :data:`EMPTY` on timeout; :class:`QueueClosed` when
        the queue is closed AND drained (pre-close items still deliver)."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        with self._cond:
            while True:
                if self._items:
                    item = self._items.popleft()
                    self._cond.notify_all()
                    return item
                if self._closed:
                    raise QueueClosed("queue is closed and drained")
                wait = self.POLL_S
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return EMPTY
                    wait = min(wait, remaining)
                self._cond.wait(wait)

    def pop_nowait(self):
        """Non-blocking get: the next item or :data:`EMPTY` (works on a
        closed queue too — the drain path)."""
        with self._cond:
            if not self._items:
                return EMPTY
            item = self._items.popleft()
            self._cond.notify_all()
            return item

    def wait_nonempty(self, timeout_s: float) -> bool:
        """Park (bounded) until an item is available or the queue closes;
        True when an item is waiting."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while not self._items and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(self.POLL_S, remaining))
            return bool(self._items)

    def close(self) -> List[Any]:
        """Close and drain: wakes every blocked putter/getter, returns the
        undelivered items (the serving batcher fails them back to their
        clients). Idempotent."""
        with self._cond:
            self._closed = True
            drained = list(self._items)
            self._items.clear()
            self._cond.notify_all()
            return drained


# ------------------------------------------------------------- the producer

class PrefetchProducer:
    """Bounded-depth background producer with ordered emission.

    ``pull()`` returns the next source item (called under one source lock,
    strictly in order — host loaders and user batch callables are not
    thread-safe) or raises ``StopIteration`` at exhaustion; ``transform``
    (sharding / block stacking / async ``device_put``) runs OUTSIDE the
    source lock, so ``workers > 1`` parallelizes the transform stage while
    emission order stays the pull order (a per-sequence turnstile).

    Consumer contract (the iterator protocol):

    - items arrive in pull order, at most ``depth`` buffered ahead;
    - a producer-side exception re-raises at the consumer's ``next()``, in
      sequence position (items pulled before it deliver first). An error
      FORFEITS the readahead: items other workers pulled past the failing
      sequence are dropped at close, so a one-shot source that was read
      ahead cannot be resumed loss-free by a fresh producer (re-pulling
      would reorder; restart the source instead);
    - source exhaustion ends iteration cleanly (``StopIteration`` — never
      the bare PEP 479 ``RuntimeError`` the old generator path leaked);
    - ``close()`` is prompt even with a producer blocked on a full queue
      or a consumer parked on an empty one (all waits are bounded), and
      idempotent; iterating a closed producer raises :class:`QueueClosed`.

    Telemetry (always-on counters — a few dict ops per batch, the serving
    SLO precedent; spans only when telemetry is enabled):

    - ``<prefix>.producer_wait`` counter: seconds the producer spent
      blocked pulling from the host source. THE slow-loader signal: when
      prefetch hides the stall, ``train.attr.data_wait`` goes quiet but
      this keeps naming the loader.
    - ``<prefix>.producer_batches`` counter, ``<prefix>.queue_depth``
      gauge, and a ``<prefix>.prefetch`` span per produced item.
    """

    JOIN_S = 30.0            # bounded close-side join (threads are daemons)
    NEXT_TIMEOUT_S = 86400.0  # consumer backstop: a wedged producer with no
    #                           end/error marker must not park next() forever

    def __init__(self, pull: Callable[[], Any],
                 transform: Optional[Callable[[Any], Any]] = None,
                 depth: int = 2, workers: int = 1, name: str = "prefetch",
                 metric_prefix: str = "data"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        if workers < 1:
            raise ValueError(f"prefetch workers must be >= 1, got {workers}")
        self._pull = pull
        self._transform = transform
        self._queue = BoundedQueue(depth)
        self._prefix = metric_prefix
        self._src_lock = san_lock()
        self._turn = san_condition()
        self._next_seq = 0        # next pull sequence (under _src_lock)
        self._next_emit = 0       # next sequence allowed to emit (under _turn)
        self._stop = san_event()
        self._src_done = False    # producer side: no more pulls (under _src_lock)
        self._consumer_done = False
        self._wait_c = telemetry.counter(f"{metric_prefix}.producer_wait")
        self._batch_c = telemetry.counter(f"{metric_prefix}.producer_batches")
        self._depth_g = telemetry.gauge(f"{metric_prefix}.queue_depth")
        self._name = name
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"{name}-{i}")
            for i in range(workers)]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------- producer

    def _work(self):
        while not self._stop.is_set():
            kind, value = "item", None
            with self._src_lock:
                if self._src_done or self._stop.is_set():
                    return
                seq = self._next_seq
                self._next_seq += 1
                t0 = time.perf_counter()
                try:
                    value = self._pull()
                except StopIteration:
                    self._src_done = True
                    kind = "end"
                except BaseException as e:  # noqa: BLE001 — re-raised at the
                    self._src_done = True   # consumer, never swallowed
                    kind, value = "error", e
                wait_s = time.perf_counter() - t0
            if kind == "item":
                self._wait_c.inc(wait_s)
                self._batch_c.inc()
                if self._transform is not None:
                    try:
                        with telemetry.span(f"{self._prefix}.prefetch",
                                            seq=seq):
                            value = self._transform(value)
                        if telemetry.enabled():
                            # Census claim on the device-staged batch: per-seq
                            # keys so every in-flight staged item is owned;
                            # tag() prunes consumed (dead-weakref) claims as
                            # new ones arrive, so the registry stays bounded
                            # at roughly the prefetch depth.
                            from autodist_tpu.telemetry import memplane
                            memplane.tag("prefetch", value,
                                         key=f"{self._name}.{seq}")
                    except BaseException as e:  # noqa: BLE001 — same contract
                        with self._src_lock:
                            self._src_done = True
                        kind, value = "error", e
            self._emit(seq, (kind, value))
            if kind != "item":
                return

    def _emit(self, seq: int, payload):
        """Ordered emission: wait (bounded) for this sequence's turn, push,
        advance the turnstile. The advance happens even when the push is
        skipped (stop/closed), so peers waiting on later turns never park."""
        with self._turn:
            while self._next_emit != seq and not self._stop.is_set():
                self._turn.wait(BoundedQueue.POLL_S)
        if not self._stop.is_set():
            self._queue.put(payload)   # False when closed under us: fine
            self._depth_g.set(len(self._queue))
        with self._turn:
            self._next_emit = max(self._next_emit, seq + 1)
            self._turn.notify_all()

    # ------------------------------------------------------------- consumer

    def __iter__(self):
        return self

    def __next__(self):
        if self._consumer_done:
            raise StopIteration
        deadline = time.monotonic() + self.NEXT_TIMEOUT_S
        while True:
            try:
                item = self._queue.get(timeout_s=BoundedQueue.POLL_S)
            except QueueClosed:
                raise QueueClosed("prefetch producer is closed") from None
            if item is EMPTY:
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"prefetch consumer waited "
                        f"{self.NEXT_TIMEOUT_S:.0f}s with no item, end "
                        f"marker, or error — the producer is wedged")
                continue
            self._depth_g.set(len(self._queue))
            kind, value = item
            if kind == "item":
                return value
            self._consumer_done = True
            if kind == "end":
                raise StopIteration
            raise value   # the producer-side exception, at its position

    def queue_depth(self) -> int:
        return len(self._queue)

    def close(self, timeout_s: Optional[float] = None):
        """Stop the workers and drop buffered items. Prompt even with a
        producer blocked on a full queue; a producer parked inside a long
        source pull exits at the pull's return (the join is bounded and the
        threads are daemons — close never hangs the caller)."""
        self._stop.set()
        self._queue.close()
        with self._turn:
            self._turn.notify_all()
        deadline = time.monotonic() + (self.JOIN_S if timeout_s is None
                                       else timeout_s)
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self._stop.set()
            self._queue.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


# ------------------------------------------------------ per-host sharding

def _modal_leading_dim(leaves, batch_rows: Optional[int] = None
                       ) -> Optional[int]:
    """The batch's row count: the most common leading dim across array
    leaves — the runner's modal-batch-dim rule, INCLUDING its refusal to
    guess: two equally common candidate dims raise instead of silently
    sharding the wrong leaf (pass ``batch_rows=`` to resolve explicitly,
    the runner's ``batch_size=`` analogue)."""
    if batch_rows is not None:
        return int(batch_rows)
    dims: Dict[int, int] = {}
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape and len(shape) >= 1:
            dims[shape[0]] = dims.get(shape[0], 0) + 1
    if not dims:
        return None
    top = max(dims.values())
    modal = [d for d, n in dims.items() if n == top]
    if len(modal) > 1:
        raise ValueError(
            f"ambiguous batch dim: leading dims {sorted(modal)} are equally "
            f"common across the batch's leaves; pass batch_rows= to name "
            f"the batch dimension explicitly (the runner's batch_size= "
            f"rule — guessing would silently shard the wrong leaf)")
    return modal[0]


def host_shard_rows(n_rows: int, process_id: int,
                    num_processes: int) -> Tuple[int, int]:
    """The contiguous ``[start, stop)`` row block of an ``n_rows`` global
    batch that process ``process_id`` of ``num_processes`` materializes —
    the canonical per-host layout (process blocks tile the batch in rank
    order). Blocks are disjoint and cover every row exactly once."""
    if not 0 <= process_id < num_processes:
        raise ValueError(f"process_id {process_id} out of "
                         f"[0, {num_processes})")
    if n_rows % num_processes != 0:
        raise ValueError(
            f"global batch of {n_rows} rows does not tile over "
            f"{num_processes} processes; make it divisible")
    per = n_rows // num_processes
    return process_id * per, (process_id + 1) * per


def host_shard(batch: Any, process_id: Optional[int] = None,
               num_processes: Optional[int] = None,
               batch_rows: Optional[int] = None) -> Any:
    """Slice a GLOBAL host batch down to this process's contiguous row
    block (:func:`host_shard_rows`); non-batch leaves (leading dim != the
    modal batch dim — ambiguity raises, ``batch_rows=`` resolves it) pass
    through whole (they replicate). The loader-side half of per-host
    sharded loading — pair with :func:`assemble_global_batch` on the
    device side, or prep the shards with ``shard_files_for_process`` so
    each host never loads foreign rows at all."""
    import jax

    if num_processes is None:
        num_processes = jax.process_count()
    if process_id is None:
        process_id = jax.process_index()
    if num_processes == 1:
        return batch
    leaves = jax.tree_util.tree_leaves(batch)
    n_rows = _modal_leading_dim(leaves, batch_rows)
    if n_rows is None:
        return batch
    start, stop = host_shard_rows(n_rows, process_id, num_processes)

    def cut(leaf):
        shape = getattr(leaf, "shape", None)
        if shape and len(shape) >= 1 and shape[0] == n_rows:
            return leaf[start:stop]
        return leaf

    return jax.tree_util.tree_map(cut, batch)


def assemble_global_batch(runner, local_batch: Any,
                          process_id: Optional[int] = None,
                          num_processes: Optional[int] = None,
                          batch_rows: Optional[int] = None) -> Any:
    """The device-side half of per-host sharded loading: build the GLOBAL
    sharded batch from this process's LOCAL rows, keyed off the runner's
    feed layout (:meth:`DistributedRunner.feed_layout`).

    Each batch leaf arrives as ``[B/num_processes, ...]`` local rows; the
    global array is assembled via per-shard callbacks
    (``jax.make_array_from_callback``), so no process ever materializes
    another's bytes — the ShardedPrefetchedLoader pattern (SNIPPETS.md
    [3]), and the multi-host contract ``place_host_value``'s full-value
    callback path cannot offer. Requires the feed layout to hand this
    process exactly its contiguous row block (the canonical data-major
    mesh layout; a layout that interleaves rows across processes raises
    with the offending range named). Non-batch leaves replicate whole.

    Gradient accumulation's micro layout is not supported here (the
    ``[k, B/k]`` reshape needs the global batch); feed global batches or
    drop accumulation on per-host pipelines."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    layout = runner.feed_layout()
    if layout.accum > 1:
        raise ValueError(
            "assemble_global_batch does not support accumulation_steps > 1 "
            "(the micro-batch [k, B/k] reshape needs the global batch); "
            "feed global batches through shard_batch instead")
    if num_processes is None:
        num_processes = jax.process_count()
    if process_id is None:
        process_id = jax.process_index()
    leaves = jax.tree_util.tree_leaves(local_batch)
    local_rows = _modal_leading_dim(leaves, batch_rows)

    def put(leaf):
        arr = leaf if isinstance(leaf, np.ndarray) else np.asarray(leaf)
        shape = arr.shape
        is_batch = (local_rows is not None and len(shape) >= 1
                    and shape[0] == local_rows)
        if is_batch:
            global_n = shape[0] * num_processes
            if global_n % layout.dp != 0:
                # Replicating is not a fallback here: each process holds
                # DIFFERENT local rows, so an unsplittable batch leaf
                # cannot be assembled at all — name the problem instead
                # of letting the callback fail with a far-away shape error.
                raise ValueError(
                    f"global batch of {global_n} rows "
                    f"({shape[0]} local x {num_processes} processes) does "
                    f"not split over the mesh's data extent "
                    f"(dp={layout.dp}); per-host assembly needs the global "
                    f"row count divisible by dp")
            global_shape = (global_n,) + shape[1:]
            spec = layout.batch_pspec(len(global_shape))
        else:
            global_shape = shape
            spec = P()
        sharding = NamedSharding(layout.mesh, spec)
        off = process_id * shape[0] if is_batch and spec != P() else 0

        def cb(idx):
            rows = idx[0] if idx else slice(None)
            start = rows.start or 0
            stop = rows.stop if rows.stop is not None else global_shape[0]
            if is_batch and spec != P():
                if start < off or stop > off + shape[0]:
                    raise ValueError(
                        f"feed layout asks process {process_id} for rows "
                        f"[{start}, {stop}) outside its local block "
                        f"[{off}, {off + shape[0]}) — the mesh's data axes "
                        f"do not tile processes into contiguous row blocks; "
                        f"feed global batches instead")
                return arr[(slice(start - off, stop - off),) + tuple(idx[1:])]
            return arr[tuple(idx)]

        return jax.make_array_from_callback(global_shape, sharding, cb)

    return jax.tree_util.tree_map(put, local_batch)


# ------------------------------------------------------------ device feed

def _as_pull(source) -> Callable[[], Any]:
    """Normalize a source — iterator/iterable (a :class:`DataLoader`, a
    generator, a list) or a 0-arg callable — into the producer's ``pull``."""
    if callable(source) and not hasattr(source, "__iter__") \
            and not hasattr(source, "__next__"):
        return source
    it = iter(source)
    return lambda: next(it)


def prefetch_to_device(source, runner, depth: int = 2, unroll: int = 1,
                       workers: Optional[int] = None, per_host: bool = False,
                       process_id: Optional[int] = None,
                       num_processes: Optional[int] = None,
                       name: str = "device-prefetch") -> PrefetchProducer:
    """The unified async device feed: a :class:`PrefetchProducer` whose
    transform is the runner's feed remapping, issuing ``shard_batch`` /
    ``shard_block`` transfers ``depth`` ahead so host loading and
    host->HBM transfer both overlap the running step.

    ``source``: a loader / iterable of host batches, or a 0-arg callable.
    With ``unroll=K`` each emitted item is a pre-sharded
    :class:`~autodist_tpu.runner.BatchBlock` of K consecutive batches
    (``depth`` then counts blocks, so ``depth * K`` steps stay in flight);
    a source that exhausts mid-block yields nothing for the partial block
    — the dropped remainder is logged, iteration ends cleanly.

    ``per_host=True``: the source yields this process's LOCAL rows
    (``global_batch / num_processes`` per batch — e.g. a loader over
    ``shard_files_for_process`` shards) and the producer assembles the
    global array from them (:func:`assemble_global_batch`); single-step
    feed only (blocks stack globally).
    """
    if unroll < 1:
        raise ValueError("unroll must be >= 1")
    if workers is None:
        workers = default_prefetch_workers()
    depth = max(1, int(depth))
    src = _as_pull(source)

    if per_host:
        if unroll > 1:
            raise ValueError("per_host prefetch supports unroll=1 only "
                             "(blocks stack the global batch)")
        transform = lambda b: assemble_global_batch(  # noqa: E731
            runner, b, process_id=process_id, num_processes=num_processes)
        return PrefetchProducer(src, transform, depth=depth, workers=workers,
                                name=name)

    if unroll > 1:
        done = [False]

        def pull_block():
            if done[0]:
                raise StopIteration
            blk = []
            for _ in range(unroll):
                try:
                    blk.append(src())
                except StopIteration:
                    done[0] = True
                    if blk:
                        logging.info(
                            "prefetch: source exhausted mid-block; dropping "
                            "the %d-batch remainder (unroll=%d)",
                            len(blk), unroll)
                    raise StopIteration from None
            return blk

        return PrefetchProducer(pull_block, runner.shard_block, depth=depth,
                                workers=workers, name=name)

    return PrefetchProducer(src, runner.shard_batch, depth=depth,
                            workers=workers, name=name)
