"""Model zoo — the training workloads the reference benchmarked.

Counterparts of the reference's examples/benchmark model set (SURVEY.md §2.3):
linear regression smoke (``examples/linear_regression.py``), image classifiers
(``examples/benchmark/imagenet.py``: ResNet/VGG), the lm1b language model
(``examples/lm1b/``), BERT pretraining (``examples/benchmark/bert.py``), and the NCF
recommender (``examples/benchmark/ncf.py``). All are implemented TPU-first: static
shapes, bf16-friendly matmuls sized for the MXU, no data-dependent Python control
flow inside jit.
"""

from autodist_tpu.models.transformer_lm import TransformerLM, TransformerLMConfig
from autodist_tpu.models.resnet import ResNet, ResNet50Config
from autodist_tpu.models.bert import Bert, BertConfig
from autodist_tpu.models.vgg import VGG16
from autodist_tpu.models.ncf import NeuMF, NeuMFConfig
from autodist_tpu.models.densenet import DenseNet, DenseNet121Config
from autodist_tpu.models.inception import InceptionV3, InceptionV3Config
from autodist_tpu.models.lstm_lm import LSTMLMWithHead, LSTMLMConfig
from autodist_tpu.models.moe import MoETransformerLM, MoETransformerLMConfig
from autodist_tpu.models.pipeline_lm import PipelineLM, PipelineLMConfig

__all__ = [
    "TransformerLM", "TransformerLMConfig", "ResNet", "ResNet50Config",
    "Bert", "BertConfig", "VGG16", "NeuMF", "NeuMFConfig",
    "DenseNet", "DenseNet121Config", "InceptionV3", "InceptionV3Config",
    "LSTMLMWithHead", "LSTMLMConfig", "MoETransformerLM", "MoETransformerLMConfig",
    "PipelineLM", "PipelineLMConfig",
]
