"""Forward-only evaluation: no update, no donation, logical shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.strategy import AllReduce, PS, UnevenPartitionedPS


def _loss(p, b):
    return jnp.mean((b["y"] - (b["x"] @ p["w"] + p["b"])) ** 2)


def _params():
    rng = np.random.RandomState(0)
    return {"w": rng.randn(5, 1).astype(np.float32), "b": np.zeros((1,), np.float32)}


def _batch(seed=1):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(32, 5).astype(np.float32),
            "y": rng.randn(32, 1).astype(np.float32)}


def _runner(strategy=None, **kw):
    ad = AutoDist(strategy_builder=strategy or AllReduce())
    return ad.create_distributed_session(_loss, _params(), optax.sgd(0.1),
                                         example_batch=_batch(), **kw)


@pytest.mark.parametrize("strategy_cls", [AllReduce, PS, UnevenPartitionedPS])
def test_evaluate_matches_loss_and_mutates_nothing(strategy_cls):
    runner = _runner(strategy_cls())
    state = runner.init(_params())
    batch = _batch()
    expected = float(_loss({k: jnp.asarray(v) for k, v in _params().items()},
                           {k: jnp.asarray(v) for k, v in batch.items()}))
    got = float(runner.evaluate(state, batch))
    assert got == pytest.approx(expected, rel=1e-6)
    # evaluate() does not donate or mutate: repeated calls on the same state
    # keep working and agree, and the state then trains normally.
    assert float(runner.evaluate(state, batch)) == pytest.approx(expected, rel=1e-6)
    p_before = jax.device_get(runner.logical_params(state))
    state2, _ = runner.run(state, batch)  # run() donates `state`, as documented
    p_after = jax.device_get(runner.logical_params(state2))
    assert not np.allclose(p_before["w"], p_after["w"])  # run() did update
    assert float(runner.evaluate(state2, batch)) < got    # eval sees new params


def test_evaluate_custom_fn_returns_predictions():
    runner = _runner()
    state = runner.init(_params())
    batch = _batch()
    preds = runner.evaluate(state, batch, fn=lambda p, b: b["x"] @ p["w"] + p["b"])
    assert preds.shape == (32, 1)
    expected = batch["x"] @ _params()["w"] + _params()["b"]
    np.testing.assert_allclose(jax.device_get(preds), expected, rtol=1e-5, atol=1e-5)


def test_evaluate_skips_micro_batching():
    runner = _runner(accumulation_steps=4)
    state = runner.init(_params())
    got = float(runner.evaluate(state, _batch()))
    plain = _runner()
    s2 = plain.init(_params())
    assert got == pytest.approx(float(plain.evaluate(s2, _batch())), rel=1e-6)


def test_evaluate_accepts_presharded_accumulation_batch():
    """A batch pre-sharded for an accumulating run() (MicroBatched leaves)
    folds back to logical layout inside evaluate()."""
    runner = _runner(accumulation_steps=4)
    state = runner.init(_params())
    sharded = runner.shard_batch(_batch())  # carries MicroBatched leaves
    got = float(runner.evaluate(state, sharded))
    assert got == pytest.approx(float(runner.evaluate(state, _batch())), rel=1e-6)


def test_evaluate_does_not_disturb_accumulating_run():
    """shard_batch takes the micro factor as a parameter, so evaluate() cannot
    race a concurrent run()'s sharding; interleaved calls stay value-exact."""
    runner = _runner(accumulation_steps=4)
    plain = _runner()
    s_a, s_p = runner.init(_params()), plain.init(_params())
    for i in range(3):
        runner.evaluate(s_a, _batch(7))    # interleaved eval between steps
        s_a, _ = runner.run(s_a, _batch(i))
        s_p, _ = plain.run(s_p, _batch(i))
    a = jax.device_get(runner.logical_params(s_a))
    p = jax.device_get(plain.logical_params(s_p))
    for k in p:
        np.testing.assert_allclose(a[k], p[k], rtol=2e-6, atol=2e-6)


def test_async_step_has_no_evaluate():
    """The async regime's worker-local state is a pass-through template; a
    step.evaluate there would score untrained params, so it is not attached."""
    ad = AutoDist(strategy_builder=PS(sync=False))
    step = ad.function(_loss, _params(), optax.sgd(0.1), example_batch=_batch())
    assert not hasattr(step, "evaluate")
    step.runner.close() if hasattr(step.runner, "close") else None


def test_async_runner_evaluates_authoritative_state_on_chief():
    """runner.evaluate in the async regime scores the parameter service's
    CURRENT state, not the caller's possibly stale handle."""
    ad = AutoDist(strategy_builder=PS(sync=False))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(0.1),
                                           example_batch=_batch())
    try:
        state0 = runner.init(_params())
        batch = _batch()
        before = float(runner.evaluate(state0, batch))
        s = state0
        for _ in range(10):
            s, _ = runner.run(s, batch)
        # Pass the ORIGINAL (stale) handle: must still reflect training.
        after = float(runner.evaluate(state0, batch))
        assert after < before
    finally:
        if hasattr(runner, "close"):
            runner.close()


def test_function_step_evaluate_tracks_training():
    ad = AutoDist(strategy_builder=AllReduce())
    step = ad.function(_loss, _params(), optax.sgd(0.1), example_batch=_batch())
    batch = _batch()
    before = float(step.evaluate(batch))
    for _ in range(10):
        step(batch)
    after = float(step.evaluate(batch))
    assert after < before  # sees the trained (current) state
