"""Fleet router: one front door over N ``InferenceServer`` replicas.

Composes three planes that already exist into fleet throughput (ROADMAP
item 1): the serving wire (``serving/transport.py``), the alert engine's
``serve_p99_burn`` burn-rate rule (``telemetry/alerts.py``), and the PR 14
recovery machinery (``parallel/recovery.py`` + the chief's respawn policy,
promoted to :class:`~autodist_tpu.coordinator.RespawnPolicy`). Policy:

- LEAST-LOADED routing: requests go to the live replica with the fewest
  router-tracked in-flight requests (the queue-slot signals ``status``
  exposes ride along in ``last_status`` for consoles).
- SHED AT ADMISSION: a replica's ``ServeBusy`` (BoundedQueue ``try_put``
  reject, or a full page pool) cascades to the next replica; when every
  replica is busy the router replies with a typed ``ServeBusy`` instantly —
  tail latency is protected by refusing work, never by queueing it.
- ROUTE AROUND DEATH: a connection failure marks the replica down, books an
  eviction, and REPLAYS the in-flight request on a surviving replica with
  the SAME request-id token — the replica-side rid dedup
  (``transport.py``) makes the replay idempotent (GL011 discipline: the
  ``generate`` op is never wire-retried; replay happens here, made safe).
  A dead replica is respawned through the budgeted
  :class:`~autodist_tpu.coordinator.RespawnPolicy`.
- AUTOSCALE OFF ALERTS: the supervisor polls each replica's ``status``; a
  replica whose ``serve_p99_burn`` alert is ACTIVE is drained (no new
  routes, in-flight completes) and a fresh replica is spawned on the same
  respawn budget; when the alert clears the drained replica rejoins.

``Router`` is the embeddable policy object (tests drive ``poll_once()``
deterministically); ``RouterServer`` puts it on the serving wire — plain
``ServeClient`` works unchanged against it, so a fleet is a config change,
not a client change.
"""

import itertools
import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from autodist_tpu import telemetry
from autodist_tpu.telemetry import cluster as _cluster
from autodist_tpu.telemetry import reqtrace as _reqtrace
from autodist_tpu.coordinator import RespawnPolicy
from autodist_tpu.parallel import recovery as _recovery
from autodist_tpu.parallel.ps_transport import _PSClient, PSClientError
from autodist_tpu.serving.batcher import ServeBusy, ServeError
from autodist_tpu.serving.transport import _wire_server
from autodist_tpu.testing import faults as _faults
from autodist_tpu.utils import logging
from autodist_tpu.utils.metrics import WireCounters
from autodist_tpu.testing.sanitizer import san_lock, san_event

# The burn-rate alert that triggers drain + scale-out (telemetry/alerts.py
# DEFAULT_RULES ships it over serve.latency_s.total).
DRAIN_ALERT = "serve_p99_burn"
# Bound on connection-failure replays for ONE request: every retry marks a
# replica down first, so more retries than replicas + respawn budget means
# the fleet is gone, not unlucky.
MAX_REPLAYS = 8


class Replica:
    """Router-side handle on one ``InferenceServer``: the owned server (or
    just an address for external replicas), a small idle-client pool, and
    the routing state (in-flight count, down/draining flags)."""

    def __init__(self, server=None, address: Optional[Tuple[str, int]] = None,
                 generation: int = 0):
        assert server is not None or address is not None
        self.server = server
        self.address = tuple(server.address if server is not None
                             else address)
        self.name = "%s:%d" % self.address
        self.generation = generation
        # Routing state below is written by request threads (in_flight) and
        # the supervisor (down/draining/last_status) while pickers and
        # snapshots read it — every access goes through _lock via the
        # accessors; name/generation/address are immutable after __init__.
        self.in_flight = 0
        self.down = False
        self.draining = False
        self.last_status: dict = {}
        self._lock = san_lock()
        self._idle: List[_PSClient] = []
        self._offset_ns: Optional[int] = None

    # ------------------------------------------------- routing-state access

    def routable(self) -> bool:
        with self._lock:
            return not self.down and not self.draining

    def load(self) -> int:
        with self._lock:
            return self.in_flight

    def is_down(self) -> bool:
        with self._lock:
            return self.down

    def mark_down(self) -> bool:
        """Set ``down``; True exactly once (the caller that books the
        eviction and respawns)."""
        with self._lock:
            if self.down:
                return False
            self.down = True
            return True

    def begin_drain(self) -> bool:
        """Set ``draining``; True exactly once per drain episode."""
        with self._lock:
            if self.draining:
                return False
            self.draining = True
            return True

    def end_drain(self) -> bool:
        """Clear ``draining``; True if this call cleared it."""
        with self._lock:
            if not self.draining:
                return False
            self.draining = False
            return True

    def note_status(self, st: dict):
        with self._lock:
            self.last_status = st

    def snapshot(self) -> dict:
        """One consistent read of the routing state (status-console row)."""
        with self._lock:
            st = self.last_status or {}
            return {"replica": self.name,
                    "generation": self.generation,
                    "in_flight": self.in_flight,
                    "down": self.down,
                    "draining": self.draining,
                    "queue_depth": st.get("queue_depth", 0),
                    "capacity": st.get("capacity", 0)}

    def clock_offset_ns(self) -> int:
        """Replica-minus-router wall-clock offset, NTP-estimated from three
        ``ping`` round-trips (:func:`telemetry.ntp_offset`) and cached for
        the replica's lifetime — the router stamps it into each forwarded
        trace token so the replica can subtract its OWN clock from the
        router's send stamp (wire-vs-queue decomposition). An unreachable
        replica estimates 0 (the route itself will fail and replay); the
        loopback test fleets share one clock, so 0 is also exact there."""
        with self._lock:
            if self._offset_ns is not None:
                return self._offset_ns
        samples = []
        try:
            for _ in range(3):
                t0 = time.time_ns()
                _, s_ns = self.call("ping", t0)
                samples.append((t0, int(s_ns), time.time_ns()))
            off, _err = _cluster.ntp_offset(samples)
        except Exception:
            off = 0
        with self._lock:
            if self._offset_ns is None:
                self._offset_ns = int(off)
            return self._offset_ns

    def call(self, op: str, *args):
        """One wire call on a pooled connection. A ``PSClientError`` is a
        SERVER-level reply over a healthy socket (the connection is
        recycled); transport-level failures discard the socket and
        propagate (the router's death signal)."""
        with self._lock:
            client = self._idle.pop() if self._idle else None
        if client is None:
            # Short connect budget: unlike a PS worker waiting out a chief
            # restart, a replica that refuses connections IS the failure
            # signal the router routes around — don't retry into it.
            client = _PSClient(self.address, connect_timeout=2.0)
        try:
            out = client.call(op, *args)
        except PSClientError:
            with self._lock:
                self._idle.append(client)
            raise
        except BaseException:
            try:
                client.close()
            except Exception:
                pass
            raise
        with self._lock:
            self._idle.append(client)
        return out

    def close(self):
        with self._lock:
            idle, self._idle = self._idle, []
        for client in idle:
            try:
                client.close()
            except Exception:
                pass
        if self.server is not None:
            self.server.close()


class Router:
    """The fleet policy object: spawn/track replicas, route, shed, replay,
    autoscale. ``replica_factory`` builds one fresh ``InferenceServer``
    (used for the initial fleet, dead-replica respawn, and alert-driven
    scale-out); pass ``addresses`` instead to front externally-managed
    replicas (no respawn possible — the supervisor only routes around
    them).

    ``start=False`` leaves the supervisor thread un-started; tests drive
    :meth:`poll_once` by hand for deterministic drain/respawn timing."""

    # Supervisor cadence + backoff (class attrs so tests tighten them,
    # mirroring Coordinator.RESPAWN_BACKOFF_S).
    POLL_S = 1.0
    RESPAWN_BACKOFF_S = 1.0
    RESPAWN_BACKOFF_CAP_S = 30.0

    def __init__(self, replica_factory: Optional[Callable] = None,
                 n_replicas: Optional[int] = None,
                 addresses: Optional[List[Tuple[str, int]]] = None,
                 max_replicas: Optional[int] = None,
                 start: bool = True):
        from autodist_tpu import const
        if replica_factory is None and not addresses:
            raise ValueError("Router needs a replica_factory or addresses")
        n = n_replicas if n_replicas is not None \
            else int(const.ENV.AUTODIST_SERVE_REPLICAS.val)
        self._factory = replica_factory
        self._lock = san_lock()
        self._replicas: List[Replica] = []
        if addresses:
            self._replicas += [Replica(address=a) for a in addresses]
        if replica_factory is not None:
            self._replicas += [Replica(server=replica_factory())
                               for _ in range(max(0, n))]
        self.max_replicas = max_replicas if max_replicas is not None \
            else 2 * len(self._replicas)
        self._policy = RespawnPolicy(self.RESPAWN_BACKOFF_S,
                                     self.RESPAWN_BACKOFF_CAP_S)
        self._rseq = itertools.count()
        self._t_started = time.monotonic()
        reg = telemetry.registry()
        self._m_routed = reg.counter("serve.router.routed")
        self._m_shed = reg.counter("serve.router.shed")
        self._m_replayed = reg.counter("serve.router.replayed")
        self._stop = san_event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self._supervise,
                                            daemon=True,
                                            name="serve-router-supervisor")
            self._thread.start()

    # --------------------------------------------------------------- routing

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    def next_rid(self) -> str:
        """A fresh fleet-scope rid. ``RouterServer`` stamps one onto each
        request whose client sent no token, so every request through the
        front door is dedup-safe and traceable."""
        return f"router-{next(self._rseq)}"

    def _pick(self, tried: List[Replica]) -> Optional[Replica]:
        """Least-loaded live replica not yet tried for this request; ties
        break by fleet order (deterministic). Advisory: state may move
        between the locked reads and the route, and the shed/replay cascade
        absorbs that."""
        cands = [r for r in self.replicas()
                 if r not in tried and r.routable()]
        if not cands:
            return None
        return min(cands, key=lambda r: r.load())

    def generate(self, prompt, max_new_tokens: int, seed: int = 0,
                 timeout: Optional[float] = None,
                 rid: Optional[str] = None):
        """Route one generation. The shed cascade tries every live replica
        on ``ServeBusy`` before rejecting; a connection failure mid-request
        marks the replica down and REPLAYS on a survivor with the same rid
        token (idempotent via the replica-side dedup)."""
        prompt = np.ascontiguousarray(np.asarray(prompt, np.int32))
        seq = next(self._rseq)
        rid = rid if rid is not None else f"router-{seq}"
        _reqtrace.mark(rid, "received", hop=0)
        tried: List[Replica] = []
        replays = 0
        while True:
            rep = self._pick(tried)
            if rep is None:
                self._m_shed.inc()
                _reqtrace.mark(rid, "shed", reason="fleet_busy")
                raise ServeBusy("all replicas are at capacity or "
                                "unavailable; retry later")
            tried.append(rep)
            # Deterministic fault injection (testing/faults.py): a matching
            # worker_crash spec hard-kills this replica NOW — the severed
            # connections exercise the exact replay path a real process
            # death produces.
            if rep.server is not None and _faults.should_fire(
                    "worker_crash", step=seq, worker=rep.name):
                rep.server.kill()
            with rep._lock:
                rep.in_flight += 1
            # Trace context rides the wire only when the request plane is
            # armed: ``(rid, send_wall_ns, hop, offset_ns)`` trailing the
            # plain 5-tuple. hop counts replays, so a replayed request
            # renders as ONE trace with a visible failover; offset_ns is
            # this replica's clock minus ours, so the replica can split
            # wire time out of its queue time.
            if _reqtrace.enabled():
                send_wall = time.time_ns()
                _reqtrace.mark(rid, "sent", replica=rep.name, hop=replays,
                               send_wall_ns=send_wall)
                extra = (rid, send_wall, replays, rep.clock_offset_ns())
            else:
                extra = (rid,)
            try:
                tokens, timing = rep.call(
                    "generate", prompt, int(max_new_tokens), int(seed),
                    timeout, *extra)
            except PSClientError as e:
                if str(e).startswith("ServeBusy:"):
                    continue          # shed cascade: next replica
                # Any other server-shipped error is deterministic — the
                # reply to this client, not a reason to retry elsewhere.
                raise ServeError(str(e)) from None
            except (ConnectionError, OSError):
                # The replica died with this request in flight: route
                # around it and re-admit elsewhere (same rid = idempotent).
                self._on_replica_failure(rep)
                self._m_replayed.inc()
                replays += 1
                _reqtrace.mark(rid, "replayed", replica=rep.name,
                               hop=replays)
                if replays >= MAX_REPLAYS:
                    raise ServeError(
                        f"request {rid} lost {replays} replicas; fleet "
                        f"unavailable") from None
                tried = []   # busy replicas may have drained; retry them
                continue
            finally:
                with rep._lock:
                    rep.in_flight -= 1
            self._m_routed.inc()
            _reqtrace.mark(rid, "finished", replica=rep.name)
            return np.asarray(tokens), timing

    # ------------------------------------------------- failure + autoscaling

    def _on_replica_failure(self, rep: Replica):
        """Mark ``rep`` down exactly once, book the eviction, respawn a
        replacement through the budgeted policy."""
        if not rep.mark_down():
            return
        logging.warning("router: replica %s is down; routing around it",
                        rep.name)
        _recovery.log_eviction(rep.name, kind="dead")
        self._respawn_replica(rep)

    def _respawn_replica(self, rep: Replica):
        if self._factory is None:
            return
        delay = self._policy.grant(rep.name)   # books recovery.log_respawn
        if delay is None:
            logging.error("router: respawn budget for %s is spent "
                          "(AUTODIST_RECOVER_MAX); replica stays down",
                          rep.name)
            return
        time.sleep(delay)                      # bounded: RESPAWN_BACKOFF_CAP_S
        try:
            new = Replica(server=self._factory(),
                          generation=rep.generation + 1)
            _recovery.log_rejoin(new.name, new.generation)
        except Exception as e:
            logging.error("router: respawn of %s failed (%s)", rep.name, e)
            return
        with self._lock:
            try:
                self._replicas[self._replicas.index(rep)] = new
            except ValueError:
                self._replicas.append(new)
        try:
            rep.close()
        except Exception:
            pass
        logging.info("router: replica %s respawned as %s (generation %d)",
                     rep.name, new.name, new.generation)

    def _scale_out(self, rep: Replica):
        """``serve_p99_burn`` fired on ``rep``: drain it (no new routes;
        in-flight completes) and spawn a fresh replica on the SAME respawn
        budget — fault recovery promoted to autoscaling."""
        if not rep.begin_drain():
            return
        logging.warning("router: replica %s draining (%s active)",
                        rep.name, DRAIN_ALERT)
        n_live = sum(not r.is_down() for r in self.replicas())
        if self._factory is None or n_live >= self.max_replicas:
            return
        delay = self._policy.grant(f"scaleout:{rep.name}")
        if delay is None:
            return
        time.sleep(delay)
        try:
            new = Replica(server=self._factory())
            _recovery.log_rejoin(new.name, new.generation)
        except Exception as e:
            logging.error("router: scale-out replica failed (%s)", e)
            return
        with self._lock:
            self._replicas.append(new)
        logging.info("router: scaled out to %s while %s drains",
                     new.name, rep.name)

    def poll_once(self):
        """One supervisor round: poll every replica's ``status``; a failed
        poll is a death (evict + respawn), an active ``serve_p99_burn``
        drains the replica + scales out, a cleared alert rejoins it."""
        for rep in self.replicas():
            if rep.is_down():
                continue
            try:
                st = rep.call("status")[0]
            except Exception:
                self._on_replica_failure(rep)
                continue
            rep.note_status(st)
            active = {a.get("rule")
                      for a in (st.get("alerts") or {}).get("active", [])}
            if DRAIN_ALERT in active:
                self._scale_out(rep)
            elif rep.end_drain():
                _recovery.log_rejoin(rep.name, rep.generation)
                logging.info("router: replica %s rejoined (alert cleared)",
                             rep.name)

    def _supervise(self):
        while not self._stop.wait(self.POLL_S):
            try:
                self.poll_once()
            except Exception as e:   # the supervisor must outlive one bad poll
                logging.warning("router supervisor: %s", e)

    # ---------------------------------------------------------------- status

    def fleet_snapshot(self) -> List[dict]:
        return [rep.snapshot() for rep in self.replicas()]

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        for rep in self.replicas():
            try:
                rep.close()
            except Exception:
                pass


class RouterServer:
    """The router on the serving wire: same opcode vocabulary as
    :class:`~autodist_tpu.serving.transport.InferenceServer` (``generate``/
    ``stats``/``status``/``ping``), so a plain ``ServeClient`` fronts the
    whole fleet. Binds ``AUTODIST_ROUTER_ADDR`` when set, else loopback on
    an ephemeral port."""

    def __init__(self, router: Router, host: Optional[str] = None,
                 port: Optional[int] = None):
        from autodist_tpu import const
        if host is None and port is None:
            addr = str(const.ENV.AUTODIST_ROUTER_ADDR.val)
            if addr:
                h, sep, p = addr.rpartition(":")
                host, port = (h, int(p)) if sep else (addr, 0)
        if host is None or port is None:
            env_host, env_port = ("127.0.0.1", 0)
            host = env_host if host is None else host
            port = env_port if port is None else port
        self._router = router
        self._t_started = time.monotonic()
        self.wire = WireCounters()
        self._conns: set = set()
        self._server = _wire_server(host, port, self)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        logging.info("RouterServer fronting %d replicas on %s:%d",
                     len(router.replicas()), *self._server.server_address)

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def status_snapshot(self) -> dict:
        """Live-ops view (``kind="router"``): router counters + the
        per-replica fleet table + the shared alert/recovery sections, so
        adtop/adfleet render a router endpoint next to its replicas."""
        from autodist_tpu.parallel import recovery as _rec
        from autodist_tpu.telemetry import alerts as _alerts
        from autodist_tpu.telemetry import memplane as _memplane
        return {"registry": telemetry.snapshot(),
                "wire": self.wire.snapshot(),
                "uptime_s": round(time.monotonic() - self._t_started, 3),
                "kind": "router",
                "replicas": self._router.fleet_snapshot(),
                "alerts": _alerts.alerts_snapshot(),
                "recovery": _rec.recovery_snapshot(),
                "memory": _memplane.memory_snapshot(),
                "events": telemetry.events()}

    def _dispatch(self, msg, sp=None):
        if not isinstance(msg, tuple) or not msg \
                or not isinstance(msg[0], str):
            return ("error", "ServeError",
                    f"malformed protocol message: expected (op, ...) tuple, "
                    f"got {type(msg).__name__}")
        op = msg[0]
        try:
            if op == "generate":
                # Same arity contract as the replica arm, trailing rid
                # included — a client-supplied dedup token is honored
                # end to end; absent one, the router mints the fleet-scope
                # rid HERE so the transport span carries it (span-ring and
                # reqtrace records join on this id).
                _, prompt, max_new, seed, timeout, *rest = msg
                rid = str(rest[0]) if rest else self._router.next_rid()
                if sp is not None:
                    sp.set(rid=rid)
                tokens, timing = self._router.generate(
                    prompt, int(max_new), seed=int(seed), timeout=timeout,
                    rid=rid)
                return ("ok", tokens, timing)
            if op == "stats":
                return ("ok", self.status_snapshot())
            if op == "status":
                return ("ok", self.status_snapshot())
            if op == "trace":
                # Span-ring pull: the router process's lane in the merged
                # fleet timeline (tools/adtrace.py).
                since = msg[1] if len(msg) > 1 else None
                return ("ok", telemetry.local_trace_state(since_ns=since))
            if op == "reqtrace":
                # Request-lifecycle pull: the router-side marks (received/
                # sent/replayed/shed/finished) for the fleet merge.
                since = msg[1] if len(msg) > 1 else None
                return ("ok",
                        telemetry.local_reqtrace_state(since_ns=since))
            if op == "ping":
                return ("ok", msg[1] if len(msg) > 1 else None,
                        time.time_ns())
            return ("error", "ServeError", f"unknown op {op!r}")
        except Exception as e:  # ship the failure to the client, keep serving
            return ("error", type(e).__name__, str(e))

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._router.close()
        if self.wire.msgs_received:
            logging.info("RouterServer closed: %s | up %.1fs",
                         self.wire.format_line(),
                         time.monotonic() - self._t_started)
