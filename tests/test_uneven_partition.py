"""Physical uneven partitioning: padded storage + masked updates.

The reference sliced remainder shards for real (``kernel/partitioner.py:660-704``);
XLA shardings need even tiles, so the TPU-native form is zero-padded storage on the
partition mesh axis with the logical view sliced back around the user's loss
(``parallel/plan.py`` pad/unpad). These tests prove the parameter is *actually*
sharded (not silently replicated) and that training stays value-exact vs a
single-device run — the reference's c0 criterion.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu import AutoDist, ResourceSpec
from autodist_tpu.strategy import AllReduce, UnevenPartitionedPS

LR = 0.1
BATCH = 16

# 8 devices: model axis 4 (neither 7 nor 3 tiles evenly), data absorbs the rest.
SPEC = ResourceSpec(resource_info={
    "nodes": [{"address": "localhost", "tpus": 8, "chief": True}],
    "mesh": {"model": 4, "data": -1},
})


def _data(seed=7):
    rng = np.random.RandomState(seed)
    x = rng.randn(BATCH, 7).astype(np.float32)
    y = rng.randn(BATCH, 3).astype(np.float32)
    return {"x": x, "y": y}


def _params():
    rng = np.random.RandomState(0)
    return {"w": jnp.asarray(rng.randn(7, 3), jnp.float32),
            "b": jnp.asarray(rng.randn(3), jnp.float32)}


def _loss(p, b):
    pred = b["x"] @ p["w"] + p["b"]
    return jnp.mean((b["y"] - pred) ** 2)


def _single_device_step(params, batch, steps=1):
    """Reference: plain jax.grad SGD, no mesh, logical shapes."""
    p = {k: np.asarray(v) for k, v in params.items()}
    for _ in range(steps):
        g = jax.grad(_loss)({k: jnp.asarray(v) for k, v in p.items()}, batch)
        p = {k: p[k] - LR * np.asarray(g[k]) for k in p}
    return p


def _make_runner():
    ad = AutoDist(SPEC, UnevenPartitionedPS())
    params = _params()
    runner = ad.create_distributed_session(
        _loss, params, optax.sgd(LR), example_batch=_data())
    return runner, params


def test_storage_is_physically_sharded_and_padded():
    runner, params = _make_runner()
    state = runner.init(params)
    w, b = state.params["w"], state.params["b"]
    # 7 -> 8 and 3 -> 4 along the 4-way model axis.
    assert w.shape == (8, 3)
    assert b.shape == (4,)
    assert w.sharding.spec == P("model", None) or w.sharding.spec == P("model")
    assert b.sharding.spec == P("model")
    # Each device holds a 2-row tile of w, not the full matrix.
    shard_shapes = {s.data.shape for s in w.addressable_shards}
    assert shard_shapes == {(2, 3)}
    # Pad region is zero.
    np.testing.assert_array_equal(np.asarray(w)[7:], 0.0)
    np.testing.assert_array_equal(np.asarray(b)[3:], 0.0)


def test_one_step_value_exact_vs_single_device():
    batch = _data()
    runner, params = _make_runner()
    state = runner.init(params)
    state, loss = runner.run(state, batch)
    want = _single_device_step(params, batch)
    got = runner.logical_params(state)
    assert np.asarray(got["w"]).shape == (7, 3)
    np.testing.assert_allclose(np.asarray(got["w"]), want["w"], rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got["b"]), want["b"], rtol=1e-5, atol=1e-6)
    # Pad region still zero after the update (masked update).
    np.testing.assert_array_equal(np.asarray(state.params["w"])[7:], 0.0)


def test_multi_step_training_converges_and_pad_stays_zero():
    batch = _data()
    runner, params = _make_runner()
    state = runner.init(params)
    losses = []
    for _ in range(10):
        state, loss = runner.run(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    np.testing.assert_array_equal(np.asarray(state.params["w"])[7:], 0.0)
    want = _single_device_step(params, batch, steps=10)
    got = runner.logical_params(state)
    np.testing.assert_allclose(np.asarray(got["w"]), want["w"], rtol=1e-4, atol=1e-5)


def test_checkpoint_roundtrip_is_strategy_independent(tmp_path):
    """Save from padded-uneven storage, restore into an AllReduce runner: the
    checkpoint must carry logical shapes (original names, reference saver.py:47-61)."""
    from autodist_tpu.checkpoint import Saver

    batch = _data()
    runner, params = _make_runner()
    state = runner.init(params)
    state, _ = runner.run(state, batch)

    saver = Saver()
    # No plan argument: the TrainState carries its runner's plan, so unpadding to
    # logical shapes is automatic.
    prefix = saver.save(state, str(tmp_path / "ckpt"))

    # Manifest records logical shapes.
    restored_flat = saver.restore_params(prefix)
    assert restored_flat["w"].shape == (7, 3)
    assert restored_flat["b"].shape == (3,)

    ad2 = AutoDist(strategy_builder=AllReduce())
    runner2 = ad2.create_distributed_session(
        _loss, params, optax.sgd(LR), example_batch=batch)
    state2 = saver.restore(prefix, runner=runner2)
    np.testing.assert_allclose(
        np.asarray(state2.params["w"]),
        np.asarray(runner.logical_params(state)["w"]), rtol=1e-6)

    # And back into a fresh uneven runner (restore re-pads).
    runner3, _ = _make_runner()
    state3 = saver.restore(prefix, runner=runner3)
    assert state3.params["w"].shape == (8, 3)
    np.testing.assert_allclose(
        np.asarray(runner3.logical_params(state3)["w"]),
        np.asarray(runner.logical_params(state)["w"]), rtol=1e-6)
