"""Cross-process strategy-matrix script (driver in test_multiprocess.py).

The reference's 2-machine CI stage ran its full strategy dict across nodes
(``tests/integration/test_dist.py:14-42``, ``Jenkinsfile:91-131``). This script
is the TPU-native equivalent for the lowerings whose cross-process sharding is
non-trivial:

- ``ps``          — PS/ZeRO: Adam opt state physically sharded along ``reduce``
                    across the 2-process mesh.
- ``partitioned`` — UnevenPartitionedPS: model-axis storage including a
                    padded-uneven parameter (7 rows on a 2-way model axis).
- ``parallax``    — the explicit ``shard_map`` lowering: sparse (indices, rows)
                    wire for the embedding + BF16_EF compressed dense params.

Each config runs 3 steps through the public API. Two modes, selected by env
``AUTODIST_MATRIX_SINGLE``:

- unset: 2-process mode — the chief runs this script, the Coordinator
  re-executes it as the worker, both join one ``jax.distributed`` program over
  a 4-device (2 proc x 2 CPU devices) mesh.
- "1": single-process reference — same strategy on a 4-device single-process
  mesh. Identical global mesh => identical shard count => identical collective
  and bf16-rounding behavior, so the 2-process run must match value-exactly.

The chief writes final logical params, per-step losses, and physical-sharding
evidence (shard shapes, padded storage shapes, sparse-wire/EF flags) to the
JSON path in argv[1]; argv[2] picks the config.

An optional argv[3] phase drives the checkpoint legs (the reference's c10
2-node NFS saver contract, ``tests/integration/cases/c10.py:1-12``, against
cross-process-sharded state). ``AUTODIST_MATRIX_CKPT_DIR`` names the shared
checkpoint directory:

- ``ckpt_save``     — steps 0..2, then every process calls ``Saver.save``
                      (collective sharded save) and the program EXITS (the kill).
- ``ckpt_restore``  — a fresh 2-process program restores the latest checkpoint
                      (each process placing its own shards) and continues
                      steps 3..4.
- ``straight``      — 5 uninterrupted steps (the value-exact reference).
- ``train_save`` / ``train_resume`` — same protocol driven entirely through
  ``training.train`` (collective save + automatic resume inside the loop).
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist  # noqa: E402
from autodist_tpu.resource_spec import ResourceSpec  # noqa: E402
from autodist_tpu.strategy import (AllReduce, PS, Parallax,  # noqa: E402
                                   PartitionedAR, UnevenPartitionedPS)

BATCH = 16
LR = 0.05
STEPS = 3
STEPS_TOTAL = 5   # checkpoint legs: save after 3, continue to 5
VOCAB, DIM = 33, 4

SINGLE = os.environ.get("AUTODIST_MATRIX_SINGLE") == "1"
# Process count for the distributed mode (2 devices per process). The single-
# process reference uses one node with the same GLOBAL device count, so the
# mesh — and therefore collective/rounding behavior — is identical.
PROCS = int(os.environ.get("AUTODIST_MATRIX_PROCS", "2"))


def _spec(mesh=None):
    if SINGLE:
        nodes = [{"address": "localhost", "tpus": 2 * PROCS, "chief": True}]
    else:
        # Node addresses must be unique (the reference's cluster-spec key
        # contract); distinct 127/8 loopback IPs model multiple processes on
        # one host and all take the local launch fast path.
        nodes = [{"address": "localhost", "tpus": 2, "chief": True}] + \
                [{"address": f"127.0.0.{i + 2}", "tpus": 2}
                 for i in range(PROCS - 1)]
    info = {"nodes": nodes}
    if mesh:
        info["mesh"] = mesh
    return ResourceSpec(resource_info=info)


def make_batch(step: int):
    rng = np.random.RandomState(2000 + step)
    return {"idx": rng.randint(0, VOCAB, (BATCH,)),
            "x": rng.randn(BATCH, 7).astype(np.float32),
            "y": rng.randn(BATCH, DIM).astype(np.float32)}


def make_params():
    rng = np.random.RandomState(5)
    return {"emb": rng.randn(VOCAB, DIM).astype(np.float32) * 0.1,
            "wu": rng.randn(7, DIM).astype(np.float32) * 0.1,   # uneven dim0
            "w2": rng.randn(DIM, DIM).astype(np.float32) * 0.1,
            "b": np.zeros((DIM,), np.float32)}


def loss_fn(p, b):
    rows = jnp.take(p["emb"], b["idx"], axis=0)        # sparse gather
    h = rows + b["x"] @ p["wu"]
    pred = h @ p["w2"] + p["b"]
    return jnp.mean((b["y"] - pred) ** 2)


CONFIGS = {
    # PS/ZeRO: full weight-update sharding; Adam states shard along reduce.
    "ps": dict(builder=lambda: PS(), mesh=None,
               optimizer=lambda: optax.adam(1e-2)),
    # Model-axis storage with a padded-uneven param (7 -> 8 over 2 shards);
    # Adam, so the moments live padded + model-sharded across processes too.
    "partitioned": dict(builder=lambda: UnevenPartitionedPS(),
                        mesh={"model": 2, "data": -1},
                        optimizer=lambda: optax.adam(1e-2)),
    # Explicit shard_map lowering: sparse wire + BF16_EF on dense grads.
    "parallax": dict(
        builder=lambda: Parallax(compressor="HorovodCompressorEF"),
        mesh=None, optimizer=lambda: optax.sgd(LR)),
    # Hierarchical two-phase reduce across the process boundary: the inner
    # `reduce` axis lies within each process's 2 devices (the ICI tier on a
    # real pod), the outer `data` axis spans the two processes (the DCN tier).
    # jax.devices() lists process 0's devices first, so the row-major [data,
    # reduce] mesh puts reduce innermost-per-process by construction.
    "dcn": dict(
        builder=lambda: AllReduce(all_reduce_spec="DCN",
                                  compressor="HorovodCompressor",
                                  chunk_size=4),
        mesh={"data": 2, "reduce": 2},
        optimizer=lambda: optax.sgd(LR)),
    # Low-rank PowerSGD factors (P/Q matmuls + QR + two factor pmeans) across
    # the process boundary; deterministic, so exact vs single-process.
    "powersgd": dict(
        builder=lambda: AllReduce(compressor="PowerSGDCompressor",
                                  power_sgd_rank=2),
        mesh=None, optimizer=lambda: optax.sgd(LR)),
    # The 3-tier mesh for the 4-process leg (AUTODIST_MATRIX_PROCS=4,
    # 8 devices): model axis INSIDE each process's 2 devices (padded-uneven
    # storage never crosses a process), reduce ACROSS process pairs (Adam
    # moments ZeRO-sharded over the process boundary), data across the pair
    # groups. Mesh axis order is (data, reduce, model) row-major over
    # jax.devices(), which lists processes in order — so the coordinates
    # land exactly there by construction.
    "tp_zero": dict(builder=lambda: UnevenPartitionedPS(),
                    mesh={"model": 2, "reduce": 2, "data": -1},
                    optimizer=lambda: optax.adam(1e-2)),
    # PartitionedAR: model-axis storage sharding (incl. padded-uneven wu,
    # 7 -> 8) with all-reduce gradient sync. Canonical axis order puts data
    # outermost, so on 2 processes the model shards live IN-process and the
    # per-shard gradient all-reduce is what crosses the boundary — the
    # partitioned-storage + cross-process-AR lowering the other configs
    # don't cover. (tp_zero is the config whose storage spans processes.)
    "par": dict(builder=lambda: PartitionedAR(),
                mesh={"model": 2, "data": -1},
                optimizer=lambda: optax.adam(1e-2)),
}


def _shard_evidence(state, runner):
    """Physical-sharding facts the driver asserts (chief's local view)."""
    from autodist_tpu.parallel.synchronization import EFState
    ev = {}
    w2_opt_shards = None
    for leaf in jax.tree_util.tree_leaves(state.opt_state):
        if getattr(leaf, "ndim", 0) == 2 and leaf.shape[-1] == DIM \
                and leaf.shape[0] == DIM:
            w2_opt_shards = sorted({tuple(s.data.shape)
                                    for s in leaf.addressable_shards})
            break
    ev["w2_opt_shard_shapes"] = w2_opt_shards
    ev["wu_storage_shape"] = list(state.params["wu"].shape)
    ev["wu_shard_shapes"] = sorted({tuple(s.data.shape)
                                    for s in state.params["wu"].addressable_shards})
    ev["sparse_wire_params"] = sorted(runner.plan.sparse_wire_params)
    ef = state.ef_state
    leaves = jax.tree_util.tree_leaves(
        ef, is_leaf=lambda x: isinstance(x, EFState))
    ev["ef_params_dp"] = sorted(
        int(l.error.shape[0]) for l in leaves if isinstance(l, EFState))
    return ev


def main(out_path: str, config: str, phase: str = ""):
    cfg = CONFIGS[config]
    ad = AutoDist(_spec(cfg["mesh"]), cfg["builder"]())
    params = make_params()
    runner = ad.create_distributed_session(
        loss_fn, params, cfg["optimizer"](), example_batch=make_batch(0))
    if not SINGLE:
        assert jax.process_count() == PROCS, \
            f"process_count={jax.process_count()} != {PROCS}"
    assert jax.device_count() == 2 * PROCS, \
        f"device_count={jax.device_count()} != {2 * PROCS}"

    ckpt_dir = os.environ.get("AUTODIST_MATRIX_CKPT_DIR")

    if phase in ("train_save", "train_resume"):
        # The whole c10 protocol driven through training.train: collective
        # sharded saves inside the loop, automatic latest-checkpoint resume.
        from autodist_tpu.training import train
        steps = STEPS if phase == "train_save" else STEPS_TOTAL
        state = train(runner, params, make_batch, steps=steps,
                      checkpoint_dir=ckpt_dir, checkpoint_name="trainloop",
                      save_every=10_000, log_every=0)
        if phase == "train_resume":
            assert int(state.step) == STEPS_TOTAL, int(state.step)
        _write_result(out_path, config, runner, state, losses=[],
                      extra={"step": int(state.step),
                             "ckpt_files": _ckpt_listing(ckpt_dir)})
        return

    from autodist_tpu.checkpoint.saver import Saver
    if phase == "ckpt_restore":
        latest = Saver.latest_checkpoint(ckpt_dir, name="model")
        assert latest is not None, f"no checkpoint under {ckpt_dir}"
        state = Saver().restore(latest, runner=runner)
        assert int(state.step) == STEPS, int(state.step)
        lo, hi = STEPS, STEPS_TOTAL
    else:
        state = runner.init(params)
        lo, hi = 0, (STEPS_TOTAL if phase == "straight" else STEPS)

    evidence = _shard_evidence(state, runner)
    losses = []
    for step in range(lo, hi):
        state, loss = runner.run(state, make_batch(step))
        losses.append(float(loss))

    if phase == "ckpt_save":
        # COLLECTIVE: every process writes the state shards it owns; the chief
        # publishes the manifest. The program exits right after — the "kill".
        Saver().save(state, os.path.join(ckpt_dir, "model"), runner=runner)
        evidence["ckpt_files"] = _ckpt_listing(ckpt_dir)

    _write_result(out_path, config, runner, state, losses, extra=evidence)


def _ckpt_listing(ckpt_dir):
    if jax.process_index() != 0:
        return []
    return sorted(os.listdir(ckpt_dir))


def _write_result(out_path, config, runner, state, losses, extra):
    if jax.process_index() != 0:
        return
    logical = jax.device_get(runner.logical_params(state))
    result = {
        "config": config,
        "losses": losses,
        "params": {k: np.asarray(v).tolist() for k, v in logical.items()},
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "mesh": {k: int(v) for k, v in dict(runner.mesh.shape).items()},
        **extra,
    }
    with open(out_path, "w") as f:
        json.dump(result, f)


def run_single_reference(out_path: str, config: str, workdir: str,
                         timeout: int = 300, phase: str = ""):
    """Run this script once, single-process, on a sim mesh matching the
    multi-process run's global device count (2 devices per process)."""
    import subprocess

    from tests.mp_env import repo_root, single_reference_env
    procs = int(os.environ.get("AUTODIST_MATRIX_PROCS", "2"))
    env = single_reference_env(workdir, device_count=2 * procs)
    args = [sys.executable, os.path.abspath(__file__), out_path, config]
    if phase:
        args.append(phase)
    return subprocess.run(args, env=env, cwd=repo_root(), capture_output=True,
                          text=True, timeout=timeout)


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2], sys.argv[3] if len(sys.argv) > 3 else "")
