"""GL003 — use-after-donate.

``runner.py`` builds its compiled steps with ``donate_argnums=(0,)``: the
input TrainState's buffers are handed to XLA for in-place reuse, and reading
the donated tree afterwards raises (or, on some backends, returns freed
memory). The hazard is invisible at the call site — the variable still looks
alive in Python — so this check tracks locals passed at donated positions and
flags later reads.
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, Module, register


def _donated_positions(call: ast.Call) -> Optional[Set[int]]:
    """Literal donate_argnums positions of a ``jax.jit(...)`` call, when
    statically knowable (int or tuple/list of ints); None otherwise."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            out = set()
            for elt in v.elts:
                if not (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, int)):
                    return None
                out.add(elt.value)
            return out
        return None
    return None


def _scopes(tree: ast.Module):
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _walk_scope(scope):
    """Nodes of this scope's OWN executed flow (if/try bodies included,
    nested defs excluded — they are yielded by :func:`_scopes` separately)."""
    starts = scope.body \
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)) \
        else [scope]
    for start in starts:
        yield from callgraph.walk_executed(start)


@register("GL003", "use of a buffer after donation to a jitted call")
def check_use_after_donate(module: Module, ctx: Context) -> List[Finding]:
    """GL003 — use-after-donate.

    Within one function (or module) scope: a name assigned
    ``f = jax.jit(g, donate_argnums=...)`` with literal argnums, later called
    ``f(x, ...)`` with a plain variable at a donated position, and that
    variable read again afterwards (before any rebinding) — flagged at the
    offending read. Donated buffers are deleted by XLA on dispatch; the read
    raises ``RuntimeError: Array has been deleted`` at best. The repo-wide
    convention this encodes: after ``new_state = step_fn(state, batch)`` the
    old ``state`` is dead (see ``DistributedRunner.run``), and the async
    runners disable donation entirely because stale workers legitimately
    hold old parameter snapshots (``AsyncPSRunner.__init__``).

    Only same-scope, literal-argnums flows are tracked; dynamic wiring (like
    runner.py's ``donate = (0,) if self._donate else ()``) is out of scope by
    design — the check is a tripwire for the common direct pattern, not an
    escape analysis.
    """
    if module.tree is None:
        return []
    findings: List[Finding] = []
    for scope in _scopes(module.tree):
        # jitted-with-donation names assigned anywhere in THIS scope's own
        # flow (if/try bodies included; nested defs are their own scope —
        # walk_executed keeps the per-scope analyses disjoint).
        donors: Dict[str, Set[int]] = {}
        for node in _walk_scope(scope):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                fn = callgraph.dotted_name(node.value.func) or ""
                if fn == "jit" or fn.endswith(".jit"):
                    positions = _donated_positions(node.value)
                    if positions:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                donors[t.id] = positions
        if not donors:
            continue
        # donation events: (var, call_line)
        events: List[Tuple[str, int]] = []
        for sub in _walk_scope(scope):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                    and sub.func.id in donors:
                for pos in donors[sub.func.id]:
                    if pos < len(sub.args) \
                            and isinstance(sub.args[pos], ast.Name):
                        events.append((sub.args[pos].id, sub.lineno))
        for var, call_line in events:
            # First rebinding at/after the call ends the donated window —
            # same-line counts: `state = step(state, ...)` rebinds the name
            # to the call's (live) result.
            rebind = min((n.lineno for n in _walk_scope(scope)
                          if isinstance(n, ast.Name) and n.id == var
                          and isinstance(n.ctx, ast.Store)
                          and n.lineno >= call_line), default=None)
            for n in _walk_scope(scope):
                if isinstance(n, ast.Name) and n.id == var \
                        and isinstance(n.ctx, ast.Load) \
                        and n.lineno > call_line \
                        and (rebind is None or n.lineno < rebind):
                    findings.append(Finding(
                        "GL003", module.relpath, n.lineno, n.col_offset,
                        f"`{var}` was passed at a donated position of a "
                        f"jitted call and is read afterwards; donated "
                        f"buffers are deleted by XLA (use the call's result, "
                        f"or drop donate_argnums)",
                        scope=module.scope_at(n)))
                    break  # one finding per donation event
    return findings
