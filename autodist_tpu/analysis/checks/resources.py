"""GL010 — resource-close discipline for registered closeables.

PR 12's review log: six example call sites constructed prefetch feeds and
never closed them — each one a leaked producer thread parked on a bounded
queue, invisible until a box slowly fills with daemon threads (or a test
run wedges at interpreter exit). The close contracts already exist
(``PrefetchProducer.close`` is prompt and idempotent, ``DataLoader.close``
joins its ring, servers unbind their port); what was missing is anything
making call sites USE them.

GL010 finds the closeable classes itself: any class in the linted program
that defines a ``close`` method is closeable, and any function that RETURNS
a construction of a closeable (``prefetch_to_device`` ->
``PrefetchProducer``; ``device_prefetch`` -> ``prefetch_to_device``) is a
closeable factory — computed to a fixpoint, so the whole feed-factory chain
is covered without a hand-kept list. In package/example/tool code (tests
are exempt: a leaked thread there dies with the short-lived process and a
hang is loud), a local ``x = Closeable(...)`` must reach ``close()`` on all
paths:

- ``with Closeable(...) as x:`` / ``with x:`` / ``contextlib.closing(x)``
  — clean;
- ``x.close()`` inside a ``try/finally`` — clean;
- ``x`` escaping (returned, yielded, stored on an object/container, passed
  to a non-builtin call) — ownership transferred, not this site's job;
- ``x.close()`` only on the straight-line path — flagged: an exception
  between construction and close leaks the resource exactly when things are
  already going wrong;
- no close at all — flagged.
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, register_program

# Builtins that read/iterate a value without taking ownership of it —
# `next(feed)` must not count as the feed escaping.
_NON_OWNING_CALLS = {
    "next", "iter", "len", "bool", "str", "repr", "print", "id", "type",
    "isinstance", "hash", "format", "getattr", "hasattr", "enumerate"}

_CHECKED_PREFIXES = ("autodist_tpu/", "examples/", "tools/")


def _checked_path(relpath: str) -> bool:
    return relpath.startswith(_CHECKED_PREFIXES) or "/" not in relpath


def closeable_classes(program) -> Dict[Tuple[str, str], ast.ClassDef]:
    """``(relpath, class name) -> ClassDef`` for classes defining close()."""
    out: Dict[Tuple[str, str], ast.ClassDef] = {}
    for info in program.modules():
        for name, cls in info.classes.items():
            if (name, "close") in info.index.methods:
                out[(info.relpath, name)] = cls
    return out


def closeable_factories(program, classes) -> Set[Tuple[str, str]]:
    """``(relpath, function name)`` for functions whose ``return`` is a
    construction of a closeable class or a call of another closeable
    factory — iterated to a fixpoint across the program."""
    factories: Set[Tuple[str, str]] = set()

    def returns_closeable(info, fn) -> bool:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return) \
                    or not isinstance(node.value, ast.Call):
                continue
            resolved = _resolve_construction(program, info, node.value,
                                             classes, factories)
            if resolved is not None:
                return True
        return False

    changed = True
    while changed:
        changed = False
        for info in program.modules():
            for name, fn in info.index.module_funcs.items():
                key = (info.relpath, name)
                if key not in factories and returns_closeable(info, fn):
                    factories.add(key)
                    changed = True
    return factories


def _resolve_construction(program, info, call: ast.Call, classes,
                          factories) -> Optional[str]:
    """The closeable class/factory name ``call`` constructs, or None."""
    dotted = callgraph.dotted_name(call.func)
    if dotted is None:
        return None
    hit = program.resolve_class(info, dotted)
    if hit is not None and (hit[0].relpath, hit[1].name) in classes:
        return hit[1].name
    resolved = program.resolve_call(info, call, None)
    if resolved is not None and resolved.cls is None \
            and (resolved.info.relpath, resolved.fn.name) in factories:
        return resolved.fn.name
    return None


def _scopes(tree):
    """(scope_body_owner, statements) for the module and every def."""
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body


def _name_used_in(node, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


@register_program("GL010", "registered closeable never reaches close() "
                           "on all paths")
def check_resource_close(program, ctx: Context) -> List[Finding]:
    """GL010 — resource-close discipline (see the module docstring).

    A finding means a locally-constructed closeable (prefetch producer,
    loader, server, client, metrics history — anything with a ``close``
    method, or a factory chain ending in one) neither escapes this scope
    nor reliably reaches ``close()``: either it is never closed at all, or
    the close sits on the straight-line path only, where the first
    exception skips it — the PR 12 "six leaked feeds" class. Fix with
    ``try/finally`` or a ``with`` block; when the leak is intentional
    (process-lifetime singleton), suppress with a reason.
    """
    findings: List[Finding] = []
    classes = closeable_classes(program)
    if not classes:
        return []
    factories = closeable_factories(program, classes)

    for info in program.modules():
        module = info.module
        if not _checked_path(module.relpath):
            continue
        # Class-attribute constructions (`class Owner: feed = Feed()`) are
        # the class's state, like `self.feed = ...` — ownership lives with
        # the instance lifecycle, not this scope; a deferred method close
        # would be invisible to the tracer anyway.
        class_level_assigns = {
            id(stmt) for cls in ast.walk(module.tree)
            if isinstance(cls, ast.ClassDef)
            for stmt in cls.body if isinstance(stmt, ast.Assign)}
        for scope_owner, body in _scopes(module.tree):
            # Constructions inside with-items are managed by the with.
            managed_calls: Set[int] = set()
            scope_nodes = [n for stmt in body
                           for n in callgraph.walk_executed(stmt)]
            for node in scope_nodes:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        for sub in ast.walk(item.context_expr):
                            managed_calls.add(id(sub))
            for node in scope_nodes:
                if not isinstance(node, ast.Assign) \
                        or not isinstance(node.value, ast.Call) \
                        or id(node.value) in managed_calls \
                        or id(node) in class_level_assigns:
                    continue
                what = _resolve_construction(program, info, node.value,
                                             classes, factories)
                if what is None:
                    continue
                targets = [t for t in node.targets
                           if isinstance(t, ast.Name)]
                if len(targets) != len(node.targets) or not targets:
                    continue   # attribute/container target: ownership moves
                # Multi-target `a = b = Producer()`: closing through EITHER
                # alias is enough — take the best verdict across them.
                rank = {"clean": 0, "escapes": 1, "unprotected": 2,
                        "leak": 3}
                name, verdict = min(
                    ((t.id, _trace_usage(scope_owner, node, t.id))
                     for t in targets), key=lambda nv: rank[nv[1]])
                if verdict == "leak":
                    findings.append(Finding(
                        "GL010", module.relpath, node.lineno,
                        node.col_offset,
                        f"`{name}` ({what}) is constructed here but never "
                        f"closed on any path; a leaked producer "
                        f"thread/socket survives this scope (the PR 12 "
                        f"leaked-feeds class) — close it in try/finally or "
                        f"use a with block",
                        scope=module.scope_at(node)))
                elif verdict == "unprotected":
                    findings.append(Finding(
                        "GL010", module.relpath, node.lineno,
                        node.col_offset,
                        f"`{name}` ({what}) is closed only on the "
                        f"straight-line path; an exception between "
                        f"construction and close() leaks it exactly when "
                        f"the run is already failing — move the close into "
                        f"try/finally or use a with block",
                        scope=module.scope_at(node)))
    return findings


def _trace_usage(scope_owner, assign: ast.Assign, name: str) -> str:
    """Classify how ``name`` fares AFTER ``assign`` in this scope:
    ``"clean"`` / ``"escapes"`` / ``"unprotected"`` / ``"leak"``.

    Only uses at/after the assignment line count: a ``with feed:`` or
    ``feed.close()`` belonging to an EARLIER binding of the same name must
    not mark a later unclosed rebinding clean (close-old-construct-new is
    a normal pattern and the new resource still needs its own close)."""
    closed_in_finally = False
    closed_anywhere = False
    body = getattr(scope_owner, "body", [])
    executed = [n for stmt in body
                for n in callgraph.walk_executed(stmt)]
    in_finally: Set[int] = set()
    for node in executed:
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    in_finally.add(id(sub))
    # A `with feed:` / `feed.close()` inside a nested def is DEFERRED code
    # — it must not classify the construction as clean (the callback may
    # never run). But a callback CAPTURING the resource is an ownership
    # hand-off we cannot trace: escape, not leak.
    for node in executed:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not scope_owner \
                and _name_used_in(node, name):
            return "escapes"
    for node in executed:
        if getattr(node, "lineno", assign.lineno) < assign.lineno:
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Name) and expr.id == name:
                    return "clean"
                if isinstance(expr, ast.Call) \
                        and callgraph.last_attr(expr.func) == "closing" \
                        and _name_used_in(expr, name):
                    return "clean"
        if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and node.value is not None \
                and _name_used_in(node.value, name):
            return "escapes"
        if isinstance(node, ast.Assign) and node is not assign \
                and _name_used_in(node.value, name):
            # self.x = feed / d[k] = feed / alias = feed — the VALUE hands
            # the resource to another owner (or another name): escapes.
            # (`r = feed.close()` lands here too — conservative, no
            # finding, which is the safe direction.)
            if not all(isinstance(t, ast.Name) and t.id == name
                       for t in node.targets):
                return "escapes"
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == name:
                if fn.attr == "close":
                    closed_anywhere = True
                    if id(node) in in_finally:
                        closed_in_finally = True
                continue   # feed.method() — receiver use, not an escape
            callee = callgraph.last_attr(fn)
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if _name_used_in(arg, name):
                    if callee in _NON_OWNING_CALLS:
                        break
                    return "escapes"
    if closed_in_finally:
        return "clean"
    if closed_anywhere:
        return "unprotected"
    return "leak"
