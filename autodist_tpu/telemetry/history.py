"""Metric history: the registry finally gets a time axis.

Every signal PRs 4–9 built (wire counters, staleness lags, SLO latency
histograms, ``train.attr.*`` shares, MFU) is a point-in-time ``snapshot()``
— nothing retains what a gauge read five minutes ago, so nothing can answer
"has data_wait been drifting up since the topology change?". This module
keeps that series:

- :class:`MetricsHistory` samples the process-global registry into a bounded
  in-memory ring of timestamped snapshots, and — when ``AUTODIST_METRICS_DIR``
  names a directory — appends each sample as one JSONL line into
  rotation-capped shard files, so the series survives the process and a
  sidecar can tail it.
- Sampling rides EXISTING beats, never a new hot path: the train loops call
  :func:`maybe_sample` at their log boundaries (where ``emit_metrics``
  already runs), the serving batcher's scheduler loop calls it between
  rounds, and an optional wall-clock thread (``AUTODIST_METRICS_INTERVAL_S``,
  bounded ``Event.wait`` — GL005-clean) covers processes with neither beat.
  :func:`maybe_sample` throttles to at most one sample per
  ``min_interval_s``, so a 5 ms-boundary loop cannot write a snapshot per
  period.
- Each sample is also the alert engine's evaluation tick
  (:mod:`autodist_tpu.telemetry.alerts`): rules see the fresh sample plus the
  whole ring (for-duration and burn-rate windows need exactly this history).

Un-armed cost (the default): :func:`maybe_sample` is one module-global read
per call. Arming: :func:`set_history`, or any of ``AUTODIST_METRICS_DIR`` /
``AUTODIST_METRICS_INTERVAL_S`` / ``AUTODIST_ALERT_RULES`` set in the
environment (resolved once, at the first call).
"""

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from autodist_tpu import const
from autodist_tpu.telemetry import metrics as _metrics
from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock, san_event

__all__ = ["MetricsHistory", "set_history", "get_history", "get_or_create",
           "maybe_sample", "load_history_jsonl"]

# One shard file holds at most this many samples before rotation opens the
# next one; at the 10s default interval a shard is ~2.8h of history.
DEFAULT_SHARD_LINES = 1024
# Latest-K shards kept per process (older ones deleted at rotation).
DEFAULT_KEEP_SHARDS = 8
_SHARD_PREFIX = "metrics-"


def _shard_seq(name: str, prefix: str) -> int:
    """The numeric sequence of ``metrics-<seq>-w<proc>-p<pid>.jsonl``; -1
    when the name does not parse (foreign files sort first, evict never —
    they fail the per-process tag filter)."""
    try:
        return int(name[len(prefix):].split("-", 1)[0])
    except ValueError:
        return -1


class MetricsHistory:
    """Bounded ring of timestamped registry snapshots + JSONL shard store.

    ``ring`` bounds the in-memory series; ``out_dir`` (default
    ``AUTODIST_METRICS_DIR``; empty = memory-only) receives rotation-capped
    JSONL shards named ``metrics-<seq>-w<proc>-p<pid>.jsonl`` (the
    seq-first/pid-tagged scheme the flight recorder uses, so concurrent
    processes sharing a dir never clobber each other and eviction sorts
    numerically). ``min_interval_s`` (default ``AUTODIST_METRICS_INTERVAL_S``,
    falling back to 10s) throttles :meth:`maybe_sample`; :meth:`sample`
    always samples. ``engine`` is the alert engine evaluated on every sample
    (default: the process engine from :mod:`telemetry.alerts`; pass
    ``engine=False`` for a history with no alerting).

    Thread-safe: the train loop, the serving scheduler thread, and the
    wall-clock thread may all call into one history — the lock covers the
    ring and shard bookkeeping, never the alert engine's reaction (which
    must be free to capture a flight-recorder snapshot)."""

    def __init__(self, out_dir: Optional[str] = None,
                 ring: int = 512,
                 min_interval_s: Optional[float] = None,
                 shard_lines: int = DEFAULT_SHARD_LINES,
                 keep_shards: int = DEFAULT_KEEP_SHARDS,
                 engine: Any = None):
        env_dir = str(const.ENV.AUTODIST_METRICS_DIR.val)
        self.out_dir = env_dir if out_dir is None else out_dir
        self.ring = max(1, int(ring))
        if min_interval_s is None:
            min_interval_s = float(const.ENV.AUTODIST_METRICS_INTERVAL_S.val
                                   or 0.0) or 10.0
        self.min_interval_s = float(min_interval_s)
        self.shard_lines = max(1, int(shard_lines))
        self.keep_shards = max(1, int(keep_shards))
        if engine is None:
            from autodist_tpu.telemetry import alerts as _alerts
            engine = _alerts.get_or_create()
        self.engine = engine or None    # engine=False -> no alerting
        self._samples: collections.deque = collections.deque(maxlen=self.ring)
        self._lock = san_lock()
        self._last_sample = -float("inf")
        proc = int(const.ENV.AUTODIST_PROCESS_ID.val)
        self._shard_tag = f"w{proc}-p{os.getpid()}.jsonl"
        self._shard_seq = self._next_shard_seq()
        self._shard_path: Optional[str] = None
        self._shard_count = 0
        self._warned_write = False
        self._stop = san_event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------------- sampling

    def sample(self, step: Optional[int] = None,
               reason: str = "manual") -> Dict[str, Any]:
        """Take one sample NOW: snapshot the registry, append to the ring
        (and the JSONL shard when armed), then evaluate the alert rules on
        the updated history. Returns the sample record. An
        :class:`~autodist_tpu.telemetry.alerts.AlertHalt` from the engine
        (``AUTODIST_ALERT_ACTION=halt``) propagates to the caller — the
        train loop is the sampler that can actually stop a run; background
        threads catch it themselves."""
        now = time.monotonic()
        rec: Dict[str, Any] = {
            "t_wall_s": round(time.time(), 3),
            "t_mono_s": now,
            "reason": reason,
            "metrics": _metrics.snapshot(),
        }
        if step is not None:
            rec["step"] = int(step)
        with self._lock:
            self._last_sample = now
            self._samples.append(rec)
        self._append_shard(rec)
        if self.engine is not None:
            self.engine.evaluate(self)
        return rec

    def maybe_sample(self, step: Optional[int] = None,
                     reason: str = "boundary") -> Optional[Dict[str, Any]]:
        """The hot-path entry point: sample unless the last sample is younger
        than ``min_interval_s`` (returns None then). Check-and-claim runs in
        one critical section — two boundary threads racing the window write
        one sample, not two."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_sample < self.min_interval_s:
                return None
            self._last_sample = now
        return self.sample(step=step, reason=reason)

    # ----------------------------------------------------------------- queries

    def samples(self) -> List[Dict[str, Any]]:
        """A point-in-time copy of the ring, oldest first."""
        with self._lock:
            return list(self._samples)

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def window(self, seconds: float,
               now: Optional[float] = None) -> List[Dict[str, Any]]:
        """The samples of the last ``seconds`` (monotonic clock), oldest
        first — what the for-duration and burn-rate predicates evaluate."""
        now = time.monotonic() if now is None else now
        cut = now - seconds
        with self._lock:
            return [s for s in self._samples if s["t_mono_s"] >= cut]

    def series(self, name: str,
               window_s: Optional[float] = None) -> List[Tuple[float, Any]]:
        """``[(t_wall_s, value), ...]`` for one metric across the ring (or
        the last ``window_s`` seconds) — the "a gauge finally has a series"
        query. Samples missing the metric are skipped."""
        src = self.samples() if window_s is None else self.window(window_s)
        out = []
        for s in src:
            v = s["metrics"].get(name)
            if v is not None:
                out.append((s["t_wall_s"], v))
        return out

    # -------------------------------------------------------------- JSONL store

    def _next_shard_seq(self) -> int:
        """Resume shard numbering past this process's existing shards (a
        restarted run extends its history instead of clobbering it)."""
        if not self.out_dir:
            return 0
        try:
            names = os.listdir(self.out_dir)
        except OSError:
            return 0
        seqs = [_shard_seq(n, _SHARD_PREFIX) for n in names
                if n.startswith(_SHARD_PREFIX)]
        seqs = [s for s in seqs if s >= 0]
        return max(seqs) + 1 if seqs else 0

    def shards(self) -> List[str]:
        """THIS process's shard files on disk, oldest (numeric seq) first."""
        if not self.out_dir:
            return []
        try:
            names = [n for n in os.listdir(self.out_dir)
                     if n.startswith(_SHARD_PREFIX)
                     and n.endswith(self._shard_tag)]
        except OSError:
            return []
        return [os.path.join(self.out_dir, n)
                for n in sorted(names, key=lambda n: (_shard_seq(
                    n, _SHARD_PREFIX), n))]

    def _append_shard(self, rec: Dict[str, Any]):
        if not self.out_dir:
            return
        with self._lock:
            if self._shard_path is None or self._shard_count >= self.shard_lines:
                self._shard_path = os.path.join(
                    self.out_dir,
                    f"{_SHARD_PREFIX}{self._shard_seq:04d}-{self._shard_tag}")
                self._shard_seq += 1
                self._shard_count = 0
                rotate = True
            else:
                rotate = False
            path = self._shard_path
            self._shard_count += 1
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(path, "a") as f:
                f.write(json.dumps(rec, default=str) + "\n")
            if rotate:
                self._evict_shards()
        except (OSError, ValueError, TypeError) as e:
            if not self._warned_write:   # a broken disk warns once, not per tick
                self._warned_write = True
                logging.warning("metrics history: shard write to %s failed: "
                                "%s (suppressing further warnings)", path, e)

    def _evict_shards(self):
        shards = self.shards()
        for old in shards[:max(0, len(shards) - self.keep_shards)]:
            try:
                os.remove(old)
            except OSError as e:
                logging.debug("metrics history: evicting %s failed: %s",
                              old, e)

    # ------------------------------------------------------- wall-clock thread

    def start_thread(self, interval_s: Optional[float] = None):
        """Start the optional wall-clock sampler: one daemon thread taking a
        sample every ``interval_s`` (default ``min_interval_s``) — the beat
        for processes with no train loop or scheduler round (a PS chief
        between applies). Bounded ``Event.wait`` per tick; idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return
        interval = float(interval_s if interval_s is not None
                         else self.min_interval_s)
        interval = max(0.1, interval)

        def _loop():
            from autodist_tpu.telemetry import alerts as _alerts
            while not self._stop.wait(timeout=interval):   # bounded (GL005)
                try:
                    self.maybe_sample(reason="timer")
                except _alerts.AlertHalt as e:
                    # halt stops a LOOP; this thread owns none. Keep the
                    # evidence loud and keep sampling — the alert gauges and
                    # events are already booked for whoever polls status.
                    logging.warning("metrics history: %s (AUTODIST_ALERT_"
                                    "ACTION=halt has no training loop to "
                                    "stop in this process)", e)
                except Exception as e:   # a sick sampler must not die silent
                    logging.warning("metrics history: timer sample failed: "
                                    "%s", e)

        self._stop.clear()
        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="autodist-metrics-history")
        self._thread.start()

    def close(self):
        """Stop the wall-clock thread (when running). The ring and shards
        stay — history outlives its sampler."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


# ------------------------------------------------------------ process global

_HISTORY: Optional[MetricsHistory] = None
_HISTORY_LOCK = san_lock()
# Tri-state env-arming cache: None = not yet checked, False = checked and
# unarmed (maybe_sample stays a two-read no-op), True = armed.
_ENV_ARMED: Optional[bool] = None


def set_history(history: Optional[MetricsHistory]):
    """Install (or clear, with None) the process history the boundary hooks
    sample through. Clearing also resets the env-arming cache so tests that
    set ``AUTODIST_METRICS_DIR`` after a clear re-arm."""
    global _HISTORY, _ENV_ARMED
    with _HISTORY_LOCK:
        if _HISTORY is not None and _HISTORY is not history:
            _HISTORY.close()
        _HISTORY = history
        _ENV_ARMED = None


def get_history() -> Optional[MetricsHistory]:
    return _HISTORY


def _env_arms() -> bool:
    return bool(str(const.ENV.AUTODIST_METRICS_DIR.val)
                or float(const.ENV.AUTODIST_METRICS_INTERVAL_S.val or 0.0) > 0
                or str(const.ENV.AUTODIST_ALERT_RULES.val))


def get_or_create() -> MetricsHistory:
    """The installed history, or a fresh env-default one installed on the
    spot (with the wall-clock thread started when
    ``AUTODIST_METRICS_INTERVAL_S`` asks for one)."""
    global _HISTORY
    with _HISTORY_LOCK:
        if _HISTORY is None:
            _HISTORY = MetricsHistory()
            if float(const.ENV.AUTODIST_METRICS_INTERVAL_S.val or 0.0) > 0:
                _HISTORY.start_thread()
        return _HISTORY


def maybe_arm() -> Optional[MetricsHistory]:
    """Arm from the environment WITHOUT taking a sample — the attach hook
    for processes with no natural sampling beat (a PSServer chief between
    applies calls this from its constructor): when the flags say so, the
    history is installed and — with ``AUTODIST_METRICS_INTERVAL_S`` > 0 —
    its wall-clock sampler thread becomes the beat. Returns the installed
    history, or None when the environment leaves the plane off. A typo'd
    flag (``AUTODIST_METRICS_INTERVAL_S=abc``) DISARMS with a warning —
    this runs lazily inside loops the plane must never kill."""
    global _ENV_ARMED
    h = _HISTORY
    if h is not None:
        return h
    if _ENV_ARMED is False:
        return None
    try:
        if _ENV_ARMED is None:
            armed = _env_arms()
            with _HISTORY_LOCK:
                _ENV_ARMED = armed
            if not armed:
                return None
        return get_or_create()
    except (ValueError, TypeError, OSError) as e:
        logging.warning("metrics history: cannot arm from the "
                        "environment (%s); metric history is DISABLED "
                        "for this process", e)
        with _HISTORY_LOCK:
            _ENV_ARMED = False
        return None


def maybe_sample(step: Optional[int] = None, reason: str = "boundary",
                 force: bool = False) -> Optional[Dict[str, Any]]:
    """The boundary hook: throttled sample through the installed history;
    with none installed, arm one only when the environment says so
    (``AUTODIST_METRICS_DIR`` / ``AUTODIST_METRICS_INTERVAL_S`` /
    ``AUTODIST_ALERT_RULES``), else no-op. Un-armed steady-state cost: two
    module-global reads — cheap enough for every serving scheduler round.
    ``force=True`` (the end-of-run flush) bypasses the throttle so a short
    run still leaves at least one sample."""
    h = _HISTORY
    if h is None:
        h = maybe_arm()
        if h is None:
            return None
    if force:
        return h.sample(step=step, reason=reason)
    return h.maybe_sample(step=step, reason=reason)


def load_history_jsonl(path: str) -> List[Dict[str, Any]]:
    """Read one shard file back into sample records (tooling / tests — the
    on-disk mirror of :meth:`MetricsHistory.samples`). Raises ``ValueError``
    on a line that is not a sample record."""
    out = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if not isinstance(rec, dict) or "metrics" not in rec:
                raise ValueError(f"{path}:{i + 1}: not a metrics-history "
                                 f"sample record")
            out.append(rec)
    return out
