"""Cluster management: process launch across hosts.

Counterpart of reference ``autodist/cluster.py``. What changes on TPU: there are no
per-node ``tf.Server`` processes to start — a multi-host SPMD program needs every
host to run the *same* JAX program with a shared coordination service
(``jax.distributed``). So:

- ``start()`` validates connectivity and assigns the coordinator address
  (chief:port) + deterministic process ids from the sorted node list (determinism is
  load-bearing, reference ``cluster.py:70-82``), writing ``cluster_spec.json``.
- ``remote_exec`` / ``remote_file_write`` / ``remote_copy`` keep the reference's
  control-plane surface (``cluster.py:271-374``), implemented over ``ssh``/``scp``
  subprocesses (the reference used paramiko + ``ssh -tt``).
- Local addresses take a fast path: plain subprocess, no ssh (reference treated the
  chief's own node the same way, ``cluster.py:193-196``).
"""

import functools
import json
import os
import shlex
import signal
import socket
import subprocess
from typing import Dict, List, Optional

from autodist_tpu import const
from autodist_tpu.resource_spec import ResourceSpec, SSHConfig
from autodist_tpu.utils import logging

_LOOPBACK_ADDRESSES = ("localhost", "127.0.0.1", "0.0.0.0", "::1")


@functools.lru_cache(maxsize=None)
def _own_addresses() -> frozenset:
    """Every address this host answers to: loopback names, hostname/FQDN and their
    resolutions, per-interface IPv4 addresses, and the primary outbound address.
    The stdlib equivalent of the reference's netifaces enumeration
    (utils/network.py:21-75), so a resource spec listing the chief's real IP takes
    the local fast path instead of SSHing to itself."""
    addrs = set(_LOOPBACK_ADDRESSES)
    hostname = socket.gethostname()
    addrs.add(hostname)
    for name in (hostname, socket.getfqdn()):
        addrs.add(name)
        try:
            for info in socket.getaddrinfo(name, None):
                addrs.add(info[4][0])
        except OSError:
            pass
    try:  # per-interface IPv4 addresses (Linux SIOCGIFADDR, like netifaces)
        import fcntl
        import struct
        for _, ifname in socket.if_nameindex():
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                try:
                    packed = fcntl.ioctl(s.fileno(), 0x8915,  # SIOCGIFADDR
                                         struct.pack("256s", ifname[:15].encode()))
                    addrs.add(socket.inet_ntoa(packed[20:24]))
                except OSError:
                    pass
    except (ImportError, OSError):
        pass
    try:  # primary outbound interface, no packet sent
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            addrs.add(s.getsockname()[0])
    except OSError:
        pass
    return frozenset(addrs)


def is_local_address(address: str) -> bool:
    """True for loopback addresses and this host's own names/IPs.

    The whole 127.0.0.0/8 (and ::1) counts: Linux binds the full block to
    ``lo``, and distinct loopback IPs are how a spec models several processes
    on one host (node addresses must be unique, like the reference's
    per-host cluster spec keys)."""
    if address in _LOOPBACK_ADDRESSES or address in _own_addresses():
        return True
    try:
        import ipaddress
        return ipaddress.ip_address(address).is_loopback
    except ValueError:
        return False


class Cluster:
    """Process/launch manager for one resource spec."""

    def __init__(self, resource_spec: ResourceSpec):
        self._spec = resource_spec
        self._processes: List[subprocess.Popen] = []
        self.cluster_spec = self._build_cluster_spec()

    def _build_cluster_spec(self) -> Dict:
        """Deterministic host ordering -> process ids (every host derives the same
        mapping independently, reference cluster.py:70-82)."""
        nodes = self._spec.sorted_nodes
        port = const.ENV.AUTODIST_COORDINATOR_PORT.val
        coordinator = f"{self._spec.chief_address}:{port}"
        return {
            "coordinator": coordinator,
            "processes": [
                {"address": n.address, "process_id": i,
                 "num_devices": len(n.accelerator_devices) or 1}
                for i, n in enumerate(nodes)
            ],
        }

    @property
    def num_processes(self) -> int:
        return len(self.cluster_spec["processes"])

    def process_id_of(self, address: str) -> int:
        for p in self.cluster_spec["processes"]:
            if p["address"] == address:
                return p["process_id"]
        raise KeyError(address)

    # ------------------------------------------------------------------ start
    def start(self):
        """Write cluster_spec.json under the working dir (reference wrote the same
        file for tf.Servers, cluster.py:192) and sanity-check remote reachability."""
        os.makedirs(const.DEFAULT_WORKING_DIR, exist_ok=True)
        path = os.path.join(const.DEFAULT_WORKING_DIR, "cluster_spec.json")
        with open(path, "w") as f:
            json.dump(self.cluster_spec, f, indent=1)
        logging.info("Cluster spec: %s", self.cluster_spec)

    def terminate(self):
        """Kill every launched process group (reference cluster.py:212-216)."""
        for proc in self._processes:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    proc.terminate()
        self._processes.clear()

    # ------------------------------------------------------------- remote ops
    def _ssh_config(self, address: str) -> Optional[SSHConfig]:
        return self._spec.ssh_config_for(address)

    def _ssh_command(self, address: str) -> List[str]:
        conf = self._ssh_config(address)
        cmd = ["ssh", "-tt", "-o", "StrictHostKeyChecking=no"]
        if conf:
            if conf.port != 22:
                cmd += ["-p", str(conf.port)]
            if conf.key_file:
                cmd += ["-i", conf.key_file]
            target = f"{conf.username}@{address}" if conf.username else address
        else:
            target = address
        return cmd + [target]

    def remote_exec(self, args: List[str], address: str,
                    env: Optional[Dict[str, str]] = None) -> subprocess.Popen:
        """Run a command on a node (reference cluster.py:316-345). Local addresses
        run directly in a new process group; remote go over ssh."""
        env_prefix = ""
        full_env = None
        if env:
            env_prefix = " ".join(f"{k}={shlex.quote(str(v))}" for k, v in env.items()) + " "
        if is_local_address(address):
            full_env = dict(os.environ)
            full_env.update({k: str(v) for k, v in (env or {}).items()})
            proc = subprocess.Popen(args, env=full_env, start_new_session=True)
        else:
            conf = self._ssh_config(address)
            # All env assignments (shared_envs + role env) must prefix the user
            # command itself — a prefix on the `source venv` statement would not
            # survive past the `;`.
            if conf and conf.shared_envs:
                env_prefix = " ".join(f"{k}={shlex.quote(str(v))}"
                                      for k, v in conf.shared_envs.items()) + " " + env_prefix
            inner = env_prefix + " ".join(shlex.quote(a) for a in args)
            if conf and conf.python_venv:
                inner = f"{conf.python_venv}; {inner}"
            cmd = self._ssh_command(address) + [f"bash -c {shlex.quote(inner)}"]
            if const.ENV.AUTODIST_DEBUG_REMOTE.val:
                logging.info("remote_exec[%s]: %s", address, cmd)
            proc = subprocess.Popen(cmd, start_new_session=True)
        self._processes.append(proc)
        return proc

    def remote_file_write(self, remote_path: str, data: str, address: str):
        """Write a file on a node (reference cluster.py:347-358)."""
        if is_local_address(address):
            os.makedirs(os.path.dirname(remote_path) or ".", exist_ok=True)
            with open(remote_path, "w") as f:
                f.write(data)
            return
        cmd = self._ssh_command(address) + [
            f"bash -c {shlex.quote(f'mkdir -p {os.path.dirname(remote_path)} && cat > {remote_path}')}"]
        subprocess.run(cmd, input=data.encode(), check=True)

    def remote_copy(self, local_path: str, remote_dir: str, address: str):
        """Copy a local file to a node (reference cluster.py:360-374)."""
        if is_local_address(address):
            os.makedirs(remote_dir, exist_ok=True)
            dest = os.path.join(remote_dir, os.path.basename(local_path))
            if os.path.abspath(dest) != os.path.abspath(local_path):
                with open(local_path, "rb") as src, open(dest, "wb") as dst:
                    dst.write(src.read())
            return
        conf = self._ssh_config(address)
        cmd = ["scp", "-o", "StrictHostKeyChecking=no"]
        if conf:
            if conf.port != 22:
                cmd += ["-P", str(conf.port)]
            if conf.key_file:
                cmd += ["-i", conf.key_file]
            target = f"{conf.username}@{address}" if conf.username else address
        else:
            target = address
        subprocess.run(cmd + [local_path, f"{target}:{remote_dir}/"], check=True)


# Backwards-compatible alias mirroring the reference's class split (Cluster ABC +
# SSHCluster impl, cluster.py:271-276); one class covers both here.
SSHCluster = Cluster
