"""The decoder-scaling example (examples/scale_lm.py) runs on tiny shapes."""

import examples.scale_lm as sl


def test_scale_lm_example_runs():
    rate = sl.main(["--d_model", "64", "--n_layers", "2", "--batch_size", "8",
                    "--seq_len", "64", "--vocab", "256", "--steps", "2"])
    assert rate > 0
