"""Input-data plane: the unified async sharded prefetch pipeline.

The pipeline (``data/prefetch.py``) is a pure performance transform over the
synchronous feed — same batches, same order, same math — so the contracts
asserted here are exact: bit-identical training results (per-step AND
``unroll=K`` blocks), bounded queue depth, producer exceptions re-raised at
the consumer, clean close with a blocked producer, clean exhaustion (no
PEP 479 ``RuntimeError``), per-host shard disjointness keyed off the
runner's feed layout, producer-wait telemetry, typed flags, and the
autotuner enumerating + pricing the ``prefetch_depth`` knob.

Pure in-process (no subprocess): named to sort in-window, right after
test_data_loader.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, const, telemetry, train
from autodist_tpu.data import DataLoader, device_prefetch
from autodist_tpu.data import prefetch as pf
from autodist_tpu.runner import BatchBlock
from autodist_tpu.strategy import AllReduce

BATCH = 32


def _loss(p, b):
    return jnp.mean((b["y"] - (b["x"] @ p["w"] + p["b"])) ** 2)


def _params():
    rng = np.random.RandomState(7)
    return {"w": rng.randn(4, 1).astype(np.float32),
            "b": np.zeros((1,), np.float32)}


def _batch_fn(i):
    rng = np.random.RandomState(100 + i)
    return {"x": rng.randn(BATCH, 4).astype(np.float32),
            "y": rng.randn(BATCH, 1).astype(np.float32)}


def _session(accum=1):
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(
        _loss, _params(), optax.adam(1e-2), example_batch=_batch_fn(0),
        accumulation_steps=accum)
    return runner, runner.init(_params())


def _assert_trees_equal(a, b):
    a, b = jax.device_get(a), jax.device_get(b)
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------- queue core

def test_bounded_queue_basics_and_close_semantics():
    q = pf.BoundedQueue(2)
    assert q.try_put(1) and q.try_put(2)
    assert not q.try_put(3)          # full -> instant False, never blocks
    assert len(q) == 2
    assert q.get() == 1
    assert q.pop_nowait() == 2
    assert q.pop_nowait() is pf.EMPTY
    assert q.get(timeout_s=0.01) is pf.EMPTY   # bounded timeout, no item
    q.try_put("leftover")
    drained = q.close()
    assert drained == ["leftover"]   # close drains undelivered items
    with pytest.raises(pf.QueueClosed):
        q.try_put("late")            # post-close puts reject instantly
    with pytest.raises(pf.QueueClosed):
        q.get(timeout_s=0.01)        # closed AND drained -> QueueClosed


def test_bounded_queue_blocking_put_unblocks_on_close():
    q = pf.BoundedQueue(1)
    q.try_put("full")
    result = {}

    def blocked_put():
        result["ok"] = q.put("second")   # parks: queue is full

    t = threading.Thread(target=blocked_put, daemon=True)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()              # genuinely blocked on the full queue
    q.close()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert result["ok"] is False     # closed-under-us returns False


# ----------------------------------------------------------- producer

def test_producer_preserves_order_and_ends_cleanly():
    items = list(range(17))
    it = iter(items)
    prod = pf.PrefetchProducer(lambda: next(it), transform=lambda x: x * 10,
                               depth=3)
    # Clean exhaustion: plain StopIteration at the end — list() would raise
    # the PEP 479 RuntimeError the old generator path leaked.
    assert list(prod) == [x * 10 for x in items]
    prod.close()


def test_producer_multiworker_order_matches_source_order():
    items = list(range(40))
    it = iter(items)

    def jittery(x):   # uneven transform latency scrambles completion order
        time.sleep(0.001 * (x % 3))
        return x

    prod = pf.PrefetchProducer(lambda: next(it), transform=jittery,
                               depth=4, workers=3)
    assert list(prod) == items   # emission order == pull order regardless
    prod.close()


def test_producer_depth_bounds_readahead():
    pulled = []

    def pull():
        if len(pulled) >= 50:
            raise StopIteration
        pulled.append(len(pulled))
        return pulled[-1]

    prod = pf.PrefetchProducer(pull, depth=3, workers=1)
    time.sleep(0.3)   # give the producer every chance to race ahead
    # At most depth buffered + one in flight: the queue, not the source,
    # paces the producer.
    assert len(pulled) <= 3 + 1
    assert prod.queue_depth() <= 3
    prod.close()


def test_producer_exception_propagates_in_order():
    def pull():
        if not hasattr(pull, "n"):
            pull.n = 0
        pull.n += 1
        if pull.n == 3:
            raise ValueError("loader exploded")
        return pull.n

    prod = pf.PrefetchProducer(pull, depth=4)
    assert next(prod) == 1
    assert next(prod) == 2     # items before the failure deliver first
    with pytest.raises(ValueError, match="loader exploded"):
        next(prod)             # then the producer's exception, in position
    prod.close()


def test_producer_close_with_blocked_producer_is_prompt():
    release = threading.Event()

    def slow_pull():
        release.wait(10.0)     # a loader parked mid-gather
        return 1

    prod = pf.PrefetchProducer(slow_pull, depth=1)
    time.sleep(0.05)
    t0 = time.perf_counter()
    prod.close(timeout_s=0.5)  # must not wait out the pull
    assert time.perf_counter() - t0 < 5.0
    release.set()              # let the daemon thread exit
    with pytest.raises(pf.QueueClosed):
        next(prod)             # iterating a closed producer says so


def test_producer_wait_telemetry_books_loader_seconds():
    wait0 = telemetry.counter("data.producer_wait").value
    batches0 = telemetry.counter("data.producer_batches").value

    def slow_pull():
        if not hasattr(slow_pull, "n"):
            slow_pull.n = 0
        if slow_pull.n >= 4:
            raise StopIteration
        slow_pull.n += 1
        time.sleep(0.02)
        return slow_pull.n

    prod = pf.PrefetchProducer(slow_pull, depth=2)
    assert len(list(prod)) == 4
    prod.close()
    waited = telemetry.counter("data.producer_wait").value - wait0
    assert waited >= 4 * 0.02 * 0.5   # the loader seconds are BOOKED
    assert telemetry.counter("data.producer_batches").value - batches0 == 4


# ------------------------------------------------- device feed parity

def test_device_prefetch_bit_identical_to_sync_per_step():
    K = 8
    batches = [_batch_fn(i) for i in range(K)]

    runner_a, state_a = _session()
    for b in batches:
        state_a, _ = runner_a.run(state_a, b)

    runner_b, state_b = _session()
    feed = device_prefetch(iter(batches), runner_b, depth=3)
    n = 0
    for sharded in feed:
        state_b, _ = runner_b.run(state_b, sharded)
        n += 1
    feed.close()
    assert n == K                      # exhaustion ends cleanly, no drop
    _assert_trees_equal(state_a.params, state_b.params)


def test_device_prefetch_unroll_blocks_bit_identical():
    K, U = 8, 2
    batches = [_batch_fn(i) for i in range(K)]

    runner_a, state_a = _session()
    for b in batches:
        state_a, _ = runner_a.run(state_a, b)

    runner_b, state_b = _session()
    feed = device_prefetch(iter(batches), runner_b, depth=2, unroll=U)
    n_blocks = 0
    for block in feed:
        assert isinstance(block, BatchBlock) and len(block) == U
        state_b, _ = runner_b.run_many(state_b, block)
        n_blocks += 1
    feed.close()
    assert n_blocks == K // U
    _assert_trees_equal(state_a.params, state_b.params)


def test_device_prefetch_unroll_drops_partial_remainder():
    """7 batches at unroll=2: three full blocks, the 1-batch remainder is
    dropped (logged) and iteration ends cleanly instead of crashing."""
    batches = [_batch_fn(i) for i in range(7)]
    runner, _ = _session()
    feed = device_prefetch(iter(batches), runner, depth=2, unroll=2)
    blocks = list(feed)
    feed.close()
    assert len(blocks) == 3
    assert all(len(b) == 2 for b in blocks)


def test_train_prefetch_bit_identical_both_loops():
    """train(prefetch_depth=K) vs the synchronous feed: bit-identical final
    params through BOTH loops (per-step and unroll=K blocks), with eval
    cadence forcing clipped blocks on the unrolled path."""
    steps = 12

    def run(prefetch_depth, unroll):
        runner, _ = _session()
        evals = []
        state = train(runner, _params(), _batch_fn, steps, log_every=4,
                      unroll=unroll, prefetch_depth=prefetch_depth,
                      eval_every=5, eval_batch=_batch_fn(999),
                      on_eval=lambda s, v: evals.append(s))
        return jax.device_get(runner.logical_params(state)), evals

    base_1, evals_base1 = run(0, 1)
    pf_1, evals_pf1 = run(3, 1)
    _assert_trees_equal(base_1, pf_1)
    assert evals_pf1 == evals_base1    # cadence points unchanged

    base_u, evals_baseu = run(0, 4)
    pf_u, evals_pfu = run(3, 4)
    _assert_trees_equal(base_u, pf_u)
    _assert_trees_equal(base_1, base_u)
    assert evals_pfu == evals_baseu    # blocks clip at the same boundaries


def test_train_prefetch_iterable_exhaustion_matches_sync():
    """A finite iterable ends the prefetched run at the same step as the
    synchronous run (and the producer's readahead never trains extra
    steps)."""
    def run(prefetch_depth):
        runner, _ = _session()
        state = train(runner, _params(),
                      iter([_batch_fn(i) for i in range(9)]), 50,
                      log_every=0, prefetch_depth=prefetch_depth)
        return int(state.step), jax.device_get(runner.logical_params(state))

    steps_sync, params_sync = run(0)
    steps_pf, params_pf = run(2)
    assert steps_pf == steps_sync == 9
    _assert_trees_equal(params_sync, params_pf)


def test_meter_sizing_folds_microbatched_leaves():
    """The prefetched per-step loop meters the TRANSFORMED batch; under
    gradient accumulation its MicroBatched [k, B/k] leaves must still size
    the meter at B (examples/s would otherwise under-report by B/k)."""
    from autodist_tpu.training import _make_meter

    runner, _ = _session(accum=2)
    sharded = runner.shard_batch(_batch_fn(0))
    assert _make_meter(sharded, None, 1).batch_size == BATCH
    assert _make_meter(_batch_fn(0), None, 1).batch_size == BATCH


def test_native_loader_next_after_close_raises_cleanly():
    """A native loader closed under an async producer: next() during AND
    after the close raises the documented error (never falls into the
    uninitialized numpy-fallback branch)."""
    data = {"x": np.arange(16, dtype=np.float32).reshape(8, 2)}
    dl = DataLoader(data, batch_size=2, shuffle=False)
    if not dl.is_native:
        pytest.skip("no native toolchain in this environment")
    dl.next()
    dl.close()
    with pytest.raises(RuntimeError, match="shut down"):
        dl.next()


def test_train_adopts_tuned_plan_prefetch_depth():
    """train(prefetch_depth=None) adopts a tuned plan's nonzero depth: the
    producer runs (data.producer_batches advances)."""
    from autodist_tpu.strategy.autotune import TunedPlan

    runner, _ = _session()
    runner.tuned_plan = TunedPlan(builder_spec={"name": "AllReduce"},
                                  unroll=1, prefetch_depth=2)
    before = telemetry.counter("data.producer_batches").value
    train(runner, _params(), _batch_fn, 4, log_every=0)
    # The producer pulled every consumed batch (it may have pulled up to
    # depth further ahead before close — readahead, not extra training).
    assert telemetry.counter("data.producer_batches").value - before >= 4


# ------------------------------------------------- per-host sharding

def test_host_shard_rows_disjoint_and_complete():
    n, procs = 96, 4
    seen = []
    blocks = []
    for pid in range(procs):
        start, stop = pf.host_shard_rows(n, pid, procs)
        blocks.append((start, stop))
        seen.extend(range(start, stop))
    assert sorted(seen) == list(range(n))          # disjoint AND complete
    assert all(b[1] - b[0] == n // procs for b in blocks)
    with pytest.raises(ValueError, match="tile"):
        pf.host_shard_rows(10, 0, 3)               # non-divisible refused
    with pytest.raises(ValueError, match="out of"):
        pf.host_shard_rows(8, 4, 4)


def test_train_prefetch_never_calls_source_past_steps():
    """The producer's readahead must stay inside the run's contract: a
    callable source is never invoked with a step index >= steps."""
    calls = []

    def src(i):
        calls.append(i)
        return _batch_fn(i)

    runner, _ = _session()
    train(runner, _params(), src, 6, log_every=0, prefetch_depth=3)
    assert calls == list(range(6))     # every step once, none past the end


def test_host_shard_refuses_ambiguous_batch_dim():
    """Two equally common leading dims: refuse to guess (the runner's
    rule), resolve explicitly with batch_rows=."""
    batch = {"x": np.zeros((32, 2), np.float32),
             "neg": np.zeros((64, 3), np.float32)}
    with pytest.raises(ValueError, match="ambiguous"):
        pf.host_shard(batch, 0, 2)
    s = pf.host_shard(batch, 0, 2, batch_rows=32)
    assert s["x"].shape[0] == 16 and s["neg"].shape[0] == 64


def test_host_shard_slices_batch_leaves_only():
    batch = {"x": np.arange(32).reshape(8, 4), "y": np.arange(8),
             "aux": np.arange(3)}                  # non-batch leaf
    shards = [pf.host_shard(batch, pid, 2) for pid in range(2)]
    np.testing.assert_array_equal(
        np.concatenate([s["x"] for s in shards]), batch["x"])
    np.testing.assert_array_equal(
        np.concatenate([s["y"] for s in shards]), batch["y"])
    for s in shards:                               # aux replicates whole
        np.testing.assert_array_equal(s["aux"], batch["aux"])


def test_assemble_global_batch_matches_shard_batch():
    """Single-process identity: assembling from 'local' rows (the whole
    batch at process 0 of 1) is bit-identical to the runner's shard_batch
    placement — the per-host path and the classic path share one feed
    layout."""
    runner, state = _session()
    batch = _batch_fn(3)
    local = pf.host_shard(batch, 0, 1)
    assembled = pf.assemble_global_batch(runner, local)
    direct = runner.shard_batch(batch)
    _assert_trees_equal(assembled, direct)
    # And it trains: the assembled batch is a valid feed.
    state2, loss_a = runner.run(state, assembled)
    layout = runner.feed_layout()
    assert layout.dp >= 1 and layout.accum == 1


def test_assemble_global_batch_refuses_accumulation():
    runner, _ = _session(accum=2)
    with pytest.raises(ValueError, match="accumulation"):
        pf.assemble_global_batch(runner, _batch_fn(0))


# -------------------------------------------------- flags + autotuner

def test_prefetch_flags_registered_and_typed():
    assert "AUTODIST_PREFETCH_DEPTH" in const.KNOWN_FLAGS
    assert "AUTODIST_PREFETCH_WORKERS" in const.KNOWN_FLAGS
    assert isinstance(const.ENV.AUTODIST_PREFETCH_DEPTH.val, int)
    assert isinstance(const.ENV.AUTODIST_PREFETCH_WORKERS.val, int)
    assert pf.default_prefetch_depth() == 0        # sync feed by default
    assert pf.default_prefetch_workers() >= 1


def test_autotuner_enumerates_and_prices_prefetch_depth():
    """With a declared loader cost the candidate space crosses
    prefetch_depth, the cost model prices the residual data wait
    (max(0, loader_s - hidden_s)), depth-on candidates rank ahead of
    their depth-0 twins, and the knob rides TunedPlan/knobs_dict into
    the applied-plan manifest."""
    from autodist_tpu.model_spec import ModelSpec
    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.strategy.autotune import (DEFAULT_CALIBRATION,
                                                TunedPlan,
                                                enumerate_candidates)
    from autodist_tpu.telemetry import costmodel, profiling

    spec = ModelSpec(_params(), sparse_names=())
    rs = ResourceSpec(None)
    plain = enumerate_candidates(spec, rs, optax.sgd(0.1))
    assert all(c.prefetch_depth == 0 for c in plain)   # no loader: no knob
    cands = enumerate_candidates(spec, rs, optax.sgd(0.1),
                                 loader_s_per_step=0.004, budget=64)
    depths = {c.prefetch_depth for c in cands if not c.asynchronous}
    assert depths == {0, 2}                            # the knob enumerated
    assert any("pf=2" in c.name for c in cands)

    # Pricing: a loader slower than everything the pipeline can hide
    # behind leaves a residual; depth >= 1 hides hidden_s of it.
    rec = {"flops": 1e9, "bytes_accessed": 1e8, "steps": 1, "dispatches": 1}
    p0 = costmodel.predict(rec, DEFAULT_CALIBRATION,
                           loader_s_per_step=0.5, prefetch_depth=0)
    p2 = costmodel.predict(rec, DEFAULT_CALIBRATION,
                           loader_s_per_step=0.5, prefetch_depth=2)
    assert p0["breakdown"]["data_wait_s"] == pytest.approx(0.5)
    hidden = (p0["breakdown"]["compute_s"] + p0["breakdown"]["host_s"]
              + p0["breakdown"]["comm_s"])
    assert hidden < 0.5   # the probe program is far cheaper than the loader
    assert p2["breakdown"]["data_wait_s"] == pytest.approx(0.5 - hidden)
    assert p2["step_s"] < p0["step_s"]
    assert p0["bound"] == "data_wait"

    # The knob round-trips the plan record and lands in the applied-plan
    # manifest (what flight-recorder snapshots and adprof diffs read).
    plan = TunedPlan(builder_spec={"name": "AllReduce"}, unroll=4,
                     prefetch_depth=2)
    assert plan.knobs_dict()["prefetch_depth"] == 2
    assert "pf=2" in plan.name
    assert TunedPlan.from_dict(plan.to_dict()).prefetch_depth == 2
    prior = profiling.applied_plan()
    try:
        profiling.set_applied_plan(dict(plan.to_dict(), name=plan.name))
        recorded = profiling.profile_document()["plan"]
        assert recorded["knobs"]["prefetch_depth"] == 2
    finally:
        profiling.set_applied_plan(prior)


def test_serving_staging_rides_bounded_queue():
    """The serving batcher's admission queue IS the input-plane queue core
    (one staging implementation): full -> instant rejection, close ->
    drained requests fail back."""
    from autodist_tpu.serving.batcher import (Batcher, ServeConfig,
                                              ServeError)

    class _Engine:
        capacity = 1
        buckets = (8,)
        max_len = 16

        def admit(self, slot, prompt, key):
            return 1

        def step(self, keys):
            return np.ones(1, np.int32)

        def free(self, slot):
            pass

        def make_keys(self, seed, n):
            return None

    b = Batcher(_Engine(), ServeConfig(max_batch=1, max_queue=2),
                start=False)
    assert isinstance(b._waiting, pf.BoundedQueue)
    b.submit(np.array([1], np.int32), 1)
    b.submit(np.array([1], np.int32), 1)
    with pytest.raises(ServeError, match="full"):
        b.submit(np.array([1], np.int32), 1)       # instant, bounded
    b.close()
    with pytest.raises(ServeError, match="shutting down"):
        b.submit(np.array([1], np.int32), 1)       # closed queue rejects

    # max_queue=0 stays a valid reject-everything (drain) configuration.
    drain = Batcher(_Engine(), ServeConfig(max_batch=1, max_queue=0),
                    start=False)
    with pytest.raises(ServeError, match="full"):
        drain.submit(np.array([1], np.int32), 1)
    drain.close()
