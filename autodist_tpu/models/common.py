"""Shared loss helpers for the model zoo."""

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def jit_init(model, *args, rng: Optional[jax.Array] = None):
    """``model.init`` under jit, returning the params tree.

    One compiled program instead of eager op-by-op dispatch: on a tunneled chip
    every eager op costs a host round trip, which made deep-CNN initialization
    (DenseNet-121) take minutes; jitted it takes seconds. The single place all
    model zoo init paths go through."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    return jax.jit(model.init)(rng, *args)["params"]


def num_groups(channels: int, max_groups: int) -> int:
    """Largest GroupNorm group count <= max_groups that divides the channel count
    (CNN widths like 80/48/76 are not multiples of the usual 32)."""
    g = min(max_groups, channels)
    while channels % g:
        g -= 1
    return g


def sample_logits(logits, key, temperature: float = 0.0, top_k: int = 0,
                  top_p: float = 0.0):
    """One sampling step over ``[B, vocab]`` logits -> ``[B]`` int32 tokens.

    ``temperature=0`` is greedy argmax (``key`` unused); otherwise logits are
    scaled by ``1/temperature``, then optionally truncated to the ``top_k``
    best and/or the nucleus of smallest-count tokens whose probability mass
    reaches ``top_p`` (0 < p <= 1; the first token past the threshold is
    kept, so the nucleus always covers >= p and is never empty). Both filters
    compose (k first, then p over the survivors). f32 throughout — bf16
    logit gaps near the distribution tail would quantize away."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p > 0.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]   # descending
        cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # Keep ranks whose PRECEDING mass is < p (shift by one): the token
        # crossing the threshold stays in the nucleus.
        keep = jnp.concatenate(
            [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < top_p],
            axis=-1)
        # Smallest kept logit per row = the nucleus cutoff.
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def lm_head_logits(h, params, tied: bool = False):
    """``[..., D] hidden -> [..., V] logits`` through the zoo's LM-head param
    contract (same table/layout rule as :func:`fused_lm_head_nll`; same
    compute-dtype convention as the flax head: table cast to the activation
    dtype). Lets callers project a SLICE of positions — e.g. generation's
    prefill needs only the last position's logits, not a [B, P, V] tensor."""
    if tied:
        table = params["embed"]["embedding"]          # [V, D]
        return h @ table.astype(h.dtype).T
    return h @ params["lm_head"]["kernel"].astype(h.dtype)  # [D, V]


def fused_lm_head_nll(h, params, targets, tied: bool = False):
    """Per-token NLL [B, T] through the fused pallas head+loss for the zoo's
    flax LM-head convention — THE single definition of which param is the head
    table and in which layout (untied: ``params['lm_head']['kernel']``, [D, V];
    tied: ``params['embed']['embedding']``, [V, D]) so no model's fused loss
    can drift from another's."""
    from autodist_tpu.ops.fused_xent import fused_softmax_xent
    h2 = h.reshape(-1, h.shape[-1])
    if tied:
        nll = fused_softmax_xent(h2, params["embed"]["embedding"],
                                 targets.reshape(-1), w_layout="vd")
    else:
        nll = fused_softmax_xent(h2, params["lm_head"]["kernel"],
                                 targets.reshape(-1))
    return nll.reshape(targets.shape)


def make_classification_loss_fn(model) -> Callable:
    """Softmax cross entropy over {"images", "labels"} batches (ResNet/VGG style)."""

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["images"])
        logprobs = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logprobs, batch["labels"][:, None], axis=-1)[:, 0]
        return nll.mean()

    return loss_fn
