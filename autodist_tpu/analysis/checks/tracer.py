"""GL004 — tracer leaks and host effects inside jit-compiled functions.

A jitted body runs ONCE at trace time with abstract tracers; host-side
effects inside it (mutating captured objects, ``print``, ``time``/``random``
reads) either leak tracers onto live objects — poisoning later non-traced
code with escaped-tracer errors — or silently bake a trace-time value into
the compiled program forever (a ``time.time()`` timestamp, a ``random``
draw). Both bug classes are invisible until a cache hit skips the retrace.
"""

import ast
from typing import List, Set

from autodist_tpu.analysis import callgraph
from autodist_tpu.analysis.core import Context, Finding, Module, register

_IMPURE_EXACT = {"print", "input", "breakpoint", "open"}
_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "jax.debug.breakpoint")


def _is_jit_decorator(dec) -> bool:
    name = callgraph.dotted_name(dec)
    if name is not None:
        return name == "jit" or name.endswith(".jit")
    if isinstance(dec, ast.Call):
        fn = callgraph.dotted_name(dec.func) or ""
        if fn == "jit" or fn.endswith(".jit"):
            return True
        if fn.endswith("partial") and dec.args:
            first = callgraph.dotted_name(dec.args[0]) or ""
            return first == "jit" or first.endswith(".jit")
    return False


def _jitted_defs(tree: ast.Module):
    """FunctionDefs compiled by jit: decorated, or passed to ``jax.jit(f)``."""
    wrapped: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = callgraph.dotted_name(node.func) or ""
            if (fn == "jit" or fn.endswith(".jit")) and node.args \
                    and isinstance(node.args[0], ast.Name):
                wrapped.add(node.args[0].id)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if any(_is_jit_decorator(d) for d in node.decorator_list) \
                or node.name in wrapped:
            yield node


def _bound_names(fn) -> Set[str]:
    """Names bound inside the function: locals (any Name store anywhere in
    the body, including nested defs/comprehensions) — NOT the parameters:
    storing attributes onto a parameter is itself a leak (arguments are
    tracers/pytrees owned by the caller)."""
    bound: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            bound.add(node.name)
    return bound


@register("GL004", "host effect / state mutation inside a jitted function")
def check_tracer_leak(module: Module, ctx: Context) -> List[Finding]:
    """GL004 — tracer leak.

    Inside a jit-compiled function (``@jax.jit``-decorated, or a local def
    later wrapped ``jax.jit(f)``), flags:

    - ``global``/``nonlocal`` declarations — mutating outer scope under
      trace stores a tracer (or a trace-time constant) where runtime code
      will read it;
    - attribute stores onto objects the function did not create
      (``self.x = ...``, ``captured.field = ...``) — the classic escaped
      tracer, which surfaces later as an UnexpectedTracerError in unrelated
      code (locals created inside the body are fine);
    - host-effect calls (``print``, ``time.*``, ``random.*``,
      ``np.random.*``, ``open``): they run once at trace time, so their
      value/effect is frozen into the executable — a jitted step "logging"
      via print prints once per compile, not per step, and a ``random``
      draw becomes a compile-time constant. Use ``jax.debug.print`` /
      ``jax.random`` with threaded keys instead.

    The repo keeps jitted bodies pure by construction (see
    ``runner._make_step_body``); this check keeps them that way.
    """
    if module.tree is None:
        return []
    findings: List[Finding] = []
    for fn in _jitted_defs(module.tree):
        bound = _bound_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                kind = "global" if isinstance(node, ast.Global) else "nonlocal"
                findings.append(Finding(
                    "GL004", module.relpath, node.lineno, node.col_offset,
                    f"`{kind} {', '.join(node.names)}` inside jitted "
                    f"`{fn.name}`: mutating outer scope under trace leaks "
                    f"tracers / freezes trace-time values",
                    scope=module.scope_at(node)))
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if not isinstance(t, ast.Attribute):
                        continue
                    root = t
                    while isinstance(root, ast.Attribute):
                        root = root.value
                    if isinstance(root, ast.Name) and root.id not in bound:
                        findings.append(Finding(
                            "GL004", module.relpath, node.lineno,
                            node.col_offset,
                            f"jitted `{fn.name}` stores onto captured object "
                            f"`{callgraph.dotted_name(t)}`: traced values "
                            f"escaping onto live objects poison later "
                            f"non-traced code (UnexpectedTracerError)",
                            scope=module.scope_at(node)))
            elif isinstance(node, ast.Call):
                name = callgraph.dotted_name(node.func) or ""
                if name in _IMPURE_EXACT \
                        or name.startswith(_IMPURE_PREFIXES):
                    findings.append(Finding(
                        "GL004", module.relpath, node.lineno, node.col_offset,
                        f"host call `{name}` inside jitted `{fn.name}` runs "
                        f"once at trace time, not per step (use "
                        f"jax.debug.print / jax.random with threaded keys)",
                        scope=module.scope_at(node)))
    return findings
