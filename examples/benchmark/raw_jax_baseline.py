"""Framework-free baseline: raw jax.jit(value_and_grad) + optax, no AutoDist.

The "no-framework program" side of each benchmark row's two-sided ceiling
proof (docs/usage/performance.md): if this rate matches the framework step's,
the distance to peak belongs to XLA/the model shape, not the strategy
machinery. Mirrors the imagenet benchmark's configs (same models, dtype,
optimizer, synthetic input, device-resident batch).

    python examples/benchmark/raw_jax_baseline.py --model densenet121 --batch_size 128
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import optax


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="densenet121",
                        choices=["resnet50", "vgg16", "densenet121",
                                 "inceptionv3"])
    parser.add_argument("--batch_size", type=int, default=128)
    parser.add_argument("--image_size", type=int, default=224)
    parser.add_argument("--steps", type=int, default=60)
    args = parser.parse_args(argv)

    from autodist_tpu.models import densenet, inception, resnet, vgg

    on_accel = jax.default_backend() != "cpu"
    dtype = jnp.bfloat16 if on_accel else jnp.float32
    if args.model == "inceptionv3":
        args.image_size = max(args.image_size, 299)

    if args.model == "resnet50":
        cfg = resnet.ResNet50Config(dtype=dtype)
        model, params = resnet.init_params(cfg, image_size=args.image_size)
        loss_fn = resnet.make_loss_fn(model)
        batch = resnet.synthetic_batch(cfg, args.batch_size, args.image_size)
    elif args.model == "densenet121":
        cfg = densenet.DenseNet121Config(dtype=dtype)
        model, params = densenet.init_params(cfg, image_size=args.image_size)
        loss_fn = densenet.make_loss_fn(model)
        batch = densenet.synthetic_batch(cfg, args.batch_size, args.image_size)
    elif args.model == "inceptionv3":
        cfg = inception.InceptionV3Config(dtype=dtype)
        model, params = inception.init_params(cfg, image_size=args.image_size)
        loss_fn = inception.make_loss_fn(model)
        batch = inception.synthetic_batch(cfg, args.batch_size, args.image_size)
    else:
        model = vgg.VGG16(dtype=dtype)
        params = vgg.init_params(model, image_size=args.image_size)
        loss_fn = vgg.make_loss_fn(model)
        batch = vgg.synthetic_batch(model.num_classes, args.batch_size,
                                    args.image_size)

    tx = optax.sgd(0.01, momentum=0.9)  # the imagenet benchmark's optimizer
    opt_state = tx.init(params)
    batch = {k: jax.device_put(jnp.asarray(v)) for k, v in batch.items()}

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, batch)
    jax.device_get(loss)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, batch)
    jax.device_get(loss)
    dt = time.perf_counter() - t0
    rate = args.batch_size * args.steps / dt
    print(f"raw-jax {args.model} bs{args.batch_size}: {rate:,.1f} examples/sec")

    from autodist_tpu.utils import flops as flops_util
    per_step = flops_util.jit_flops(step, params, opt_state, batch)
    flops_util.report_mfu(per_step, rate / args.batch_size)
    return rate


if __name__ == "__main__":
    main()
