// Native host data pipeline: a threaded prefetch ring over in-memory or
// memory-mapped datasets.
//
// Role in the framework: the reference delegated its input pipeline to TF's C++
// runtime (queues/iterators/staging, SURVEY.md §2.4 "host data plane"); here the
// equivalent native capability is owned in-tree. A background thread shuffles row
// indices (per-epoch reshuffle, seeded), gathers rows from the caller's arrays
// into pre-allocated batch slots, and hands full slots to the consumer — all
// outside the Python GIL (ctypes releases it for the duration of each call, and
// the gather/memcpy work happens on the worker thread regardless).
//
// Sources may be SEGMENTED: each key's rows live in one or more base pointers
// (file shards mapped with mmap via numpy's .npy memmap). The gather thread
// resolves a global row to (segment, local row) with a binary search over the
// shared segment-boundary table, so page faults on cold file pages happen on
// the worker thread, overlapped with the accelerator step — files larger than
// RAM stream through the page cache without ever materializing in full.
//
// C ABI only (no pybind11 in this environment): handles are opaque pointers,
// arrays are (ptr, row_bytes) pairs, batches are delivered by memcpy into
// caller-provided buffers.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct SourceArray {
  std::vector<const uint8_t*> segment_bases;  // one per dataset segment
  uint64_t row_bytes;
};

struct Slot {
  std::vector<std::vector<uint8_t>> buffers;  // one per source array
  bool full = false;
};

struct Loader {
  std::vector<SourceArray> arrays;
  // Segment boundaries in global row space: seg_starts[s] = first row of
  // segment s; seg_starts[n_segments] = n_rows. All keys share the table
  // (shards are row-aligned across keys).
  std::vector<uint64_t> seg_starts;
  uint64_t n_rows = 0;
  uint64_t batch_size = 0;
  bool shuffle = false;
  bool drop_last = true;  // continuous stream: partial batches are never emitted

  std::vector<Slot> slots;
  uint64_t produce_idx = 0;  // next slot the worker fills
  uint64_t consume_idx = 0;  // next slot the consumer drains
  std::mutex mu;
  std::condition_variable cv_can_produce;
  std::condition_variable cv_can_consume;

  std::thread worker;
  std::atomic<bool> stop{false};

  std::vector<uint64_t> perm;
  uint64_t cursor = 0;  // position within perm
  std::mt19937_64 rng;
  // Written by the worker thread outside the slot mutex (fill_slot runs
  // unlocked); read from Python at any time — atomic, not mutex-guarded.
  std::atomic<uint64_t> epochs_completed{0};

  void refill_perm() {
    if (perm.empty()) {
      perm.resize(n_rows);
      for (uint64_t i = 0; i < n_rows; ++i) perm[i] = i;
    }
    if (shuffle) {
      for (uint64_t i = n_rows - 1; i > 0; --i) {
        std::uniform_int_distribution<uint64_t> d(0, i);
        std::swap(perm[i], perm[d(rng)]);
      }
    }
    cursor = 0;
  }

  // Global row -> (segment, local row). One segment (the in-memory case) is
  // branch-free; multi-segment uses a binary search over seg_starts (the
  // memcpy dominates, so the log(n_segments) lookup is noise).
  inline void locate(uint64_t row, size_t* seg, uint64_t* local) const {
    if (seg_starts.size() == 2) {
      *seg = 0;
      *local = row;
      return;
    }
    size_t lo = 0, hi = seg_starts.size() - 1;
    while (hi - lo > 1) {
      const size_t mid = (lo + hi) / 2;
      if (seg_starts[mid] <= row) lo = mid; else hi = mid;
    }
    *seg = lo;
    *local = row - seg_starts[lo];
  }

  void fill_slot(Slot& slot) {
    // drop_last semantics: a tail shorter than batch_size is skipped and the
    // next (reshuffled) epoch begins — no partial batches, static shapes only.
    if (n_rows - cursor < batch_size) {
      ++epochs_completed;
      refill_perm();
    }
    for (uint64_t j = 0; j < batch_size; ++j) {
      const uint64_t row = perm[cursor++];
      size_t seg;
      uint64_t local;
      locate(row, &seg, &local);
      for (size_t a = 0; a < arrays.size(); ++a) {
        const uint64_t rb = arrays[a].row_bytes;
        std::memcpy(slot.buffers[a].data() + j * rb,
                    arrays[a].segment_bases[seg] + local * rb, rb);
      }
    }
  }

  void run() {
    refill_perm();
    for (;;) {
      std::unique_lock<std::mutex> lk(mu);
      cv_can_produce.wait(lk, [&] {
        return stop.load() || !slots[produce_idx % slots.size()].full;
      });
      if (stop.load()) return;
      Slot& slot = slots[produce_idx % slots.size()];
      lk.unlock();

      fill_slot(slot);  // the heavy gather happens without the lock

      lk.lock();
      slot.full = true;
      ++produce_idx;
      cv_can_consume.notify_one();
    }
  }
};

}  // namespace

extern "C" {

// Segmented creation: seg_ptrs is laid out [array][segment] (row-major,
// n_arrays * n_segments entries); seg_rows gives each segment's row count
// (shared by all arrays — shards are row-aligned across keys).
void* dl_create_sharded(uint64_t n_arrays, uint64_t n_segments,
                        const void** seg_ptrs, const uint64_t* row_bytes,
                        const uint64_t* seg_rows, uint64_t batch_size,
                        uint64_t queue_capacity, int shuffle, uint64_t seed) {
  if (n_arrays == 0 || n_segments == 0 || batch_size == 0 ||
      queue_capacity == 0) {
    return nullptr;
  }
  uint64_t n_rows = 0;
  for (uint64_t s = 0; s < n_segments; ++s) {
    if (seg_rows[s] == 0) return nullptr;
    n_rows += seg_rows[s];
  }
  if (batch_size > n_rows) return nullptr;
  auto* ld = new Loader();
  ld->n_rows = n_rows;
  ld->batch_size = batch_size;
  ld->shuffle = shuffle != 0;
  ld->rng.seed(seed);
  ld->seg_starts.resize(n_segments + 1);
  ld->seg_starts[0] = 0;
  for (uint64_t s = 0; s < n_segments; ++s) {
    ld->seg_starts[s + 1] = ld->seg_starts[s] + seg_rows[s];
  }
  for (uint64_t a = 0; a < n_arrays; ++a) {
    SourceArray src;
    src.row_bytes = row_bytes[a];
    for (uint64_t s = 0; s < n_segments; ++s) {
      src.segment_bases.push_back(
          static_cast<const uint8_t*>(seg_ptrs[a * n_segments + s]));
    }
    ld->arrays.push_back(std::move(src));
  }
  ld->slots.resize(queue_capacity);
  for (auto& slot : ld->slots) {
    slot.buffers.resize(n_arrays);
    for (uint64_t a = 0; a < n_arrays; ++a) {
      slot.buffers[a].resize(batch_size * row_bytes[a]);
    }
  }
  ld->worker = std::thread([ld] { ld->run(); });
  return ld;
}

// Single-segment convenience (the original in-memory ABI).
void* dl_create(uint64_t n_arrays, const void** array_ptrs,
                const uint64_t* row_bytes, uint64_t n_rows, uint64_t batch_size,
                uint64_t queue_capacity, int shuffle, uint64_t seed) {
  if (n_rows == 0) return nullptr;
  return dl_create_sharded(n_arrays, 1, array_ptrs, row_bytes, &n_rows,
                           batch_size, queue_capacity, shuffle, seed);
}

// Blocks until a batch is ready, then copies each array's rows into out_ptrs[a]
// (caller allocates batch_size * row_bytes[a] each). Returns 0 on success.
int dl_next(void* handle, void** out_ptrs) {
  auto* ld = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(ld->mu);
  ld->cv_can_consume.wait(lk, [&] {
    return ld->stop.load() || ld->slots[ld->consume_idx % ld->slots.size()].full;
  });
  if (ld->stop.load()) return 1;
  Slot& slot = ld->slots[ld->consume_idx % ld->slots.size()];
  for (size_t a = 0; a < ld->arrays.size(); ++a) {
    std::memcpy(out_ptrs[a], slot.buffers[a].data(), slot.buffers[a].size());
  }
  slot.full = false;
  ++ld->consume_idx;
  ld->cv_can_produce.notify_one();
  return 0;
}

uint64_t dl_epochs_completed(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  return ld->epochs_completed.load();
}

void dl_destroy(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(ld->mu);
    ld->stop.store(true);
  }
  ld->cv_can_produce.notify_all();
  ld->cv_can_consume.notify_all();
  if (ld->worker.joinable()) ld->worker.join();
  delete ld;
}

}  // extern "C"
