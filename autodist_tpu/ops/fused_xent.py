"""Fused LM-head softmax cross-entropy — pallas TPU kernels.

The separable-head formulation of the LM loss is

    nll_n = lse_n - true_logit_n,   lse_n = logsumexp_v(h_n . w_v + b_v)

where the [N, V] logits tensor (4.2 GB at the flagship's N=65k, V=32k, bf16) is
pure intermediate: XLA materializes it out of the head matmul, reads it for the
log-softmax reductions, and reads/writes it again for d(logits) in the backward
— the single largest HBM consumer in the training step. These kernels compute
``lse`` (and its VJP) **without ever materializing logits in HBM**: each
[n-block, v-block] logits tile lives only in VMEM, reduced on the fly with the
same online-logsumexp state machine as the flash-attention kernel
(``ops/flash_attention.py``), and the backward recomputes tiles from the saved
``lse`` exactly like flash attention recomputes scores (FlashAttention-2 style).
The true-logit term is a cheap gather-einsum left to XLA.

Three kernels:
- forward: grid (n-blocks, v-blocks); VMEM scratch carries (m, l) across the v
  dimension; last v-block writes ``lse = m + log l``.
- d(h):    grid (n-blocks, v-blocks); accumulates g*p @ w^T tiles in VMEM.
- d(w,b):  grid (v-blocks, n-blocks); accumulates h^T @ g*p and column-sums.

When to use (measured on a v5e chip): at the flagship size (N=65k, V=32k) this
is throughput-parity with XLA (73 vs 69 ms for loss+grads — the two backward
logit recomputes cost what the avoided HBM traffic saves), so the dense-head
models keep the XLA path. The win is **memory**: nothing here scales with N*V,
so configurations whose logits cannot exist run fine — measured: V=262k
(32 GiB of logits) and N=262k (16 GiB) both train where XLA OOMs, and
full-softmax cross-entropy over lm1b's exact 793,471-word vocabulary (48 GiB
of logits; the reference needed sampled softmax to avoid it) runs at ~41k
tokens/s/chip with exact gradients.

On non-TPU backends the kernels run in pallas interpret mode, so the CPU-sim
test mesh exercises the same code path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autodist_tpu.ops.blockwise_attention import NEG_INF
from autodist_tpu.ops.flash_attention import _use_interpret

_LANES = 128
DEFAULT_N_BLOCK = 512
DEFAULT_V_BLOCK = 1024


# ------------------------------------------------------------------- forward

def _fwd_kernel(h_ref, w_ref, b_ref, lse_ref, m_ref, l_ref, *, n_v: int):
    ni = pl.program_id(0)
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[0][None, :]   # [bn, bv]
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, logits.max(axis=-1, keepdims=True))
    p = jnp.exp(logits - m_new)
    l_ref[:] = jnp.broadcast_to(
        l_prev * jnp.exp(m_prev - m_new) + p.sum(axis=-1, keepdims=True),
        l_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(vi == n_v - 1)
    def _finish():
        lse_ref[0, ni, :] = m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))


def _pad_inputs(h, w, b, bn, bv):
    n, d = h.shape
    v = w.shape[1]
    n_n, n_v = pl.cdiv(n, bn), pl.cdiv(v, bv)
    if n_n * bn - n:
        h = jnp.pad(h, ((0, n_n * bn - n), (0, 0)))
    if n_v * bv - v:
        w = jnp.pad(w, ((0, 0), (0, n_v * bv - v)))
        # Padded vocab columns get a -inf bias: exp -> 0, invisible to the lse.
        b = jnp.pad(b, (0, n_v * bv - v), constant_values=NEG_INF)
    return h, w, b.reshape(1, -1), n_n, n_v


def _forward(h, w, b, bn, bv, interpret):
    n, d = h.shape
    hp, wp, bp, n_n, n_v = _pad_inputs(h, w, b, bn, bv)
    lse = pl.pallas_call(
        functools.partial(_fwd_kernel, n_v=n_v),
        grid=(n_n, n_v),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
        ],
        # Whole [n_n, bn] plane resident (a [1, bn] block violates TPU tiling);
        # 4 bytes/row — same layout rationale as the flash kernel's lse.
        out_specs=pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, n_n, bn), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((bn, _LANES), jnp.float32),   # running max
            pltpu.VMEM((bn, _LANES), jnp.float32),   # running denominator
        ],
        interpret=interpret,
    )(hp, wp, bp)
    return lse.reshape(n_n * bn)[:n]


# ------------------------------------------------------------------ backward

def _dh_kernel(h_ref, w_ref, b_ref, lse_ref, g_ref, dh_ref, acc_ref, *, n_v: int):
    ni = pl.program_id(0)
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[0][None, :]
    lse = lse_ref[0, ni, :]                                   # [bn]
    gp = jnp.exp(logits - lse[:, None]) * g_ref[0, ni, :][:, None]  # [bn, bv]
    acc_ref[:] += jax.lax.dot_general(
        gp.astype(w_ref.dtype), w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [bn, d]

    @pl.when(vi == n_v - 1)
    def _finish():
        dh_ref[...] = acc_ref[:].astype(dh_ref.dtype)


def _dwdb_kernel(h_ref, w_ref, b_ref, lse_ref, g_ref, dw_ref, db_ref,
                 dw_acc, db_acc, *, n_n: int):
    ni = pl.program_id(1)  # read at top level: program_id is invalid inside when-bodies in interpret mode

    @pl.when(ni == 0)
    def _init():
        dw_acc[:] = jnp.zeros_like(dw_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b_ref[0][None, :]   # [bn, bv]
    lse = lse_ref[0, ni, :]
    gp = jnp.exp(logits - lse[:, None]) * g_ref[0, ni, :][:, None]
    dw_acc[:] += jax.lax.dot_general(
        h_ref[...], gp.astype(h_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                   # [d, bv]
    db_acc[:, :] += jnp.broadcast_to(gp.sum(axis=0)[None, :], db_acc.shape)

    @pl.when(ni == n_n - 1)
    def _finish():
        dw_ref[...] = dw_acc[:].astype(dw_ref.dtype)
        db_ref[...] = db_acc[:1, :].astype(db_ref.dtype)


def _backward(h, w, b, lse, g, bn, bv, interpret):
    n, d = h.shape
    v = w.shape[1]
    hp, wp, bp, n_n, n_v = _pad_inputs(h, w, b, bn, bv)
    lse_p = jnp.pad(lse, (0, n_n * bn - n)).reshape(1, n_n, bn)
    # Padding rows must contribute nothing: their incoming gradient pads as zero.
    g_p = jnp.pad(g.astype(jnp.float32), (0, n_n * bn - n)).reshape(1, n_n, bn)

    dh = pl.pallas_call(
        functools.partial(_dh_kernel, n_v=n_v),
        grid=(n_n, n_v),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
            pl.BlockSpec((d, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((1, n_n, bn), lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_n * bn, d), h.dtype),
        scratch_shapes=[pltpu.VMEM((bn, d), jnp.float32)],
        interpret=interpret,
    )(hp, wp, bp, lse_p, g_p)[:n]

    dw, db = pl.pallas_call(
        functools.partial(_dwdb_kernel, n_n=n_n),
        grid=(n_v, n_n),
        in_specs=[
            pl.BlockSpec((bn, d), lambda j, i: (i, 0)),
            pl.BlockSpec((d, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, n_n, bn), lambda j, i: (0, 0, 0)),
            pl.BlockSpec((1, n_n, bn), lambda j, i: (0, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((d, bv), lambda j, i: (0, j)),
            pl.BlockSpec((1, bv), lambda j, i: (0, j)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((d, n_v * bv), w.dtype),
            jax.ShapeDtypeStruct((1, n_v * bv), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((d, bv), jnp.float32),
            pltpu.VMEM((_LANES, bv), jnp.float32),
        ],
        interpret=interpret,
    )(hp, wp, bp, lse_p, g_p)
    return dh, dw[:, :v], db[0, :v]


# ----------------------------------------------------------------- public op

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def matmul_logsumexp(h, w, b, n_block: int = DEFAULT_N_BLOCK,
                     v_block: int = DEFAULT_V_BLOCK,
                     interpret: bool = None):
    """``logsumexp(h @ w + b, axis=-1)`` without materializing the logits.

    h: [N, D] (bf16/f32), w: [D, V], b: [V] (or None for no bias).
    Returns f32 [N]. Differentiable in h, w, b (custom VJP recomputes logits
    tiles from the saved lse).
    """
    lse, _ = _mls_fwd(h, w, b, n_block, v_block, interpret)
    return lse


def _mls_fwd(h, w, b, n_block, v_block, interpret):
    if interpret is None:
        interpret = _use_interpret()
    has_bias = b is not None
    bvec = b if has_bias else jnp.zeros((w.shape[1],), jnp.float32)
    lse = _forward(h, w, bvec, n_block, v_block, interpret)
    return lse, (h, w, bvec, lse, has_bias)


def _mls_bwd(n_block, v_block, interpret, res, g):
    if interpret is None:
        interpret = _use_interpret()
    h, w, bvec, lse, has_bias = res
    dh, dw, db = _backward(h, w, bvec, lse, g, n_block, v_block, interpret)
    return dh, dw, (db if has_bias else None)


matmul_logsumexp.defvjp(_mls_fwd, _mls_bwd)


def fused_softmax_xent(h, w, targets, b=None, n_block: int = DEFAULT_N_BLOCK,
                       v_block: int = DEFAULT_V_BLOCK) -> jax.Array:
    """Per-row NLL of ``targets`` under ``softmax(h @ w + b)`` — the fused-head
    loss. h: [N, D], w: [D, V], targets: int [N]. Returns f32 [N].

    The lse term runs through the pallas kernels; the true-logit term is a
    gather-einsum XLA handles well (its grad is the row-sparse scatter).
    """
    lse = matmul_logsumexp(h, w, b, n_block, v_block, None)
    w_true = jnp.take(w, targets, axis=1)                  # [D, N]
    true_logit = jnp.einsum("nd,dn->n", h, w_true,
                            preferred_element_type=jnp.float32)
    if b is not None:
        true_logit = true_logit + b[targets]
    return lse - true_logit
