"""Elastic async-PS membership: retire on failure, register a replacement.

The reference's only failure policy was fail-fast — the coordinator hard-kills
the chief on any worker exit (``coordinator.py:98-110``); this framework's
retire/register pair makes the async plane's membership elastic: a crashed
worker is retired from the staleness gate (round-2 feature), and a replacement
process re-registers mid-run, seeded at the slowest live worker's step count so
it neither wedges the gate nor surges past the bound.
"""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.parallel.staleness import StalenessController, StalenessTimeout
from autodist_tpu.strategy import PS

BATCH = 16


def _data(seed=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(BATCH).astype(np.float32)
    return {"x": x, "y": (2.0 * x - 1.0).astype(np.float32)}


def _loss(p, b):
    return jnp.mean((b["y"] - (b["x"] * p["w"] + p["b"])) ** 2)


def _params():
    return {"w": np.zeros((), np.float32), "b": np.zeros((), np.float32)}


# ----------------------------------------------------------------- controller

def test_register_seeds_at_slowest_live_count():
    c = StalenessController(num_workers=2, staleness=2)
    for _ in range(5):
        c.start_step(0, timeout=1)
        c.finish_step(0)
        c.start_step(1, timeout=1)
        c.finish_step(1)
    c.retire(1)
    # Replacement joins at min(live) = 5, NOT 0 (0 would wedge worker 0).
    assert c.register(1) == 1
    assert c.steps == [5, 5]
    c.start_step(0, timeout=1)  # gate open: 5 - 5 < 2
    c.finish_step(0)


def test_register_zero_seed_would_have_wedged():
    """The scenario the min(live) seed exists for: without it, a rejoined
    worker at step 0 pins the gate for everyone at the bound."""
    c = StalenessController(num_workers=2, staleness=1)
    c.start_step(0, timeout=1)
    c.finish_step(0)   # worker 0 at 1, worker 1 at 0 -> 0 is at the bound
    c.retire(1)
    c.register(1)      # seeds at 1, not 0
    c.start_step(0, timeout=0.5)  # would raise StalenessTimeout with a 0 seed
    c.finish_step(0)
    with pytest.raises(StalenessTimeout):
        c.start_step(0, timeout=0.2)  # now genuinely ahead of the replacement


def test_register_live_slot_is_idempotent_noop():
    """A retried register (transport hiccup) or an operator add_worker on a
    live slot must NOT reset the worker's count — that would let it run up to
    2x the staleness bound past the true slowest."""
    c = StalenessController(num_workers=2, staleness=2)
    for _ in range(2):
        c.start_step(0, timeout=1)
        c.finish_step(0)
    assert c.register(0) == 0
    assert c.steps == [2, 0]  # count preserved, no reseed past the bound


def test_stale_retire_after_reregister_is_ignored():
    """A handler that observed the OLD occupant of a slot (generation g) must
    not retire the live replacement (generation g+1) when its dead socket
    finally errors out."""
    c = StalenessController(num_workers=2, staleness=2)
    old_gen = c.generation(1)
    c.retire(1)                      # old occupant's connection dies
    c.register(1)                    # replacement joins -> generation bumps
    c.retire(1, generation=old_gen)  # stale handler fires late: must no-op
    c.start_step(1, timeout=1)       # slot is still live
    c.finish_step(1)
    # An unconditional retire (no generation) still works.
    c.retire(1)
    assert 1 not in [i for i in range(2) if i not in c._retired]


def test_idempotent_register_still_bumps_generation():
    """A reconnecting client retries register on a LIVE slot (its old
    connection is dead but the server hasn't noticed): the count must stay,
    but the generation must bump so the old connection's deferred retire
    cannot remove the live reconnection."""
    c = StalenessController(num_workers=2, staleness=2)
    old_gen = c.generation(1)
    c.register(1)  # idempotent: live slot
    assert c.generation(1) == old_gen + 1
    c.retire(1, generation=old_gen)  # old connection finally dies: no-op
    c.start_step(1, timeout=1)
    c.finish_step(1)


def test_register_rejects_negative_id():
    c = StalenessController(num_workers=2, staleness=2)
    with pytest.raises(ValueError, match=">= 0"):
        c.register(-1)


def test_register_new_slot_allocates_next_id():
    c = StalenessController(num_workers=2, staleness=0)
    assert c.register() == 2
    assert len(c.steps) == 3


def test_register_sparse_id_leaves_gaps_retired():
    c = StalenessController(num_workers=1, staleness=2)
    assert c.register(3) == 3
    assert len(c.steps) == 4
    # The never-registered gap slots (1, 2) must not gate anyone.
    c.start_step(0, timeout=1)
    c.finish_step(0)
    c.start_step(3, timeout=1)
    c.finish_step(3)


# ------------------------------------------------------------------ in-process

def test_runner_add_worker_replaces_crashed_worker():
    batch = _data()
    ad = AutoDist(strategy_builder=PS(staleness=2))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(0.05),
                                           example_batch=batch, num_workers=2)
    runner.init(_params())
    w0, w1 = runner.worker(0), runner.worker(1)
    for _ in range(2):
        w0.step(batch, timeout=5)
        w1.step(batch, timeout=5)
    runner.controller.retire(1)  # "crash"
    # Worker 0 is not wedged by the frozen count...
    for _ in range(3):
        w0.step(batch, timeout=5)
    # ...and a replacement rejoins at the live pace and gates normally.
    w1b = runner.add_worker(1)
    w1b.step(batch, timeout=5)
    assert runner.service.updates_applied == 2 + 2 + 3 + 1
    # A brand-new elastic slot works too.
    w2 = runner.add_worker()
    assert w2.worker_id == 2
    w2.step(batch, timeout=5)
    assert runner.service.updates_applied == 9
    # Sparse elastic ids: gap slots have no handle and say so.
    w5 = runner.add_worker(5)
    assert w5.worker_id == 5
    with pytest.raises(ValueError, match="no handle"):
        runner.worker(4)


# ------------------------------------------------------------------ transport

def test_remote_replacement_worker_reregisters():
    """End-to-end over the loopback transport: a remote worker disconnects
    (server retires it), a NEW RemotePSWorker for the same slot re-registers
    and training continues — the elastic-recovery path the reference lacked."""
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker

    batch = _data()
    ad = AutoDist(strategy_builder=PS(staleness=2))
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(0.05),
                                           example_batch=batch, num_workers=2)
    runner.init(_params())
    server = PSServer(runner, host="127.0.0.1")
    host, port = server.address
    chief = runner.worker(0)

    remote = RemotePSWorker(f"{host}:{port}", runner, worker_id=1)
    remote.step(batch, timeout=10)
    chief.step(batch, timeout=10)
    remote.close()  # simulated crash: the server handler retires worker 1

    # The chief keeps going (retirement frees the gate)...
    import time
    deadline = time.time() + 10
    while 1 not in runner.controller._retired and time.time() < deadline:
        time.sleep(0.02)
    for _ in range(4):
        chief.step(batch, timeout=10)

    # ...and a replacement process re-registers the slot and steps.
    remote2 = RemotePSWorker(f"{host}:{port}", runner, worker_id=1)
    assert remote2.register() == 1
    for _ in range(2):
        remote2.step(batch, timeout=10)
    assert runner.service.updates_applied == 1 + 1 + 4 + 2
    # A remote register routes through add_worker: chief-side bookkeeping
    # (num_workers, handle table) tracks the gate.
    assert runner.num_workers >= 2 and 1 in runner._workers
    # Gate is live again: the chief is bounded by the replacement's pace.
    assert runner.controller.steps[1] >= 2

    # A replacement that registers and dies BEFORE its first step must still
    # be retired (the handler learns the id from the register op itself).
    remote2.close()
    deadline = time.time() + 10
    while 1 not in runner.controller._retired and time.time() < deadline:
        time.sleep(0.02)
    assert 1 in runner.controller._retired
    remote3 = RemotePSWorker(f"{host}:{port}", runner, worker_id=1)
    assert remote3.register() == 1
    remote3.close()  # dies having never stepped
    deadline = time.time() + 10
    while 1 not in runner.controller._retired and time.time() < deadline:
        time.sleep(0.02)
    assert 1 in runner.controller._retired
    # The chief is not wedged by the stillborn replacement.
    for _ in range(3):
        chief.step(batch, timeout=10)
    server.close()
