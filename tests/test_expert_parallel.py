"""Expert parallelism: Switch routing semantics, expert-axis sharding, e2e training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from autodist_tpu import AutoDist, ResourceSpec
from autodist_tpu.models import moe
from autodist_tpu.parallel.plan import ShardingPlan
from autodist_tpu.strategy import ExpertParallel, StrategyCompiler
from autodist_tpu.model_spec import ModelSpec

TINY = moe.MoETransformerLMConfig(
    vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_len=32,
    n_experts=4, capacity_factor=2.0, dtype=jnp.float32)


def _spec_for(n_devices=8, mesh=None):
    return ResourceSpec(resource_info={
        "nodes": [{"address": "localhost", "tpus": n_devices, "chief": True}],
        **({"mesh": mesh} if mesh else {}),
    })


def test_switch_route_matches_per_token_reference():
    # With capacity >= tokens, nothing drops: the MoE FFN must equal applying each
    # token's argmax expert FFN individually, weighted by its router probability.
    rng = np.random.RandomState(0)
    b, s, m, e, f = 2, 8, 6, 4, 10
    x = rng.randn(b, s, m).astype(np.float32)
    wr = rng.randn(m, e).astype(np.float32)
    w_in = rng.randn(e, m, f).astype(np.float32)
    w_out = rng.randn(e, f, m).astype(np.float32)

    dispatch, combine, _aux = moe.switch_route(jnp.asarray(x @ wr), capacity=s)
    expert_in = jnp.einsum("bsec,bsm->ebcm", dispatch, jnp.asarray(x))
    h = jax.nn.gelu(jnp.einsum("ebcm,emf->ebcf", expert_in, jnp.asarray(w_in)))
    out = jnp.einsum("ebcf,efm->ebcm", h, jnp.asarray(w_out))
    y = np.asarray(jnp.einsum("bsec,ebcm->bsm", combine, out))

    probs = np.exp(x @ wr - (x @ wr).max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    for bi in range(b):
        for si in range(s):
            ei = int(np.argmax(probs[bi, si]))
            ref = np.asarray(
                jax.nn.gelu(jnp.asarray(x[bi, si] @ w_in[ei]))) @ w_out[ei]
            np.testing.assert_allclose(y[bi, si], probs[bi, si, ei] * ref,
                                       rtol=1e-4, atol=1e-5)


def test_switch_route_respects_capacity():
    # All tokens prefer expert 0; with capacity 2 only the first 2 per batch row
    # may be dispatched, the rest drop (all-zero dispatch rows).
    logits = jnp.zeros((1, 6, 4)).at[:, :, 0].set(10.0)
    dispatch, combine, _ = moe.switch_route(logits, capacity=2)
    per_token = np.asarray(dispatch.sum(axis=(2, 3)))     # [1, 6]
    np.testing.assert_array_equal(per_token[0], [1, 1, 0, 0, 0, 0])
    assert float(dispatch[..., 0, :].sum()) == 2.0        # expert 0 exactly full


def test_expert_parallel_plan_shards_expert_axis():
    model, params = moe.init_params(TINY)
    model_spec = ModelSpec.from_params(params)
    rs = _spec_for(8)
    builder = ExpertParallel(num_experts=TINY.n_experts, expert_axis_size=2)
    strategy = StrategyCompiler(model_spec, rs).compile(builder.build(model_spec, rs))
    assert strategy.mesh_axes()["expert"] == 2
    assert strategy.mesh_axes()["data"] == 4

    plan = ShardingPlan.from_strategy(strategy, model_spec)
    expert_plans = [p for n, p in plan.params.items() if "experts_" in n]
    assert len(expert_plans) == 2 * TINY.n_layers
    for p in expert_plans:
        assert p.partition_mesh_axis == "expert"
        assert p.pspec[0] == "expert"
    # Non-expert params stay replicated.
    assert plan.params[[n for n in plan.params if "router" in n][0]].pspec == \
        jax.sharding.PartitionSpec()


def test_moe_fused_head_matches_xla_head():
    """fused_head=True on the MoE LM equals the XLA-head loss (incl. the
    router aux term) and trains under ExpertParallel."""
    import dataclasses

    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.strategy import ExpertParallel

    cfg_f = dataclasses.replace(TINY, fused_head=True)
    model, params = moe.init_params(TINY)
    model_f = moe.MoETransformerLM(cfg_f)
    batch = moe.synthetic_batch(TINY, batch_size=4, seq_len=16)
    l_xla = float(moe.make_loss_fn(model)(params, batch))
    l_fused = float(moe.make_loss_fn(model_f)(params, batch))
    np.testing.assert_allclose(l_fused, l_xla, rtol=1e-5)

    ad = AutoDist(_spec_for(), strategy_builder=ExpertParallel(num_experts=4))
    step = ad.function(moe.make_loss_fn(model_f), params, optax.adam(1e-2),
                       example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_moe_trains_expert_parallel_and_state_is_sharded():
    model, params = moe.init_params(TINY)
    loss_fn = moe.make_loss_fn(model)
    batch = moe.synthetic_batch(TINY, batch_size=8, seq_len=16)
    ad = AutoDist(_spec_for(8), strategy_builder=ExpertParallel(
        num_experts=TINY.n_experts, expert_axis_size=2))
    step = ad.function(loss_fn, params, optax.adam(1e-2), example_batch=batch)
    losses = [float(step(batch)) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

    # The live expert weights are stored sharded over the expert mesh axis.
    state = step.get_state()
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    expert_leaves = [(path, leaf) for path, leaf in flat
                     if "experts_" in "/".join(str(p) for p in path)]
    assert expert_leaves
    for _, leaf in expert_leaves:
        spec = leaf.sharding.spec
        assert spec and spec[0] == "expert"


def test_moe_expert_parallel_matches_single_device():
    # Same params, same batch: the expert-parallel step's loss equals the
    # unsharded loss (routing and dispatch are deterministic).
    model, params = moe.init_params(TINY)
    loss_fn = moe.make_loss_fn(model)
    batch = moe.synthetic_batch(TINY, batch_size=8, seq_len=16)
    expected = float(loss_fn(params, {k: jnp.asarray(v) for k, v in batch.items()}))

    ad = AutoDist(_spec_for(8), strategy_builder=ExpertParallel(
        num_experts=TINY.n_experts, expert_axis_size=2))
    step = ad.function(loss_fn, params, optax.sgd(0.0), example_batch=batch)
    np.testing.assert_allclose(float(step(batch)), expected, rtol=2e-5)
