"""Native C++ framed-transport data plane vs the Python fallback.

Both speak the identical framing (8-byte big-endian length + payload) and the
typed wire payload codec (``parallel/wire.py`` — NOT pickle), so any mix of
endpoints interoperates; these tests drive every pairing over a real
socketpair with multi-MB tensor payloads, and prove no pickle ever touches
the wire path.
"""

import socket
import struct
import threading

import numpy as np
import pytest

from autodist_tpu.parallel import ps_transport as tp
from autodist_tpu.parallel import wire


def _python_send(sock, obj):
    """Hand-rolled fallback endpoint: explicit framing + wire payload."""
    payload = wire.encode(obj)
    sock.sendall(struct.Struct("!Q").pack(len(payload)) + payload)


def _python_recv(sock):
    hdr = struct.Struct("!Q")
    (n,) = hdr.unpack(tp._recv_exact(sock, hdr.size))
    return wire.decode(tp._recv_exact(sock, n))


def _payloads():
    rng = np.random.RandomState(0)
    return [
        {"grads": {"w": rng.randn(512, 513).astype(np.float32)},
         "version": 7, "worker": 1},
        ("pull", 3),
        {"big": rng.randn(1 << 21).astype(np.float32)},   # 8 MB
        b"",
    ]


def _roundtrip(send_fn, recv_fn):
    a, b = socket.socketpair()
    try:
        results = []
        def reader():
            for _ in range(len(_payloads())):
                results.append(recv_fn(b))
        t = threading.Thread(target=reader)
        t.start()
        for msg in _payloads():
            send_fn(a, msg)
        t.join(timeout=30)
        assert not t.is_alive()
        return results
    finally:
        a.close()
        b.close()


def _check(results):
    expected = _payloads()
    assert len(results) == len(expected)
    np.testing.assert_array_equal(results[0]["grads"]["w"],
                                  expected[0]["grads"]["w"])
    assert results[0]["version"] == 7
    assert results[1] == ("pull", 3)
    np.testing.assert_array_equal(results[2]["big"], expected[2]["big"])
    assert results[3] == b""


def test_python_fallback_roundtrip():
    _check(_roundtrip(_python_send, _python_recv))


@pytest.mark.skipif(tp._native_transport() is None,
                    reason="native transport unavailable (no g++)")
@pytest.mark.parametrize("pairing", ["native<->native", "native->python",
                                     "python->native"])
def test_native_and_mixed_roundtrips(pairing):
    send_fn = tp._send_msg if pairing != "python->native" else _python_send
    recv_fn = (lambda s: tp._recv_msg(s)[0]) if pairing != "native->python" \
        else _python_recv
    # _send_msg/_recv_msg route to the native lib (sockets are blocking here).
    _check(_roundtrip(send_fn, recv_fn))


@pytest.mark.skipif(tp._native_transport() is None,
                    reason="native transport unavailable (no g++)")
def test_timeout_sockets_use_python_path():
    """A socket with a timeout must keep Python timeout semantics (native raw
    -fd syscalls would bypass them), and still interoperate."""
    a, b = socket.socketpair()
    try:
        b.settimeout(30.0)
        tp._send_msg(a, {"x": 1})              # native (blocking side)
        assert tp._recv_msg(b)[0] == {"x": 1}  # python (timeout side)
        with pytest.raises(socket.timeout):
            b.settimeout(0.2)
            tp._recv_msg(b)
    finally:
        a.close()
        b.close()


def test_peer_close_raises_connection_error():
    a, b = socket.socketpair()
    a.close()
    try:
        with pytest.raises(ConnectionError):
            tp._recv_msg(b)
    finally:
        b.close()


# ------------------------------------------------------------ typed wire path

def test_wire_codec_protocol_vocabulary():
    """Every shape the protocol sends round-trips: nested numpy pytrees,
    scalars, None timeouts, error tuples, big ints, bf16 tensors, and
    registered compressor-state dataclasses."""
    import jax.numpy as jnp

    from autodist_tpu.parallel.synchronization import EFState, PowerSGDState

    rng = np.random.RandomState(3)
    msgs = [
        ("start_step", 1, None),
        ("start_step", 0, 10.0),
        ("ok", {"layer": {"w": rng.randn(33, 4).astype(np.float32),
                          "b": np.zeros((4,), np.float32)}},
         {"layer": {"w": EFState(error=rng.randn(2, 33, 4))}}, 12),
        ("ok", {"q": PowerSGDState(error=rng.randn(1, 8, 4),
                                   q=rng.randn(4, 2))}, None, 3),
        ("error", "StalenessTimeout", "worker 1 ... after 10s"),
        ("ok", 1 << 80),
        {"bf16": np.asarray(jnp.ones((3, 2), jnp.bfloat16)),
         "flags": [True, False, None], "nested": (1, "two", b"\x00\xff")},
        # Scalar (0-d) gradients must stay 0-d: ascontiguousarray-style
        # promotion to (1,) would silently reshape the service's params.
        ("apply", {"w": np.float32(0.5), "b": np.zeros((), np.float32)}),
    ]
    for m in msgs:
        d = wire.decode(wire.encode(m))
        flat_a = _flatten(m)
        flat_b = _flatten(d)
        assert len(flat_a) == len(flat_b)
        for x, y in zip(flat_a, flat_b):
            if isinstance(x, np.ndarray):
                assert x.dtype == y.dtype and x.shape == y.shape
                np.testing.assert_array_equal(
                    np.asarray(x, np.float32), np.asarray(y, np.float32))
            else:
                assert x == y, (x, y)


def _flatten(obj):
    import jax
    from autodist_tpu.parallel.synchronization import EFState, PowerSGDState
    leaves = jax.tree_util.tree_leaves(
        obj, is_leaf=lambda x: isinstance(x, (np.ndarray, bytes)))
    return [np.asarray(l) if hasattr(l, "dtype") else l for l in leaves]


def test_wire_codec_fuzz_roundtrip():
    """Property fuzz: 200 random nested structures from the wire vocabulary
    round-trip exactly, and random byte garbage never escapes WireError."""
    rng = np.random.RandomState(7)
    dtypes = [np.float32, np.int32, np.int64, np.uint8, np.float64, np.bool_]

    def rand_value(depth=0):
        kind = rng.randint(0, 10 if depth < 3 else 7)
        if kind == 0:
            return None
        if kind == 1:
            return bool(rng.randint(2))
        if kind == 2:
            # Mix i64-range ints with arbitrary-precision ones so the 'I'
            # decimal-string escape path gets fuzzed in nested shapes too.
            if rng.randint(4) == 0:
                return int(rng.randint(-2**40, 2**40)) << 70
            return int(rng.randint(-2**40, 2**40))
        if kind == 3:
            return float(rng.randn())
        if kind == 4:
            return "".join(chr(rng.randint(32, 0x2FA0))
                           for _ in range(rng.randint(0, 12)))
        if kind == 5:
            return bytes(rng.randint(0, 256, size=rng.randint(0, 20),
                                     dtype=np.uint8))
        if kind == 6:
            shape = tuple(rng.randint(0, 4)
                          for _ in range(rng.randint(0, 3)))
            dt = dtypes[rng.randint(len(dtypes))]
            arr = np.asarray(rng.randn(*shape) * 100).astype(dt)
            if rng.randint(2) and arr.ndim >= 2:
                arr = np.asfortranarray(arr)   # layout must not matter
            return arr
        n = rng.randint(0, 4)
        if kind == 7:
            return tuple(rand_value(depth + 1) for _ in range(n))
        if kind == 8:
            return [rand_value(depth + 1) for _ in range(n)]
        return {f"k{j}": rand_value(depth + 1) for j in range(n)}

    def eq(a, b):
        if isinstance(a, np.ndarray):
            return (isinstance(b, np.ndarray) and a.dtype == b.dtype
                    and a.shape == b.shape and np.array_equal(a, b))
        if isinstance(a, tuple):
            return (isinstance(b, tuple) and len(a) == len(b)
                    and all(eq(x, y) for x, y in zip(a, b)))
        if isinstance(a, list):
            return (isinstance(b, list) and len(a) == len(b)
                    and all(eq(x, y) for x, y in zip(a, b)))
        if isinstance(a, dict):
            return (isinstance(b, dict) and a.keys() == b.keys()
                    and all(eq(v, b[k]) for k, v in a.items()))
        return type(a) is type(b) and a == b

    for _ in range(200):
        v = rand_value()
        assert eq(v, wire.decode(wire.encode(v))), v

    for _ in range(200):
        junk = bytes(rng.randint(0, 256, size=rng.randint(1, 64),
                                 dtype=np.uint8))
        try:
            wire.decode(junk)
        except wire.WireError:
            pass  # the only acceptable failure type


def test_no_pickle_anywhere_in_wire_path(monkeypatch):
    """A full server<->remote-worker exchange with pickle disabled outright:
    the protocol must never touch it (the reference's typed protobuf plane
    property, grpc servers notwithstanding)."""
    import pickle

    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker
    from autodist_tpu.strategy import PS

    def poisoned(*a, **k):
        raise AssertionError("pickle reached the wire path")

    params = {"w": np.zeros((4,), np.float32)}
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 4).astype(np.float32),
             "y": rng.randn(16).astype(np.float32)}

    def loss(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=PS(staleness=2))
    runner = ad.create_distributed_session(loss, params, optax.sgd(0.05),
                                           example_batch=batch, num_workers=2)
    runner.init(params)
    server = PSServer(runner, host="127.0.0.1")
    host, port = server.address
    try:
        monkeypatch.setattr(pickle, "dumps", poisoned)
        monkeypatch.setattr(pickle, "loads", poisoned)
        monkeypatch.setattr(pickle, "Pickler", poisoned)
        monkeypatch.setattr(pickle, "Unpickler", poisoned)
        remote = RemotePSWorker(f"{host}:{port}", runner, worker_id=1)
        chief = runner.worker(0)
        for _ in range(2):
            remote.step(batch, timeout=10)
            chief.step(batch, timeout=10)
        assert remote.version == 4
        remote.close()
    finally:
        server.close()


def test_hostile_payload_cannot_execute(monkeypatch):
    """A peer that frames a PICKLE payload (the classic RCE vector) gets its
    connection dropped with nothing evaluated; the server keeps serving."""
    import pickle

    import jax.numpy as jnp
    import optax

    from autodist_tpu import AutoDist
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker
    from autodist_tpu.strategy import PS

    params = {"w": np.zeros((4,), np.float32)}
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(16, 4).astype(np.float32),
             "y": rng.randn(16).astype(np.float32)}

    def loss(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    ad = AutoDist(strategy_builder=PS(staleness=1))
    runner = ad.create_distributed_session(loss, params, optax.sgd(0.05),
                                           example_batch=batch, num_workers=1)
    runner.init(params)
    server = PSServer(runner, host="127.0.0.1")
    host, port = server.address

    executed = []

    class Bomb:
        def __reduce__(self):
            return (executed.append, ("boom",))

    try:
        evil = pickle.dumps(Bomb())
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(struct.Struct("!Q").pack(len(evil)) + evil)
        # Server must close the connection without evaluating anything.
        s.settimeout(10)
        assert s.recv(1) == b""  # EOF: dropped
        s.close()
        assert executed == []
        # And it still serves well-formed clients.
        remote = RemotePSWorker(f"{host}:{port}", runner, worker_id=0)
        remote.step(batch, timeout=10)
        remote.close()
    finally:
        server.close()
