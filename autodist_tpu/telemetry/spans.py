"""Host-side span tracing: a thread-aware timeline for the dispatch loop.

``jax.profiler`` answers "what did the DEVICE do"; nothing answered "what did
the HOST do between dispatches" — data wait, feed sharding, gate round-trips,
readback sync. This module records named wall-clock spans into a bounded
in-memory ring buffer, exportable as Chrome trace-event JSON
(:func:`autodist_tpu.telemetry.export_chrome_trace`) that loads in Perfetto
next to the device trace (``docs/usage/observability.md`` shows the overlay
workflow).

Cost contract: when telemetry is DISABLED (the default), :func:`span` performs
exactly one attribute read and returns a shared no-op context manager — the
instrumented hot paths (``runner.run``, the train loop, the PS client) pay
nanoseconds per step, gated in ``bench.py --telemetry-overhead``. When
enabled, a span costs two ``perf_counter_ns`` reads plus, under one
uncontended lock, two intern-table lookups and five deque appends (the ring
is columnar — see :class:`_State` — so full-ring exports are C-speed; that
side is gated by ``bench.py --trace-pull-overhead``).

Spans nest by containment: Chrome's trace viewer stacks same-thread ``"X"``
(complete) events whose time ranges nest, so no explicit parent ids are kept.
"""

import collections
import functools
import os
import threading
import time
from typing import Any, Dict, Optional

from autodist_tpu import const
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["span", "traced", "enable", "disable", "enabled", "clear",
           "snapshot_spans"]


class _State:
    """Process-global telemetry state. ``enabled`` is THE hot-path gate: the
    disabled fast path reads this one attribute and nothing else.

    The ring is COLUMNAR: five aligned deques (interned name id, interned
    tid id, t0, dur, args) appended in lockstep under the lock, with the
    name/tid intern tables alongside. Recording costs a couple of dict
    lookups and five C appends; the payoff is that a FULL-ring export
    (the cluster trace plane's ``trace`` opcode pull) is a handful of
    ``list(deque)``/``np.array`` C calls instead of 65k Python tuple
    visits — ``bench.py --trace-pull-overhead`` gates exactly that."""

    __slots__ = ("enabled", "name_ids", "tid_ids", "ring_name", "ring_tid",
                 "ring_t0", "ring_dur", "ring_args", "thread_names", "lock",
                 "epoch_ns")

    def __init__(self, capacity: int):
        self.enabled = False
        # Intern tables: name/tid -> dense id (insertion-ordered; the export
        # tables are list(...) of the keys). Bounded by the set of distinct
        # span names / threads, like thread_names.
        self.name_ids: Dict[str, int] = {}
        self.tid_ids: Dict[int, int] = {}
        self.ring_name = collections.deque(maxlen=capacity)
        self.ring_tid = collections.deque(maxlen=capacity)
        self.ring_t0 = collections.deque(maxlen=capacity)
        self.ring_dur = collections.deque(maxlen=capacity)
        self.ring_args = collections.deque(maxlen=capacity)
        self.thread_names: Dict[int, str] = {}
        self.lock = san_lock()
        # Export offsets span timestamps against this epoch so traces start
        # near t=0 instead of at an arbitrary monotonic-clock origin.
        self.epoch_ns = time.perf_counter_ns()

    def ring_len(self) -> int:
        return len(self.ring_t0)


def _ring_capacity() -> int:
    cap = const.ENV.AUTODIST_TELEMETRY_RING.val
    return max(1, int(cap))


_STATE = _State(_ring_capacity())


class _NullSpan:
    """The shared disabled-mode context manager / decorator: every method is
    a no-op and ``span()`` returns this one instance, so the disabled cost is
    a single attribute check plus an identity return."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):
        """No-op twin of :meth:`_Span.set` (disabled mode)."""
        return self


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records ``(name, tid, t0_ns, dur_ns, args)`` on exit."""

    __slots__ = ("name", "args", "_t0")

    def __init__(self, name: str, args: Optional[Dict[str, Any]]):
        self.name = name
        self.args = args
        self._t0 = 0

    def set(self, **args):
        """Merge args onto a LIVE span (recorded at exit) — for values that
        only exist after the span opened, e.g. the request id a serving
        dispatch assigns mid-span. Returns the span for chaining."""
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        st = _STATE
        tid = threading.get_ident()
        # Recording takes the state lock: the five ring columns must append
        # in lockstep (a reader between two appends would see misaligned
        # columns), and readers snapshot under the same lock. One uncontended
        # lock + two intern lookups + five C appends per span exit is well
        # inside the enabled-mode budget bench.py --telemetry-overhead
        # tracks.
        with st.lock:
            nid = st.name_ids.get(self.name)
            if nid is None:
                nid = st.name_ids[self.name] = len(st.name_ids)
            tix = st.tid_ids.get(tid)
            if tix is None:
                tix = st.tid_ids[tid] = len(st.tid_ids)
                st.thread_names[tid] = threading.current_thread().name
            st.ring_name.append(nid)
            st.ring_tid.append(tix)
            st.ring_t0.append(self._t0)
            st.ring_dur.append(t1 - self._t0)
            st.ring_args.append(self.args)
        return False


def span(name: str, **args):
    """Record the enclosed block as a named host-timeline span.

    ``with telemetry.span("dispatch"): ...`` — extra keyword arguments ride
    into the Chrome trace event's ``args`` (keep them small and
    JSON-serializable). Disabled mode returns a shared no-op context manager
    after a single attribute check."""
    if not _STATE.enabled:
        return _NULL_SPAN
    return _Span(name, args or None)


def traced(name: Optional[str] = None, **args):
    """Decorator face of :func:`span`: ``@telemetry.traced("load_batch")``
    (or bare ``@telemetry.traced()`` to use the function's qualname). The
    enabled check happens per CALL, so functions decorated at import time
    start recording when telemetry is enabled later."""
    def deco(fn):
        label = name or fn.__qualname__
        span_args = args or None

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not _STATE.enabled:
                return fn(*a, **kw)
            with _Span(label, span_args):
                return fn(*a, **kw)
        return wrapper
    return deco


def enable():
    """Turn span recording (and registry mirroring) on for this process."""
    _STATE.enabled = True


def disable():
    _STATE.enabled = False


def enabled() -> bool:
    return _STATE.enabled


def clear():
    """Drop all recorded spans, intern tables, and thread names (the registry
    is separate — see :func:`autodist_tpu.telemetry.registry`)."""
    with _STATE.lock:
        _STATE.ring_name.clear()
        _STATE.ring_tid.clear()
        _STATE.ring_t0.clear()
        _STATE.ring_dur.clear()
        _STATE.ring_args.clear()
        _STATE.name_ids.clear()
        _STATE.tid_ids.clear()
        _STATE.thread_names.clear()
        _STATE.epoch_ns = time.perf_counter_ns()


def _export_columns(since_ns: Optional[int] = None):
    """The raw columnar snapshot, C-speed: ``(pid, epoch_ns, names_table,
    tids_table, name_idx, tid_idx, t0_list, dur_list, args_list,
    thread_names, wall_ns, perf_ns)``. ``name_idx``/``tid_idx`` index the
    two tables; ``since_ns`` filters to spans started at/after that
    ``perf_counter_ns`` stamp.

    ``wall_ns``/``perf_ns`` are one wall-clock / monotonic-clock pair sampled
    back-to-back under the ring lock: span timestamps are monotonic, and the
    cluster trace plane maps them onto the wall clock via
    ``wall_ns + (t0 - perf_ns)`` so rings from different processes can be
    rebased onto one timeline (:mod:`autodist_tpu.telemetry.cluster`)."""
    st = _STATE
    with st.lock:
        names = list(st.name_ids)
        tids = [int(t) for t in st.tid_ids]
        name_idx = list(st.ring_name)
        tid_idx = list(st.ring_tid)
        t0s = list(st.ring_t0)
        durs = list(st.ring_dur)
        args = list(st.ring_args)
        thread_names = dict(st.thread_names)
        epoch = st.epoch_ns
        wall_ns = time.time_ns()
        perf_ns = time.perf_counter_ns()
    if since_ns is not None and any(t0 < since_ns for t0 in t0s):
        keep = [i for i, t0 in enumerate(t0s) if t0 >= since_ns]
        name_idx = [name_idx[i] for i in keep]
        tid_idx = [tid_idx[i] for i in keep]
        t0s = [t0s[i] for i in keep]
        durs = [durs[i] for i in keep]
        args = [args[i] for i in keep]
    return (os.getpid(), epoch, names, tids, name_idx, tid_idx, t0s, durs,
            args, thread_names, wall_ns, perf_ns)


def snapshot_spans():
    """A point-in-time copy of the ring: a list of
    ``(name, tid, t0_ns, dur_ns, args)`` tuples, oldest first."""
    return _export_state()[2]


def _export_state(since_ns: Optional[int] = None):
    """(pid, epoch_ns, spans, thread_names, wall_ns, perf_ns) — the row-wise
    view over :func:`_export_columns` (spans as ``(name, tid, t0_ns, dur_ns,
    args)`` tuples) for the per-process Chrome exporter and
    :func:`snapshot_spans`; bulk consumers (the cluster trace plane) read
    the columns directly."""
    (pid, epoch, names, tids, name_idx, tid_idx, t0s, durs, args,
     thread_names, wall_ns, perf_ns) = _export_columns(since_ns)
    spans = [(names[n], tids[t], t0, dur, a)
             for n, t, t0, dur, a in zip(name_idx, tid_idx, t0s, durs, args)]
    return pid, epoch, spans, thread_names, wall_ns, perf_ns


# AUTODIST_TELEMETRY=1 enables at import so every entry point (examples,
# bench, worker processes the coordinator launches with an inherited env)
# records without code changes.
if const.ENV.AUTODIST_TELEMETRY.val:
    enable()
