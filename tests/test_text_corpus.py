"""Text-corpus ingestion: vocab files, OOV hashing, streaming windowing.

The reference consumed the real 1B-word-benchmark corpus as whitespace token
streams windowed into training rows with a vocab-file lookup (reference
``examples/lm1b/lm1b_train.py:26-50``, ``language_model.py:108-111``); these
tests pin that behavior for the TPU-native streaming tokenizer.
"""

import glob
import os
import zlib

import numpy as np
import pytest

from autodist_tpu.data import DataLoader, text_corpus
from autodist_tpu.data.text_corpus import (Vocabulary, build_vocab, load_vocab,
                                           tokenize_to_shards)


def _write(path, text):
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    return str(path)


def test_vocabulary_lookup_and_oov_hashing():
    v = Vocabulary(["the", "cat", "sat"], oov_buckets=2)
    assert [v.lookup(w) for w in ("the", "cat", "sat")] == [0, 1, 2]
    assert v.vocab_size == 5
    # OOV ids land in [n_words, n_words + buckets), crc32-stable (NOT the
    # per-process-salted builtin hash — chief and workers must agree).
    wid = v.lookup("dog")
    assert wid == 3 + zlib.crc32(b"dog") % 2
    assert v.lookup("dog") == wid


def test_load_vocab_first_column_and_truncation(tmp_path):
    path = _write(tmp_path / "vocab.txt",
                  "the 1000\ncat 500\nsat 400\nmat 100\n")
    v = load_vocab(path, max_size=2)
    assert v.n_words == 2 and v.lookup("the") == 0 and v.lookup("cat") == 1
    assert v.lookup("sat") >= v.n_words  # truncated entries hash as OOV


def test_build_vocab_frequency_sorted_deterministic(tmp_path):
    path = _write(tmp_path / "c.txt", "b a a c b a\nb c d\n")
    v = build_vocab(path, max_size=3)
    # a:3 b:3 c:2 — tie between a and b breaks by first appearance (b first).
    assert [v.lookup(w) for w in ("b", "a", "c")] == [0, 1, 2]
    assert v.lookup("d") == v.n_words  # beyond max_size -> OOV bucket


def test_tokenize_streams_across_lines_and_files(tmp_path):
    """The word stream is continuous across line and file boundaries, windows
    are non-overlapping by default, and the tail is dropped."""
    f1 = _write(tmp_path / "p1.txt", "w0 w1 w2\nw3 w4\n")
    f2 = _write(tmp_path / "p2.txt", "w5 w6 w7 w8 w9 w10\n")
    v = Vocabulary([f"w{i}" for i in range(11)])
    out = tmp_path / "shards"
    paths = tokenize_to_shards([f1, f2], v, str(out), seq_len=3,
                               rows_per_shard=2)
    rows = np.concatenate([np.load(p) for p in paths])
    # 11 words -> two full 4-token windows, 3-word tail dropped.
    assert rows.tolist() == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert rows.dtype == np.int32
    meta = text_corpus.read_meta(str(out))
    assert meta["vocab_size"] == v.vocab_size and meta["rows"] == 2


def test_tokenize_stride_one_matches_reference_windowing(tmp_path):
    """stride=1 reproduces the reference's every-word-starts-a-window dataset
    (its .window(num_step, 1, 1, True), lm1b_train.py:43)."""
    f = _write(tmp_path / "c.txt", "w0 w1 w2 w3 w4\n")
    v = Vocabulary([f"w{i}" for i in range(5)])
    paths = tokenize_to_shards(f, v, str(tmp_path / "s"), seq_len=2,
                               stride=1)
    rows = np.concatenate([np.load(p) for p in paths])
    assert rows.tolist() == [[0, 1, 2], [1, 2, 3], [2, 3, 4]]


def test_tokenize_stride_beyond_window_subsamples(tmp_path):
    """stride > seq_len+1 skips the tokens between windows (subsampling) —
    and the meta sidecar records the stride that actually applied."""
    f = _write(tmp_path / "c.txt", " ".join(f"w{i}" for i in range(10)))
    v = Vocabulary([f"w{i}" for i in range(10)])
    paths = tokenize_to_shards(f, v, str(tmp_path / "s"), seq_len=2,
                               stride=5)
    rows = np.concatenate([np.load(p) for p in paths])
    # Windows start at 0 and 5; tokens 3-4 and 8-9 are skipped.
    assert rows.tolist() == [[0, 1, 2], [5, 6, 7]]
    assert text_corpus.read_meta(str(tmp_path / "s"))["stride"] == 5


def test_tokenize_sweeps_stale_shards_and_streams_through_loader(tmp_path):
    f = _write(tmp_path / "c.txt", " ".join(f"w{i % 7}" for i in range(100)))
    v = build_vocab(f, max_size=7)
    out = tmp_path / "shards"
    tokenize_to_shards(f, v, str(out), seq_len=4, rows_per_shard=3)
    first = sorted(glob.glob(str(out / "tokens-*.npy")))
    assert len(first) > 1  # actually sharded
    # Re-prepare smaller: stale high-numbered shards must vanish.
    f2 = _write(tmp_path / "c2.txt", " ".join(f"w{i % 7}" for i in range(10)))
    paths = tokenize_to_shards(f2, v, str(out), seq_len=4)
    assert sorted(glob.glob(str(out / "tokens-*.npy"))) == sorted(paths)
    # And the shards stream through the (native) DataLoader.
    dl = DataLoader(files={"tokens": paths}, batch_size=2, shuffle=False)
    batch = dl.next()["tokens"]
    assert batch.shape == (2, 5) and batch.max() < v.vocab_size
    dl.close()


def test_tokenize_validates(tmp_path):
    f = _write(tmp_path / "c.txt", "a b\n")
    v = Vocabulary(["a", "b"])
    with pytest.raises(ValueError, match="fewer than seq_len"):
        tokenize_to_shards(f, v, str(tmp_path / "s"), seq_len=5)
    with pytest.raises(FileNotFoundError):
        tokenize_to_shards(str(tmp_path / "missing.txt"), v,
                           str(tmp_path / "s"), seq_len=1)
    with pytest.raises(ValueError, match="no corpus files"):
        build_vocab(str(tmp_path / "none-*.txt"), max_size=3)
    with pytest.raises(ValueError, match="oov_buckets"):
        Vocabulary(["a"], oov_buckets=0)


def test_lm1b_example_tokenizes_and_trains(tmp_path):
    """End to end: raw text -> --tokenize_corpus -> --data_dir training, the
    reference's real-corpus path (lm1b_train.py:26-50) TPU-first."""
    corpus = _write(tmp_path / "news.en-00001-of-00100",
                    "\n".join(" ".join(f"tok{(i * 13 + j) % 50}"
                                       for j in range(30))
                              for i in range(40)))
    import examples.lm1b.lm1b_train as mod
    data_dir = str(tmp_path / "tokens")
    mod.main(["--tokenize_corpus", corpus, "--data_dir", data_dir,
              "--vocab", "64", "--seq_len", "16"])
    meta = text_corpus.read_meta(data_dir)
    assert meta is not None and meta["vocab_size"] <= 64
    wps = mod.main(["--data_dir", data_dir, "--vocab", "64", "--seq_len", "16",
                    "--steps", "6", "--log_every", "3", "--batch_size", "4",
                    "--d_model", "32", "--n_layers", "1"])
    assert wps is None or wps > 0
    # A too-small embedding is refused up front, not at gather time.
    with pytest.raises(SystemExit):
        mod.main(["--data_dir", data_dir, "--vocab", "8", "--seq_len", "16",
                  "--steps", "1", "--batch_size", "4",
                  "--d_model", "32", "--n_layers", "1"])
