"""Load-balanced PS strategy — greedy bin-packing of parameters onto PS shards.

Port of the reference's default builder (``autodist/strategy/ps_lb_strategy.py``,
default per ``autodist.py:70``): parameters are assigned to the least-loaded
destination by byte size (``:64-83``, ``byte_size_load_fn`` ``:86-117``). Destinations
here are coordinates along the ``reduce`` mesh axis rather than CPU hosts.
"""

from typing import Callable

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec, ParamSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.base import PS_DEFAULT_AXES, Strategy, StrategyBuilder


def byte_size_load_fn(spec: ParamSpec) -> int:
    """Load estimate for one parameter (reference ps_lb_strategy.py:86-117).

    The reference special-cased unknown shapes; JAX shapes are always static, so the
    estimate is exact: bytes of the parameter (optimizer state scales with it too).
    """
    return max(spec.byte_size, 1)


class PSLoadBalancing(StrategyBuilder):
    def __init__(self, local_proxy_variable: bool = False, sync: bool = True,
                 staleness: int = 0, load_fn: Callable[[ParamSpec], int] = byte_size_load_fn):
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._load_fn = load_fn

    # The axis defaults this family records; Parallax overrides to stay data-primary.
    _default_axes = PS_DEFAULT_AXES

    def _num_destinations(self, resource_spec: ResourceSpec) -> int:
        """PS shard count, derived from the same axes build() records in the mesh."""
        return self._resolved_axes(resource_spec, self._default_axes)[const.MESH_AXIS_REDUCE]

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        strategy = Strategy()
        n_dest = self._num_destinations(resource_spec)
        loads = [0] * n_dest
        # Greedy: largest parameters first onto the least-loaded shard (reference
        # iterated in graph order; size-descending gives strictly better packing and
        # identical results for uniform sizes).
        ordered = sorted(model_spec.trainable.values(),
                         key=lambda s: -self._load_fn(s))
        for spec in ordered:
            dest = min(range(n_dest), key=loads.__getitem__)
            loads[dest] += self._load_fn(spec)
            node = strategy.proto.node_config.add(var_name=spec.name)
            node.ps_synchronizer.reduction_destination = f"reduce:{dest}"
            node.ps_synchronizer.local_replication = self._local_proxy_variable
            node.ps_synchronizer.sync = self._sync
            node.ps_synchronizer.staleness = self._staleness
            node.sparse = spec.sparse
        self._fill_mesh_config(strategy, resource_spec,
                               self._resolved_axes(resource_spec, self._default_axes))
        return strategy
