"""Coordinator: the chief re-executes the user's script on every host.

Parity with reference ``autodist/coordinator.py``:

- ``launch_clients()`` ships the serialized strategy to each worker host, then runs
  the user's own command (``python + sys.argv``) there with the role env set
  (``AUTODIST_WORKER=<ip>``, ``AUTODIST_STRATEGY_ID=<id>``, reference ``:66-90``),
  plus the TPU-native bootstrap env (coordinator address, process count/id) that
  ``jax.distributed.initialize`` consumes on each host.
- A watchdog thread per remote process reacts to any nonzero worker exit per
  the ``AUTODIST_WORKER_FAILURE`` policy: ``halt`` fail-fasts the chief
  (``os._exit(1)``, the reference's only behavior, ``:98-110``); ``respawn``
  relaunches the worker with bounded exponential backoff — machine loss is
  routine at pod scale, and a relaunched async-PS worker re-registers the
  staleness gate and catches up on the chief's live params with no checkpoint
  (``parallel/recovery.py``). Respawns are budgeted per worker
  (``AUTODIST_RECOVER_MAX``); an exhausted budget escalates to ``halt``.
"""

import os
import sys
import threading
import time
from typing import Dict, List, Optional

from autodist_tpu import const
from autodist_tpu.cluster import Cluster, is_local_address
from autodist_tpu.parallel import recovery as _recovery
from autodist_tpu.utils import logging


class RespawnPolicy:
    """Budgeted, jittered-exponential-backoff respawn ledger — the chief's
    worker-failure reaction (:meth:`Coordinator._respawn`), promoted to a
    reusable policy object so the serving fleet router drives the SAME
    discipline for dead-replica replacement and alert-driven autoscaling
    (``serving/router.py``): at most ``AUTODIST_RECOVER_MAX`` granted
    attempts per key, each booked via ``recovery.log_respawn`` with its
    backoff delay."""

    def __init__(self, base_s: float = 1.0, cap_s: float = 30.0):
        self.base_s = base_s
        self.cap_s = cap_s
        self.attempts: Dict[str, int] = {}

    def budget(self) -> int:
        return _recovery.recover_max()

    def grant(self, key: str) -> Optional[float]:
        """One respawn attempt for ``key``: the backoff delay (seconds) the
        caller should wait before relaunching, booked in the recovery
        plane; ``None`` when the budget is spent (the caller escalates —
        halt for the chief, stay-down for a router replica)."""
        n = self.attempts.get(key, 0)
        if n >= self.budget():
            return None
        self.attempts[key] = n + 1
        delay = _recovery.backoff_s(n, self.base_s, self.cap_s)
        _recovery.log_respawn(str(key), n + 1, delay)
        return delay


class Coordinator:
    # Respawn backoff: base doubles per attempt (jittered), capped. Class
    # attributes so tests (and future elastic policies) can tighten them.
    RESPAWN_BACKOFF_S = 1.0
    RESPAWN_BACKOFF_CAP_S = 30.0

    def __init__(self, strategy, cluster: Cluster,
                 argv: Optional[List[str]] = None):
        self._strategy = strategy
        self._cluster = cluster
        self._argv = argv if argv is not None else sys.argv
        self._procs = []
        self._watchdogs: List[threading.Thread] = []
        # Per-address relaunch spec (cmd + env + respawn attempt count) —
        # what the respawn policy re-executes when a worker dies.
        self._launch_specs: Dict[str, dict] = {}

    def launch_clients(self, extra_env: Optional[dict] = None):
        """Ship strategy + relaunch the user script on every non-chief host.

        ``extra_env``: additional env vars for the workers (e.g. the async PS
        transport address, ``AUTODIST_PS_ADDR``)."""
        strategy_path = self._strategy.serialize()
        spec = self._cluster.cluster_spec
        coordinator_addr = spec["coordinator"]
        n = self._cluster.num_processes

        for proc_info in spec["processes"]:
            address = proc_info["address"]
            if proc_info["process_id"] == 0:
                continue  # the chief is this process
            if not is_local_address(address):
                self._cluster.remote_copy(strategy_path, const.DEFAULT_SERIALIZATION_DIR,
                                          address)
            env = {
                const.ENV.AUTODIST_WORKER.name: address,
                const.ENV.AUTODIST_STRATEGY_ID.name: self._strategy.id,
                const.ENV.AUTODIST_COORDINATOR_ADDR.name: coordinator_addr,
                const.ENV.AUTODIST_COORDINATOR_PORT.name:
                    str(const.ENV.AUTODIST_COORDINATOR_PORT.val),
                const.ENV.AUTODIST_NUM_PROCESSES.name: str(n),
                const.ENV.AUTODIST_PROCESS_ID.name: str(proc_info["process_id"]),
                const.ENV.AUTODIST_MIN_LOG_LEVEL.name: const.ENV.AUTODIST_MIN_LOG_LEVEL.val,
            }
            if const.ENV.AUTODIST_IS_TESTING.val:
                env[const.ENV.AUTODIST_IS_TESTING.name] = "1"
            # The reference propagated its path env vars to every worker
            # (coordinator.py:70-79); a user script driven by SYS_RESOURCE_PATH /
            # SYS_DATA_PATH must resolve them identically when re-executed.
            for var in (const.ENV.SYS_RESOURCE_PATH, const.ENV.SYS_DATA_PATH):
                if var.val:
                    env[var.name] = var.val
            if extra_env:
                env.update({k: str(v) for k, v in extra_env.items()})
            cmd = [sys.executable] + self._argv
            self._launch_specs[address] = {"cmd": cmd, "env": env,
                                           "respawns": 0}
            logging.info("Launching worker on %s (process %d/%d)",
                         address, proc_info["process_id"], n)
            proc = self._cluster.remote_exec(cmd, address, env=env)
            self._procs.append(proc)
            self._watch(proc, address)

    def _on_worker_failure(self, address: str, code: int):
        """React to a nonzero worker exit per ``AUTODIST_WORKER_FAILURE``:

        - ``halt`` (default): kill the chief (reference coordinator.py:98-110).
        - ``respawn``: relaunch the worker's exact command/env after a
          bounded, jittered exponential backoff — an async-PS replacement
          re-registers the gate and pulls the chief's live params on its own
          (checkpoint-free restart). At most ``AUTODIST_RECOVER_MAX``
          respawns per worker; exhaustion (or a worker never launched by
          this coordinator) escalates to ``halt``.

        Overridable for tests and custom elastic policies; runs on the dead
        worker's daemon watchdog thread."""
        policy = str(const.ENV.AUTODIST_WORKER_FAILURE.val)
        if policy not in ("halt", "respawn"):
            logging.warning("AUTODIST_WORKER_FAILURE=%r is not a policy "
                            "(halt|respawn); treating as halt", policy)
            policy = "halt"
        if policy == "respawn":
            # A failed relaunch (fork failure, vanished interpreter, ssh
            # error) must ESCALATE to halt, not kill this daemon watchdog
            # thread silently — a dead worker with no respawn AND no halt
            # would park the surviving workers at the staleness bound
            # forever, strictly worse than the fail-fast it replaced.
            try:
                if self._respawn(address, code):
                    return
            except Exception as e:
                logging.error("Worker %s respawn failed (%s); escalating "
                              "to halt", address, e)
        logging.error("Worker %s exited with code %s; terminating chief",
                      address, code)
        os._exit(1)

    def _respawn(self, address: str, code: int) -> bool:
        """One respawn attempt for ``address`` via :class:`RespawnPolicy`;
        False when the budget is spent or the address is unknown (caller
        escalates to halt). The attempt ledger lives in the launch spec
        (``spec["respawns"]``) so it survives across failures."""
        spec = self._launch_specs.get(address)
        if spec is None:
            return False
        policy = RespawnPolicy(self.RESPAWN_BACKOFF_S,
                               self.RESPAWN_BACKOFF_CAP_S)
        policy.attempts[address] = spec["respawns"]
        delay = policy.grant(address)      # books recovery.log_respawn
        if delay is None:
            logging.error(
                "Worker %s exited with code %s and its respawn budget "
                "(%d, AUTODIST_RECOVER_MAX) is spent; escalating to "
                "halt", address, code, policy.budget())
            return False
        spec["respawns"] = policy.attempts[address]
        logging.warning(
            "Worker %s exited with code %s; respawning in %.1fs "
            "(attempt %d/%d)", address, code, delay, spec["respawns"],
            policy.budget())
        time.sleep(delay)   # bounded: RESPAWN_BACKOFF_CAP_S
        proc = self._cluster.remote_exec(spec["cmd"], address,
                                         env=spec["env"])
        self._procs.append(proc)
        self._watch(proc, address)
        return True

    def _watch(self, proc, address: str):
        def wait():
            code = proc.wait()
            if code != 0:
                self._on_worker_failure(address, code)

        thread = threading.Thread(target=wait, daemon=True)
        thread.start()
        self._watchdogs.append(thread)

    def join(self, timeout: Optional[float] = None):
        """Wait for all workers. With a timeout, returns False if any worker is
        still running when it expires (the caller decides whether to terminate)."""
        import subprocess
        done = True
        for proc in self._procs:
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                done = False
        return done
