"""MovieLens-format preprocessing for the NCF recommender — real data in.

The reference's recommendation pipeline (~3.5k LoC under
``examples/benchmark/utils/recommendation/``) downloaded MovieLens, coerced it
to a standard CSV, then (``data_preprocessing.py:52-120``):

1. filtered out users with fewer than 20 ratings,
2. zero-indexed user and item ids,
3. sorted by (user, timestamp) and held out each user's LAST item as the
   evaluation positive (leave-last-out),
4. sampled training negatives per epoch and 100 evaluation negatives per
   user for the HR@K / NDCG@K protocol (``ncf_common.py``).

This module is that pipeline TPU-first and offline (this environment has no
egress; point it at a ratings file you already have): numpy parsing of both
the standard ``user,item,rating,timestamp`` CSV and the raw ml-1m
``user::item::rating::timestamp`` format, the same filter/zero-index/
leave-last-out transforms, per-epoch uniform training negatives (the classic
NCF protocol — false negatives allowed in training, excluded in eval), and
row-aligned ``.npy`` shards (``save_shards``) that stream through the native
DataLoader. ``hit_rate_and_ndcg`` scores a trained NeuMF with the reference's
eval protocol.
"""

import dataclasses
import os
from typing import Callable, Dict, Optional

import numpy as np

from autodist_tpu.data.loader import save_shards
from autodist_tpu.utils import logging

MIN_NUM_RATINGS = 20          # reference rconst.MIN_NUM_RATINGS
NUM_EVAL_NEGATIVES = 100      # reference rconst.NUM_EVAL_NEGATIVES


@dataclasses.dataclass(frozen=True)
class MovieLensData:
    """Preprocessed interactions, zero-indexed and leave-last-out split."""

    num_users: int
    num_items: int
    train_users: np.ndarray    # [N] int32, sorted by (user, timestamp)
    train_items: np.ndarray    # [N] int32
    eval_users: np.ndarray     # [num_users] int32 (one row per kept user)
    eval_items: np.ndarray     # [num_users] int32 — the held-out LAST item

    @property
    def num_train(self) -> int:
        return len(self.train_users)


def load_ratings(path: str, min_ratings: int = MIN_NUM_RATINGS) -> MovieLensData:
    """Parse + filter + zero-index + sort + leave-last-out split.

    Accepts the standard ``user_id,item_id,rating,timestamp`` CSV (with or
    without a header) or the raw ml-1m ``::``-separated ``.dat`` format —
    the same two shapes the reference's ``_transform_csv`` normalized
    (``movielens.py:159-180``).
    """
    with open(path) as f:
        first = f.readline()
    sep = "::" if "::" in first else ","
    skip = 0 if first.split(sep)[0].strip().isdigit() else 1
    source = path
    if sep == "::":
        # np.loadtxt needs a single-char delimiter; normalize ml-1m's "::"
        # in memory (the 1m file is ~24 MB — cheap).
        import io
        with open(path) as f:
            source = io.StringIO(f.read().replace("::", ","))
    raw = np.loadtxt(source, delimiter=",", skiprows=skip, usecols=(0, 1, 3),
                     dtype=np.int64, ndmin=2)
    users, items, stamps = raw[:, 0], raw[:, 1], raw[:, 2]

    # 1) drop users with < min_ratings interactions (reference filter).
    uniq, inverse, counts = np.unique(users, return_inverse=True,
                                      return_counts=True)
    keep = counts[inverse] >= min_ratings
    users, items, stamps = users[keep], items[keep], stamps[keep]
    if len(users) == 0:
        raise ValueError(
            f"{path}: no user has >= {min_ratings} ratings; lower min_ratings")

    # 2) zero-index users and items (largest id = count - 1).
    uniq_users, users = np.unique(users, return_inverse=True)
    uniq_items, items = np.unique(items, return_inverse=True)

    # 3) sort by (user, timestamp) so each user's slice is contiguous and the
    # eval positive is simply the slice's last element.
    order = np.lexsort((stamps, users))
    users, items = users[order].astype(np.int32), items[order].astype(np.int32)

    # Leave-last-out: the final interaction per user is the eval positive.
    last_of_user = np.r_[users[1:] != users[:-1], True]
    eval_users = users[last_of_user]
    eval_items = items[last_of_user]
    data = MovieLensData(
        num_users=len(uniq_users), num_items=len(uniq_items),
        train_users=users[~last_of_user], train_items=items[~last_of_user],
        eval_users=eval_users, eval_items=eval_items)
    logging.info(
        "MovieLens %s: %d ratings -> %d train + %d eval positives, "
        "%d users x %d items (min_ratings=%d)", os.path.basename(path),
        len(raw), data.num_train, len(eval_users), data.num_users,
        data.num_items, min_ratings)
    return data


def sample_training_epoch(data: MovieLensData, num_neg: int = 4,
                          seed: int = 0) -> Dict[str, np.ndarray]:
    """One epoch of training examples: every positive plus ``num_neg``
    uniform-random negatives per positive (labels 1/0), shuffled.

    Uniform sampling MAY produce false negatives — the classic NCF training
    protocol the reference used (``stat_utils.py`` sampled with replacement);
    the eval negatives below are the ones that exclude seen items."""
    rng = np.random.RandomState(seed)
    n = data.num_train
    users = np.concatenate([data.train_users,
                            np.repeat(data.train_users, num_neg)])
    items = np.concatenate([data.train_items,
                            rng.randint(0, data.num_items, size=n * num_neg,
                                        dtype=np.int64).astype(np.int32)])
    labels = np.concatenate([np.ones(n, np.float32),
                             np.zeros(n * num_neg, np.float32)])
    perm = rng.permutation(len(users))
    return {"users": users[perm], "items": items[perm], "labels": labels[perm]}


def sample_eval_negatives(data: MovieLensData,
                          num_negatives: int = NUM_EVAL_NEGATIVES,
                          seed: int = 0) -> np.ndarray:
    """[num_users, num_negatives] items the user has NOT interacted with
    (train positives + the eval positive excluded) — the HR@K candidates."""
    rng = np.random.RandomState(seed)
    seen = {}
    for u, i in zip(data.train_users, data.train_items):
        seen.setdefault(int(u), set()).add(int(i))
    for u, i in zip(data.eval_users, data.eval_items):
        seen.setdefault(int(u), set()).add(int(i))
    # Small corpora cannot supply the full protocol count of DISTINCT unseen
    # items; clamp to the worst-case feasible pool (comparable across users)
    # rather than failing — MovieLens-scale data never clamps.
    feasible = min(data.num_items - len(seen[int(u)])
                   for u in data.eval_users)
    if feasible < 1:
        raise ValueError(
            "some user has interacted with every item; no eval negatives "
            "exist")
    if feasible < num_negatives:
        logging.warning(
            "Eval negatives clamped %d -> %d (smallest unseen-item pool "
            "across users)", num_negatives, feasible)
        num_negatives = feasible
    out = np.empty((len(data.eval_users), num_negatives), np.int32)
    for row, u in enumerate(data.eval_users):
        excluded = set(seen[int(u)])  # one copy per user; mutated below
        picked = []
        while len(picked) < num_negatives:
            cand = rng.randint(0, data.num_items,
                               size=2 * (num_negatives - len(picked)))
            for c in cand:
                if c not in excluded:
                    picked.append(c)
                    excluded.add(int(c))  # negatives are distinct
                    if len(picked) == num_negatives:
                        break
        out[row] = picked
    return out


def write_training_shards(data: MovieLensData, directory: str,
                          num_neg: int = 4, rows_per_shard: int = 1 << 20,
                          seed: int = 0) -> Dict[str, list]:
    """Materialize one sampled epoch as row-aligned ``.npy`` shards for
    ``DataLoader(files=...)`` (re-run with a new ``seed`` per epoch, like the
    reference's per-epoch negative regeneration)."""
    return save_shards(sample_training_epoch(data, num_neg, seed), directory,
                       rows_per_shard=rows_per_shard)


def hit_rate_and_ndcg(score_fn: Callable, data: MovieLensData, k: int = 10,
                      num_negatives: int = NUM_EVAL_NEGATIVES, seed: int = 0,
                      batch_users: Optional[int] = None,
                      negatives: Optional[np.ndarray] = None):
    """HR@k and NDCG@k under the reference's protocol: rank each user's held
    -out positive among ``num_negatives`` unseen items.

    ``score_fn(users, items) -> scores`` takes flat int32 arrays (e.g.
    ``lambda u, i: model.apply({'params': p}, u, i)``). ``negatives``
    (``[num_users, n]``) overrides the sampling — pass the array from
    :func:`sample_eval_negatives` to also know the post-clamp count. Returns
    ``(hit_rate, ndcg)``.
    """
    if negatives is None:
        negatives = sample_eval_negatives(data, num_negatives, seed)
    n_users = len(data.eval_users)
    cands = np.concatenate([data.eval_items[:, None], negatives], axis=1)
    n_cand = cands.shape[1]
    hits = ndcg = 0.0
    step = batch_users or n_users
    for lo in range(0, n_users, step):
        cu = data.eval_users[lo:lo + step]
        ci = cands[lo:lo + step]
        flat_u = np.repeat(cu, n_cand).astype(np.int32)
        flat_i = ci.reshape(-1).astype(np.int32)
        scores = np.asarray(score_fn(flat_u, flat_i)).reshape(len(cu), n_cand)
        # Tie handling = EXACT expectation under uniform tie placement: with
        # s strictly-better negatives and t ties, the positive's rank is
        # uniform over [s, s+t], so HR@k averages the indicator and NDCG@k
        # averages 1/log2(rank+2) over that window. Strictly-greater alone
        # would hand a CONSTANT scorer rank 0 (perfect metrics for a model
        # that learned nothing); a mid-rank point estimate still gives
        # all-or-nothing credit through the rank<k threshold.
        s = (scores[:, 1:] > scores[:, :1]).sum(axis=1)           # [U]
        t = (scores[:, 1:] == scores[:, :1]).sum(axis=1)          # [U]
        pos = np.arange(n_cand)[None, :]                          # [1, C]
        in_window = (pos >= s[:, None]) & (pos <= (s + t)[:, None])
        gain = np.where(pos < k, 1.0 / np.log2(pos + 2), 0.0)
        width = (t + 1).astype(np.float64)
        hits += ((in_window & (pos < k)).sum(axis=1) / width).sum()
        ndcg += ((in_window * gain).sum(axis=1) / width).sum()
    return hits / n_users, ndcg / n_users
