"""Telemetry exporters: Chrome trace-event JSON and benchmark-logger JSONL.

Two sinks for the two telemetry planes:

- :func:`export_chrome_trace` writes the span ring buffer as a Chrome
  trace-event file (the ``{"traceEvents": [...]}`` object form) that loads in
  ui.perfetto.dev or ``chrome://tracing`` — alongside a ``jax.profiler``
  device trace for a host+device overlay (``utils/tracing.trace`` with
  ``with_host_spans=True`` writes both; see docs/usage/observability.md).
- :func:`emit_metrics` writes the metrics-registry snapshot as JSONL metric
  rows through the existing :mod:`autodist_tpu.utils.benchmark_logger` file
  sink (one ``metric.log`` line per instrument), so registry metrics land in
  the same file scrapers already parse.
"""

import json
from typing import Optional

from autodist_tpu.telemetry import metrics as _metrics
from autodist_tpu.telemetry import spans as _spans
from autodist_tpu.utils import logging

__all__ = ["export_chrome_trace", "emit_metrics", "sample_device_memory",
           "opt_state_bytes"]


def opt_state_bytes(opt_state) -> int:
    """Per-device resident bytes of an optimizer-state tree: the max over
    local devices of the shard bytes each holds. A replicated leaf counts its
    full size on every device; a ZeRO-sharded leaf counts ``1/dp`` — so this
    is exactly the number weight-update sharding divides (`bench.py --zero`
    gates the ratio, and ``train()`` samples it as the
    ``train.opt_state_bytes`` gauge at log boundaries). Host (numpy) leaves
    count once, as chief-resident."""
    import jax
    per_dev: dict = {}
    host = 0
    for leaf in jax.tree_util.tree_leaves(opt_state):
        if isinstance(leaf, jax.Array):
            try:
                for sh in leaf.addressable_shards:
                    dev = sh.device.id
                    per_dev[dev] = per_dev.get(dev, 0) + int(sh.data.nbytes)
                continue
            except (RuntimeError, ValueError, TypeError, AttributeError):
                pass  # deleted/donated or exotic backend: fall through
        host += int(getattr(leaf, "nbytes", 0) or 0)
    return (max(per_dev.values()) if per_dev else 0) + host


def chrome_trace_events(since_ns=None, pid: Optional[int] = None,
                        clock_offset_ns: int = 0) -> list:
    """The recorded spans as a list of Chrome trace-event dicts: one ``"M"``
    thread_name metadata event per recorded thread, then one ``"X"``
    (complete) event per span with microsecond ``ts``/``dur`` relative to the
    ring's epoch. ``since_ns`` (a ``time.perf_counter_ns`` stamp) keeps only
    spans that started at/after it — the traced-window filter.

    ``pid`` overrides the lane id (Chrome groups events by pid, so each
    worker exporting under its own lane id merges collision-free) and
    ``clock_offset_ns`` is ADDED to every span timestamp before the µs
    conversion — together they let per-worker exports land on one shared
    timeline with no post-hoc JSON rewriting (the cluster trace plane's
    :mod:`autodist_tpu.telemetry.cluster` computes the offsets)."""
    real_pid, epoch_ns, recorded, thread_names, _, _ = \
        _spans._export_state(since_ns)
    if pid is None:
        pid = real_pid
    events = []
    for tid, name in sorted(thread_names.items()):
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})
    for name, tid, t0_ns, dur_ns, args in recorded:
        events.append({
            "name": name,
            "ph": "X",
            "cat": "host",
            # trace-event ts unit: usec
            "ts": (t0_ns - epoch_ns + clock_offset_ns) / 1e3,
            "dur": dur_ns / 1e3,
            "pid": pid,
            "tid": tid,
            "args": args or {},
        })
    return events


def export_chrome_trace(path: str, since_ns=None, pid: Optional[int] = None,
                        clock_offset_ns: int = 0) -> str:
    """Write the span ring buffer to ``path`` as Chrome trace-event JSON;
    returns ``path``. Safe to call repeatedly (each call snapshots the ring);
    an empty ring writes a valid empty trace. ``since_ns`` restricts the
    export to spans started at/after that ``perf_counter_ns`` stamp; ``pid``
    and ``clock_offset_ns`` relabel/rebase the lane for merged multi-worker
    timelines (see :func:`chrome_trace_events`)."""
    doc = {"traceEvents": chrome_trace_events(since_ns, pid=pid,
                                              clock_offset_ns=clock_offset_ns),
           "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(doc, f)
    logging.info("Wrote %d host span event(s) to %s",
                 len(doc["traceEvents"]), path)
    return path


def sample_device_memory(opt_state=None) -> int:
    """Sample live-buffer and device-memory gauges into the registry; returns
    the number of gauges written.

    Gauges: ``device.live_buffers`` / ``device.live_bytes`` (count and host
    view of bytes across ``jax.live_arrays()`` — a leak shows as monotonic
    growth across log boundaries) and, where the backend reports allocator
    stats (TPU/GPU; CPU returns none), per-device
    ``device.mem.bytes_in_use.d<id>`` / ``device.mem.bytes_limit.d<id>``.
    With ``opt_state``, additionally writes ``train.opt_state_bytes`` — the
    per-device optimizer-state footprint (:func:`opt_state_bytes`), the gauge
    ZeRO weight-update sharding divides by the data-parallel size.

    The memory plane's attribution pass rides the same sample: the live
    bytes are decomposed over the :mod:`~autodist_tpu.telemetry.memplane`
    tag registry into ``mem.owned.{params,opt_state,kv_pages,prefetch,
    snapshots,other}`` gauges (``other`` = live minus claimed, the
    leak-hunting residual, clamped at zero) plus the ``mem.pressure``
    ratio the shipped ``mem_pressure`` alert rule thresholds — so owners
    and pressure flow into history shards, OpenMetrics, and adfleet with
    no extra sampling path.
    Called by ``train()`` at log boundaries when telemetry is enabled; a
    diagnostics sampler must never break training, so backend hiccups are
    swallowed at debug level."""
    import jax
    wrote = 0
    live_bytes = 0
    if opt_state is not None:
        try:
            _metrics.gauge("train.opt_state_bytes").set(
                opt_state_bytes(opt_state))
            wrote += 1
        except (RuntimeError, ValueError, TypeError, AttributeError) as e:
            logging.debug("opt-state byte sampling unavailable: %s", e)
    try:
        live = jax.live_arrays()
        live_bytes = int(sum(int(getattr(a, "nbytes", 0) or 0)
                             for a in live))
        _metrics.gauge("device.live_buffers").set(len(live))
        _metrics.gauge("device.live_bytes").set(live_bytes)
        wrote += 2
    except (RuntimeError, ValueError, TypeError, AttributeError) as e:
        logging.debug("live-array sampling unavailable: %s", e)
    try:
        from autodist_tpu.telemetry import memplane as _memplane
        for owner, nbytes in _memplane.attribute(live_bytes).items():
            _metrics.gauge(f"mem.owned.{owner}").set(int(nbytes))
            wrote += 1
        _memplane.current_pressure(max_age_s=0.0)   # books mem.pressure
        wrote += 1
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
        logging.debug("memory attribution unavailable: %s", e)
    try:
        devices = jax.local_devices()
    except RuntimeError as e:  # backend not initialized yet
        logging.debug("device-memory sampling unavailable: %s", e)
        return wrote
    for d in devices:
        try:
            stats = d.memory_stats()
        except (RuntimeError, ValueError, TypeError, AttributeError):
            stats = None
        if not stats:
            continue
        for key, gauge_name in (("bytes_in_use", "bytes_in_use"),
                                ("bytes_limit", "bytes_limit")):
            value = stats.get(key)
            if value is not None:
                _metrics.gauge(
                    f"device.mem.{gauge_name}.d{d.id}").set(int(value))
                wrote += 1
    return wrote


_EMIT_LOGGER = None


def emit_metrics(global_step: Optional[int] = None, logger=None,
                 require_file_sink: bool = True) -> int:
    """Emit the registry snapshot through the benchmark-logger sink; returns
    the number of rows written.

    With ``require_file_sink`` (the default) emission is a no-op unless
    ``AUTODIST_BENCHMARK_LOG_DIR`` selects the JSONL file sink — the train
    loop calls this every log period, and mirroring a whole snapshot into the
    console logger each period would be noise, not observability. Histograms
    emit their ``count`` as the value with the full bucket dict in
    ``extras``."""
    global _EMIT_LOGGER
    from autodist_tpu.utils import benchmark_logger
    if logger is None:
        if _EMIT_LOGGER is None:
            candidate = benchmark_logger.get_benchmark_logger()
            if isinstance(candidate, benchmark_logger.BenchmarkFileLogger):
                # Cache ONLY the file sink (one open handle per process). A
                # base-logger result is re-evaluated next call, so setting
                # AUTODIST_BENCHMARK_LOG_DIR later in the process still
                # switches emission on instead of being frozen out forever.
                _EMIT_LOGGER = candidate
            elif require_file_sink:
                return 0
            logger = _EMIT_LOGGER if _EMIT_LOGGER is not None else candidate
        else:
            logger = _EMIT_LOGGER
    return logger.log_metrics(_metrics.snapshot(), global_step=global_step)
