"""PowerSGD compressor — rank-r low-rank gradient sync with error feedback.

The reference drafted ``PowerSGDCompressor`` but shipped it commented out
(``kernel/synchronization/compressor.py:208-284``); this build implements it
(``parallel/synchronization.py``). These tests prove: the factorized wire format is
actually used, matrix parameters still learn, error feedback keeps the compressed
run tracking the exact run, and vectors/scalars bypass factorization (exact sync,
like the reference draft's rank>=2 gate).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.parallel.synchronization import (EFState, PowerSGDState,
                                                   init_ef_state)
from autodist_tpu.strategy import AllReduce
from shardmap_compat import requires_shard_map

BATCH = 16
DIM_IN, DIM_OUT = 8, 4


def _data(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(BATCH, DIM_IN).astype(np.float32)
    w_true = rng.randn(DIM_IN, DIM_OUT).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.randn(BATCH, DIM_OUT)).astype(np.float32)
    return {"x": x, "y": y}


def _loss(p, batch):
    pred = batch["x"] @ p["w"] + p["b"]
    return jnp.mean((batch["y"] - pred) ** 2)


def _params():
    return {"w": jnp.zeros((DIM_IN, DIM_OUT)), "b": jnp.zeros((DIM_OUT,))}


def test_powersgd_state_shapes():
    """Matrix params get PowerSGDState (per-replica residual + [n, r] Q); the
    vector bias gets a plain scalar placeholder (exact sync path)."""
    batch = _data()
    ad = AutoDist(strategy_builder=AllReduce(compressor="PowerSGDCompressor",
                                             power_sgd_rank=2))
    step = ad.function(_loss, _params(), optax.sgd(0.1), example_batch=batch)
    state = step.runner.init(_params())
    ef = state.ef_state
    assert isinstance(ef["w"], PowerSGDState)
    dp = step.runner.plan.dp_size
    assert ef["w"].error.shape == (dp, DIM_IN, DIM_OUT)
    assert ef["w"].q.shape == (DIM_OUT, 2)
    # Q warm start is orthonormal.
    qtq = np.asarray(ef["w"].q.T @ ef["w"].q)
    np.testing.assert_allclose(qtq, np.eye(2), atol=1e-5)
    assert not isinstance(ef["b"], (PowerSGDState, EFState))
    assert np.asarray(ef["b"]).shape == ()


def test_powersgd_rank_clamped_to_matrix_dims():
    ad = AutoDist(strategy_builder=AllReduce(compressor="PowerSGDCompressor",
                                             power_sgd_rank=64))
    batch = _data()
    step = ad.function(_loss, _params(), optax.sgd(0.1), example_batch=batch)
    state = step.runner.init(_params())
    # rank clamps to min(64, m, n) = DIM_OUT
    assert state.ef_state["w"].q.shape == (DIM_OUT, DIM_OUT)


@requires_shard_map
def test_powersgd_loss_decreases():
    batch = _data()
    ad = AutoDist(strategy_builder=AllReduce(compressor="PowerSGDCompressor",
                                             power_sgd_rank=1))
    step = ad.function(_loss, _params(), optax.sgd(0.05), example_batch=batch)
    # Rank-1 factorization of a rank-4 problem: EF drip-feeds the residual, so
    # convergence is slower than exact sync but steady.
    losses = [float(step(batch)) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.15


@requires_shard_map
def test_powersgd_full_rank_with_ef_tracks_exact_run():
    """With warm-started Q, one power iteration per step, and error feedback, the
    full-rank PowerSGD run converges to the same parameters as the exact run."""
    batch = _data()

    ad_ref = AutoDist(strategy_builder=AllReduce())
    step_ref = ad_ref.function(_loss, _params(), optax.sgd(0.05), example_batch=batch)
    ad_psgd = AutoDist(strategy_builder=AllReduce(compressor="PowerSGDCompressor",
                                                  power_sgd_rank=DIM_OUT))
    step_psgd = ad_psgd.function(_loss, _params(), optax.sgd(0.05), example_batch=batch)

    for _ in range(40):
        step_ref(batch)
        step_psgd(batch)
    w_ref = np.asarray(step_ref.get_state().params["w"])
    w_psgd = np.asarray(step_psgd.get_state().params["w"])
    np.testing.assert_allclose(w_psgd, w_ref, atol=5e-3)


@requires_shard_map
def test_powersgd_bias_syncs_exactly():
    """The 1-D bias bypasses factorization: after one step it must match the exact
    (uncompressed) update to float precision, whatever happens to the matrix."""
    batch = _data()
    ad_ref = AutoDist(strategy_builder=AllReduce())
    step_ref = ad_ref.function(_loss, _params(), optax.sgd(0.1), example_batch=batch)
    ad_psgd = AutoDist(strategy_builder=AllReduce(compressor="PowerSGDCompressor"))
    step_psgd = ad_psgd.function(_loss, _params(), optax.sgd(0.1), example_batch=batch)
    step_ref(batch)
    step_psgd(batch)
    np.testing.assert_allclose(np.asarray(step_psgd.get_state().params["b"]),
                               np.asarray(step_ref.get_state().params["b"]),
                               rtol=1e-5)


@requires_shard_map
def test_bf16_ef_residual_is_per_replica():
    """BF16_EF residuals carry a leading dp dim sharded over the data axes: each
    replica owns its own residual (the reference kept one residual per worker
    process, compressor.py:120-143)."""
    batch = _data()
    ad = AutoDist(strategy_builder=AllReduce(compressor="HorovodCompressorEF"))
    step = ad.function(_loss, _params(), optax.sgd(0.1), example_batch=batch)
    state = step.runner.init(_params())
    dp = step.runner.plan.dp_size
    assert isinstance(state.ef_state["w"], EFState)
    assert state.ef_state["w"].error.shape == (dp, DIM_IN, DIM_OUT)
    # After a step over distinct per-replica batch shards the residuals differ.
    state2, _ = step.runner.run(state, batch)
    err = np.asarray(state2.ef_state["w"].error)
    assert err.shape[0] == dp
    if dp > 1:
        assert not np.allclose(err[0], err[1])


def test_init_ef_state_plain_params_no_compression():
    ad = AutoDist(strategy_builder=AllReduce())
    batch = _data()
    step = ad.function(_loss, _params(), optax.sgd(0.1), example_batch=batch)
    state = step.runner.init(_params())
    leaves = jax.tree_util.tree_leaves(state.ef_state)
    assert all(np.asarray(l).shape == () for l in leaves)


@pytest.mark.parametrize("name", ["PowerSGDCompressor", "power_sgd"])
def test_builder_accepts_powersgd_spellings(name):
    AllReduce(compressor=name)


@requires_shard_map
def test_ef_state_sized_by_actual_mesh_not_plan():
    """A strategy built for 8 devices can run on a smaller local mesh (the runner
    rebuilds it, runner.py:_mesh_from_plan); residuals must be sized per the mesh the
    state lives on, not the plan's original dp size."""
    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.plan import ShardingPlan
    from autodist_tpu.model_spec import ModelSpec
    from autodist_tpu.runner import DistributedRunner

    params = _params()
    spec_model = ModelSpec(params)
    strategy = AllReduce(compressor="PowerSGDCompressor").build(
        spec_model, AutoDist().resource_spec)  # built for all 8 visible devices
    plan = ShardingPlan.from_strategy(strategy, spec_model)
    small_mesh = build_mesh(axes={"data": 4}, devices=jax.devices()[:4])
    runner = DistributedRunner(strategy, spec_model, _loss, optax.sgd(0.05),
                               mesh=small_mesh, plan=plan)
    state = runner.init(params)
    assert state.ef_state["w"].error.shape == (4, DIM_IN, DIM_OUT)
    batch = _data()
    state2, loss = runner.run(state, batch)
    assert np.isfinite(float(loss))
    assert state2.ef_state["w"].error.shape == (4, DIM_IN, DIM_OUT)


@requires_shard_map
def test_powersgd_matrix_without_state_raises():
    """A matrix POWER_SGD param whose ef leaf is not a PowerSGDState must raise, not
    silently fall back to uncompressed sync (mirror of the BF16_EF guard)."""
    from autodist_tpu.parallel import synchronization
    from autodist_tpu.parallel.plan import ShardingPlan
    from autodist_tpu.model_spec import ModelSpec
    from autodist_tpu.parallel.mesh import build_mesh

    params = _params()
    spec_model = ModelSpec(params)
    strategy = AllReduce(compressor="PowerSGDCompressor").build(
        spec_model, AutoDist().resource_spec)
    plan = ShardingPlan.from_strategy(strategy, spec_model)
    mesh = build_mesh(axes={"data": len(jax.devices())})
    grad_fn = synchronization.make_grad_fn(plan, spec_model, mesh, _loss)
    bad_ef = jax.tree_util.tree_map(
        lambda _: jnp.zeros(()), params)  # bypassed init_ef_state
    with pytest.raises(TypeError, match="PowerSGDState"):
        grad_fn(params, _data(), bad_ef)
