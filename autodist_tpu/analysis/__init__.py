"""graftlint — project-specific static analysis for autodist_tpu.

An AST-based analyzer (stdlib ``ast``/``tokenize`` only — importable with no
jax present) that machine-enforces the hazard rules this codebase keeps
re-learning the hard way: locks held across XLA dispatch (the PR 2 deadlock
class), lock-order inversions, buffer use-after-donation, tracer leaks out of
jitted functions, unbounded blocking in transport handlers, wire-protocol
opcode exhaustiveness, the ``AUTODIST_*`` env-flag registry, and the tier-1
test-window naming convention.

Entry points:

- ``tools/graftlint.py`` — the CLI (text/JSON output, ``--explain``, committed
  baseline for grandfathered findings).
- :func:`autodist_tpu.analysis.core.lint_paths` — the library API the test
  suite's self-clean meta-test drives.

Checks register themselves via :func:`autodist_tpu.analysis.core.register`;
importing :mod:`autodist_tpu.analysis.checks` populates the registry. Inline
suppression: ``# graftlint: disable=GL001(reason)`` — the reason is mandatory
(a bare ``disable=GL001`` is itself a GL000 finding).
"""

from autodist_tpu.analysis.core import (  # noqa: F401
    Context, Finding, LintResult, all_checks, lint_paths, register)
