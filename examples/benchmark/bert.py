"""BERT pretraining benchmark.

Port of reference ``examples/benchmark/bert.py:41-47,194-215`` (BERT-large
pretraining inside the AutoDist scope): masked-LM objective, AllReduce with bf16
mixed precision, examples/sec instrumentation. Synthetic input with the
fixed-prediction-slot layout the reference used (max_predictions_per_seq).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import optax

from autodist_tpu import AutoDist
from autodist_tpu.models import bert
from autodist_tpu.strategy import AllReduce
from autodist_tpu.utils.metrics import ThroughputMeter

SIZES = {
    "tiny": dict(d_model=128, n_heads=2, n_layers=2, d_ff=512),
    "base": dict(d_model=768, n_heads=12, n_layers=12, d_ff=3072),
    "large": dict(d_model=1024, n_heads=16, n_layers=24, d_ff=4096),
}


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", choices=list(SIZES), default="base")
    parser.add_argument("--steps", type=int, default=110)
    parser.add_argument("--batch_size", type=int, default=0)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--log_every", type=int, default=100)
    parser.add_argument("--resource_spec", type=str, default=None)
    args = parser.parse_args(argv)

    n_dev = len(jax.devices())
    batch_size = args.batch_size or 8 * n_dev
    on_accel = jax.default_backend() != "cpu"
    cfg = bert.BertConfig(max_len=args.seq_len,
                          dtype=jnp.bfloat16 if on_accel else jnp.float32,
                          **SIZES[args.size])

    model = bert.Bert(cfg)
    batch = bert.synthetic_batch(cfg, batch_size, args.seq_len)
    from autodist_tpu.models.common import jit_init
    params = jit_init(model, jnp.asarray(batch["tokens"]),
                      jnp.asarray(batch["token_types"]))
    loss_fn = bert.make_mlm_loss_fn(model)

    ad = AutoDist(args.resource_spec, AllReduce(compressor="HorovodCompressor"))
    step = ad.function(loss_fn, params, optax.adamw(1e-4), example_batch=batch)
    # Keep the synthetic batch device-resident: re-shipping it from host
    # every step benchmarks the host link, not the chip.
    batch = step.runner.shard_batch(batch)

    meter = ThroughputMeter(batch_size=batch_size, log_every=args.log_every)
    loss = None
    for _ in range(args.steps):
        loss = step(batch)
        meter.step(sync=loss)
    print(f"bert-{args.size}: final loss {float(loss):.4f}, "
          f"{meter.average or 0:.1f} examples/sec")
    from autodist_tpu.utils import flops as flops_util
    flops_util.report_mfu(
        flops_util.train_step_flops(step.runner, step.get_state(), batch),
        (meter.average or 0) / batch_size)
    return meter.average


if __name__ == "__main__":
    main()
