"""Multi-process linear regression: the 2-process minimum slice.

Run directly as the chief (``python examples/multiprocess_linear_regression.py
out.json``); the Coordinator re-executes this same script as the worker with the
role env set — the reference's protocol of re-running ``python + sys.argv`` per
host (reference ``coordinator.py:66-90``).
Both processes join one ``jax.distributed`` coordination service (the TPU-native
replacement for the per-node ``tf.Server`` of reference ``cluster.py:160-210``),
build the global 4-device mesh (2 processes x 2 CPU devices), and run 3 SGD steps
of the minimum slice through the normal ``create_distributed_session`` path. The
chief writes final params + losses to the JSON path given in argv[1]; the pytest
driver asserts value-exact parity with a hand-computed single-process run.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # the axon plugin overrides the env var

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from autodist_tpu import AutoDist  # noqa: E402
from autodist_tpu.strategy import AllReduce  # noqa: E402

# Default spec: two processes on one machine (the pytest / dryrun shape).
# SYS_RESOURCE_PATH (the reference's resource-spec env var, propagated to
# workers by the Coordinator) points at a spec FILE instead, so the same
# script drives the two-container distributed CI stage
# (docker/compose.dist.yml), where the worker is a separate host over ssh.
SPEC = os.environ.get("SYS_RESOURCE_PATH") or (
    "nodes: [{address: localhost, tpus: 2, chief: true}, "
    "{address: 127.0.0.1, tpus: 2}]")
BATCH = 16
LR = 0.1
STEPS = 3


def make_batch(step: int):
    rng = np.random.RandomState(1000 + step)
    x = rng.randn(BATCH).astype(np.float32)
    y = (3.0 * x + 2.0 + 0.1 * rng.randn(BATCH)).astype(np.float32)
    return {"x": x, "y": y}


def loss_fn(p, b):
    pred = b["x"] * p["w"] + p["b"]
    return jnp.mean((b["y"] - pred) ** 2)


def main(out_path: str):
    ad = AutoDist(SPEC, AllReduce())
    # numpy (not jnp) until the session exists: touching the XLA backend before
    # jax.distributed.initialize is illegal, and create_distributed_session is
    # what runs the multi-host bootstrap (the standard multi-host JAX constraint,
    # surfaced through the AutoDist session protocol).
    params = {"w": np.zeros((), np.float32), "b": np.zeros((), np.float32)}
    runner = ad.create_distributed_session(
        loss_fn, params, optax.sgd(LR), example_batch=make_batch(0))
    # The session setup must have joined both processes into one SPMD program.
    assert jax.process_count() == 2, f"process_count={jax.process_count()}"
    assert jax.device_count() == 4, f"device_count={jax.device_count()}"

    state = runner.init(params)
    losses = []
    for step in range(STEPS):
        state, loss = runner.run(state, make_batch(step))
        losses.append(float(loss))

    if jax.process_index() == 0:
        result = {
            "w": float(state.params["w"]),
            "b": float(state.params["b"]),
            "losses": losses,
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
        }
        with open(out_path, "w") as f:
            json.dump(result, f)


# Role env a chief subprocess must NOT inherit from its parent (a stale worker env
# would make it think it is a worker; a stale coordinator env would misroute init).
# The coordinator port is not here: run_two_process_chief always sets it fresh.
ROLE_ENV_VARS = ("AUTODIST_WORKER", "AUTODIST_STRATEGY_ID", "AUTODIST_PROCESS_ID",
                 "AUTODIST_NUM_PROCESSES", "AUTODIST_COORDINATOR_ADDR",
                 # A spec path exported while driving the docker dist stage must
                 # not leak into subprocess tests (it would swap their localhost
                 # spec for the container spec and try to ssh to 'worker').
                 "SYS_RESOURCE_PATH", "SYS_DATA_PATH")


def run_two_process_chief(out_path: str, workdir: str, timeout: int = 300,
                          attempts: int = 3, script: str = None,
                          extra_args=()):
    """Launch this script as the chief subprocess on a fresh port; the Coordinator
    inside it re-launches the worker. Shared by ``tests/test_multiprocess.py`` and
    ``__graft_entry__._dryrun_multiprocess`` so the env construction (clean role
    env, CPU platform, 2 local devices) stays in one place.
    Returns the completed chief process (check ``.returncode`` and read out_path).

    Port selection (bind ephemeral, close, reuse) has an inherent race: another
    process can claim the port before the coordinator binds it, so bind failures
    retry on a new port up to ``attempts`` times."""
    import socket
    import subprocess

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    for k in ROLE_ENV_VARS:
        env.pop(k, None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # 2 local CPU devices per process -> 4 global devices across 2 processes.
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "AUTODIST_WORKING_DIR": workdir,
        # Run-by-path puts this file's dir on sys.path, not the repo root.
        "PYTHONPATH": repo_root + os.pathsep + env.get("PYTHONPATH", ""),
    })

    for attempt in range(attempts):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        env["AUTODIST_COORDINATOR_PORT"] = str(s.getsockname()[1])
        s.close()
        try:
            proc = subprocess.run(
                [sys.executable, script or os.path.abspath(__file__),
                 str(out_path), *extra_args],
                env=env, cwd=repo_root, capture_output=True, text=True,
                timeout=timeout)
        except subprocess.TimeoutExpired as e:
            # A missed gloo/coordination handshake (DEADLINE_EXCEEDED under
            # heavy host load, e.g. sharded CI) leaves both processes waiting
            # forever; a fresh attempt on a fresh port recovers.
            if attempt == attempts - 1:
                raise
            print(f"run_two_process_chief: attempt {attempt + 1} timed out "
                  f"({'DEADLINE_EXCEEDED' if e.stderr and b'DEADLINE_EXCEEDED' in e.stderr else 'no handshake error visible'}); retrying",
                  flush=True)
            continue
        retryable = proc.returncode != 0 and (
            "address already in use" in proc.stderr.lower()
            or "failed to bind" in proc.stderr.lower()
            or "deadline_exceeded" in proc.stderr.lower())
        if not retryable or attempt == attempts - 1:
            return proc
    return proc


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/autodist_tpu/mp_lr_result.json")
