"""Deterministic fault injection for the self-healing runtime.

The recovery plane's chaos tests and the ``bench.py --selfheal`` gate must
drive REAL failures through the REAL code paths — a mocked "eviction" proves
nothing about the staleness gate, and a mocked "NaN" proves nothing about the
health monitors. This module is the shared fault harness: a small set of
fault POINTS, each keyed deterministically (exact step index, worker id,
firing count), installed either programmatically (:func:`install`) or via the
``AUTODIST_FAULTS`` env flag, and consulted by a handful of instrumented
sites in the product code:

=================  ==========================================  =============
kind               instrumented site                           effect
=================  ==========================================  =============
``worker_crash``   ``RemotePSWorker.step`` /                   sockets closed
                   ``AsyncWorker.step``                        abruptly, then
                                                               :class:`WorkerCrashed`
``worker_hang``    same sites                                  bounded
                                                               ``time.sleep(for_s)``
``nan_grads``      ``train()``'s per-step loop                 batch floats
                                                               NaN-filled (real
                                                               NaN gradients
                                                               through the real
                                                               compiled step)
``wire_refuse``    ``_PSClient`` connect attempts              ``ConnectionRefusedError``
``wire_reset``     ``_PSClient.call_raw`` (keyed by ``op``)    socket closed +
                                                               ``ConnectionResetError``
                                                               before the send
``wire_slow``      ``ps_transport._send_payload``              payload sends
                                                               throttled to
                                                               ``bytes_per_s``
                                                               (sleep before
                                                               send)
=================  ==========================================  =============

Spec grammar (``AUTODIST_FAULTS`` or :func:`install`): semicolon-separated
points, each ``kind@key=value,key=value``::

    worker_crash@step=3,worker=1;nan_grads@step=5;wire_refuse@count=2
    worker_hang@step=2,worker=0,for_s=0.5;wire_reset@op=read

``count`` bounds how many times a point fires (default 1 — a fault that
fired is consumed, so a recover-and-replay pass sails through the step that
failed the first time; set ``count`` high to model a persistent fault).
Matching and consumption happen under one lock, so concurrent workers see
each firing exactly once — the determinism the chaos tests pin.

Un-armed cost: :func:`armed` is one module-global read (plus, once per
process, one env read to adopt ``AUTODIST_FAULTS``). The product sites gate
every other call on it.
"""

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Union

from autodist_tpu.utils import logging
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["FaultPoint", "WorkerCrashed", "KINDS", "parse", "install",
           "clear", "armed", "should_fire", "hang_s", "corrupt_batch",
           "points", "throttle_s"]

KINDS = ("worker_crash", "worker_hang", "nan_grads", "wire_refuse",
         "wire_reset", "wire_slow")


class WorkerCrashed(RuntimeError):
    """Raised at a ``worker_crash`` fault point after the worker's transport
    sockets were torn down — the in-process stand-in for a killed worker
    process (the server observes exactly what a real crash produces: an
    abrupt EOF). Supervising harnesses catch it and respawn."""


@dataclasses.dataclass
class FaultPoint:
    """One deterministic fault: ``kind`` plus its match keys. ``None`` keys
    match anything; ``fired`` counts consumptions against ``count``."""

    kind: str
    step: Optional[int] = None      # exact step index (site-defined counter)
    worker: Optional[int] = None    # exact worker id
    op: Optional[str] = None        # wire opcode (wire_reset)
    count: int = 1                  # firings before the point is spent
    for_s: float = 0.0              # hang duration (worker_hang)
    bytes_per_s: float = 0.0        # injected wire bandwidth (wire_slow)
    fired: int = 0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; valid: "
                             f"{', '.join(KINDS)}")
        if self.count < 1:
            raise ValueError("fault count must be >= 1")

    def matches(self, step, worker, op) -> bool:
        if self.fired >= self.count:
            return False
        if self.step is not None and step != self.step:
            return False
        if self.worker is not None and worker != self.worker:
            return False
        if self.op is not None and op != self.op:
            return False
        return True


_INT_KEYS = ("step", "worker", "count")
_FLOAT_KEYS = ("for_s", "bytes_per_s")


def parse(spec: str) -> List[FaultPoint]:
    """Parse the spec grammar into fault points; raises ``ValueError`` on a
    malformed spec (fault injection is an explicit test/ops act — a typo
    must fail loudly, unlike the alert rules' degrade-and-warn contract)."""
    out: List[FaultPoint] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, args = part.partition("@")
        kwargs: Dict[str, Any] = {}
        for pair in filter(None, (p.strip() for p in args.split(","))):
            key, sep, value = pair.partition("=")
            if not sep:
                raise ValueError(f"fault spec {part!r}: expected key=value, "
                                 f"got {pair!r}")
            key = key.strip()
            if key in _INT_KEYS:
                kwargs[key] = int(value)
            elif key in _FLOAT_KEYS:
                kwargs[key] = float(value)
            elif key == "op":
                kwargs[key] = value.strip()
            else:
                raise ValueError(f"fault spec {part!r}: unknown key {key!r}")
        out.append(FaultPoint(kind=kind.strip(), **kwargs))
    return out


_LOCK = san_lock()
_PLAN: Optional[List[FaultPoint]] = None
_ENV_CHECKED = False


def install(spec: Union[str, List[FaultPoint]]) -> List[FaultPoint]:
    """Arm the harness with a spec string or a pre-built point list; returns
    the live points (their ``fired`` counters update in place)."""
    global _PLAN, _ENV_CHECKED
    plan = parse(spec) if isinstance(spec, str) else list(spec)
    with _LOCK:
        _PLAN = plan
        _ENV_CHECKED = True   # an explicit install overrides the env spec
    if plan:
        logging.warning("faults: armed with %d fault point(s): %s",
                        len(plan), "; ".join(p.kind for p in plan))
    return plan


def clear():
    """Disarm (tests' teardown). Also suppresses re-arming from the env —
    a cleared harness stays cleared for the process."""
    global _PLAN, _ENV_CHECKED
    with _LOCK:
        _PLAN = None
        _ENV_CHECKED = True


def armed() -> bool:
    """True when any fault plan is installed. First call adopts
    ``AUTODIST_FAULTS`` when set (one env read per process)."""
    global _PLAN, _ENV_CHECKED
    if _PLAN is not None:
        return True
    if not _ENV_CHECKED:
        with _LOCK:
            if not _ENV_CHECKED:
                _ENV_CHECKED = True
                from autodist_tpu import const
                spec = str(const.ENV.AUTODIST_FAULTS.val)
                if spec:
                    _PLAN = parse(spec)
                    logging.warning("faults: armed from AUTODIST_FAULTS "
                                    "(%d point(s))", len(_PLAN))
    return _PLAN is not None


def points() -> List[FaultPoint]:
    """The live plan (empty when disarmed) — tests assert consumption."""
    with _LOCK:
        return list(_PLAN or [])


def should_fire(kind: str, step: Optional[int] = None,
                worker: Optional[int] = None,
                op: Optional[str] = None) -> bool:
    """Match-and-consume one firing of ``kind`` against the installed plan.
    The check and the ``fired`` bump share one critical section, so N
    concurrent callers consume exactly ``count`` firings total."""
    plan = _PLAN
    if plan is None:
        return False
    with _LOCK:
        for p in plan:
            if p.kind == kind and p.matches(step, worker, op):
                p.fired += 1
                logging.warning("faults: firing %s (step=%s worker=%s op=%s, "
                                "%d/%d)", kind, step, worker, op, p.fired,
                                p.count)
                return True
    return False


def hang_s(step: Optional[int] = None,
           worker: Optional[int] = None) -> float:
    """Consume a ``worker_hang`` firing; returns its bounded duration
    (0.0 when none fires). The caller sleeps — the harness never parks a
    thread itself."""
    plan = _PLAN
    if plan is None:
        return 0.0
    with _LOCK:
        for p in plan:
            if p.kind == "worker_hang" and p.matches(step, worker, None):
                p.fired += 1
                logging.warning("faults: hanging worker %s at step %s for "
                                "%.3fs", worker, step, p.for_s)
                return max(0.0, float(p.for_s))
    return 0.0


def throttle_s(nbytes: int) -> float:
    """Seconds a ``wire_slow`` point charges a payload of ``nbytes`` — the
    injected-bandwidth model behind ``bench.py --wire-compress``. Unlike the
    discrete faults this does NOT consume a firing: a bandwidth is a
    standing condition, not an event (``count`` is ignored; ``clear()``
    lifts it). The caller sleeps — the harness never parks a thread."""
    plan = _PLAN
    if plan is None:
        return 0.0
    with _LOCK:
        for p in plan:
            if p.kind == "wire_slow" and p.bytes_per_s > 0:
                return nbytes / p.bytes_per_s
    return 0.0


def maybe_hang(step: Optional[int] = None, worker: Optional[int] = None):
    """Sleep out a matching ``worker_hang`` point (bounded by its spec)."""
    duration = hang_s(step=step, worker=worker)
    if duration > 0.0:
        time.sleep(duration)   # bounded by the installed spec


def corrupt_batch(batch):
    """NaN-fill every float leaf of a host/device batch pytree (integer and
    bool leaves — token ids, labels — keep their values so the step still
    traces identically); the real compiled step then produces real NaN
    gradients. Leaves are returned as host arrays — every feed path
    re-shards host batches."""
    import jax
    import numpy as np
    from autodist_tpu.runner import MicroBatched

    def _nanify(leaf):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            return arr
        return np.full(arr.shape, np.nan, arr.dtype)

    def _leaf(leaf):
        if isinstance(leaf, MicroBatched):
            return MicroBatched(_nanify(leaf.value))
        return _nanify(leaf)

    return jax.tree_util.tree_map(
        _leaf, batch, is_leaf=lambda x: isinstance(x, MicroBatched))
