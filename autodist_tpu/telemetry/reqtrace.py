"""Request-scoped distributed tracing: the per-process lifecycle ring.

The span ring (:mod:`autodist_tpu.telemetry.spans`) answers "what did this
THREAD do"; the metrics plane aggregates request latency into histograms.
Neither follows ONE request across the fleet — router queue vs. admission
wait vs. prefill vs. decode cadence vs. a replay after a replica death is
invisible once the request crosses a process boundary. This module records
request lifecycle MARKS — ``(rid, phase, t_ns, args)`` — into a bounded
columnar ring at the points that already know the rid (the router's route
loop, the serving wire arm, the batcher's admission/completion sites), keyed
by the ROUTER-SCOPE rid so marks from different processes join into one
trace (:mod:`autodist_tpu.telemetry.cluster` merges them onto one clock;
``tools/adtrace.py`` renders waterfalls and flow-linked Chrome traces).

Phases (:data:`PHASES`): ``received`` / ``queued`` / ``admitted`` /
``prefill_start`` / ``prefill_end`` / ``first_token`` / ``done`` on the
replica; ``received`` / ``sent`` / ``replayed`` / ``shed`` / ``finished``
on the router. A replayed request repeats ``sent`` with a bumped ``hop``
arg — one rid, one trace, a visible failover.

Cost contract (the :mod:`spans` contract exactly): DISARMED (the default),
:func:`mark` performs one attribute read and returns — the serving hot
paths pay nanoseconds per request, gated by ``bench.py
--reqtrace-overhead``. Armed (``AUTODIST_REQTRACE=1``), a mark costs one
``perf_counter_ns`` read plus, under one uncontended lock, one intern
lookup and four deque appends. The ring is columnar (aligned deques
appended in lockstep) so a full-ring export — the ``reqtrace`` pull opcode
— is a handful of C-speed ``list(deque)`` calls. Rids are stored VERBATIM
(not interned): unlike span names they are unbounded, and an intern table
would leak one entry per request ever seen while the ring forgot the marks.
"""

import collections
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from autodist_tpu import const
from autodist_tpu.testing.sanitizer import san_lock

__all__ = ["mark", "enable", "disable", "enabled", "clear", "PHASES",
           "snapshot_marks", "group_records"]

# The vocabulary adtrace renders; marks with other phases still record (the
# ring is a log, not a schema) but the waterfall only prices these.
PHASES = ("received", "queued", "admitted", "prefill_start", "prefill_end",
          "first_token", "done", "shed", "replayed", "sent", "finished")


class _State:
    """Process-global request-trace state. ``enabled`` is THE hot-path gate:
    the disarmed fast path reads this one attribute and nothing else.

    Columnar ring: four aligned deques (rid verbatim, interned phase id,
    t_ns, args) appended in lockstep under the lock; the phase intern table
    is bounded by :data:`PHASES`' size, so memory is bounded by the ring."""

    __slots__ = ("enabled", "phase_ids", "ring_rid", "ring_phase", "ring_t",
                 "ring_args", "lock", "epoch_ns")

    def __init__(self, capacity: int):
        self.enabled = False
        self.phase_ids: Dict[str, int] = {}
        self.ring_rid = collections.deque(maxlen=capacity)
        self.ring_phase = collections.deque(maxlen=capacity)
        self.ring_t = collections.deque(maxlen=capacity)
        self.ring_args = collections.deque(maxlen=capacity)
        self.lock = san_lock()
        # Export offsets mark timestamps against this epoch so offline dumps
        # start near t=0 (same role as the span ring's epoch).
        self.epoch_ns = time.perf_counter_ns()

    def ring_len(self) -> int:
        return len(self.ring_t)


def _ring_capacity() -> int:
    cap = const.ENV.AUTODIST_REQTRACE_RING.val
    return max(1, int(cap))


_STATE = _State(_ring_capacity())


def mark(rid, phase: str, **args):
    """Record one lifecycle mark for request ``rid`` (the router-scope rid
    token where one exists — that key is what joins marks across
    processes). Extra keyword args ride into the record (keep them small
    and wire/JSON-safe: ``hop``, ``replica``, ``wire_ns``...). Disarmed
    cost is a single attribute check."""
    if not _STATE.enabled:
        return
    t = time.perf_counter_ns()
    st = _STATE
    with st.lock:
        pix = st.phase_ids.get(phase)
        if pix is None:
            pix = st.phase_ids[phase] = len(st.phase_ids)
        st.ring_rid.append(rid)
        st.ring_phase.append(pix)
        st.ring_t.append(t)
        st.ring_args.append(args or None)


def enable():
    """Arm request-lifecycle recording for this process."""
    _STATE.enabled = True


def disable():
    _STATE.enabled = False


def enabled() -> bool:
    return _STATE.enabled


def clear():
    """Drop all recorded marks and the phase intern table."""
    with _STATE.lock:
        _STATE.ring_rid.clear()
        _STATE.ring_phase.clear()
        _STATE.ring_t.clear()
        _STATE.ring_args.clear()
        _STATE.phase_ids.clear()
        _STATE.epoch_ns = time.perf_counter_ns()


def _export_columns(since_ns: Optional[int] = None):
    """The raw columnar snapshot, C-speed: ``(pid, epoch_ns, phases_table,
    rids, phase_idx, t_ns, args, wall_ns, perf_ns)``. ``phase_idx`` indexes
    the phase table; ``since_ns`` filters to marks stamped at/after that
    ``perf_counter_ns`` value. ``wall_ns``/``perf_ns`` are one wall/monotonic
    pair sampled back-to-back under the ring lock — the cluster plane maps a
    mark onto the wall clock via ``wall_ns + (t - perf_ns)`` exactly as it
    does for spans."""
    st = _STATE
    with st.lock:
        phases = list(st.phase_ids)
        rids = list(st.ring_rid)
        phase_idx = list(st.ring_phase)
        t_ns = list(st.ring_t)
        args = list(st.ring_args)
        epoch = st.epoch_ns
        wall_ns = time.time_ns()
        perf_ns = time.perf_counter_ns()
    if since_ns is not None and any(t < since_ns for t in t_ns):
        keep = [i for i, t in enumerate(t_ns) if t >= since_ns]
        rids = [rids[i] for i in keep]
        phase_idx = [phase_idx[i] for i in keep]
        t_ns = [t_ns[i] for i in keep]
        args = [args[i] for i in keep]
    return (os.getpid(), epoch, phases, rids, phase_idx, t_ns, args,
            wall_ns, perf_ns)


def snapshot_marks() -> List[Tuple[Any, str, int, Optional[Dict[str, Any]]]]:
    """A point-in-time copy of the ring as ``(rid, phase, t_ns, args)``
    tuples, oldest first (tests and in-process consumers; bulk consumers —
    the ``reqtrace`` opcode — read :func:`_export_columns` directly)."""
    (_, _, phases, rids, phase_idx, t_ns, args, _, _) = _export_columns()
    return [(r, phases[p], t, a)
            for r, p, t, a in zip(rids, phase_idx, t_ns, args)]


def group_records(marks) -> "Dict[Any, List[Tuple[str, int, dict]]]":
    """Group row-wise marks — ``(rid, phase, t_ns, args)`` tuples, or the
    cluster plane's rebased ``{rid, phase, wall_ns, args, ...}`` dicts —
    into one time-ordered ``[(phase, t, args)]`` list per rid. The shared
    assembly step under adtrace's waterfalls and the per-phase breakdown
    tables."""
    out: Dict[Any, List[Tuple[str, int, dict]]] = {}
    for m in marks:
        if isinstance(m, dict):
            rid, phase, t, args = (m.get("rid"), m.get("phase"),
                                   m.get("wall_ns", m.get("t_ns")),
                                   m.get("args") or {})
        else:
            rid, phase, t, args = m[0], m[1], m[2], (m[3] or {})
        out.setdefault(rid, []).append((phase, int(t), dict(args)))
    for recs in out.values():
        recs.sort(key=lambda r: r[1])
    return out


# AUTODIST_REQTRACE=1 arms at import so every entry point (serving replicas
# the router spawns, bench, examples) records without code changes.
if const.ENV.AUTODIST_REQTRACE.val:
    enable()
