"""Sequence-parallel strategy: long sequences sharded over the ``seq`` mesh axis.

Beyond reference parity (SURVEY.md §5.7: the reference has no sequence/context
parallelism). Parameters stay replicated with AllReduce gradient sync (this is the
AllReduce policy at the parameter level — the reference's all_reduce_strategy.py);
what changes is the mesh: a ``seq`` axis of the requested size, which the
sequence-parallel execution path (:mod:`autodist_tpu.parallel.sequence`, ring
attention) binds to shard activations along the sequence dimension.
"""

from autodist_tpu import const
from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.proto import strategy_pb2
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy.all_reduce_strategy import (fill_ar_node_configs,
                                                       parse_ar_options)
from autodist_tpu.strategy.base import Strategy, StrategyBuilder, num_devices


class SequenceParallel(StrategyBuilder):
    """Replicated params + AllReduce grad sync over a mesh with a ``seq`` axis.

    ``seq_axis_size``: size of the sequence/context axis (-1 = all devices). The
    remaining devices fill the ``data`` axis, so sequence parallelism composes
    with data parallelism in one mesh.
    """

    def __init__(self, seq_axis_size: int = -1, chunk_size: int = 128,
                 all_reduce_spec: str = "AUTO",
                 compressor: str = "NoneCompressor"):
        if seq_axis_size == 0 or seq_axis_size < -1:
            raise ValueError("seq_axis_size must be -1 (all devices) or >= 1")
        self._seq_axis_size = seq_axis_size
        self._chunk_size, self._spec, self._compressor = parse_ar_options(
            chunk_size, all_reduce_spec, compressor)
        if self._compressor != strategy_pb2.AllReduceSynchronizer.NONE:
            # The compressed grad path lowers through its own shard_map over the
            # data axes (synchronization.py), which cannot nest inside the SP
            # loss's shard_map. Fail at build time, not mid-training.
            raise ValueError(
                "SequenceParallel does not support gradient compression: the "
                "sequence-parallel loss already runs inside a shard_map and the "
                "compressed sync path cannot nest within it")

    def build(self, model_spec: ModelSpec, resource_spec: ResourceSpec) -> Strategy:
        n = num_devices(resource_spec)
        seq = n if self._seq_axis_size == -1 else self._seq_axis_size
        if n % seq != 0:
            raise ValueError(f"seq_axis_size={seq} does not divide {n} devices")

        strategy = Strategy()
        fill_ar_node_configs(strategy, model_spec, spec=self._spec,
                             compressor=self._compressor,
                             chunk_size=self._chunk_size)
        axes = {const.MESH_AXIS_SEQ: seq, const.MESH_AXIS_DATA: -1}
        self._fill_mesh_config(strategy, resource_spec,
                               self._resolved_axes(resource_spec, axes))
        return strategy
