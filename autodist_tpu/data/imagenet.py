"""ImageNet-class image pipeline: image tree -> uint8 shards -> device augment.

Counterpart of the reference CNN benchmark's real input path
(``examples/benchmark/imagenet.py:219-229`` ``input_fn`` reading tfrecords
through ``utils/imagenet_preprocessing.py``: decode, sampled crop, flip,
resize, mean subtraction). The TPU-first redesign splits the work by where it
runs best:

- **Offline prep** (:func:`prepare_image_shards`): decode + aspect-preserving
  resize + center crop to a fixed ``record_size`` square, stored as uint8 NHWC
  ``images-*.npy`` / int32 ``labels-*.npy`` row-aligned shards — the files the
  native ``DataLoader(files=...)`` memory-maps and gathers off the GIL. uint8
  records keep disk/page-cache bandwidth 4x below float32.
- **Train-time augmentation ON DEVICE** (:func:`augment_images`): random
  ``image_size`` crop out of the record + horizontal flip + channel-mean
  subtraction + cast, all inside the jitted train step (fused by XLA, runs at
  HBM speed). Crop offsets and flip bits are drawn per batch on the host
  (:class:`AugmentingBatcher`) — two tiny int arrays, so the step stays a pure
  function of its inputs and masking determinism is a host seed.

The reference's *bbox-sampled* distorted crop resizes a different-shaped
window per example — per-example dynamic shapes, which XLA cannot tile onto
the MXU. The fixed-record random-crop + flip here is the classic alternative
("VGG preprocessing" in the reference's own taxonomy,
``imagenet_preprocessing.py:26-31``) and keeps every shape static; eval uses
the standard center crop, no flip.
"""

import glob as globlib
import json
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from autodist_tpu.utils import logging

# Reference imagenet_preprocessing.py:53-57 (RGB means; subtraction only, no
# std scaling — kept for parity).
CHANNEL_MEANS = (123.68, 116.78, 103.94)

META_NAME = "images-meta.json"
_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


def _iter_image_files(src_dir: str) -> Iterator[Tuple[str, str]]:
    """Yield (class_name, path) over a ``src_dir/<class>/<image>`` tree in
    deterministic (sorted) order."""
    classes = sorted(d for d in os.listdir(src_dir)
                     if os.path.isdir(os.path.join(src_dir, d)))
    if not classes:
        raise ValueError(f"{src_dir!r} has no class subdirectories")
    for cls in classes:
        for name in sorted(os.listdir(os.path.join(src_dir, cls))):
            if name.lower().endswith(_EXTS):
                yield cls, os.path.join(src_dir, cls, name)


def _decode_record(path: str, record_size: int) -> np.ndarray:
    """Decode one image file -> uint8 [record_size, record_size, 3]:
    aspect-preserving resize (short side = record_size, the reference's
    _RESIZE_MIN step) then center crop."""
    from PIL import Image
    with Image.open(path) as im:
        im = im.convert("RGB")
        w, h = im.size
        scale = record_size / min(w, h)
        nw, nh = max(record_size, round(w * scale)), max(record_size, round(h * scale))
        im = im.resize((nw, nh), Image.BILINEAR)
        left, top = (nw - record_size) // 2, (nh - record_size) // 2
        im = im.crop((left, top, left + record_size, top + record_size))
        return np.asarray(im, np.uint8)


def prepare_image_shards(src_dir: str, directory: str, record_size: int = 256,
                         rows_per_shard: int = 1024,
                         shuffle_seed: Optional[int] = 0) -> Dict[str, List[str]]:
    """Decode a ``src_dir/<class>/<image>`` tree into row-aligned uint8
    ``images-*.npy`` + int32 ``labels-*.npy`` shards under ``directory``.

    Labels are the sorted class-directory index. Files are shuffled once
    before sharding (seeded; ``shuffle_seed=None`` keeps tree order) so a
    sequential reader still sees mixed classes. Memory stays bounded at one
    shard buffer. Writes an ``images-meta.json`` sidecar (record_size,
    classes, rows) the training side validates against. Returns the
    ``DataLoader(files=...)`` dict.
    """
    if record_size < 8:
        raise ValueError("record_size must be >= 8")
    if rows_per_shard < 1:
        raise ValueError("rows_per_shard must be >= 1")
    entries = list(_iter_image_files(src_dir))
    if not entries:
        raise ValueError(f"no image files under {src_dir!r}")
    classes = sorted({cls for cls, _ in entries})
    cls_id = {c: i for i, c in enumerate(classes)}
    if shuffle_seed is not None:
        np.random.RandomState(shuffle_seed).shuffle(entries)

    os.makedirs(directory, exist_ok=True)
    for key in ("images", "labels"):
        for stale in globlib.glob(os.path.join(globlib.escape(directory),
                                               f"{key}-*.npy")):
            os.remove(stale)

    img_buf = np.empty((rows_per_shard, record_size, record_size, 3), np.uint8)
    lab_buf = np.empty((rows_per_shard,), np.int32)
    n_buf = 0
    paths: Dict[str, List[str]] = {"images": [], "labels": []}

    def flush():
        nonlocal n_buf
        if n_buf == 0:
            return
        for key, buf in (("images", img_buf), ("labels", lab_buf)):
            path = os.path.join(directory, f"{key}-{len(paths[key]):05d}.npy")
            np.save(path, buf[:n_buf])
            paths[key].append(path)
        n_buf = 0

    n_rows = 0
    for cls, path in entries:
        img_buf[n_buf] = _decode_record(path, record_size)
        lab_buf[n_buf] = cls_id[cls]
        n_buf += 1
        n_rows += 1
        if n_buf == rows_per_shard:
            flush()
    flush()

    with open(os.path.join(directory, META_NAME), "w") as f:
        json.dump({"record_size": record_size, "rows": n_rows,
                   "classes": classes}, f, indent=1)
    logging.info("Prepared %d image records (%dx%d uint8, %d classes) across "
                 "%d shards in %s", n_rows, record_size, record_size,
                 len(classes), len(paths["images"]), directory)
    return paths


def read_meta(directory: str) -> Optional[dict]:
    path = os.path.join(directory, META_NAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def open_image_loader(directory: str, batch_size: int, **loader_kw):
    """DataLoader over a prepared shard directory (+ its meta)."""
    from autodist_tpu.data.loader import DataLoader
    meta = read_meta(directory)
    if meta is None:
        raise FileNotFoundError(f"no {META_NAME} under {directory!r} "
                                f"(prepare_image_shards writes one)")
    files = {k: sorted(globlib.glob(os.path.join(globlib.escape(directory),
                                                 f"{k}-*.npy")))
             for k in ("images", "labels")}
    return DataLoader(files=files, batch_size=batch_size, **loader_kw), meta


def augment_images(images, crop_yx, flip, image_size: int, dtype=None):
    """Device-side train augmentation: per-example ``image_size`` crop at
    ``crop_yx``, horizontal flip where ``flip``, channel-mean subtraction,
    cast. Runs inside the jitted step — XLA fuses it into the input side of
    the first conv. ``images`` uint8 [B, R, R, 3]; returns [B, S, S, 3]."""
    import jax
    import jax.numpy as jnp

    def crop_one(img, yx):
        return jax.lax.dynamic_slice(img, (yx[0], yx[1], 0),
                                     (image_size, image_size, 3))

    x = jax.vmap(crop_one)(images, crop_yx)
    x = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
    x = x.astype(jnp.float32) - jnp.asarray(CHANNEL_MEANS, jnp.float32)
    return x.astype(dtype) if dtype is not None else x


def make_augmented_loss_fn(model, image_size: int, dtype=None):
    """Classification loss over RAW record batches: augmentation happens in
    the same jit as the model (one fused program, nothing materializes on
    host). Batch keys: ``images`` (uint8 records), ``labels``, ``crop_yx``,
    ``flip`` — the :class:`AugmentingBatcher` layout."""
    import jax
    import jax.numpy as jnp

    def loss_fn(params, batch):
        x = augment_images(batch["images"], batch["crop_yx"], batch["flip"],
                           image_size, dtype)
        logits = model.apply({"params": params}, x)
        logprobs = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logprobs, batch["labels"][:, None],
                                   axis=-1)[:, 0]
        return nll.mean()

    return loss_fn


class DeviceDatasetCache:
    """HBM-resident record pool with background refresh from disk shards.

    The reference's ``training_dataset_cache`` knob cached the training
    dataset in worker memory "when training data is in remote storage"
    (``examples/benchmark/imagenet.py:219-229``); the TPU-native analogue
    caches uint8 records IN HBM. Every step assembles its batch on device —
    a pool gather + :func:`augment_images` in one jit, so no image bytes
    cross the host link on the critical path — while a trickle of fresh
    records replaces pool slots round-robin, issued ``refresh_interval``
    steps ahead so the host->HBM transfer hides under compute. With a pool
    covering the dataset this converges to full caching (the reference knob's
    semantics); with a smaller pool it is reservoir-style streaming whose
    epoch time is bounded by the link, not the step rate.

    Use :class:`AugmentingBatcher` + ``device_prefetch`` instead when the
    host->device link is fast enough to stream full batches (a real TPU VM's
    PCIe); this class exists for weak links (remote storage, tunneled chips).
    """

    #: Default HBM budget for the record pool when ``pool_rows`` is unset —
    #: conservative against a v5e's 16 GB (model + optimizer + activations
    #: own the rest). At record_size 256 this is ~20k records.
    DEFAULT_POOL_BYTES = 4 << 30

    def __init__(self, loader, *, record_size: int, image_size: int,
                 dtype=None, pool_rows: Optional[int] = None,
                 refresh_rows: int = 64, refresh_interval: int = 16,
                 train: bool = True, seed: int = 0):
        import jax
        import jax.numpy as jnp

        if image_size > record_size:
            raise ValueError(f"image_size {image_size} exceeds record_size "
                             f"{record_size}")
        self._loader = loader
        self.image_size = image_size
        self.record_size = record_size
        self.train = train
        self._rng = np.random.Generator(np.random.PCG64(seed))
        if pool_rows is None:
            # Cap the resident pool by an HBM budget, not the dataset size —
            # real-scale datasets (ImageNet: 1.28M records) must stream
            # through a bounded pool, not OOM at startup.
            row_bytes = record_size * record_size * 3
            pool_rows = max(1, self.DEFAULT_POOL_BYTES // row_bytes)
        # The loader serves whole batches (drop-last): a pool larger than the
        # servable row count would fill its tail from the NEXT epoch's batches
        # — duplicate rows in the pool, and (sequential loaders) the dropped
        # tail never cached. Size to whole batches instead.
        servable = loader.n_rows - loader.n_rows % loader.batch_size
        self._rows = min(pool_rows, servable)
        self._buf_imgs: Optional[np.ndarray] = None  # undrained loader rows
        self._buf_labs: Optional[np.ndarray] = None
        self._refresh_rows = min(refresh_rows, self._rows) if refresh_rows else 0
        self._refresh_interval = max(1, refresh_interval)
        self._step = 0
        self._cursor = 0
        self._pending = None  # (device rows, labels, start) issued last tick

        # Fill the pool once through the loader (link-speed, one-time).
        imgs = np.empty((self._rows, record_size, record_size, 3), np.uint8)
        labs = np.empty((self._rows,), np.int32)
        filled = 0
        while filled < self._rows:
            raw = loader.next()
            take = min(len(raw["images"]), self._rows - filled)
            imgs[filled:filled + take] = raw["images"][:take]
            labs[filled:filled + take] = raw["labels"][:take]
            filled += take
        self._pool = jax.device_put(imgs)
        self._labels = labs  # host-side: labels are 4 bytes/row

        out_dtype = dtype or jnp.float32

        def _assemble(pool, idx, crop, flip):
            return augment_images(jnp.take(pool, idx, axis=0), crop, flip,
                                  image_size, out_dtype)

        self._assemble = jax.jit(_assemble)

        def _update(pool, rows, start):
            return jax.lax.dynamic_update_slice(pool, rows, (start, 0, 0, 0))

        self._update = jax.jit(_update, donate_argnums=(0,))

    @property
    def pool_rows(self) -> int:
        return self._rows

    def _tick_refresh(self):
        """Apply last tick's (now-landed) transfer, then issue the next one.
        The device_put below is async: it has ``refresh_interval`` steps of
        compute to cross the link before _update consumes it."""
        import jax
        servable = self._loader.n_rows - \
            self._loader.n_rows % self._loader.batch_size
        if self._refresh_rows == 0 or servable <= self._rows:
            if servable <= self._rows and self._refresh_rows:
                # Every row the loader can serve is resident: nothing to
                # stream (the reference cache's fully-cached steady state).
                self._refresh_rows = 0
            return
        if self._pending is not None:
            rows_dev, labs, start = self._pending
            self._pool = self._update(self._pool, rows_dev, start)
            self._labels[start:start + len(labs)] = labs
            self._pending = None
        # Buffer whole loader batches and drain refresh_rows per tick: the
        # loader's batch size is the TRAINING batch (often > refresh_rows),
        # and dropping its surplus would amplify disk/gather work 4x at the
        # defaults.
        if self._buf_imgs is None or len(self._buf_imgs) < self._refresh_rows:
            raw = self._loader.next()
            if self._buf_imgs is None or not len(self._buf_imgs):
                self._buf_imgs, self._buf_labs = raw["images"], raw["labels"]
            else:
                self._buf_imgs = np.concatenate([self._buf_imgs, raw["images"]])
                self._buf_labs = np.concatenate([self._buf_labs, raw["labels"]])
        n = min(self._refresh_rows, len(self._buf_imgs),
                self._rows - self._cursor)
        rows_dev = jax.device_put(np.ascontiguousarray(self._buf_imgs[:n]))
        self._pending = (rows_dev, self._buf_labs[:n].astype(np.int32),
                         self._cursor)
        self._buf_imgs = self._buf_imgs[n:]
        self._buf_labs = self._buf_labs[n:]
        self._cursor += n
        if self._cursor >= self._rows:
            self._cursor = 0

    def next_batch(self, batch_size: int) -> Dict[str, np.ndarray]:
        """Assemble one on-device batch: ``{"images": [B,S,S,3] device array,
        "labels": [B] int32}`` — ready for the plain classification loss."""
        if self._step % self._refresh_interval == 0:
            self._tick_refresh()
        self._step += 1
        idx = self._rng.integers(0, self._rows, size=batch_size,
                                 dtype=np.int32)
        margin = self.record_size - self.image_size
        if self.train:
            crop = self._rng.integers(0, margin + 1, size=(batch_size, 2),
                                      dtype=np.int32)
            flip = self._rng.random(batch_size) < 0.5
        else:
            crop = np.full((batch_size, 2), margin // 2, np.int32)
            flip = np.zeros(batch_size, bool)
        images = self._assemble(self._pool, idx, crop, flip)
        return {"images": images, "labels": self._labels[idx]}


class AugmentingBatcher:
    """Adds per-example crop offsets and flip bits to raw record batches.

    ``train=True`` draws uniform crops + 50% flips (seeded, deterministic
    given the loader's batch order); ``train=False`` fixes the center crop
    and no flip — the reference's eval preprocessing. The heavy pixel work
    stays on device; this only draws ``[B, 2]`` + ``[B]`` small arrays.
    """

    def __init__(self, loader, image_size: int, record_size: int,
                 train: bool = True, seed: int = 0):
        if image_size > record_size:
            raise ValueError(f"image_size {image_size} exceeds record_size "
                             f"{record_size}")
        self._loader = loader
        self.image_size = image_size
        self.record_size = record_size
        self.train = train
        self._rng = np.random.Generator(np.random.PCG64(seed))

    def next(self) -> Dict[str, np.ndarray]:
        raw = self._loader.next()
        b = len(raw["images"])
        margin = self.record_size - self.image_size
        if self.train:
            crop = self._rng.integers(0, margin + 1, size=(b, 2), dtype=np.int32)
            flip = self._rng.random(b) < 0.5
        else:
            crop = np.full((b, 2), margin // 2, np.int32)
            flip = np.zeros(b, bool)
        return {"images": raw["images"], "labels": raw["labels"].astype(np.int32),
                "crop_yx": crop, "flip": flip}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.next()
