"""Checkpoint suites — parity with reference tests/checkpoint/* and c0's assertions:
original-name checkpoints, cross-strategy restore, rotation, serving export."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.checkpoint import SavedModelBuilder, Saver
from autodist_tpu.strategy import AllReduce, PartitionedPS, PS
from shardmap_compat import requires_shard_map


def _loss(p, batch):
    pred = batch["x"] @ p["dense"]["w"] + p["dense"]["b"]
    return jnp.mean((batch["y"] - pred) ** 2)


def _params():
    rng = np.random.RandomState(7)
    return {"dense": {"w": jnp.asarray(rng.randn(16, 4), jnp.float32),
                      "b": jnp.zeros((4,))}}


def _batch():
    rng = np.random.RandomState(1)
    return {"x": rng.randn(32, 16).astype(np.float32),
            "y": rng.randn(32, 4).astype(np.float32)}


def _train(builder, n_steps, params, batch):
    ad = AutoDist(strategy_builder=builder)
    runner = ad.create_distributed_session(_loss, params, optax.adam(1e-2),
                                           example_batch=batch)
    state = runner.init(params)
    for _ in range(n_steps):
        state, _ = runner.run(state, batch)
    return runner, state


def test_save_restores_original_names(tmp_path):
    runner, state = _train(PartitionedPS(), 2, _params(), _batch())
    saver = Saver()
    prefix = saver.save(state, str(tmp_path / "ckpt"))
    flat = dict(np.load(prefix + ".npz"))
    # Original single-node names, full logical shapes — no shard suffixes.
    assert "dense/w" in flat and flat["dense/w"].shape == (16, 4)
    assert "dense/b" in flat
    assert not any("part_" in k for k in flat)


def test_cross_strategy_restore_value_equality(tmp_path):
    """Train under PartitionedPS, save, restore into AllReduce: parameters equal
    (reference restored PartitionedPS checkpoints into vanilla TF the same way)."""
    batch = _batch()
    runner_a, state_a = _train(PartitionedPS(), 3, _params(), batch)
    saver = Saver()
    prefix = saver.save(state_a, str(tmp_path / "ckpt"))

    ad_b = AutoDist(strategy_builder=AllReduce())
    runner_b = ad_b.create_distributed_session(_loss, _params(), optax.adam(1e-2),
                                               example_batch=batch)
    state_b = saver.restore(prefix, runner=runner_b)
    for name in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(state_a.params["dense"][name])),
            np.asarray(jax.device_get(state_b.params["dense"][name])), rtol=1e-6)
    # optimizer state also restored
    mu_a = jax.tree_util.tree_leaves(state_a.opt_state)
    mu_b = jax.tree_util.tree_leaves(state_b.opt_state)
    for a, b in zip(mu_a, mu_b):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)), rtol=1e-6)
    assert int(np.asarray(state_b.step)) == 3


def test_resume_training_continues_identically(tmp_path):
    """Save at step 2, restore, run 2 more: identical to 4 uninterrupted steps."""
    batch = _batch()
    runner, state = _train(PS(), 2, _params(), batch)
    saver = Saver()
    prefix = saver.save(state, str(tmp_path / "ckpt"))

    for _ in range(2):
        state, _ = runner.run(state, batch)

    ad2 = AutoDist(strategy_builder=PS())
    runner2 = ad2.create_distributed_session(_loss, _params(), optax.adam(1e-2),
                                             example_batch=batch)
    state2 = saver.restore(prefix, runner=runner2)
    for _ in range(2):
        state2, _ = runner2.run(state2, batch)

    np.testing.assert_allclose(
        np.asarray(jax.device_get(state.params["dense"]["w"])),
        np.asarray(jax.device_get(state2.params["dense"]["w"])), rtol=1e-6)


def test_restore_to_host_numpy_without_runner(tmp_path):
    runner, state = _train(PS(), 1, _params(), _batch())
    prefix = Saver().save(state, str(tmp_path / "ckpt"))
    params = Saver().restore_params(prefix)
    assert set(params) == {"dense"}
    assert params["dense"]["w"].shape == (16, 4)
    np.testing.assert_allclose(
        params["dense"]["w"],
        np.asarray(jax.device_get(state.params["dense"]["w"])))


def test_latest_checkpoint_and_rotation(tmp_path):
    saver = Saver(max_to_keep=2)
    params = _params()
    for step in range(4):
        saver.save(params, str(tmp_path / "ckpt"), global_step=step)
    latest = Saver.latest_checkpoint(str(tmp_path))
    assert latest.endswith("ckpt-3")
    remaining = sorted(p for p in os.listdir(tmp_path) if p.endswith(".npz"))
    assert remaining == ["ckpt-2.npz", "ckpt-3.npz"]


def test_missing_param_raises(tmp_path):
    prefix = Saver().save({"w": jnp.zeros((2,))}, str(tmp_path / "ckpt"))
    ad = AutoDist(strategy_builder=AllReduce())
    runner = ad.create_distributed_session(_loss, _params(), optax.sgd(0.1),
                                           example_batch=_batch())
    with pytest.raises(KeyError, match="dense/"):
        Saver().restore(prefix, runner=runner)


def test_saved_model_export_roundtrip(tmp_path):
    params = _params()
    export_dir = str(tmp_path / "serve")
    builder = SavedModelBuilder(export_dir)

    def apply_fn(p, x):
        return x @ p["dense"]["w"] + p["dense"]["b"]

    x = np.asarray(np.random.RandomState(5).randn(2, 16), np.float32)
    builder.save(params, model_config={"kind": "linear"}, apply_fn=apply_fn,
                 example_args=(x,))
    assert os.path.exists(os.path.join(export_dir, "params.npz"))
    assert os.path.exists(os.path.join(export_dir, "apply.hlo"))
    assert os.path.exists(os.path.join(export_dir, "apply.export"))
    loaded = SavedModelBuilder.load_params(export_dir)
    np.testing.assert_allclose(loaded["dense"]["w"],
                               np.asarray(params["dense"]["w"]))
    # The artifact EXECUTES: deserialize apply.export and serve it against the
    # reloaded params, matching the live apply fn (reference proved its export
    # by serving the SavedModel in vanilla TF, test_saved_model.py:26-40).
    serve = SavedModelBuilder.load_serving_fn(export_dir)
    np.testing.assert_allclose(np.asarray(serve(loaded, x)),
                               np.asarray(apply_fn(params, x)),
                               rtol=1e-6, atol=1e-6)
    # Re-saving WITHOUT apply_fn must sweep the executable graph: serving a
    # stale apply.export against replaced params is silent wrong output.
    builder.save(params, model_config={"kind": "linear"})
    assert not os.path.exists(os.path.join(export_dir, "apply.export"))
    assert not os.path.exists(os.path.join(export_dir, "apply.hlo"))


def test_saved_model_serves_without_model_code(tmp_path):
    """A fresh process with the model zoo import-blocked serves the artifact:
    params come from params.npz, the graph from apply.export — nothing rebuilds
    or traces the model. The TPU analogue of serving the reference's exported
    GraphDef in vanilla TF (test_saved_model.py:26-40)."""
    import subprocess
    import sys

    from autodist_tpu.models import transformer_lm

    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=89, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_len=16,
        dtype=jnp.float32)
    model, params = transformer_lm.init_params(cfg)
    toks = np.random.RandomState(0).randint(0, 89, (3, 8)).astype(np.int32)

    def apply_fn(p, tokens):
        return model.apply({"params": p}, tokens)

    export_dir = str(tmp_path / "serve_lm")
    SavedModelBuilder(export_dir).save(
        params, model_config={"family": "transformer_lm"},
        apply_fn=apply_fn, example_args=(toks,))
    expected = np.asarray(apply_fn(params, jnp.asarray(toks)))
    np.save(str(tmp_path / "tokens.npy"), toks)
    np.save(str(tmp_path / "expected.npy"), expected)

    driver = f"""
import sys
# Serving must not need the model zoo: make importing it a hard failure.
sys.modules["autodist_tpu.models"] = None
sys.modules["autodist_tpu.models.transformer_lm"] = None
# Pin the child to CPU: the env var alone is overridden when the image's
# sitecustomize registers a hardware backend, and expected.npy was computed
# on CPU — a hardware-matmul child would differ beyond tolerance.
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from autodist_tpu.checkpoint.saved_model_builder import SavedModelBuilder
params = SavedModelBuilder.load_params({export_dir!r})
serve = SavedModelBuilder.load_serving_fn({export_dir!r})
out = np.asarray(serve(params, np.load({str(tmp_path / "tokens.npy")!r})))
np.testing.assert_allclose(out, np.load({str(tmp_path / "expected.npy")!r}),
                           rtol=1e-5, atol=1e-5)
print("SERVED_OK", out.shape)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    proc = subprocess.run([sys.executable, "-c", driver], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "SERVED_OK" in proc.stdout


def test_saved_model_polymorphic_batch(tmp_path):
    """polymorphic_batch=True bakes a symbolic leading dim: one artifact serves
    any batch size. Scalar example args stay concrete (no rank promotion)."""
    params = _params()

    def apply_fn(p, x, scale):
        return (x @ p["dense"]["w"] + p["dense"]["b"]) * scale

    export_dir = str(tmp_path / "serve_poly")
    SavedModelBuilder(export_dir).save(
        params, apply_fn=apply_fn,
        example_args=(np.zeros((2, 16), np.float32), np.float32(2.0)),
        polymorphic_batch=True)
    serve = SavedModelBuilder.load_serving_fn(export_dir)
    loaded = SavedModelBuilder.load_params(export_dir)
    for batch in (1, 2, 7):
        x = np.asarray(np.random.RandomState(batch).randn(batch, 16), np.float32)
        np.testing.assert_allclose(np.asarray(serve(loaded, x, np.float32(2.0))),
                                   np.asarray(apply_fn(params, x, 2.0)),
                                   rtol=1e-6, atol=1e-6)


@requires_shard_map
def test_ef_restore_across_dp_topologies(tmp_path):
    """Checkpoints with per-replica compressor residuals restore onto a different
    data-parallel size: shape-stable leaves (PowerSGD Q) restore, dp-sized residuals
    reinitialize to zeros instead of hard-failing."""
    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.plan import ShardingPlan
    from autodist_tpu.model_spec import ModelSpec
    from autodist_tpu.runner import DistributedRunner

    params, batch = _params(), _batch()
    builder = AllReduce(compressor="PowerSGDCompressor", power_sgd_rank=2)
    runner_a, state_a = _train(builder, 3, params, batch)
    saver = Saver()
    prefix = saver.save(state_a, str(tmp_path / "ckpt"))

    # Same strategy, but a 4-device mesh (dp=4 instead of 8).
    spec_model = ModelSpec(params)
    strategy = builder.build(spec_model, AutoDist().resource_spec)
    plan = ShardingPlan.from_strategy(strategy, spec_model)
    mesh_b = build_mesh(axes={"data": 4}, devices=jax.devices()[:4])
    runner_b = DistributedRunner(strategy, spec_model, _loss, optax.adam(1e-2),
                                 mesh=mesh_b, plan=plan)
    state_b = saver.restore(prefix, runner=runner_b)
    np.testing.assert_allclose(np.asarray(state_b.params["dense"]["w"]),
                               np.asarray(jax.device_get(state_a.params["dense"]["w"])),
                               rtol=1e-6)
    # Q is topology-independent: restored. Residual reinitialized at dp=4.
    np.testing.assert_allclose(np.asarray(state_b.ef_state["dense"]["w"].q),
                               np.asarray(jax.device_get(state_a.ef_state["dense"]["w"].q)),
                               rtol=1e-6)
    err_b = np.asarray(state_b.ef_state["dense"]["w"].error)
    assert err_b.shape[0] == 4
    assert np.all(err_b == 0)
    # And training continues.
    state_b2, loss = runner_b.run(state_b, batch)
    assert np.isfinite(float(loss))


def test_rotation_survives_restart(tmp_path):
    """A restarted trainer (fresh Saver) must keep rotating checkpoints written
    before the restart: rotation state persists in the 'checkpoint' state file."""
    import glob

    import numpy as np

    from autodist_tpu.checkpoint import Saver

    params = {"w": np.ones((2,), np.float32)}
    s1 = Saver(max_to_keep=2)
    for step in range(3):
        s1.save(params, str(tmp_path / "ck"), global_step=step)
    assert sorted(glob.glob(str(tmp_path / "ck-*.npz"))) == [
        str(tmp_path / "ck-1.npz"), str(tmp_path / "ck-2.npz")]

    s2 = Saver(max_to_keep=2)  # simulated restart
    s2.save(params, str(tmp_path / "ck"), global_step=3)
    assert sorted(glob.glob(str(tmp_path / "ck-*.npz"))) == [
        str(tmp_path / "ck-2.npz"), str(tmp_path / "ck-3.npz")]


def test_user_preserved_checkpoint_survives_restart_rotation(tmp_path):
    """A matching-name file the user copied into the directory to keep (never
    recorded in the rotation list) must not be rotate-deleted after a restart;
    only the recorded checkpoints rotate."""
    import glob
    import shutil

    import numpy as np

    from autodist_tpu.checkpoint import Saver

    params = {"w": np.ones((2,), np.float32)}
    s1 = Saver(max_to_keep=2)
    for step in range(3):
        s1.save(params, str(tmp_path / "ck"), global_step=step)
    # User deliberately preserves step 1 beyond rotation under the same pattern.
    shutil.copy(str(tmp_path / "ck-1.npz"), str(tmp_path / "ck-100.npz"))

    s2 = Saver(max_to_keep=2)  # restart: adopts only the RECORDED rotation list
    for step in (3, 4, 5):
        s2.save(params, str(tmp_path / "ck"), global_step=step)
    remaining = sorted(glob.glob(str(tmp_path / "ck-*.npz")))
    assert str(tmp_path / "ck-100.npz") in remaining
    assert remaining == [str(tmp_path / "ck-100.npz"),
                         str(tmp_path / "ck-4.npz"), str(tmp_path / "ck-5.npz")]


def test_sharded_format_roundtrip_and_rotation(tmp_path):
    """Forced sharded format in one process: manifest + shard files written,
    restore (with runner and to host numpy) is value-exact, rotation sweeps
    the per-shard files, and latest_checkpoint resolves manifest-only
    checkpoints."""
    import glob

    batch = _batch()
    runner, state = _train(PS(), 2, _params(), batch)
    saver = Saver(max_to_keep=2)
    for step in (2, 3, 4):
        prefix = saver.save(state, str(tmp_path / "ck"), global_step=step,
                            sharded=True)
    assert not [f for f in glob.glob(str(tmp_path / "ck-*.npz"))
                if ".shard" not in f]  # no monolithic files
    shard_files = glob.glob(str(tmp_path / "ck-*.shard*-of-*.npz"))
    assert {os.path.basename(f).split(".")[0] for f in shard_files} == \
        {"ck-3", "ck-4"}  # ck-2 rotated away, shards swept with it
    assert Saver.latest_checkpoint(str(tmp_path), name="ck") == \
        str(tmp_path / "ck-4")

    state_b = Saver().restore(str(tmp_path / "ck-4"), runner=runner)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(state_b.params["dense"]["w"])),
        np.asarray(jax.device_get(state.params["dense"]["w"])), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                    jax.tree_util.tree_leaves(state_b.opt_state)):
        np.testing.assert_allclose(np.asarray(jax.device_get(a)),
                                   np.asarray(jax.device_get(b)), rtol=1e-6)
    host = Saver().restore_params(str(tmp_path / "ck-4"))
    assert host["dense"]["w"].shape == (16, 4)


def test_sharded_format_bf16_leaves(tmp_path):
    """bfloat16 leaves round-trip through the sharded format (stored as
    same-width uints, true dtype recorded in the manifest)."""
    params = {"w": jnp.asarray(np.random.RandomState(0).randn(8, 4),
                               jnp.bfloat16),
              "b": jnp.zeros((4,), jnp.float32)}
    prefix = Saver().save(params, str(tmp_path / "bf"), sharded=True)
    loaded = Saver().restore_params(prefix)
    assert loaded["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(loaded["w"], np.float32),
        np.asarray(jax.device_get(params["w"]), np.float32))


def test_async_save_double_buffered(tmp_path):
    """async_write snapshots synchronously and writes in the background; a
    following save joins the previous write, wait() surfaces the result, and
    the files are complete and loadable afterwards."""
    runner, state = _train(PS(), 1, _params(), _batch())
    saver = Saver(max_to_keep=5)
    for step in (1, 2):
        saver.save(state, str(tmp_path / "as"), global_step=step,
                   async_write=True)
    saver.wait()
    assert os.path.exists(str(tmp_path / "as-1.npz"))
    latest = Saver.latest_checkpoint(str(tmp_path), name="as")
    assert latest == str(tmp_path / "as-2")
    restored = Saver().restore(latest, runner=runner)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(restored.params["dense"]["w"])),
        np.asarray(jax.device_get(state.params["dense"]["w"])), rtol=1e-6)


def test_async_save_failure_surfaces_on_wait(tmp_path, monkeypatch):
    """A background write that dies re-raises from wait() (and from the next
    save), not silently."""
    saver = Saver()
    target = tmp_path / "x"
    saver.save({"w": jnp.zeros((2,))}, str(target), async_write=True)
    saver.wait()

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)
    saver.save({"w": jnp.zeros((2,))}, str(target), global_step=7,
               async_write=True)
    with pytest.raises(RuntimeError, match="async checkpoint write"):
        saver.wait()


def test_fresh_directory_without_state_file_still_adopts(tmp_path):
    """No state file (e.g. deleted, or checkpoints rsynced in): fall back to
    adopting the on-disk scan so rotation still bounds disk use."""
    import glob
    import os

    import numpy as np

    from autodist_tpu.checkpoint import Saver

    params = {"w": np.ones((2,), np.float32)}
    s1 = Saver(max_to_keep=2)
    for step in range(3):
        s1.save(params, str(tmp_path / "ck"), global_step=step)
    os.remove(str(tmp_path / "checkpoint"))

    s2 = Saver(max_to_keep=2)
    s2.save(params, str(tmp_path / "ck"), global_step=3)
    assert sorted(glob.glob(str(tmp_path / "ck-*.npz"))) == [
        str(tmp_path / "ck-2.npz"), str(tmp_path / "ck-3.npz")]
