"""Aux subsystems: throughput meter, tracing/graph dumps, example smoke runs."""

import glob
import os
import time

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist, const
from autodist_tpu.strategy import AllReduce
from autodist_tpu.utils.metrics import ThroughputMeter
from autodist_tpu.utils import tracing
from shardmap_compat import requires_shard_map


def test_throughput_meter_periods_and_average():
    meter = ThroughputMeter(batch_size=10, log_every=2, warmup_steps=1)
    for _ in range(5):  # 1 warmup + 4 counted
        meter.step()
        time.sleep(0.01)
    assert len(meter.history) == 2          # two completed periods of 2 steps
    assert meter.average is not None
    assert 10 < meter.average < 10_000      # ~10 examples / ~0.01s

def test_throughput_meter_excludes_warmup():
    meter = ThroughputMeter(batch_size=1, log_every=100, warmup_steps=2)
    meter.step()
    time.sleep(0.2)                         # slow compile step
    meter.step()
    t0 = time.perf_counter()
    for _ in range(5):
        meter.step()
        time.sleep(0.001)
    fast_elapsed = time.perf_counter() - t0
    avg = meter.average
    # The property is EXCLUSION of the warmup, not an absolute rate (which a
    # loaded CI host can depress arbitrarily): the reported average must beat
    # the rate the same steps would show with the 0.2 s warmup counted.
    with_warmup = 7 / (0.2 + fast_elapsed)
    assert avg > 2 * with_warmup, (avg, with_warmup)


def test_dump_stage_writes_jaxpr_and_hlo(tmp_path):
    def f(x):
        return jnp.sin(x) * 2

    base = tracing.dump_stage("t", "0-original", f, jnp.ones((4,)),
                              dump_dir=str(tmp_path))
    assert base is not None
    assert os.path.exists(base + ".jaxpr.txt")
    assert os.path.exists(base + ".stablehlo.txt")
    assert "stablehlo" in open(base + ".stablehlo.txt").read()


def test_trace_writes_profile(tmp_path):
    import jax
    with tracing.trace("unit", trace_dir=str(tmp_path / "tr")) as d:
        _ = jax.jit(lambda x: x * 2)(jnp.ones((8,))).block_until_ready()
    # jax profiler writes plugins/profile/<ts>/*.pb files
    found = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in found)


def test_runner_graph_dump_flag(tmp_path, monkeypatch):
    monkeypatch.setenv("AUTODIST_DUMP_GRAPHS", "1")
    monkeypatch.setattr(const, "DEFAULT_GRAPH_DUMP_DIR", str(tmp_path))
    ad = AutoDist(strategy_builder=AllReduce())
    params = {"w": jnp.zeros(())}
    batch = {"x": np.ones(8, np.float32), "y": np.ones(8, np.float32)}

    def loss(p, b):
        return jnp.mean((b["y"] - b["x"] * p["w"]) ** 2)

    step = ad.function(loss, params, optax.sgd(0.1), example_batch=batch)
    step(batch)
    dumped = glob.glob(str(tmp_path / "train_step" / "*"))
    names = {os.path.basename(p) for p in dumped}
    assert "0-original.jaxpr.txt" in names
    assert "1-distributed.stablehlo.txt" in names


def test_image_classifier_example():
    import examples.image_classifier as ic
    losses = ic.main(epochs=2, batch_size=64)
    assert losses[-1] < losses[0]


@requires_shard_map
def test_sentiment_example_routes_embedding_to_ps():
    import examples.sentiment_classifier as sc
    losses = sc.main(steps=12)
    assert losses[-1] < losses[0]


def test_lm1b_example_runs():
    import examples.lm1b.lm1b_train as lm
    avg = lm.main(["--steps", "4", "--batch_size", "8", "--seq_len", "16",
                   "--d_model", "32", "--n_layers", "1", "--vocab", "128",
                   "--log_every", "2"])
    assert avg is None or avg > 0


def test_lm1b_example_trains_from_disk_shards(tmp_path):
    """The real-input path: corpus prep writes .npy shards, then training
    streams them memory-mapped through the native ring + device_prefetch."""
    import examples.lm1b.lm1b_train as lm
    common = ["--seq_len", "16", "--vocab", "128", "--data_dir", str(tmp_path)]
    assert lm.main(["--write_synthetic_corpus", "64", *common]) is None
    import glob
    assert len(glob.glob(str(tmp_path / "tokens-*.npy"))) == 8
    avg = lm.main(["--steps", "4", "--batch_size", "8", "--d_model", "32",
                   "--n_layers", "1", "--log_every", "2", *common])
    assert avg is None or avg > 0


def test_imagenet_benchmark_tiny():
    import examples.benchmark.imagenet as im
    # --stages 1,1: the example's plumbing (flags, meter, MFU report) is what
    # this smokes; the full-depth ResNet-50 costs ~100s of compile on the CPU
    # test host for no extra example coverage.
    avg = im.main(["--model", "resnet50", "--strategy", "AllReduce",
                   "--steps", "3", "--batch_size", "8", "--image_size", "64",
                   "--stages", "1,1", "--log_every", "2"])
    assert avg is None or avg >= 0


@requires_shard_map
def test_ncf_benchmark_tiny():
    import examples.benchmark.ncf as n
    avg = n.main(["--steps", "3", "--batch_size", "64", "--log_every", "2"])
    assert avg is None or avg >= 0


@requires_shard_map
def test_bert_benchmark_tiny():
    import examples.benchmark.bert as b
    avg = b.main(["--size", "tiny", "--steps", "3", "--batch_size", "8",
                  "--seq_len", "16", "--log_every", "2"])
    assert avg is None or avg >= 0


def test_run_all_regression_gate(tmp_path, monkeypatch, capsys):
    """run_all diffs rows against the recorded-best snapshot: >threshold drops
    are flagged, --update_baseline raises (never lowers) beaten rows."""
    import json as _json

    import examples.benchmark.run_all as run_all

    base = tmp_path / "base.json"
    base.write_text(_json.dumps({"threshold_pct": 2.0, "rows": {
        "resnet50": {"rate": 1000.0, "unit": "examples/s"},
        "vgg16": {"rate": 1000.0, "unit": "examples/s"}}}))
    canned = {"resnet50": 900.0, "vgg16": 1100.0}
    monkeypatch.setattr(run_all, "run_config", lambda name, steps: {
        "name": name, "unit": "examples/s", "rate": canned[name],
        "mfu_pct": None, "error": None})
    # The gate is per-chip and accelerator-only; fake a 1-chip TPU so the
    # comparison runs on the CPU test host.
    monkeypatch.setattr(run_all, "_probe_devices", lambda: (1, "tpu"))

    results = run_all.main(["--only", "resnet50,vgg16",
                            "--baseline", str(base), "--update_baseline"])
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "resnet50" in out
    assert results[0]["vs_best_pct"] == -10.0
    assert results[1]["vs_best_pct"] == 10.0
    snap = _json.loads(base.read_text())
    assert snap["rows"]["vgg16"]["rate"] == 1100.0   # raised
    assert snap["rows"]["resnet50"]["rate"] == 1000.0  # never lowered


def test_throughput_meter_zero_warmup():
    meter = ThroughputMeter(batch_size=4, log_every=2, warmup_steps=0)
    for _ in range(4):
        meter.step()
        time.sleep(0.001)
    assert len(meter.history) == 2
    assert meter.average > 0


# ---------------------------------------------------------- benchmark logging

def test_benchmark_file_logger_writes_json_lines(tmp_path):
    from autodist_tpu.utils.benchmark_logger import (BENCHMARK_RUN_LOG_FILE_NAME,
                                                     METRIC_LOG_FILE_NAME,
                                                     BenchmarkFileLogger,
                                                     gather_run_info)
    import json as _json
    logger = BenchmarkFileLogger(str(tmp_path))
    logger.log_metric("examples_per_second", 123.4, unit="examples/s",
                      global_step=100, extras={"model": "resnet50"})
    logger.log_metric("bad", object())  # non-numeric: dropped, not crashed
    logger.log_run_info(gather_run_info("resnet50", strategy_name="AllReduce",
                                        batch_size=256))
    logger.on_finish()
    lines = (tmp_path / METRIC_LOG_FILE_NAME).read_text().strip().splitlines()
    recs = [_json.loads(l) for l in lines]
    assert recs[0]["name"] == "examples_per_second"
    assert recs[0]["value"] == 123.4
    assert recs[0]["extras"] == {"model": "resnet50"}
    assert recs[-1]["name"] == "run_status"
    run = _json.loads((tmp_path / BENCHMARK_RUN_LOG_FILE_NAME).read_text())
    assert run["model_name"] == "resnet50"
    assert run["machine_config"]["num_devices"] == 8


def test_benchmark_logger_env_selection(tmp_path, monkeypatch):
    from autodist_tpu.utils import benchmark_logger as bl
    monkeypatch.setenv("AUTODIST_BENCHMARK_LOG_DIR", str(tmp_path))
    assert isinstance(bl.get_benchmark_logger(), bl.BenchmarkFileLogger)
    monkeypatch.delenv("AUTODIST_BENCHMARK_LOG_DIR")
    logger = bl.get_benchmark_logger()
    assert isinstance(logger, bl.BaseBenchmarkLogger)
    logger.log_metric("x", 1.0)  # must not raise


def test_mlperf_log_format():
    import json as _json
    from autodist_tpu.utils.benchmark_logger import mlperf_log
    out = []
    line = mlperf_log("global_batch_size", 4096, out=out)
    assert out == [line]
    assert line.startswith(":::MLL ")
    rec = _json.loads(line[len(":::MLL "):])
    assert rec["key"] == "global_batch_size"
    assert rec["value"] == 4096
    assert rec["event_type"] == "POINT_IN_TIME"
