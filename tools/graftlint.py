#!/usr/bin/env python
"""graftlint — project-specific static analysis for autodist_tpu.

Usage:
    python tools/graftlint.py [paths...]           # text output, baseline on
    python tools/graftlint.py --format json ...    # machine-readable (CI)
    python tools/graftlint.py --format sarif ...   # static-analysis interchange
    python tools/graftlint.py --explain GL001      # why a check exists
    python tools/graftlint.py --list-checks
    python tools/graftlint.py --changed-only       # pre-commit: git-changed files
    python tools/graftlint.py --write-baseline ... # re-grandfather findings
    python tools/graftlint.py --crosscheck ...     # merge sanitizer-observed
                                                   # lock edges into GL002's
                                                   # static graph

Default paths mirror the CI gate: autodist_tpu tests examples bench.py.
Exit status: 0 = clean (only suppressed/baselined findings), 1 = new
findings, 2 = usage error. Findings are suppressed inline with
``# graftlint: disable=GLnnn(reason)`` — the reason is mandatory — and
grandfathered via tools/graftlint_baseline.json (new findings fail, old ones
don't). See docs/usage/static_analysis.md for the check catalog.

Results are cached under ``.graftlint_cache/`` keyed on file content hashes
plus the analyzer's own source hash, with a whole-program layer on top: an
unchanged tree re-lints in file-hash time (``--no-cache`` disables, the JSON
output reports hit/miss stats and wall time). ``--changed-only`` lints just
the git-modified files for pre-commit speed — whole-program registry checks
(GL009/GL011) are skipped there because a partial file set cannot prove a
producer/arm is missing, and the interprocedural GL001/GL002 pass sees only
call targets INSIDE the changed set (a cross-module hazard through an
unchanged helper surfaces in CI's full pass, not pre-commit); CI still runs
the full pass.
"""

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from autodist_tpu.analysis import core  # noqa: E402

DEFAULT_PATHS = ["autodist_tpu", "tests", "examples", "bench.py"]
DEFAULT_BASELINE = os.path.join(ROOT, "tools", "graftlint_baseline.json")
DEFAULT_CACHE_DIR = os.path.join(ROOT, ".graftlint_cache")

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def changed_py_files():
    """Repo-relative .py files changed vs HEAD (tracked mods + untracked),
    restricted to the default path set; None when git is unavailable —
    BOTH git commands must succeed, or a transient failure of the
    untracked listing would silently drop exactly the new files a
    pre-commit run exists to lint."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=ROOT, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=ROOT, capture_output=True, text=True, timeout=30)
        if diff.returncode != 0 or untracked.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    names = set(diff.stdout.splitlines()) | set(untracked.stdout.splitlines())
    out = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        if not any(name == p or name.startswith(p.rstrip("/") + "/")
                   for p in DEFAULT_PATHS):
            continue
        if os.path.isfile(os.path.join(ROOT, name)):
            out.append(name)
    return out


def to_sarif(result, checks) -> dict:
    """SARIF 2.1.0 for the NEW findings (the failing set — baselined and
    suppressed findings are by definition not actionable results)."""
    used = sorted({f.check for f in result.findings})
    rules = [{"id": cid,
              "name": cid,
              "shortDescription": {"text": checks[cid].title
                                   if cid in checks else cid},
              "helpUri": "docs/usage/static_analysis.md"}
             for cid in used]
    results = [{
        "ruleId": f.check,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line,
                           "startColumn": max(1, f.col + 1)}},
            "logicalLocations": ([{"fullyQualifiedName": f.scope}]
                                 if f.scope else []),
        }]} for f in result.findings]
    return {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "docs/usage/static_analysis.md",
                "rules": rules}},
            "results": results,
        }],
    }


def run_crosscheck(paths, observed_path: str, fmt: str) -> int:
    """``--crosscheck``: merge the sanitizer's observed lock-order edges
    (``testing/sanitizer.py`` export) into GL002's static identity graph.

    A dedicated tool path, NOT part of ``lint_paths``: its input is a
    run-dependent artifact, so its results must never enter the lint result
    cache (the warm-cache CI assertion stays meaningful) or the baseline.
    Exit 1 on dynamic-only findings; unexercised static edges are
    informational (exit 0)."""
    from autodist_tpu.analysis.checks import concurrency
    from autodist_tpu.analysis.program import ProgramIndex

    records = []
    try:
        with open(observed_path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if "outer" in rec and "inner" in rec:
                    records.append(rec)
    except OSError as e:
        print(f"graftlint: --crosscheck cannot read observed edges "
              f"({e}); run a sanitizer-armed suite "
              f"(AUTODIST_SANITIZE=locks) first", file=sys.stderr)
        return 2

    modules = {}
    try:
        for path in core.iter_py_files(paths, ROOT):
            rel = os.path.relpath(path, ROOT)
            try:
                with open(path, encoding="utf-8") as fh:
                    source = fh.read()
            except (OSError, UnicodeDecodeError):
                continue
            mod = core.Module(path, rel, source)
            if mod.parse_error is None:
                modules[rel] = mod
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2

    program = ProgramIndex(modules)
    findings, unexercised = concurrency.crosscheck(program, records)

    if fmt == "json":
        print(json.dumps({
            "version": 1,
            "observed_edges": len(records),
            "modules": len(modules),
            "findings": [f.to_json() for f in findings],
            "unexercised": unexercised,
            "ok": not findings,
        }, indent=1))
        return 0 if not findings else 1

    for f in findings:
        print(f.render())
    for u in unexercised:
        print(f"graftlint: crosscheck: static edge "
              f"{u['outer']['path']}:{u['outer']['name']} -> "
              f"{u['inner']['path']}:{u['inner']['name']} "
              f"(established at {u['path']}:{u['line']}) was never observed "
              f"at runtime — the lock model has coverage the run didn't "
              f"earn")
    print(f"graftlint --crosscheck: {len(findings)} dynamic finding(s), "
          f"{len(unexercised)} unexercised static edge(s), "
          f"{len(records)} observed edge(s) over {len(modules)} module(s)")
    return 0 if not findings else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings as failures too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0")
    ap.add_argument("--explain", metavar="GLnnn",
                    help="print a check's rationale and exit")
    ap.add_argument("--list-checks", action="store_true")
    ap.add_argument("--check", action="append", metavar="GLnnn",
                    help="run only these checks (repeatable)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the on-disk result cache")
    ap.add_argument("--cache-dir", default=DEFAULT_CACHE_DIR,
                    help="result cache directory (default: .graftlint_cache)")
    ap.add_argument("--crosscheck", action="store_true",
                    help="merge sanitizer-observed lock-order edges "
                         "(--observed) into GL002's static graph: "
                         "dynamic-only cycles and order contradictions "
                         "fail; unexercised static edges are reported "
                         "informationally")
    ap.add_argument("--observed",
                    default=os.path.join(DEFAULT_CACHE_DIR,
                                         "observed_locks.jsonl"),
                    help="observed-edges JSONL exported by a "
                         "sanitizer-armed run (AUTODIST_SANITIZE=locks)")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only git-changed files (pre-commit mode). "
                         "Whole-program registry checks (GL009/GL011) are "
                         "skipped, and interprocedural GL001/GL002 see "
                         "only the changed files' import-closure-free "
                         "subset — CI's full pass remains the authority")
    args = ap.parse_args(argv)

    checks = core.all_checks()
    if args.list_checks:
        for cid in sorted(checks):
            kind = " [program]" if checks[cid].program else ""
            print(f"{cid}  {checks[cid].title}{kind}")
        return 0
    if args.explain:
        check = checks.get(args.explain)
        if check is None:
            print(f"unknown check {args.explain!r}; known: "
                  f"{', '.join(sorted(checks))}", file=sys.stderr)
            return 2
        print(f"{check.id} — {check.title}\n")
        print((check.doc or "(no documentation)").strip())
        return 0
    if args.check:
        unknown = [c for c in args.check if c not in checks]
        if unknown:
            print(f"unknown check(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
    if args.crosscheck:
        if args.format == "sarif":
            print("--crosscheck supports text/json output", file=sys.stderr)
            return 2
        return run_crosscheck(args.paths or DEFAULT_PATHS, args.observed,
                              args.format)

    skip_full_program = False
    partial_paths = False
    if args.changed_only:
        if args.paths:
            print("--changed-only derives its own path set; drop the "
                  "positional paths", file=sys.stderr)
            return 2
        if args.write_baseline:
            print("--changed-only + --write-baseline would rewrite the "
                  "FULL baseline from a partial file set, dropping every "
                  "grandfathered finding in unchanged files; run "
                  "--write-baseline over the full path set", file=sys.stderr)
            return 2
        if args.check and all(checks[c].full_program for c in args.check):
            print("--changed-only skips whole-program registry checks "
                  f"({', '.join(args.check)} — unsound on a partial file "
                  "set); this run would check NOTHING. Run them over the "
                  "full path set instead", file=sys.stderr)
            return 2
        changed = changed_py_files()
        if changed is None:
            print("graftlint: --changed-only needs git; falling back to "
                  "the full path set", file=sys.stderr)
            paths = DEFAULT_PATHS
        elif not changed:
            print("graftlint: no changed .py files under the lint path set")
            return 0
        else:
            paths = changed
            skip_full_program = True
    else:
        paths = args.paths or DEFAULT_PATHS
        # An explicit PARTIAL path set gets the --changed-only soundness
        # treatment: registry checks (GL009/GL011) over a subset cannot
        # prove a producer/arm is missing (reproduced: linting alerts.py
        # alone reports every shipped selector as dead), and a baseline
        # rewritten from a subset drops every grandfathered finding in the
        # unlinted rest. An explicit --check of a full-program check is an
        # informed opt-in and still honored. Paths are normalized first —
        # `autodist_tpu/` from tab-completion IS the full set.
        norm = {os.path.normpath(p) for p in paths}
        if norm != {os.path.normpath(p) for p in DEFAULT_PATHS}:
            partial_paths = True
            if args.write_baseline:
                print("--write-baseline over a partial path set would "
                      "rewrite the FULL baseline from partial findings; "
                      "run it over the default path set", file=sys.stderr)
                return 2
            if not args.check:
                skip_full_program = True
                print("graftlint: partial path set — whole-program "
                      "registry checks (GL009/GL011) skipped; the full "
                      "path set (or CI) checks them", file=sys.stderr)

    baseline = set() if (args.no_baseline or args.write_baseline) \
        else core.load_baseline(args.baseline)
    cache = None if args.no_cache else core.LintCache(args.cache_dir)
    try:
        result = core.lint_paths(paths, root=ROOT, baseline=baseline,
                                 checks=args.check, cache=cache,
                                 skip_full_program=skip_full_program)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if args.changed_only or partial_paths:
        # Baseline entries for files outside the linted subset are not
        # "stale" — they were simply not linted this run (and the prune
        # advice would point at --write-baseline, which partial runs
        # refuse).
        result.stale_baseline = []

    if args.write_baseline:
        core.write_baseline(args.baseline, result.findings)
        print(f"graftlint: wrote {len(result.findings)} grandfathered "
              f"finding(s) to {os.path.relpath(args.baseline, ROOT)}")
        return 0

    if args.format == "sarif":
        print(json.dumps(to_sarif(result, checks), indent=1))
        return 0 if result.ok else 1

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "files_checked": result.files_checked,
            "wall_time_s": result.wall_time_s,
            "cache": result.cache_info or {"enabled": False},
            "findings": [f.to_json() for f in result.findings],
            "baselined": [f.to_json() for f in result.baselined],
            "suppressed": [{"finding": f.to_json(), "reason": r}
                           for f, r in result.suppressed],
            "stale_baseline": result.stale_baseline,
            "ok": result.ok,
        }, indent=1))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.render())
    tail = (f"graftlint: {len(result.findings)} new finding(s) over "
            f"{result.files_checked} file(s)"
            f" ({len(result.suppressed)} suppressed, "
            f"{len(result.baselined)} baselined)"
            f" in {result.wall_time_s:.2f}s")
    if result.cache_info and result.cache_info.get("program_hit"):
        tail += " [cache: whole-program hit]"
    if result.stale_baseline:
        tail += (f"; {len(result.stale_baseline)} stale baseline entr"
                 f"{'y' if len(result.stale_baseline) == 1 else 'ies'} "
                 f"(fixed findings — prune with --write-baseline)")
    print(tail)
    if result.findings:
        print("explain a check: python tools/graftlint.py --explain GLnnn; "
              "suppress with `# graftlint: disable=GLnnn(reason)`")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
