"""Strategy serialize round-trip — parity with reference tests/test_strategy_base.py."""

import jax.numpy as jnp

from autodist_tpu.model_spec import ModelSpec
from autodist_tpu.resource_spec import ResourceSpec
from autodist_tpu.strategy import AllReduce, Strategy, StrategyCompiler


def _model():
    return ModelSpec({"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))})


def test_serialize_roundtrip(tmp_path):
    spec = ResourceSpec("nodes: [{address: localhost, tpus: 8}]")
    strategy = AllReduce(chunk_size=1).build(_model(), spec)
    path = strategy.serialize(str(tmp_path / "s"))
    loaded = Strategy.deserialize(path=path)
    assert loaded.id == strategy.id
    assert [n.var_name for n in loaded.node_config] == [n.var_name for n in strategy.node_config]
    assert loaded.mesh_axes() == strategy.mesh_axes()


def test_deserialize_by_id(tmp_path, monkeypatch):
    import autodist_tpu.strategy.base as base
    monkeypatch.setattr(base.const, "DEFAULT_SERIALIZATION_DIR", str(tmp_path))
    spec = ResourceSpec("nodes: [{address: localhost, tpus: 8}]")
    strategy = AllReduce().build(_model(), spec)
    strategy.serialize()
    loaded = Strategy.deserialize(strategy.id)
    assert loaded.id == strategy.id


def test_compiler_prunes_non_trainable():
    res = ResourceSpec("nodes: [{address: localhost, tpus: 8}]")
    model = ModelSpec({"w": jnp.zeros((8, 4)), "frozen": jnp.zeros((2,))},
                      trainable_filter=lambda n: n != "frozen")
    # Build with a model spec that still contains the frozen param.
    full = ModelSpec({"w": jnp.zeros((8, 4)), "frozen": jnp.zeros((2,))})
    strategy = AllReduce().build(full, res)
    assert len(strategy.node_config) == 2
    compiled = StrategyCompiler(model, res).compile(strategy)
    assert [n.var_name for n in compiled.node_config] == ["w"]


def test_compiler_fills_mesh_axes():
    res = ResourceSpec("nodes: [{address: localhost, tpus: 8}]")
    strategy = AllReduce().build(_model(), res)
    compiled = StrategyCompiler(_model(), res).compile(strategy)
    axes = compiled.mesh_axes()
    assert axes["data"] == 8
    import numpy as np
    assert int(np.prod(list(axes.values()))) == 8
