"""Check modules; importing this package populates the registry.

Each module registers with :func:`autodist_tpu.analysis.core.register`.
Check ownership:

- concurrency:   GL001 lock-held-across-dispatch, GL002 lock-order,
                 GL005 unbounded-blocking
- donation:      GL003 use-after-donate
- tracer:        GL004 tracer leak
- wire_protocol: GL006 opcode/tag exhaustiveness + frame-version order
- envflags:      GL007 AUTODIST_* flag registry
- testlayout:    GL008 tier-1 test-window conventions
"""

from autodist_tpu.analysis.checks import (  # noqa: F401
    concurrency, donation, envflags, testlayout, tracer, wire_protocol)
