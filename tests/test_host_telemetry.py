"""Unified runtime telemetry: spans, metrics registry, stats plane.

Covers the three telemetry planes (docs/usage/observability.md): host span
recording and its Chrome trace-event export schema, the Counter/Gauge/
Histogram registry's deterministic wire-encodable snapshot, the disabled-mode
no-op contract (one attribute read per span), and a ``stats``-opcode
round-trip over a real loopback PS pair. Plus the satellite pins: the
ThroughputMeter's frozen run clock, narrow ``_sync`` failure handling,
collision-free trace dirs, and ``_RecvBuffer`` recycle accounting.

Pure in-process host tests — no subprocess spawns (GL008-clean), named to
sort inside the tier-1 window.
"""

import json
import threading
import time

import numpy as np
import pytest

from autodist_tpu import telemetry
from autodist_tpu.telemetry import metrics as tmetrics
from autodist_tpu.telemetry import spans as tspans


@pytest.fixture(autouse=True)
def _telemetry_reset():
    """Leave process-global telemetry exactly as found: disabled, empty ring
    (the registry is additive-only and harmless to share)."""
    telemetry.disable()
    telemetry.clear()
    yield
    telemetry.disable()
    telemetry.clear()


# ------------------------------------------------------------------- spans

def test_span_records_and_nests():
    telemetry.enable()
    with telemetry.span("outer", kind="test"):
        with telemetry.span("inner"):
            time.sleep(0.001)
    recorded = {name: (tid, t0, dur, args)
                for name, tid, t0, dur, args in telemetry.snapshot_spans()}
    assert set(recorded) == {"outer", "inner"}
    o_tid, o_t0, o_dur, o_args = recorded["outer"]
    i_tid, i_t0, i_dur, _ = recorded["inner"]
    assert o_tid == i_tid == threading.get_ident()
    # Containment is the nesting contract (Perfetto stacks same-thread
    # complete events by time-range containment).
    assert o_t0 <= i_t0
    assert i_t0 + i_dur <= o_t0 + o_dur
    assert o_args == {"kind": "test"}


def test_span_thread_awareness():
    telemetry.enable()
    done = threading.Event()

    def worker():
        with telemetry.span("from_thread"):
            pass
        done.set()

    t = threading.Thread(target=worker, name="telemetry-test-thread")
    with telemetry.span("from_main"):
        t.start()
        t.join(timeout=10)
    assert done.wait(timeout=10)
    tids = {name: tid for name, tid, *_ in telemetry.snapshot_spans()}
    assert tids["from_main"] != tids["from_thread"]


def test_traced_decorator_records_per_call():
    @telemetry.traced("deco_span")
    def f(x):
        return x + 1

    assert f(1) == 2                       # disabled at call: no record
    assert telemetry.snapshot_spans() == []
    telemetry.enable()                      # decorated BEFORE enabling
    assert f(2) == 3
    assert [s[0] for s in telemetry.snapshot_spans()] == ["deco_span"]


def test_span_ring_is_bounded():
    telemetry.enable()
    cap = tspans._STATE.ring_t0.maxlen
    assert cap is not None and cap >= 1
    for i in range(min(cap, 1000) + 50):
        with telemetry.span("s"):
            pass
    assert tspans._STATE.ring_len() <= cap
    # The five ring columns evict in lockstep — they can never misalign.
    st = tspans._STATE
    assert len(st.ring_name) == len(st.ring_tid) == len(st.ring_t0) \
        == len(st.ring_dur) == len(st.ring_args)


def test_chrome_trace_export_schema(tmp_path):
    telemetry.enable()
    with telemetry.span("a", step=3):
        with telemetry.span("b"):
            pass
    path = str(tmp_path / "host_spans.json")
    assert telemetry.export_chrome_trace(path) == path
    doc = json.load(open(path))
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    for ev in events:
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        assert isinstance(ev["name"], str)
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], float) and ev["ts"] >= 0.0
            assert isinstance(ev["dur"], float) and ev["dur"] >= 0.0
            assert isinstance(ev["args"], dict)
        else:
            assert ev["name"] == "thread_name"
    names = [ev["name"] for ev in events if ev["ph"] == "X"]
    assert sorted(names) == ["a", "b"]
    arg_ev = next(ev for ev in events if ev["name"] == "a")
    assert arg_ev["args"] == {"step": 3}
    # pid/clock_offset_ns parameters (cluster trace plane): same schema, the
    # lane relabeled and every ts uniformly shifted — defaults unchanged.
    shifted = telemetry.chrome_trace_events(pid=9, clock_offset_ns=1_000)
    assert all(ev["pid"] == 9 for ev in shifted)
    for ev, base_ev in zip((e for e in shifted if e["ph"] == "X"),
                           (e for e in events if e["ph"] == "X")):
        assert ev["ts"] - base_ev["ts"] == pytest.approx(1.0)  # 1000ns = 1µs


def test_disabled_span_is_single_attribute_check():
    """The disabled fast path's cost contract: exactly ONE attribute read per
    ``span()`` call, returning the shared no-op context manager, with no ring
    growth. A second attribute touch here is a hot-path regression (gated at
    runtime by bench.py --telemetry-overhead)."""

    class _CountingState:
        def __init__(self):
            self.reads = 0

        @property
        def enabled(self):
            self.reads += 1
            return False

    counting = _CountingState()
    real = tspans._STATE
    tspans._STATE = counting
    try:
        cms = {telemetry.span("x"), telemetry.span("y", k=1)}
        for _ in range(48):
            with telemetry.span("z"):
                pass
        reads = counting.reads
    finally:
        tspans._STATE = real
    assert len(cms) == 1                       # the one shared null span
    assert reads == 50                         # one read per span() call
    assert telemetry.snapshot_spans() == []    # nothing recorded


# ----------------------------------------------------------------- registry

def test_registry_snapshot_deterministic():
    r1, r2 = tmetrics.Registry(), tmetrics.Registry()
    for reg, order in ((r1, ("b.z", "a.x", "m.c")),
                      (r2, ("m.c", "b.z", "a.x"))):
        for name in order:
            reg.counter(name)
        reg.counter("b.z").inc(2)
        reg.counter("a.x").inc(1)
        reg.counter("m.c").inc(3)
        reg.gauge("g.depth").set(1)
        reg.histogram("h.lag", buckets=(1, 2)).observe(1.5)
    assert r1.snapshot() == r2.snapshot()      # registration order irrelevant
    assert list(r1.snapshot()) == sorted(r1.snapshot())
    assert r1.snapshot()["b.z"] == 2
    # snapshot values are wire-encodable as-is (the stats opcode's contract)
    from autodist_tpu.parallel import wire
    assert wire.decode(wire.encode(r1.snapshot())) == r1.snapshot()


def test_histogram_family_bucket_overrides():
    """Per-family default-bucket resolution (PR 7): names under a
    BUCKET_FAMILIES prefix get that family's edges (serve latencies resolve
    at ms scale), longest prefix wins, explicit buckets always override, and
    names outside every family keep the pre-existing SECONDS_BUCKETS default
    — the snapshot schema of old histograms is unchanged."""
    assert tmetrics.family_buckets("serve.latency_s") == tmetrics.MS_BUCKETS
    assert tmetrics.family_buckets("serve.latency_s.total") \
        == tmetrics.MS_BUCKETS
    # Prefix match is component-wise: a sibling name is NOT in the family.
    assert tmetrics.family_buckets("serve.latency_sx") \
        == tmetrics.SECONDS_BUCKETS
    assert tmetrics.family_buckets("train.step_s") == tmetrics.SECONDS_BUCKETS

    reg = tmetrics.Registry()
    ms = reg.histogram("serve.latency_s.queue")
    assert ms.buckets == tmetrics.MS_BUCKETS
    old = reg.histogram("train.step_s")
    assert old.buckets == tmetrics.SECONDS_BUCKETS
    explicit = reg.histogram("serve.latency_s.custom", buckets=(1, 2))
    assert explicit.buckets == (1, 2)
    # Snapshot schema: the family's edges appear as le: keys, same shape as
    # every other histogram.
    ms.observe(0.003)
    snap = reg.snapshot()["serve.latency_s.queue"]
    assert set(snap) == {f"le:{b:g}" for b in tmetrics.MS_BUCKETS} \
        | {"le:+inf", "count", "sum"}
    assert snap["le:0.005"] == 1


def test_registry_get_or_create_and_type_guard():
    reg = tmetrics.Registry()
    assert reg.counter("c") is reg.counter("c")
    with pytest.raises(TypeError):
        reg.gauge("c")


def test_histogram_bucket_edges():
    h = tmetrics.Histogram("h", buckets=(1, 2, 4))
    for v in (0.5, 1, 1.5, 2, 4.5):
        h.observe(v)
    snap = h.snapshot()
    # le semantics: a value equal to a bound lands IN that bound's bucket.
    assert snap["le:1"] == 2      # 0.5, 1
    assert snap["le:2"] == 2      # 1.5, 2
    assert snap["le:4"] == 0
    assert snap["le:+inf"] == 1   # 4.5
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(9.5)
    assert h.format_compact() == "{1:2,2:2,+inf:1}"
    with pytest.raises(ValueError):
        tmetrics.Histogram("bad", buckets=(2, 1))


def test_emit_metrics_rides_benchmark_logger():
    from autodist_tpu.utils.benchmark_logger import BaseBenchmarkLogger

    class _Capture(BaseBenchmarkLogger):
        def __init__(self):
            self.rows = []

        def log_metric(self, name, value, unit=None, global_step=None,
                       extras=None):
            self.rows.append((name, value, global_step, extras))

    reg = telemetry.registry()
    reg.counter("emit.test_counter").inc(7)
    reg.histogram("emit.test_hist", buckets=(1,)).observe(0.5)
    sink = _Capture()
    n = telemetry.emit_metrics(global_step=42, logger=sink)
    assert n == len(sink.rows) >= 2
    rows = {name: (value, step, extras) for name, value, step, extras
            in sink.rows}
    assert rows["emit.test_counter"][0] == 7
    assert rows["emit.test_counter"][1] == 42
    value, _, extras = rows["emit.test_hist"]
    assert value == 1 and extras["le:1"] == 1  # count + bucket dict in extras


# -------------------------------------------------- wire counters / satellites

def test_wire_counters_format_line_pinned():
    from autodist_tpu.utils.metrics import WireCounters
    wc = WireCounters()
    wc.add_sent(12_300_000, encode_s=0.0012)
    wc.add_received(67_800_000, decode_s=0.0034)
    assert wc.format_line() == ("wire tx 12.3MB/1 rx 67.8MB/1 "
                                "enc 1.20ms/msg dec 3.40ms/msg")
    assert wc.snapshot() == {"bytes_sent": 12_300_000,
                             "bytes_received": 67_800_000,
                             "msgs_sent": 1, "msgs_received": 1,
                             "encode_s": 0.0012, "decode_s": 0.0034}


def test_wire_counters_mirror_into_registry():
    from autodist_tpu.utils.metrics import WireCounters
    telemetry.enable()
    before = telemetry.registry().counter("ps.wire.bytes_sent").value
    WireCounters().add_sent(1000)
    WireCounters(mirror=False).add_sent(5000)   # per-worker views: no mirror
    after = telemetry.registry().counter("ps.wire.bytes_sent").value
    assert after - before == 1000


def test_throughput_meter_finish_freezes_average():
    from autodist_tpu.utils.metrics import ThroughputMeter
    meter = ThroughputMeter(batch_size=10, log_every=2, warmup_steps=1,
                            log=False)
    for _ in range(5):
        meter.step()
        time.sleep(0.005)
    frozen = meter.finish()
    assert frozen == meter.average is not None
    time.sleep(0.08)
    # Post-run wall time (eval/teardown) no longer dilutes the rate.
    assert meter.average == frozen
    meter.step()              # training again: the clock unfreezes
    time.sleep(0.08)
    assert meter.average != frozen


def test_sync_failure_is_narrow_and_silent():
    import jax

    from autodist_tpu.utils import metrics as umetrics
    real = jax.device_get
    jax.device_get = lambda v: (_ for _ in ()).throw(RuntimeError("boom"))
    try:
        elapsed = umetrics._sync(np.ones((2,)))   # must not raise
    finally:
        jax.device_get = real
    assert isinstance(elapsed, float) and elapsed >= 0.0
    assert umetrics._sync(None) == 0.0


def test_trace_dirs_never_collide():
    from autodist_tpu import const
    from autodist_tpu.utils import tracing
    dirs = {tracing._unique_trace_dir("t") for _ in range(8)}
    assert len(dirs) == 8          # same wall-clock second, distinct dirs
    assert all(d.startswith(const.DEFAULT_TRACE_DIR) for d in dirs)


def test_recv_buffer_counts_recycles_and_fresh():
    from autodist_tpu.parallel.ps_transport import _RecvBuffer
    buf = _RecvBuffer()
    view = buf.take(128)
    assert (buf.fresh_allocs, buf.recycles) == (1, 0)
    del view                       # consume-then-drop: next take recycles
    buf.take(128)
    assert (buf.fresh_allocs, buf.recycles) == (1, 1)
    holder = buf.take(128)         # held alias: next take must go fresh
    assert buf.recycles == 2
    buf.take(128)
    assert (buf.fresh_allocs, buf.recycles) == (2, 2)
    del holder


# -------------------------------------------------------------- stats plane

class _StubPSRunner:
    """The minimal surface PSServer._dispatch drives, over a numpy-only
    ParameterService — a real gate and service without model compilation."""

    def __init__(self, staleness=2):
        from autodist_tpu.parallel.staleness import (ParameterService,
                                                     StalenessController)
        from autodist_tpu.runner import TrainState
        state = TrainState(step=np.zeros((), np.int32),
                           params={"w": np.ones((64,), np.float32)},
                           opt_state=(), ef_state=())
        self.service = ParameterService(state, lambda s, grads: s)
        self.controller = StalenessController(1, staleness=staleness)

    def add_worker(self, worker_id=None, with_generation=False):
        wid, gen = self.controller.register_with_generation(worker_id)
        handle = type("H", (), {"worker_id": wid})()
        return (handle, gen) if with_generation else handle


def test_stats_opcode_roundtrip_over_loopback():
    from autodist_tpu.parallel.ps_transport import PSServer, RemotePSWorker

    telemetry.enable()
    server = PSServer(_StubPSRunner(), host="127.0.0.1")
    host, port = server.address
    remote = RemotePSWorker(f"{host}:{port}", runner=None, worker_id=0,
                            overlap=False)
    try:
        # Drive the gate + a parameter read so there is per-worker traffic.
        remote._client.call("start_step", 0, 5.0)
        params, _, version = remote._client.call("read")
        remote._client.call("finish_step", 0)
        np.testing.assert_allclose(params["w"], 1.0)

        snap = remote.stats()
        assert set(snap) >= {"registry", "wire", "per_worker"}
        # Aggregate wire counters cover every exchange so far.
        assert snap["wire"]["msgs_received"] >= 4
        assert snap["wire"]["bytes_received"] > 0
        # Per-worker breakdown: this worker's traffic + its staleness
        # distribution from the gate (one entry, zero lag).
        w0 = snap["per_worker"][0]
        assert w0["wire"]["msgs_received"] >= 2
        assert w0["staleness"]["count"] == 1
        assert w0["staleness"]["le:0"] == 1
        # The registry snapshot mirrors the wire counters (telemetry is on).
        assert snap["registry"]["ps.wire.bytes_received"] > 0
        # The reply crossed the typed wire, so it is JSON-able plain data.
        json.dumps(snap)
    finally:
        remote.close()
        server.close()


def test_unknown_op_still_errors():
    """The stats arm must not loosen the dispatch's unknown-op handling."""
    from autodist_tpu.parallel.ps_transport import PSClientError, PSServer, \
        RemotePSWorker

    server = PSServer(_StubPSRunner(), host="127.0.0.1")
    host, port = server.address
    remote = RemotePSWorker(f"{host}:{port}", runner=None, worker_id=0,
                            overlap=False)
    try:
        with pytest.raises(PSClientError, match="unknown op"):
            remote._client.call("no_such_op")
    finally:
        remote.close()
        server.close()
