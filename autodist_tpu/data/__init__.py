"""Host data pipeline (native prefetch loader + device prefetch + datasets)."""

from autodist_tpu.data import imagenet, mlm, movielens, text_corpus
from autodist_tpu.data.loader import (DataLoader, device_prefetch,
                                      save_shards, shard_files_for_process)

__all__ = ["DataLoader", "device_prefetch", "save_shards",
           "shard_files_for_process", "imagenet", "mlm", "movielens",
           "text_corpus"]
