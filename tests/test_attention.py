"""Attention stack: blockwise == plain softmax; flash kernel == blockwise;
ring attention over the seq axis == single-device attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from autodist_tpu import const
from autodist_tpu.models.transformer_lm import causal_mask, dot_product_attention
from autodist_tpu.ops.blockwise_attention import blockwise_attention
from autodist_tpu.ops.flash_attention import flash_attention
from autodist_tpu.parallel.mesh import build_mesh
from autodist_tpu.parallel.ring_attention import ring_attention
from shardmap_compat import requires_shard_map

B, L, H, D = 2, 64, 4, 16

# Ring/sequence-parallel cases shard over an 8-way mesh; a single real chip
# can't host them (the CPU-sim suite provides 8 virtual devices).
_NEEDS_MESH = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs an 8-device mesh (run under the CPU-sim suite)")


def _close(a, b, atol, rtol=1e-7, mxu=0.01, **kw):
    """Backend-aware comparison: exact-ish on the CPU suite (deterministic
    orderings); on Mosaic-compiling backends both sides run matmuls at MXU
    (bf16-pass) precision with different orderings, so two correct
    implementations legitimately differ at MXU bf16-pass resolution —
    bounded at ``mxu`` (1e-2 for normalized outputs; gradient and raw
    carry-state comparisons pass 5e-2 — the backward chains two more matmuls
    and the unnormalized accumulators run at larger magnitudes)."""
    if jax.default_backend() in ("tpu", "axon"):
        atol, rtol = max(atol, mxu), max(rtol, mxu)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=rtol,
                               atol=atol, **kw)


def _qkv(seed=0, l=L):
    rng = np.random.RandomState(seed)
    shape = (B, l, H, D)
    return (jnp.asarray(rng.randn(*shape), jnp.float32),
            jnp.asarray(rng.randn(*shape), jnp.float32),
            jnp.asarray(rng.randn(*shape), jnp.float32))


def _reference(q, k, v, causal=True):
    mask = causal_mask(q.shape[1], jnp.float32) if causal else jnp.zeros(())
    return dot_product_attention(q, k, v, mask, jnp.float32)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block", [16, 17, 64, 256])
def test_blockwise_matches_reference(causal, block):
    q, k, v = _qkv()
    want = _reference(q, k, v, causal)
    got = blockwise_attention(q, k, v, causal=causal, block_size=block)
    _close(got, want, atol=2e-5)


def test_blockwise_gradients_match_reference():
    q, k, v = _qkv(1)

    def f_ref(q, k, v):
        return jnp.sum(_reference(q, k, v) ** 2)

    def f_blk(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, block_size=16) ** 2)

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(f_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        _close(a, b, atol=3e-4, mxu=0.05)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    q, k, v = _qkv(2)
    want = _reference(q, k, v, causal)
    got = flash_attention(q, k, v, causal=causal, q_block=32, k_block=32)
    _close(got, want, atol=2e-5)


def test_flash_kernel_ragged_length():
    # L=60 not divisible by the 32-blocks: padding must not leak into results.
    q, k, v = _qkv(3, l=60)
    want = _reference(q, k, v, True)
    got = flash_attention(q, k, v, causal=True, q_block=32, k_block=32)
    _close(got, want, atol=2e-5)


def test_flash_gradients_flow():
    q, k, v = _qkv(4)

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, q_block=32, k_block=32) ** 2)

    grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    def f_ref(q, k, v):
        return jnp.sum(_reference(q, k, v) ** 2)

    want = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, want):
        _close(a, b, atol=3e-4, mxu=0.05)


@requires_shard_map
@_NEEDS_MESH
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_single_device(causal):
    """Sequence sharded over a 4-way seq axis: ring result == full attention."""
    mesh = build_mesh(axes={const.MESH_AXIS_SEQ: 4, const.MESH_AXIS_DATA: 2})
    q, k, v = _qkv(5)
    want = _reference(q, k, v, causal)

    spec = P(const.MESH_AXIS_DATA, const.MESH_AXIS_SEQ, None, None)
    fn = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=causal, block_size=16),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    got = fn(q, k, v)
    _close(got, want, atol=2e-5)


@_NEEDS_MESH
@requires_shard_map
def test_ring_attention_gradients_flow():
    mesh = build_mesh(axes={const.MESH_AXIS_SEQ: 4, const.MESH_AXIS_DATA: 2})
    q, k, v = _qkv(6)
    spec = P(const.MESH_AXIS_DATA, const.MESH_AXIS_SEQ, None, None)
    fn = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, causal=True, block_size=16),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def loss_ref(q, k, v):
        return jnp.sum(_reference(q, k, v) ** 2)

    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, want):
        _close(a, b, atol=3e-4, mxu=0.05)


def test_transformer_with_flash_attention_matches_dot():
    import dataclasses
    from autodist_tpu.models import transformer_lm
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_len=64,
        dtype=jnp.float32)
    model, params = transformer_lm.init_params(cfg)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=4, seq_len=32)
    loss_dot = transformer_lm.make_loss_fn(model)(params, batch)
    cfg_flash = dataclasses.replace(cfg, attention_impl="flash")
    model_flash = transformer_lm.TransformerLM(cfg_flash)
    loss_flash = transformer_lm.make_loss_fn(model_flash)(params, batch)
    _close(float(loss_dot), float(loss_flash), atol=0, rtol=1e-5)


def test_transformer_with_blockwise_attention_matches_dot():
    """attention_impl='blockwise' (the O(L)-memory pure-JAX path the
    long-context example uses off-Mosaic) is value-identical to dot."""
    import dataclasses
    from autodist_tpu.models import transformer_lm
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_len=64,
        dtype=jnp.float32)
    model, params = transformer_lm.init_params(cfg)
    batch = transformer_lm.synthetic_batch(cfg, batch_size=4, seq_len=32)
    loss_dot = transformer_lm.make_loss_fn(model)(params, batch)
    cfg_bw = dataclasses.replace(cfg, attention_impl="blockwise")
    model_bw = transformer_lm.TransformerLM(cfg_bw)
    loss_bw = transformer_lm.make_loss_fn(model_bw)(params, batch)
    _close(float(loss_dot), float(loss_bw), atol=0, rtol=1e-5)


def test_flash_carry_matches_blockwise_carry():
    """The pallas carry variant and the pure-JAX carry produce the same
    (acc, m, l) state, including with offsets and a carry-in (the ring step)."""
    from autodist_tpu.ops.blockwise_attention import blockwise_attention_with_carry
    from autodist_tpu.ops.flash_attention import flash_attention_with_carry

    rng = np.random.RandomState(0)
    b, l, h, d = 2, 32, 2, 8
    q = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    k1 = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    v1 = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    k2 = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    v2 = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)

    # Two chained steps with global offsets, as the ring executes them: q shard at
    # offset l attends its own kv (offset l) then the previous shard's (offset 0).
    bw = blockwise_attention_with_carry(q, k1, v1, None, causal=True,
                                        block_size=16, q_offset=l, k_offset=l)
    bw = blockwise_attention_with_carry(q, k2, v2, bw, causal=True,
                                        block_size=16, q_offset=l, k_offset=0)
    fl = flash_attention_with_carry(q, k1, v1, None, causal=True,
                                    q_offset=l, k_offset=l,
                                    q_block=16, k_block=16)
    fl = flash_attention_with_carry(q, k2, v2, fl, causal=True,
                                    q_offset=l, k_offset=0,
                                    q_block=16, k_block=16)
    for a, b_, name in zip(fl, bw, ("acc", "m", "l")):
        _close(a, b_, atol=1e-5, rtol=1e-5, mxu=0.05, err_msg=name)


@requires_shard_map
@_NEEDS_MESH
@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_ring_blockwise(causal):
    """Forward AND gradients of the pallas-backed ring equal the pure-JAX ring."""
    from functools import partial

    mesh = build_mesh(axes={"seq": 4, "data": 2})
    rng = np.random.RandomState(1)
    b, l, h, d = 2, 64, 2, 8
    q = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, l, h, d), jnp.float32)

    def run(impl):
        spec = P(("data", "reduce"), "seq", None, None)
        fn = jax.shard_map(
            lambda q_, k_, v_: ring_attention(q_, k_, v_, causal=causal,
                                              block_size=16, impl=impl),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)

        def loss(q_, k_, v_):
            return jnp.sum(fn(q_, k_, v_) ** 2)

        with mesh:
            val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return val, grads

    val_bw, g_bw = run("blockwise")
    val_fl, g_fl = run("flash")
    _close(float(val_fl), float(val_bw), atol=0, rtol=1e-5)
    for a, b_, name in zip(g_fl, g_bw, "qkv"):
        _close(a, b_, atol=1e-4, rtol=1e-4, mxu=0.05, err_msg=f"d{name}")
