"""End-to-end sequence/context parallelism.

The long-context capability (prompt/SURVEY.md §5.7: absent from the reference, a
first-class requirement here): sequence sharded over the ``seq`` mesh axis, ring
attention rotating K/V shards, position embeddings globally offset, loss a global
token mean. Proven by value equivalence against the single-shard model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from autodist_tpu import AutoDist
from autodist_tpu.models import transformer_lm
from autodist_tpu.parallel.sequence import (create_sequence_parallel_session,
                                            make_sequence_parallel_loss_fn)
from autodist_tpu.strategy import SequenceParallel
from shardmap_compat import requires_shard_map

SEQ = 32
BATCH = 4


def _model(attention_impl):
    cfg = transformer_lm.TransformerLMConfig(
        vocab_size=128, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_len=SEQ, dtype=jnp.float32, tied_output=False,
        attention_impl=attention_impl)
    return transformer_lm.init_params(cfg) + (cfg,)


def _batch(cfg, seed=0):
    # seq_len targets => tokens [B, SEQ+1] => inputs [B, SEQ], divisible by seq axis
    return transformer_lm.synthetic_batch(cfg, batch_size=BATCH, seq_len=SEQ,
                                          seed=seed)


@requires_shard_map
def test_sp_loss_and_grads_match_single_device():
    """SP loss/grads over a (data=2, seq=4) mesh == the plain single-shard model
    with identical parameters."""
    model_ring, params, cfg = _model("ring")
    model_dot, _, _ = _model("dot")
    batch = _batch(cfg)

    ref_loss_fn = transformer_lm.make_loss_fn(model_dot)
    ref_loss, ref_grads = jax.value_and_grad(ref_loss_fn)(params, batch)

    ad = AutoDist(strategy_builder=SequenceParallel(seq_axis_size=4))
    runner = create_sequence_parallel_session(ad, model_ring, params,
                                              optax.sgd(0.1))
    assert runner.mesh.shape["seq"] == 4
    sp_loss_fn = make_sequence_parallel_loss_fn(model_ring, runner.mesh)
    sp_loss, sp_grads = jax.value_and_grad(sp_loss_fn)(params, batch)

    np.testing.assert_allclose(float(sp_loss), float(ref_loss), rtol=1e-5)
    flat_ref = jax.tree_util.tree_leaves(ref_grads)
    flat_sp = jax.tree_util.tree_leaves(sp_grads)
    for a, b in zip(flat_ref, flat_sp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


@requires_shard_map
@pytest.mark.parametrize("tied", [False, True])
def test_sp_fused_head_matches_plain_sp(tied):
    """The fused pallas head composes with sequence parallelism: same loss and
    gradients as the SP path with the XLA head — tied (embedding-table head,
    vd layout, gradient summing gather + fused dw) and untied."""
    import dataclasses
    _, _, cfg = _model("ring")
    cfg = dataclasses.replace(cfg, tied_output=tied)
    model_ring, params = transformer_lm.init_params(cfg)
    cfg_f = dataclasses.replace(cfg, fused_head=True)
    model_fused = transformer_lm.TransformerLM(cfg_f)
    batch = _batch(cfg)

    ad = AutoDist(strategy_builder=SequenceParallel(seq_axis_size=4))
    runner = create_sequence_parallel_session(ad, model_ring, params,
                                              optax.sgd(0.1))
    loss_plain = make_sequence_parallel_loss_fn(model_ring, runner.mesh)
    loss_fused = make_sequence_parallel_loss_fn(model_fused, runner.mesh)
    state = runner.init(params)
    p = runner.logical_params(state)
    with runner.mesh:
        lp, gp = jax.value_and_grad(loss_plain)(p, batch)
        lf, gf = jax.value_and_grad(loss_fused)(p, batch)
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-5)
    for a, e in zip(jax.tree_util.tree_leaves(gf), jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=5e-4, atol=5e-5)


@requires_shard_map
def test_sp_training_decreases_loss():
    model, params, cfg = _model("ring")
    batch = _batch(cfg)
    ad = AutoDist(strategy_builder=SequenceParallel(seq_axis_size=4))
    runner = create_sequence_parallel_session(ad, model, params, optax.adam(1e-2))
    state = runner.init(params)
    losses = []
    for _ in range(6):
        state, loss = runner.run(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.all(np.isfinite(losses))


@requires_shard_map
def test_sp_composes_with_data_parallelism():
    """seq=2 leaves data=4: batch shards over data, sequence over seq, same loss."""
    model_ring, params, cfg = _model("ring")
    model_dot, _, _ = _model("dot")
    batch = _batch(cfg)
    ref = float(transformer_lm.make_loss_fn(model_dot)(params, batch))

    ad = AutoDist(strategy_builder=SequenceParallel(seq_axis_size=2))
    runner = create_sequence_parallel_session(ad, model_ring, params,
                                              optax.sgd(0.1))
    assert runner.mesh.shape["data"] == 4 and runner.mesh.shape["seq"] == 2
    loss_fn = make_sequence_parallel_loss_fn(model_ring, runner.mesh)
    np.testing.assert_allclose(float(loss_fn(params, batch)), ref, rtol=1e-5)


@requires_shard_map
def test_sp_rejects_indivisible_sequence():
    model, params, cfg = _model("ring")
    ad = AutoDist(strategy_builder=SequenceParallel(seq_axis_size=4))
    runner = create_sequence_parallel_session(ad, model, params, optax.sgd(0.1))
    loss_fn = make_sequence_parallel_loss_fn(model, runner.mesh)
    bad = {"tokens": np.zeros((BATCH, 31), np.int32)}  # L=30 not divisible by 4
    with pytest.raises(ValueError, match="not divisible"):
        loss_fn(params, bad)


def test_sp_builder_validation():
    with pytest.raises(ValueError):
        SequenceParallel(seq_axis_size=0)
    with pytest.raises(ValueError):
        SequenceParallel(seq_axis_size=-2)
    model, params, cfg = _model("ring")
    from autodist_tpu.model_spec import ModelSpec
    from autodist_tpu import ResourceSpec
    with pytest.raises(ValueError, match="does not divide"):
        SequenceParallel(seq_axis_size=3).build(ModelSpec(params), ResourceSpec())


def test_sp_rejects_compressor():
    with pytest.raises(ValueError, match="compression"):
        SequenceParallel(seq_axis_size=2, compressor="HorovodCompressor")


@requires_shard_map
def test_sp_rejects_sequence_beyond_max_len():
    """Out-of-range position offsets would silently clamp per-shard; the global
    length check fails loudly instead."""
    model, params, cfg = _model("ring")
    ad = AutoDist(strategy_builder=SequenceParallel(seq_axis_size=4))
    runner = create_sequence_parallel_session(ad, model, params, optax.sgd(0.1))
    loss_fn = make_sequence_parallel_loss_fn(model, runner.mesh)
    too_long = {"tokens": np.zeros((BATCH, 2 * SEQ + 1), np.int32)}
    with pytest.raises(ValueError, match="max_len"):
        loss_fn(params, too_long)


# ------------------------------------------------------------------ Ulysses

@requires_shard_map
def test_ulysses_attention_matches_single_device():
    """All-to-all SP: seq-sharded ulysses attention == full attention."""
    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.ulysses import make_ulysses_attention_fn
    from autodist_tpu.models.transformer_lm import (causal_mask,
                                                    dot_product_attention)
    rng = np.random.RandomState(0)
    B, L, H, D = 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.randn(B, L, H, D), jnp.float32) for _ in range(3))
    mesh = build_mesh(axes={"data": 2, "seq": 4})
    ul = make_ulysses_attention_fn(mesh, causal=True)(q, k, v)
    ref = dot_product_attention(q, k, v, causal_mask(L, jnp.float32), jnp.float32)
    np.testing.assert_allclose(np.asarray(ul), np.asarray(ref), atol=2e-5)


@requires_shard_map
def test_ulysses_sp_loss_and_grads_match_single_device():
    """Full SP training path with attention_impl='ulysses'."""
    model_ul, params, cfg = _model("ulysses")
    model_dot, _, _ = _model("dot")
    batch = _batch(cfg)

    ref_loss, ref_grads = jax.value_and_grad(
        transformer_lm.make_loss_fn(model_dot))(params, batch)

    ad = AutoDist(strategy_builder=SequenceParallel(seq_axis_size=2))
    runner = create_sequence_parallel_session(ad, model_ul, params, optax.sgd(0.1))
    sp_loss_fn = make_sequence_parallel_loss_fn(model_ul, runner.mesh)
    sp_loss, sp_grads = jax.value_and_grad(sp_loss_fn)(params, batch)

    np.testing.assert_allclose(float(sp_loss), float(ref_loss), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ref_grads),
                    jax.tree_util.tree_leaves(sp_grads)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-5)


@requires_shard_map
def test_ulysses_rejects_indivisible_heads():
    from autodist_tpu.parallel.mesh import build_mesh
    from autodist_tpu.parallel.ulysses import make_ulysses_attention_fn
    rng = np.random.RandomState(0)
    q = k = v = jnp.asarray(rng.randn(2, 32, 3, 8), jnp.float32)  # 3 heads, seq=4
    mesh = build_mesh(axes={"data": 2, "seq": 4})
    with pytest.raises(ValueError, match="divisible"):
        make_ulysses_attention_fn(mesh)(q, k, v)
